"""Cross-device / cross-process metric-state synchronization.

TPU-native replacement for the reference's ``torchmetrics/utilities/distributed.py``
(``gather_all_tensors``, ``reduce``, ``class_reduce``) and the ``Metric._sync_dist``
machinery (``metric.py:217-242``). Two paths:

- **In-jit collectives** (:func:`sync_in_jit`): states are pytree leaves reduced
  with ``jax.lax.psum`` / ``pmean`` / ``pmax`` / ``pmin`` over a named mesh axis;
  "cat" states use ``jax.lax.all_gather(..., tiled=True)``. Use inside
  ``shard_map`` / ``pmap`` — collectives ride ICI, one fused XLA program.
- **Host path** (:func:`host_sync_state`): out-of-jit sync across JAX
  processes via ``multihost_utils.process_allgather``, mirroring the reference's
  eager ``compute()``-time gather. Uneven leading dims are handled with the
  gather-sizes → pad-to-max → gather → trim protocol (reference
  ``distributed.py:122-145``) because XLA collectives need static shapes.
  After the health header verifies, the payload defaults to the **bucketed
  fused path** (``parallel/bucketing.py``): one collective per dtype/fx
  class for the whole state (or a whole ``MetricCollection``), with per-rank
  lengths riding the header instead of per-leaf shape gathers
  (``METRICS_TPU_FUSED_SYNC=0`` restores the per-leaf path). A collection's
  compute groups (``core/collections.py``) dedupe the combined payload one
  layer up: one gathered state per group of schema/update-identical members,
  so the bytes a grouped collection moves scale with its *unique* states.

**Aliasing contract with the compiled eager hot path.** ``Metric.sync``
hands this module the *pre-sync cache* (``Metric._cache``) — whose array
leaves alias the live state — and restores either the gathered result or
that cache later. Host gathers never mutate or consume their inputs (the
collectives copy), and in the single-process short-circuit the "synced"
leaves are returned by reference; both are safe because the compiled
dispatch layer (``core/compiled.py``) donates a state buffer to XLA only
after proving sole ownership: every restore (``Metric._restore``) clears
the ``_donation_ready`` latch, so the first compiled update after a
sync/unsync round re-copies its leaves instead of invalidating the cache
or the just-restored snapshot in place.
"""
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax

ReduceFx = Union[str, Callable, None]

_EPS = 1e-6


def jit_distributed_available() -> bool:
    """More than one JAX process participating (multi-host)."""
    return jax.process_count() > 1


def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none'.

    Analogue of reference ``utilities/distributed.py:21-40``.
    """
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Per-class fraction reduction: 'micro' | 'macro' | 'weighted' | 'none'.

    Analogue of reference ``utilities/distributed.py:43-87``.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    if class_reduction == "micro":
        fraction = jnp.sum(num) / (jnp.sum(denom) + _EPS)
    else:
        fraction = num / (denom + _EPS)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


# ---------------------------------------------------------------------------
# In-jit collectives (inside shard_map / pmap, over a named mesh axis)
# ---------------------------------------------------------------------------

def sync_leaf_in_jit(value: Array, fx: ReduceFx, axis_name: str) -> Array:
    """Apply the declared cross-device reduction to one state leaf inside jit."""
    if fx == "sum":
        return lax.psum(value, axis_name)
    if fx == "mean":
        return lax.pmean(value, axis_name)
    if fx == "max":
        return lax.pmax(value, axis_name)
    if fx == "min":
        return lax.pmin(value, axis_name)
    if fx == "cat" or fx is None:
        v = value[None] if value.ndim == 0 else value
        return lax.all_gather(v, axis_name, tiled=True)
    if callable(fx):
        return fx(value, axis_name)
    raise ValueError(f"Unknown dist_reduce_fx {fx!r}")


# metricslint: the empty-list skip below branches on per-rank data before
# emitting collectives. That is legal ONLY here: this function runs at trace
# time inside shard_map/pmap, where SPMD guarantees every device executes the
# ONE traced program — python branches resolve once, identically, for the
# whole mesh. Multi-HOST jit programs must feed every process identical state
# schemas (empty vs non-empty included); the host path (host_sync_state)
# verifies exactly that with the health header before its own collectives.
def sync_in_jit(  # metricslint: disable=data-dependent-collective
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    axis_name: str,
    fused: bool = False,
) -> Dict[str, Any]:
    """Synchronize a whole metric-state dict over ``axis_name`` inside jit.

    List-valued ("cat") states are concatenated locally first so each state
    costs exactly one collective — the fused analogue of reference
    ``metric.py:220-223`` (pre-concatenate to reduce the number of gathers).
    A callable ``fx`` on a list state is honored (applied to the local
    concat with the in-jit ``fx(value, axis_name)`` convention, same as
    array leaves) instead of the historical unconditional ``"cat"``. Note
    the host path's convention differs: ``host_sync_leaf`` gathers list
    states regardless of ``fx``, and its callable convention is the
    single-argument ``fx(gathered)``.

    ``fused=True`` additionally buckets the reduce-style array leaves
    (``sum``/``mean``/``max``/``min``) by ``(dtype, fx)`` and concatenates
    each bucket into ONE flat ``psum``/``pmean``/``pmax``/``pmin``, so a
    shard_map program emits O(#dtypes × #fx-classes) collective ops for XLA
    to schedule instead of one per leaf — elementwise over the same mesh
    axis, so results are identical to the per-leaf collectives. The
    partition itself comes from the unified execution plan
    (``core/plan.py`` via :func:`~metrics_tpu.parallel.bucketing.build_sync_plan`):
    the in-jit fused sync and the host bucketed gather share ONE
    schema-keyed layout decision instead of re-deriving it per trace.
    """
    from metrics_tpu.core.cat_buffer import CatBuffer, sync_cat_buffer_in_jit

    bucket_of: Dict[str, Any] = {}
    if fused:
        from metrics_tpu.parallel.bucketing import build_sync_plan

        layout = build_sync_plan(state, reductions)
        for bkey, specs in layout.reduce_buckets.items():
            for spec in specs:
                bucket_of[spec.name] = bkey

    out: Dict[str, Any] = {}
    buckets: Dict[Any, list] = {}
    for name, value in state.items():
        fx = reductions.get(name)
        if isinstance(value, CatBuffer):
            out[name] = sync_cat_buffer_in_jit(value, axis_name)
        elif isinstance(value, (list, tuple)):
            if len(value) == 0:
                out[name] = value
                continue
            value = jnp.concatenate([v[None] if v.ndim == 0 else v for v in value], axis=0)
            if callable(fx):
                out[name] = [fx(value, axis_name)]
            else:
                out[name] = [sync_leaf_in_jit(value, "cat", axis_name)]
        elif name in bucket_of and fx in ("sum", "mean", "max", "min"):
            arr = jnp.asarray(value)
            buckets.setdefault(bucket_of[name], []).append((name, arr))
        else:
            out[name] = sync_leaf_in_jit(value, fx, axis_name)
    for (_dtype, fx), leaves in buckets.items():
        if len(leaves) == 1:
            name, arr = leaves[0]
            out[name] = sync_leaf_in_jit(arr, fx, axis_name)
            continue
        flat = jnp.concatenate([arr.reshape(-1) for _, arr in leaves])
        reduced = sync_leaf_in_jit(flat, fx, axis_name)
        offset = 0
        for name, arr in leaves:
            out[name] = reduced[offset : offset + arr.size].reshape(arr.shape)
            offset += arr.size
    return out


# ---------------------------------------------------------------------------
# Host (out-of-jit, multi-process) path
# ---------------------------------------------------------------------------

def _raw_process_allgather(x: Array) -> Array:
    """The bare cross-process collective.

    Kept as its own seam so the fault-injection harness
    (``tests/parallel/test_fault_injection.py``) can monkeypatch it to
    simulate dead, slow, and divergent peers while the watchdog wrapper in
    :func:`_process_allgather` stays in the loop.
    """
    from jax.experimental import multihost_utils

    return jnp.asarray(multihost_utils.process_allgather(x))


def _process_allgather(x: Array, timeout: Optional[float] = None) -> Array:
    """Watchdog-guarded ``process_allgather``: raises
    :class:`~metrics_tpu.utils.exceptions.SyncTimeoutError` instead of
    blocking forever on a dead/stalled peer.

    On the non-degraded fast path this is exactly the full-world collective.
    Once a quorum transition shrank the membership
    (``parallel/resilience.py``), the gather routes through the installed
    subset transport instead — same watchdog, same call shape, but issued
    over the survivor set only.
    """
    from metrics_tpu.parallel.health import call_with_sync_watchdog
    from metrics_tpu.parallel.resilience import active_subset_transport

    subset = active_subset_transport()
    gather = _raw_process_allgather if subset is None else subset
    return call_with_sync_watchdog(
        lambda: gather(x), timeout=timeout, what="process_allgather"
    )


def gather_all_arrays(
    result: Array,
    group: Optional[Any] = None,
    timeout: Optional[float] = None,
    all_shapes: Optional[Any] = None,
) -> List[Array]:
    """Gather one array from every process; supports uneven leading dims.

    Behavioral analogue of reference ``gather_all_tensors``
    (``utilities/distributed.py:96-145``): returns a list with one entry per
    process, trimmed back to each process's true shape.

    ``all_shapes`` (``[world, ndim]``) lets a caller that already knows
    every rank's shape — the bucketed planner supplies them from the sync
    header, and reduce-style leaves have schema-verified static shapes —
    skip the shape pre-gather entirely, saving one collective per call.
    """
    from metrics_tpu.parallel.resilience import effective_world

    result = jnp.asarray(result)
    world = effective_world()
    if world == 1:
        return [result]
    if all_shapes is None:
        local_shape = jnp.asarray(result.shape, dtype=jnp.int32)
        all_shapes = np.asarray(_process_allgather(local_shape, timeout=timeout))  # [world, ndim]
    else:
        all_shapes = np.asarray(all_shapes, dtype=np.int32)
        if all_shapes.shape != (world, result.ndim):
            raise ValueError(
                f"gather_all_arrays: all_shapes must be [world={world}, "
                f"ndim={result.ndim}], got {all_shapes.shape}"
            )
    max_shape = all_shapes.max(axis=0)
    if (all_shapes == all_shapes[0]).all():
        gathered = _process_allgather(result, timeout=timeout)  # [world, ...]
        return [gathered[i] for i in range(world)]
    pad = [(0, int(m - s)) for s, m in zip(result.shape, max_shape)]
    padded = jnp.pad(result, pad)
    gathered = _process_allgather(padded, timeout=timeout)
    out = []
    for i in range(world):
        slices = tuple(slice(0, int(d)) for d in all_shapes[i])
        out.append(gathered[i][slices])
    return out


def host_sync_leaf(
    value: Any,
    fx: ReduceFx,
    precheck: bool = True,
    timeout: Optional[float] = None,
) -> Any:
    """Host-path sync of one state leaf across processes (eager).

    ``precheck=True`` (standalone use) gathers the leaf's own count/overflow
    words first so an empty or corrupted rank fails symmetrically with a
    typed :class:`~metrics_tpu.utils.exceptions.SyncError`. When the caller
    has already verified the whole state with the sync-header protocol
    (:func:`host_sync_state`), pass ``precheck=False`` to skip the redundant
    per-leaf collectives — that is how N sequential count/flag gathers
    collapse into the one health-word gather.
    """
    from metrics_tpu.core.cat_buffer import CatBuffer
    from metrics_tpu.utils.exceptions import StateDivergenceError, SyncError

    if isinstance(value, CatBuffer):
        if not jit_distributed_available():
            return value.copy()
        from metrics_tpu.parallel.resilience import effective_world

        world = effective_world()
        if precheck:
            # packed (count, overflow-flag) word: one collective for both
            # symmetric checks instead of the historical two
            word = np.asarray(
                _process_allgather(
                    jnp.asarray([len(value), int(bool(np.asarray(value.overflowed)))], jnp.int32),
                    timeout=timeout,
                )
            )
            if (word[:, 0] == 0).any():
                raise StateDivergenceError(
                    "Cannot sync a CatBuffer state across processes: at least one process "
                    "has an empty state (no update() before sync()). All processes raised."
                )
            if (word[:, 1] != 0).any():
                raise SyncError(
                    "Cannot sync a CatBuffer state across processes: at least one process "
                    "overflowed its capacity (rows were overwritten inside jit). "
                    "All processes raised. Use a larger `with_capacity(...)`."
                )
        pieces = gather_all_arrays(value.values(), timeout=timeout)  # uneven rows handled
        merged = CatBuffer(world * value.capacity)
        for p in pieces:
            merged.append(p)
        return merged
    if isinstance(value, (list, tuple)):
        vals: List[Array] = (
            [jnp.concatenate([v[None] if v.ndim == 0 else v for v in value], axis=0)]
            if value
            else []
        )
        if not jit_distributed_available():
            return list(vals)
        if precheck:
            # all ranks first gather their element counts, so a rank with an
            # empty list still participates in a collective (no one-sided
            # hang); if any rank is empty, every rank raises together.
            counts = np.asarray(
                _process_allgather(jnp.asarray(len(vals), dtype=jnp.int32), timeout=timeout)
            )
            if (counts == 0).any():
                raise StateDivergenceError(
                    "Cannot sync a list-state across processes: at least one process has "
                    "an empty state (no update() before sync()). All processes raised."
                )
        return list(gather_all_arrays(vals[0], timeout=timeout))
    if not jit_distributed_available():
        return value
    value = jnp.asarray(value)
    known_shapes = None
    if not precheck and fx not in ("cat", None):
        from metrics_tpu.parallel.resilience import effective_world

        # the caller verified the sync header, whose schema hash covers the
        # FULL shape of reduce/callable-fx leaves — every rank's shape is
        # known-equal, so the shape pre-gather would be a redundant collective
        known_shapes = np.tile(np.asarray(value.shape, np.int32), (effective_world(), 1))
    pieces = gather_all_arrays(value, timeout=timeout, all_shapes=known_shapes)
    if fx == "cat" or fx is None:
        return jnp.concatenate([p[None] if p.ndim == 0 else p for p in pieces], axis=0)
    gathered = jnp.stack(pieces, axis=0)
    if fx == "sum":
        return jnp.sum(gathered, axis=0)
    if fx == "mean":
        return jnp.mean(gathered, axis=0)
    if fx == "max":
        return jnp.max(gathered, axis=0)
    if fx == "min":
        return jnp.min(gathered, axis=0)
    if callable(fx):
        return fx(gathered)
    raise ValueError(f"Unknown dist_reduce_fx {fx!r}")


# metricslint: the channel-suspect refusal below deliberately trades schedule
# symmetry for safety AFTER a watchdog already fired: collective ordering is
# known-poisoned at that point (the timed-out rank may still sit inside its
# stale gather), so refusing to emit anything further — even though the latch
# is per-process state — is strictly safer than emitting a collective that
# could pair with the abandoned one. reset_channel_health() restores symmetry.
def host_sync_state(  # metricslint: disable=data-dependent-collective
    state: Dict[str, Any],
    reductions: Dict[str, ReduceFx],
    update_count: int = 0,
    check_health: bool = True,
    strict_update_count: bool = False,
    timeout: Optional[float] = None,
    metric_name: str = "metric",
    fused: Optional[bool] = None,
    sync_epoch: int = 0,
    on_missing: str = "raise",
    sync_precision: Optional[str] = None,
    stats: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Host-path sync of a whole metric-state dict across processes.

    With ``check_health`` (the default in multi-process runs), every rank
    first contributes one fixed-shape health word in a *single*
    ``process_allgather`` (``parallel/health.py``): empty-state, overflow,
    schema-mismatch, non-finite-poisoning and (strict) update-count-skew
    divergences all raise the same typed ``SyncError`` subclass on every
    rank *before* any payload gather, and the per-leaf count/flag
    prechecks are skipped as redundant — one collective where the leaf
    loop used to issue up to two per state.

    After a verified header the *payload* defaults to the **bucketed fused
    path** (``parallel/bucketing.py``): reduce leaves grouped by
    ``(dtype, fx)`` into flat buffers, cat-family leaves by dtype into one
    padded ragged buffer sized from the header's length columns — the whole
    state syncs in O(#dtypes × #fx-classes) collectives instead of one or
    more per leaf, bit-identical to the per-leaf path. ``fused=None`` reads
    the ``METRICS_TPU_FUSED_SYNC`` env knob (default on; ``0`` is the
    escape hatch); ``check_health=False`` always uses the per-leaf path
    (the planner requires a verified header).

    ``sync_epoch`` tags the health word with the overlapped-sync round this
    gather belongs to (``0`` = blocking): the header verifies the column
    equal across ranks, so a rank resolving an in-flight background round
    can never pair its collectives with a peer's foreground sync
    (``parallel/async_sync.py`` sets it per round).

    ``sync_precision`` (``"bf16"``/``"int8"``, explicit opt-in threaded from
    the Metric/MetricCollection constructor) engages the quantized slow hop
    of the tiered schedule (``parallel/bucketing.py``); the value rides the
    health word's precision column (protocol v5) so a mixed-precision fleet
    raises symmetrically before any payload moves. ``stats`` is the owner's
    ``sync``-domain telemetry dict — the bucketed engine bumps the per-hop
    byte counters into it.

    ``on_missing`` decides what a *missing-rank* failure (watchdog timeout,
    dead transport, divergent header) means: ``"raise"`` (default, the
    pre-quorum behavior — the typed error propagates to the ``on_error``
    ladder), ``"quorum"`` (negotiate a shrunken membership over the
    survivors via ``parallel/resilience.py`` and re-run the health-checked
    gather over the survivor set only — bit-identical to the default when
    every rank is live), or ``"local"`` (the caller degrades to local state
    for missing-rank failures regardless of ``on_error`` — threaded by
    ``Metric._handle_sync_failure``; this function treats it like
    ``"raise"``).

    Once a watchdog has fired anywhere in the process, the cross-process
    channel is *suspect* (the abandoned worker may still sit inside the
    timed-out gather, so a fresh collective could pair with a peer's stale
    one and return wrong data without erroring) — further syncs raise
    :class:`~metrics_tpu.utils.exceptions.SyncTimeoutError` immediately,
    before issuing any collective, while the probation machine
    (``parallel/resilience.py``) cools the channel down; once the cooldown
    elapses one sync is admitted as the *probe round*, and its success
    readmits the channel automatically (``reset_channel_health`` remains
    the manual override).
    """
    if not jit_distributed_available():
        return {name: host_sync_leaf(value, reductions.get(name)) for name, value in state.items()}
    from metrics_tpu.observability import journal
    from metrics_tpu.parallel import resilience
    from metrics_tpu.parallel.async_sync import sync_channel
    from metrics_tpu.utils.exceptions import SyncError

    if journal.ACTIVE:
        journal.record(
            "sync.gather", label=metric_name, sync_epoch=int(sync_epoch),
            states=len(state), fused=fused,
        )

    gate = resilience.channel_gate()
    if gate == "refuse":
        from metrics_tpu.utils.exceptions import SyncTimeoutError

        raise SyncTimeoutError(
            f"host sync of {metric_name} refused: an earlier collective timed "
            "out, so cross-process collective ordering can no longer be "
            "trusted (a new gather could silently pair with a peer's stale "
            "one). Recover with on_error='local' degradation; the channel "
            "will admit a probe round after its probation cooldown, or "
            "restart the process group and call "
            "metrics_tpu.parallel.health.reset_channel_health()."
        )

    def _attempt() -> Dict[str, Any]:
        precheck = True
        if check_health:
            from metrics_tpu.parallel.health import build_health_word, verify_health_words

            word = build_health_word(
                state, reductions, update_count=update_count, sync_epoch=sync_epoch,
                sync_precision=sync_precision,
            )
            words = np.asarray(_process_allgather(jnp.asarray(word), timeout=timeout))
            verify_health_words(
                words,
                state,
                reductions,
                strict_update_count=strict_update_count,
                metric_name=metric_name,
            )
            precheck = False
            from metrics_tpu.parallel.bucketing import fused_sync_enabled, host_sync_state_bucketed

            if fused_sync_enabled() if fused is None else fused:
                return host_sync_state_bucketed(
                    state, reductions, words=words, timeout=timeout,
                    sync_precision=sync_precision, stats=stats,
                )
        return {
            name: host_sync_leaf(value, reductions.get(name), precheck=precheck, timeout=timeout)
            for name, value in state.items()
        }

    # the channel guard orders this whole sync after any in-flight
    # background round (``parallel/async_sync.py``): a foreground sync first
    # drains rounds already launched on every rank (program order is SPMD-
    # identical, so the global collective order stays deterministic)
    with sync_channel():
        if on_missing == "quorum":
            resilience.note_sync_round()
            resilience.maybe_rejoin(metric_name=metric_name)
        try:
            synced = _attempt()
        except Exception as err:
            if (
                on_missing == "quorum"
                and isinstance(err, SyncError)
                and resilience.is_missing_rank_error(err)
                and resilience.negotiate_quorum(err, metric_name=metric_name) is not None
            ):
                # membership shrank: re-run the full health-checked gather
                # over the survivor set. Safe in a handler: negotiate_quorum
                # already re-established symmetry — every survivor ran the
                # same negotiation and agreed the same membership epoch, the
                # header re-verifies it, and payload collectives route
                # through the survivor-set transport.
                synced = _attempt()  # metricslint: disable=collective-in-handler
            else:
                raise
    if gate == "probe":
        resilience.channel_probe_succeeded()
    return synced
