"""ROUGE score — analogue of reference
``torchmetrics/functional/text/rouge.py:37-325``.

All string work (normalization, stemming, n-gram/LCS matching) runs on host;
per-sentence precision/recall/F1 become device arrays accumulated as
cat-states by the module class.

Unlike the reference, stemming and ``rougeLsum`` need no nltk: a built-in
classic Porter (1980) stemmer and a regex sentence splitter are used when
nltk is absent (nltk is preferred when importable, for rouge-score parity).
"""
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.imports import _NLTK_AVAILABLE

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    **{f"rouge{n}": n for n in range(1, 10)},
    "rougeL": "L",
    "rougeLsum": "Lsum",
}


# ---------------------------------------------------------------------------
# built-in Porter stemmer (Porter, 1980 — "An algorithm for suffix stripping")
# ---------------------------------------------------------------------------

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC (vowel-consonant) transitions in the stem."""
    m = 0
    prev_cons = None
    for i in range(len(stem)):
        cons = _is_cons(stem, i)
        if prev_cons is False and cons:
            m += 1
        prev_cons = cons
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return len(word) >= 2 and word[-1] == word[-2] and _is_cons(word, len(word) - 1)


def _ends_cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (
        _is_cons(word, len(word) - 3)
        and not _is_cons(word, len(word) - 2)
        and _is_cons(word, len(word) - 1)
        and word[-1] not in "wxy"
    )


def _replace_suffix(word: str, suffix: str, repl: str, min_measure: int) -> Optional[str]:
    if word.endswith(suffix):
        stem = word[: len(word) - len(suffix)]
        if _measure(stem) > min_measure:
            return stem + repl
    return None


_STEP2_RULES = (
    ("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
    ("izer", "ize"), ("abli", "able"), ("alli", "al"), ("entli", "ent"), ("eli", "e"),
    ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"), ("ator", "ate"),
    ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"), ("ousness", "ous"),
    ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
)
_STEP3_RULES = (
    ("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
    ("ical", "ic"), ("ful", ""), ("ness", ""),
)
_STEP4_SUFFIXES = (
    "al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement", "ment",
    "ent", "ion", "ou", "ism", "ate", "iti", "ous", "ive", "ize",
)


class PorterStemmer:
    """Classic Porter stemmer; drop-in for nltk's when nltk is unavailable."""

    def stem(self, word: str) -> str:
        if len(word) <= 2:
            return word
        word = self._step1a(word)
        word = self._step1b(word)
        word = self._step1c(word)
        word = self._step2(word)
        word = self._step3(word)
        word = self._step4(word)
        word = self._step5(word)
        return word

    @staticmethod
    def _step1a(w: str) -> str:
        if w.endswith("sses"):
            return w[:-2]
        if w.endswith("ies"):
            return w[:-2]
        if w.endswith("ss"):
            return w
        if w.endswith("s"):
            return w[:-1]
        return w

    @staticmethod
    def _step1b(w: str) -> str:
        if w.endswith("eed"):
            return w[:-1] if _measure(w[:-3]) > 0 else w
        fired = None
        if w.endswith("ed") and _has_vowel(w[:-2]):
            fired = w[:-2]
        elif w.endswith("ing") and _has_vowel(w[:-3]):
            fired = w[:-3]
        if fired is None:
            return w
        w = fired
        if w.endswith(("at", "bl", "iz")):
            return w + "e"
        if _ends_double_cons(w) and w[-1] not in "lsz":
            return w[:-1]
        if _measure(w) == 1 and _ends_cvc(w):
            return w + "e"
        return w

    @staticmethod
    def _step1c(w: str) -> str:
        if w.endswith("y") and _has_vowel(w[:-1]):
            return w[:-1] + "i"
        return w

    @staticmethod
    def _step2(w: str) -> str:
        for suffix, repl in _STEP2_RULES:
            out = _replace_suffix(w, suffix, repl, 0)
            if out is not None:
                return out
        return w

    @staticmethod
    def _step3(w: str) -> str:
        for suffix, repl in _STEP3_RULES:
            out = _replace_suffix(w, suffix, repl, 0)
            if out is not None:
                return out
        return w

    @staticmethod
    def _step4(w: str) -> str:
        for suffix in _STEP4_SUFFIXES:
            if w.endswith(suffix):
                stem = w[: len(w) - len(suffix)]
                if _measure(stem) > 1:
                    if suffix == "ion" and not stem.endswith(("s", "t")):
                        continue
                    return stem
                return w
        return w

    @staticmethod
    def _step5(w: str) -> str:
        if w.endswith("e"):
            stem = w[:-1]
            m = _measure(stem)
            if m > 1 or (m == 1 and not _ends_cvc(stem)):
                w = stem
        if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
            w = w[:-1]
        return w


def _get_stemmer() -> Any:
    if _NLTK_AVAILABLE:
        import nltk

        return nltk.stem.porter.PorterStemmer()
    return PorterStemmer()


def _split_sentences(text: str) -> str:
    """Newline-join sentences for rougeLsum.

    nltk's punkt tokenizer when available; a regex split on sentence-final
    punctuation otherwise (documented divergence from the reference, which
    hard-requires nltk at ``rouge.py:40-47``).
    """
    text = re.sub("<n>", "", text)  # pegasus newline token
    if _NLTK_AVAILABLE:
        import nltk

        try:
            return "\n".join(nltk.sent_tokenize(text))
        except LookupError:
            pass
    sentences = re.split(r"(?<=[.!?])\s+", text.strip())
    return "\n".join(s for s in sentences if s)


# ---------------------------------------------------------------------------
# scoring
# ---------------------------------------------------------------------------


def _normalize_and_tokenize_text(text: str, stemmer: Optional[Any] = None) -> List[str]:
    """Lowercase alphanumeric tokens, optional stemming of words >3 chars
    (mirrors rouge-score's tokenize, cf. reference ``rouge.py:92-113``)."""
    text = re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if (isinstance(x, str) and re.match(r"^[a-z0-9]+$", x))]


def _prf(hits: float, pred_len: int, target_len: int) -> Dict[str, Array]:
    precision = hits / pred_len
    recall = hits / target_len
    if precision == recall == 0.0:
        return dict(
            precision=jnp.asarray(0.0), recall=jnp.asarray(0.0), fmeasure=jnp.asarray(0.0)
        )
    fmeasure = 2 * precision * recall / (precision + recall)
    return dict(
        precision=jnp.asarray(precision),
        recall=jnp.asarray(recall),
        fmeasure=jnp.asarray(fmeasure),
    )


def _rouge_n_score(pred: List[str], target: List[str], n_gram: int) -> Dict[str, Array]:
    def ngrams(tokens: List[str]) -> Counter:
        return Counter(tuple(tokens[i : i + n_gram]) for i in range(len(tokens) - n_gram + 1))

    pred_ngrams, target_ngrams = ngrams(pred), ngrams(target)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return _prf(0.0, 1, 1)
    hits = sum((pred_ngrams & target_ngrams).values())
    return _prf(hits, max(pred_len, 1), max(target_len, 1))


def _lcs_len(pred: List[str], target: List[str]) -> int:
    """Longest common subsequence length (two-row DP)."""
    import numpy as np

    vocab = {t: i for i, t in enumerate(dict.fromkeys(pred + target))}
    a = np.asarray([vocab[t] for t in pred])
    b = np.asarray([vocab[t] for t in target])
    prev = np.zeros(b.size + 1, dtype=np.int64)
    for i in range(a.size):
        cur = np.zeros(b.size + 1, dtype=np.int64)
        for j in range(b.size):
            cur[j + 1] = prev[j] + 1 if a[i] == b[j] else max(prev[j + 1], cur[j])
        prev = cur
    return int(prev[-1])


def _rouge_l_score(pred: List[str], target: List[str]) -> Dict[str, Array]:
    pred_len, target_len = len(pred), len(target)
    if 0 in (pred_len, target_len):
        return _prf(0.0, 1, 1)
    return _prf(_lcs_len(pred, target), pred_len, target_len)


def _lcs_positions(a: List[str], b: List[str]) -> set:
    """Indices of ``a`` participating in one LCS with ``b`` (backtracked DP)."""
    dp = [[0] * (len(b) + 1) for _ in range(len(a) + 1)]
    for i in range(len(a)):
        for j in range(len(b)):
            dp[i + 1][j + 1] = dp[i][j] + 1 if a[i] == b[j] else max(dp[i][j + 1], dp[i + 1][j])
    positions = set()
    i, j = len(a), len(b)
    while i > 0 and j > 0:
        if a[i - 1] == b[j - 1] and dp[i][j] == dp[i - 1][j - 1] + 1:
            positions.add(i - 1)
            i -= 1
            j -= 1
        elif dp[i - 1][j] >= dp[i][j - 1]:
            i -= 1
        else:
            j -= 1
    return positions


def _rouge_lsum_score(
    pred_sents: List[List[str]], target_sents: List[List[str]]
) -> Dict[str, Array]:
    """Summary-level ROUGE-L: union-LCS over sentence pairs with clipping.

    For each target sentence the union of LCS-matched token positions across
    all prediction sentences counts as hits, clipped by corpus-level token
    counts (the rouge-score package's ``_summary_level_lcs``). NOTE: the
    reference's rougeLsum (``rouge.py:214-223``) flattens sentences before a
    single whole-text LCS, collapsing it onto rougeL — this implements the
    metric as defined instead.
    """
    pred_len = sum(len(s) for s in pred_sents)
    target_len = sum(len(s) for s in target_sents)
    if 0 in (pred_len, target_len):
        return _prf(0.0, 1, 1)
    pred_counts = Counter(tok for s in pred_sents for tok in s)
    target_counts = Counter(tok for s in target_sents for tok in s)
    hits = 0
    for target_sent in target_sents:
        union: set = set()
        for pred_sent in pred_sents:
            union |= _lcs_positions(target_sent, pred_sent)
        for pos in union:
            token = target_sent[pos]
            if pred_counts[token] > 0 and target_counts[token] > 0:
                hits += 1
                pred_counts[token] -= 1
                target_counts[token] -= 1
    return _prf(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    targets: Sequence[str],
    rouge_keys_values: List[Union[int, str]],
    stemmer: Optional[Any] = None,
) -> Dict[Union[int, str], List[Dict[str, Array]]]:
    """Per-sentence P/R/F for every requested rouge variant."""
    results: Dict[Union[int, str], List[Dict[str, Array]]] = {k: [] for k in rouge_keys_values}
    for pred_raw, target_raw in zip(preds, targets):
        pred = _normalize_and_tokenize_text(pred_raw, stemmer)
        target = _normalize_and_tokenize_text(target_raw, stemmer)
        if "Lsum" in rouge_keys_values:
            # per-sentence token lists (normalization would destroy the
            # newline boundaries, so split first, tokenize each sentence)
            pred_sents = [
                _normalize_and_tokenize_text(s, stemmer)
                for s in _split_sentences(pred_raw).split("\n")
            ]
            target_sents = [
                _normalize_and_tokenize_text(s, stemmer)
                for s in _split_sentences(target_raw).split("\n")
            ]
        for key in rouge_keys_values:
            if isinstance(key, int):
                score = _rouge_n_score(pred, target, key)
            elif key == "Lsum":
                score = _rouge_lsum_score(pred_sents, target_sents)
            else:
                score = _rouge_l_score(pred, target)
            results[key].append(score)
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Array]]) -> Dict[str, Array]:
    """Mean over accumulated per-sentence scores."""
    return {
        key: jnp.mean(jnp.stack([jnp.asarray(s) for s in scores])) if scores else jnp.asarray(0.0)
        for key, scores in sentence_results.items()
    }


def rouge_score(
    preds: Union[str, List[str]],
    targets: Union[str, List[str]],
    use_stemmer: bool = False,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE score for automatic summarization.

    Args:
        preds: predicted sentence(s).
        targets: target sentence(s).
        use_stemmer: Porter-stem tokens >3 chars before matching.
        rouge_keys: which variants — ``rouge1``..``rouge9``, ``rougeL``, ``rougeLsum``.

    Returns:
        dict with ``{key}_precision/_recall/_fmeasure`` entries.

    Example:
        >>> targets = "Is your name John"
        >>> preds = "My name is John"
        >>> scores = rouge_score(preds, targets, rouge_keys="rouge1")
        >>> float(scores["rouge1_fmeasure"])
        0.75
    """
    stemmer = _get_stemmer() if use_stemmer else None

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(
                f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
            )
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(preds, str):
        preds = [preds]
    if isinstance(targets, str):
        targets = [targets]

    sentence_results = _rouge_score_update(preds, targets, rouge_keys_values, stemmer=stemmer)
    output: Dict[str, List[Array]] = {}
    for key, metrics in sentence_results.items():
        for metric in metrics:
            for kind, value in metric.items():
                output.setdefault(f"rouge{key}_{kind}", []).append(value)
    return _rouge_score_compute(output)
