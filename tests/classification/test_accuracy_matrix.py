"""Accuracy 19-row fixture × subset_accuracy matrix + top-k tables.

Mirror of the reference's `tests/classification/test_accuracy.py`: every
input fixture (binary/prob/logits, multilabel ± multidim, multiclass ± prob
± logits, mdmc ± prob) × subset_accuracy through class (eager + ddp +
per-step sync) and functional paths vs sklearn's accuracy_score, plus the
hand-worked top-k expectation table, top-k wrong-input-type contracts, and
the wrong-params grid.
"""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import accuracy_score as sk_accuracy_score

from metrics_tpu import Accuracy
from metrics_tpu.functional import accuracy
from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import DataType
from tests.classification.inputs import (
    _input_binary,
    _input_binary_logits,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_logits as _input_mcls_logits,
    _input_multiclass_prob as _input_mcls_prob,
    _input_multidim_multiclass as _input_mdmc,
    _input_multidim_multiclass_prob as _input_mdmc_prob,
    _input_multilabel as _input_mlb,
    _input_multilabel_logits as _input_mlb_logits,
    _input_multilabel_multidim as _input_mlmd,
    _input_multilabel_multidim_prob as _input_mlmd_prob,
    _input_multilabel_prob as _input_mlb_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_accuracy(preds, target, subset_accuracy):
    """Reference `test_accuracy.py:44-56`, with the repo formatter."""
    sk_preds, sk_target, mode = _input_format_classification(preds, target, threshold=THRESHOLD)
    sk_preds, sk_target = np.asarray(sk_preds), np.asarray(sk_target)

    if mode == DataType.MULTIDIM_MULTICLASS and not subset_accuracy:
        sk_preds = np.transpose(sk_preds, (0, 2, 1)).reshape(-1, sk_preds.shape[1])
        sk_target = np.transpose(sk_target, (0, 2, 1)).reshape(-1, sk_target.shape[1])
    elif mode == DataType.MULTIDIM_MULTICLASS and subset_accuracy:
        return np.all(sk_preds == sk_target, axis=(1, 2)).mean()
    elif mode == DataType.MULTILABEL and not subset_accuracy:
        sk_preds, sk_target = sk_preds.reshape(-1), sk_target.reshape(-1)

    return sk_accuracy_score(y_true=sk_target, y_pred=sk_preds)


@pytest.mark.parametrize(
    "preds, target, subset_accuracy",
    [
        (_input_binary_logits.preds, _input_binary_logits.target, False),
        (_input_binary_prob.preds, _input_binary_prob.target, False),
        (_input_binary.preds, _input_binary.target, False),
        (_input_mlb_prob.preds, _input_mlb_prob.target, True),
        (_input_mlb_logits.preds, _input_mlb_logits.target, False),
        (_input_mlb_prob.preds, _input_mlb_prob.target, False),
        (_input_mlb.preds, _input_mlb.target, True),
        (_input_mlb.preds, _input_mlb.target, False),
        (_input_mcls_prob.preds, _input_mcls_prob.target, False),
        (_input_mcls_logits.preds, _input_mcls_logits.target, False),
        (_input_multiclass.preds, _input_multiclass.target, False),
        (_input_mdmc_prob.preds, _input_mdmc_prob.target, False),
        (_input_mdmc_prob.preds, _input_mdmc_prob.target, True),
        (_input_mdmc.preds, _input_mdmc.target, False),
        (_input_mdmc.preds, _input_mdmc.target, True),
        (_input_mlmd_prob.preds, _input_mlmd_prob.target, True),
        (_input_mlmd_prob.preds, _input_mlmd_prob.target, False),
        (_input_mlmd.preds, _input_mlmd.target, True),
        (_input_mlmd.preds, _input_mlmd.target, False),
    ],
)
class TestAccuracyMatrix(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    def test_accuracy_class(self, ddp, dist_sync_on_step, preds, target, subset_accuracy):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=partial(_sk_accuracy, subset_accuracy=subset_accuracy),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"threshold": THRESHOLD, "subset_accuracy": subset_accuracy},
            check_jit=False,  # jit gates per input type run in test_input_variants
        )

    def test_accuracy_fn(self, preds, target, subset_accuracy):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=accuracy,
            sk_metric=partial(_sk_accuracy, subset_accuracy=subset_accuracy),
            metric_args={"threshold": THRESHOLD, "subset_accuracy": subset_accuracy},
        )


# hand-worked top-k tables (reference `test_accuracy.py:123-172`): preds rank
# class 3 > 2 > 1 > 0 everywhere
_l1to4 = [0.1, 0.2, 0.3, 0.4]
_l1to4t3 = np.array([_l1to4, _l1to4, _l1to4])
_l1to4t3_mcls = [_l1to4t3.T, _l1to4t3.T, _l1to4t3.T]

_topk_preds_mcls = np.asarray([_l1to4t3, _l1to4t3], dtype=np.float32)
_topk_target_mcls = np.asarray([[1, 2, 3], [2, 1, 0]])

_topk_preds_mdmc = np.asarray([_l1to4t3_mcls, _l1to4t3_mcls], dtype=np.float32)
_topk_target_mdmc = np.asarray([[[1, 1, 0], [2, 2, 2], [3, 3, 3]], [[2, 2, 0], [1, 1, 1], [0, 0, 0]]])

_ml_t1 = [0.8, 0.2, 0.8, 0.2]
_ml_t2 = [_ml_t1, _ml_t1]
_av_preds_ml = np.asarray([_ml_t2, _ml_t2], dtype=np.float32)
_av_target_ml = np.asarray([[[1, 0, 1, 1], [0, 1, 1, 0]], [[1, 0, 1, 1], [0, 1, 1, 0]]])


@pytest.mark.parametrize(
    "preds, target, exp_result, k, subset_accuracy",
    [
        (_topk_preds_mcls, _topk_target_mcls, 1 / 6, 1, False),
        (_topk_preds_mcls, _topk_target_mcls, 3 / 6, 2, False),
        (_topk_preds_mcls, _topk_target_mcls, 5 / 6, 3, False),
        (_topk_preds_mcls, _topk_target_mcls, 1 / 6, 1, True),
        (_topk_preds_mcls, _topk_target_mcls, 3 / 6, 2, True),
        (_topk_preds_mcls, _topk_target_mcls, 5 / 6, 3, True),
        (_topk_preds_mdmc, _topk_target_mdmc, 1 / 6, 1, False),
        (_topk_preds_mdmc, _topk_target_mdmc, 8 / 18, 2, False),
        (_topk_preds_mdmc, _topk_target_mdmc, 13 / 18, 3, False),
        (_topk_preds_mdmc, _topk_target_mdmc, 1 / 6, 1, True),
        (_topk_preds_mdmc, _topk_target_mdmc, 2 / 6, 2, True),
        (_topk_preds_mdmc, _topk_target_mdmc, 3 / 6, 3, True),
        (_av_preds_ml, _av_target_ml, 5 / 8, None, False),
        (_av_preds_ml, _av_target_ml, 0, None, True),
    ],
)
def test_topk_accuracy(preds, target, exp_result, k, subset_accuracy):
    topk = Accuracy(top_k=k, subset_accuracy=subset_accuracy)
    for batch in range(preds.shape[0]):
        topk(jnp.asarray(preds[batch]), jnp.asarray(target[batch]))
    np.testing.assert_allclose(float(topk.compute()), exp_result, atol=1e-6)

    total = target.shape[0] * target.shape[1]
    p_flat = preds.reshape(total, 4, -1).squeeze()
    t_flat = target.reshape(total, -1).squeeze()
    np.testing.assert_allclose(
        float(accuracy(jnp.asarray(p_flat), jnp.asarray(t_flat), top_k=k, subset_accuracy=subset_accuracy)),
        exp_result,
        atol=1e-6,
    )


@pytest.mark.parametrize(
    "preds, target",
    [
        (_input_binary_prob.preds, _input_binary_prob.target),
        (_input_binary.preds, _input_binary.target),
        (_input_mlb.preds, _input_mlb.target),
        (_input_multiclass.preds, _input_multiclass.target),
        (_input_mdmc.preds, _input_mdmc.target),
        (_input_mlmd.preds, _input_mlmd.target),
    ],
)
def test_topk_accuracy_wrong_input_types(preds, target):
    """top_k is only defined for (md)mc/ml probability inputs (reference
    `test_accuracy.py:176-197`)."""
    topk = Accuracy(top_k=2)
    with pytest.raises(ValueError):
        topk(jnp.asarray(preds[0]), jnp.asarray(target[0]))
    with pytest.raises(ValueError):
        accuracy(jnp.asarray(preds[0]), jnp.asarray(target[0]), top_k=2)


@pytest.mark.parametrize(
    "average, mdmc_average, num_classes, inputs, ignore_index, top_k, threshold",
    [
        ("unknown", None, None, _input_binary, None, None, 0.5),
        ("micro", "unknown", None, _input_binary, None, None, 0.5),
        ("macro", None, None, _input_binary, None, None, 0.5),
        ("micro", None, None, _input_mdmc_prob, None, None, 0.5),
        ("micro", None, None, _input_binary_prob, 0, None, 0.5),
        ("micro", None, None, _input_mcls_prob, NUM_CLASSES, None, 0.5),
        ("micro", None, NUM_CLASSES, _input_mcls_prob, NUM_CLASSES, None, 0.5),
        (None, None, None, _input_mcls_prob, None, 0, 0.5),
        # deviation from the reference row (mcls_prob, 1.5): threshold
        # validation here is usage-aware — multiclass probs never threshold —
        # so the out-of-range case is asserted on a thresholded (binary) input
        (None, None, None, _input_binary_prob, None, None, 1.5),
    ],
)
def test_wrong_params(average, mdmc_average, num_classes, inputs, ignore_index, top_k, threshold):
    """Reference `test_accuracy.py:199-238` invalid-combination grid."""
    with pytest.raises(ValueError):
        acc = Accuracy(
            average=average, mdmc_average=mdmc_average, num_classes=num_classes,
            ignore_index=ignore_index, threshold=threshold, top_k=top_k,
        )
        acc(jnp.asarray(inputs.preds[0]), jnp.asarray(inputs.target[0]))
        acc.compute()
    with pytest.raises(ValueError):
        accuracy(
            jnp.asarray(inputs.preds[0]), jnp.asarray(inputs.target[0]),
            average=average, mdmc_average=mdmc_average, num_classes=num_classes,
            ignore_index=ignore_index, threshold=threshold, top_k=top_k,
        )
