"""Error types for API misuse and distributed-sync failures.

TPU-native analogue of the reference's ``torchmetrics/utilities/exceptions.py:16``,
extended with a typed hierarchy for cross-process synchronization faults.
Cross-replica protocols only stay correct when every rank takes the identical
branch (see ``parallel/health.py``), so sync failures are *classified*: the
health-word protocol raises the same exception type, from the same gathered
evidence, on every rank — never a one-sided raise that hangs the peers.
"""


class MetricsTPUUserError(Exception):
    """Raised when the metrics-TPU API is used incorrectly (e.g. double-sync)."""


class SyncError(RuntimeError):
    """Base class for distributed metric-state synchronization failures.

    Subclasses ``RuntimeError`` so callers of the pre-typed API (which raised
    bare ``RuntimeError`` for empty/overflowed states) keep working. All
    subclasses are raised *symmetrically*: every participating process sees
    the same gathered health words and takes the same raise branch, so a
    fault can never strand healthy ranks inside a collective.
    """


class SyncTimeoutError(SyncError):
    """A host collective did not complete within the watchdog timeout.

    The usual cause is a dead or stalled peer process. After this is raised
    the process's collective ordering can no longer be trusted — recover via
    ``on_error="local"`` degradation or by restarting the process group.
    """


class StateDivergenceError(SyncError):
    """Metric state diverged across processes before a sync.

    Covers the divergence classes the health word detects: a rank with an
    empty cat-state, mismatched state schemas (names/dtypes/item shapes),
    and update-count skew under strict checking.
    """


class NonFiniteStateError(SyncError):
    """A rank's accumulated state was poisoned by NaN/Inf values.

    Raised when ``check_finite`` screening is enabled and any participating
    rank's poison flag is set (or locally, single-process, at compute time).
    """


# Alias kept for users migrating from the reference library.
TorchMetricsUserError = MetricsTPUUserError
