"""Pearson / Spearman / CosineSimilarity / TweedieDeviance matrices.

Mirrors the reference's `tests/regression/test_pearson.py` (two input
distributions × ddp), `test_spearman.py` (three inputs incl. heavy ties ×
ddp × per-step sync + scipy `rankdata` parity), `test_cosine_similarity.py`
(single/multi-target × reduction × ddp × per-step sync) and
`test_tweedie_deviance.py` (six powers × inputs × ddp × per-step sync +
domain-error matrix), all against scipy/sklearn oracles.
"""
from collections import namedtuple
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import pearsonr, rankdata, spearmanr
from sklearn.metrics import mean_tweedie_deviance as sk_tweedie

from metrics_tpu import (
    CosineSimilarity,
    PearsonCorrcoef,
    SpearmanCorrcoef,
    TweedieDevianceScore,
)
from metrics_tpu.functional import (
    cosine_similarity,
    pearson_corrcoef,
    spearman_corrcoef,
    tweedie_deviance_score,
)
from metrics_tpu.functional.regression.spearman import _rank_data
from tests.helpers import seed_all
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

seed_all(42)

NUM_TARGETS = 5
rng = np.random.RandomState(42)

Input = namedtuple("Input", ["preds", "target"])

_uniform = Input(
    preds=rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) * 0.9 + 0.05,
    target=rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) * 0.9 + 0.05,
)
_normal = Input(
    preds=rng.randn(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
    target=rng.randn(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
)
# heavy-tie fixture (reference test_spearman.py:39-42)
_ties = Input(
    preds=np.stack([np.asarray([1.0, 0.0, 4.0, 1.0, 0.0, 3.0, 0.0], np.float32)] * NUM_BATCHES),
    target=np.stack([np.asarray([4.0, 0.0, 3.0, 3.0, 3.0, 1.0, 1.0], np.float32)] * NUM_BATCHES),
)
_multi = Input(
    preds=rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_TARGETS).astype(np.float32) * 0.9 + 0.05,
    target=rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_TARGETS).astype(np.float32) * 0.9 + 0.05,
)


# ----------------------------------------------------------------- Pearson
def _sk_pearson(preds, target):
    return pearsonr(target.reshape(-1), preds.reshape(-1))[0]


@pytest.mark.parametrize(
    "preds, target",
    [(_uniform.preds, _uniform.target), (_normal.preds, _normal.target)],
    ids=["uniform", "normal"],
)
class TestPearsonMatrix(MetricTester):
    atol = 1e-3

    @pytest.mark.parametrize("ddp", [False, True])
    def test_pearson_class(self, preds, target, ddp):
        # per-step sync is not exercised for Pearson, matching the reference
        # (test_pearson.py:56-65 fixes dist_sync_on_step=False): its states
        # carry dist_reduce_fx=None and fold through the pairwise merge.
        self.run_class_metric_test(
            ddp=ddp, preds=preds, target=target, metric_class=PearsonCorrcoef,
            sk_metric=_sk_pearson,
        )

    def test_pearson_functional(self, preds, target):
        self.run_functional_metric_test(preds, target, pearson_corrcoef, _sk_pearson)

    def test_pearson_differentiability(self, preds, target):
        self.run_differentiability_test(
            preds, target, metric_class=PearsonCorrcoef, metric_functional=pearson_corrcoef
        )


def test_pearson_error_on_different_shape():
    metric = PearsonCorrcoef()
    with pytest.raises(RuntimeError, match="same shape"):
        metric(jnp.zeros(100), jnp.zeros(50))
    with pytest.raises(ValueError, match="1 dimensional"):
        metric(jnp.zeros((100, 2)), jnp.zeros((100, 2)))


# ----------------------------------------------------------------- Spearman
@pytest.mark.parametrize(
    "preds, target",
    [
        (_uniform.preds, _uniform.target),
        (_normal.preds, _normal.target),
        (_ties.preds, _ties.target),
    ],
    ids=["uniform", "normal", "ties"],
)
def test_spearman_ranking_vs_scipy(preds, target):
    """`_rank_data` must reproduce scipy.stats.rankdata tie-averaged ranks
    (reference test_spearman.py:53-59)."""
    for p, t in zip(preds, target):
        np.testing.assert_array_equal(np.asarray(_rank_data(jnp.asarray(p))), rankdata(p))
        np.testing.assert_array_equal(np.asarray(_rank_data(jnp.asarray(t))), rankdata(t))


def _sk_spearman(preds, target):
    return spearmanr(target.reshape(-1), preds.reshape(-1))[0]


@pytest.mark.parametrize(
    "preds, target",
    [
        (_uniform.preds, _uniform.target),
        (_normal.preds, _normal.target),
        (_ties.preds, _ties.target),
    ],
    ids=["uniform", "normal", "ties"],
)
class TestSpearmanMatrix(MetricTester):
    atol = 1e-3

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    def test_spearman_class(self, preds, target, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp, preds=preds, target=target, metric_class=SpearmanCorrcoef,
            sk_metric=_sk_spearman, dist_sync_on_step=dist_sync_on_step,
        )

    def test_spearman_functional(self, preds, target):
        self.run_functional_metric_test(preds, target, spearman_corrcoef, _sk_spearman)

    def test_spearman_differentiability(self, preds, target):
        self.run_differentiability_test(
            preds, target, metric_class=SpearmanCorrcoef, metric_functional=spearman_corrcoef
        )


def test_spearman_error_on_different_shape():
    metric = SpearmanCorrcoef()
    with pytest.raises(RuntimeError, match="same shape"):
        metric(jnp.zeros(100), jnp.zeros(50))
    with pytest.raises(ValueError, match="1 dimensional"):
        metric(jnp.zeros((100, 2)), jnp.zeros((100, 2)))


# --------------------------------------------------------- CosineSimilarity
def _sk_cosine(preds, target, reduction):
    # 1-D input is a single vector; N-D is rows of last-dim vectors
    p = preds.reshape(1, -1) if preds.ndim == 1 else preds.reshape(-1, preds.shape[-1])
    t = target.reshape(1, -1) if target.ndim == 1 else target.reshape(-1, target.shape[-1])
    sim = (p * t).sum(-1) / (np.linalg.norm(p, axis=-1) * np.linalg.norm(t, axis=-1))
    return {"sum": sim.sum(), "mean": sim.mean(), "none": sim}[reduction]


@pytest.mark.parametrize("reduction", ["sum", "mean"])
@pytest.mark.parametrize(
    "preds, target",
    [(_uniform.preds, _uniform.target), (_multi.preds, _multi.target)],
    ids=["single_target", "multi_target"],
)
class TestCosineSimilarityMatrix(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    def test_cosine_class(self, reduction, preds, target, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp, preds=preds, target=target, metric_class=CosineSimilarity,
            sk_metric=partial(_sk_cosine, reduction=reduction),
            dist_sync_on_step=dist_sync_on_step,
            metric_args=dict(reduction=reduction),
        )

    def test_cosine_functional(self, reduction, preds, target):
        self.run_functional_metric_test(
            preds, target, cosine_similarity,
            partial(_sk_cosine, reduction=reduction),
            metric_args=dict(reduction=reduction),
        )


def test_cosine_invalid_reduction():
    with pytest.raises(ValueError, match="Expected reduction to be one of"):
        cosine_similarity(jnp.ones((4, 3)), jnp.ones((4, 3)), reduction="bogus")


# ----------------------------------------------------------------- Tweedie
def _sk_deviance(preds, target, power):
    return sk_tweedie(target.reshape(-1), preds.reshape(-1), power=power)


@pytest.mark.parametrize("power", [-0.5, 0, 1, 1.5, 2, 3])
@pytest.mark.parametrize(
    "preds, target",
    [(_uniform.preds, _uniform.target), (_multi.preds, _multi.target)],
    ids=["single_target", "multi_target"],
)
class TestTweedieDevianceMatrix(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    @pytest.mark.parametrize("dist_sync_on_step", [False, True])
    def test_tweedie_class(self, power, preds, target, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp, preds=preds, target=target, metric_class=TweedieDevianceScore,
            sk_metric=partial(_sk_deviance, power=power),
            dist_sync_on_step=dist_sync_on_step,
            metric_args=dict(power=power),
        )

    def test_tweedie_functional(self, power, preds, target):
        self.run_functional_metric_test(
            preds, target, tweedie_deviance_score,
            partial(_sk_deviance, power=power),
            metric_args=dict(power=power),
        )

    def test_tweedie_differentiability(self, power, preds, target):
        self.run_differentiability_test(
            preds, target, metric_class=TweedieDevianceScore,
            metric_functional=tweedie_deviance_score, metric_args=dict(power=power),
        )


def test_tweedie_error_on_different_shape():
    metric = TweedieDevianceScore()
    with pytest.raises(RuntimeError, match="same shape"):
        metric(jnp.ones(100), jnp.ones(50))


def test_tweedie_error_on_invalid_inputs():
    """Domain-error matrix (reference test_tweedie_deviance.py:120-141)."""
    with pytest.raises(ValueError, match="Deviance Score is not defined for power=0.5."):
        TweedieDevianceScore(power=0.5)

    metric = TweedieDevianceScore(power=1)
    with pytest.raises(ValueError, match="strictly positive"):
        metric(jnp.asarray([-1.0, 2.0, 3.0]), jnp.asarray([1.0, 2.0, 3.0]))
    with pytest.raises(ValueError, match="cannot be negative"):
        metric(jnp.asarray([1.0, 2.0, 3.0]), jnp.asarray([-1.0, 2.0, 3.0]))

    metric = TweedieDevianceScore(power=2)
    with pytest.raises(ValueError, match="strictly positive"):
        metric(jnp.asarray([-1.0, 2.0, 3.0]), jnp.asarray([1.0, 2.0, 3.0]))
    with pytest.raises(ValueError, match="strictly positive"):
        metric(jnp.asarray([1.0, 2.0, 3.0]), jnp.asarray([-1.0, 2.0, 3.0]))
