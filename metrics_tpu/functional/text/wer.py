"""Word error rate — analogue of reference
``torchmetrics/functional/text/wer.py:22-114``.

String preprocessing stays on host (SURVEY §7: clean host/device split for
string-carrying metrics); the edit-distance DP is vectorized with numpy —
tokens are interned to int ids and each DP row is computed with a prefix-min
scan instead of the reference's O(m·n) pure-Python double loop — and only the
two scalar counters live on device.
"""
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array


def _edit_distance(prediction_tokens: List[str], reference_tokens: List[str]) -> int:
    """Levenshtein distance between token sequences (vectorized rows).

    Row recurrence: ``cur[j] = min(prev[j]+1, prev[j-1]+sub_j, cur[j-1]+1)``.
    The last term is a running prefix-min: ``cur = accmin(cand - j) + j`` with
    ``cand`` the elementwise min of the first two — one numpy scan per row.
    """
    if not prediction_tokens:
        return len(reference_tokens)
    if not reference_tokens:
        return len(prediction_tokens)
    vocab = {t: i for i, t in enumerate(dict.fromkeys(prediction_tokens + reference_tokens))}
    a = np.asarray([vocab[t] for t in prediction_tokens])
    b = np.asarray([vocab[t] for t in reference_tokens])
    n = b.size
    idx = np.arange(n + 1)
    prev = idx.copy()
    for i in range(1, a.size + 1):
        cand = np.empty(n + 1, dtype=np.int64)
        cand[0] = i
        cand[1:] = np.minimum(prev[1:] + 1, prev[:-1] + (b != a[i - 1]))
        prev = np.minimum.accumulate(cand - idx) + idx
    return int(prev[-1])


def _wer_update(
    predictions: Union[str, List[str]], references: Union[str, List[str]]
) -> Tuple[Array, Array]:
    """Per-batch statistics: (summed edit operations, total reference words)."""
    if isinstance(predictions, str):
        predictions = [predictions]
    if isinstance(references, str):
        references = [references]
    if len(predictions) != len(references):
        raise ValueError(
            f"Number of predictions ({len(predictions)}) and references "
            f"({len(references)}) must be the same"
        )
    errors = 0
    total = 0
    for prediction, reference in zip(predictions, references):
        prediction_tokens = prediction.split()
        reference_tokens = reference.split()
        errors += _edit_distance(prediction_tokens, reference_tokens)
        total += len(reference_tokens)
    return jnp.asarray(errors, dtype=jnp.float32), jnp.asarray(total, dtype=jnp.float32)


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def wer(
    predictions: Union[str, List[str]],
    references: Union[str, List[str]],
    concatenate_texts: Optional[bool] = None,  # deprecated (reference v0.6); remove in v0.7
) -> Array:
    """Word error rate: ``(S + D + I) / N`` over all reference words.

    Args:
        predictions: transcription(s) to score.
        references: reference(s) for each input.
        concatenate_texts: deprecated no-op, mirroring the reference
            (`functional/text/wer.py:90-112`) — the counter accumulation is
            equivalent either way; only the deprecation warning remains.

    Example:
        >>> predictions = ["this is the prediction", "there is an other sample"]
        >>> references = ["this is the reference", "there is another one"]
        >>> float(wer(predictions=predictions, references=references))
        0.5
    """
    if concatenate_texts is not None:
        import warnings

        warnings.warn(
            "`concatenate_texts` has been deprecated in v0.6 and it will be removed in v0.7",
            DeprecationWarning,
        )
    errors, total = _wer_update(predictions, references)
    return _wer_compute(errors, total)
