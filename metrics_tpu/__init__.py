"""metrics_tpu — a TPU-native metrics framework.

Stateful, batch-accumulating, distributed-synchronizing metric computation for
JAX: the capabilities of TorchMetrics (reference at ``/root/reference``),
re-designed around pytree states, jit-fused update+sync+compute steps, and
XLA collectives over device meshes.
"""
import logging

_logger = logging.getLogger("metrics_tpu")
_logger.addHandler(logging.StreamHandler())
_logger.setLevel(logging.INFO)

__version__ = "0.1.0"

from metrics_tpu.core.average import AverageMeter
from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import CompositionalMetric, Metric
from metrics_tpu.classification import (
    AUC,
    AUROC,
    AveragePrecision,
    CalibrationError,
    Hinge,
    KLDivergence,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    PrecisionRecallCurve,
    ROC,
    F1,
    Accuracy,
    CohenKappa,
    ConfusionMatrix,
    FBeta,
    HammingDistance,
    IoU,
    MatthewsCorrcoef,
    Precision,
    Recall,
    Specificity,
    StatScores,
)
from metrics_tpu.regression import (
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrcoef,
    R2Score,
    SpearmanCorrcoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from metrics_tpu.image import FID, IS, KID, LPIPS, PSNR, SSIM
from metrics_tpu.retrieval import (
    RetrievalCollection,
    RetrievalFallOut,
    RetrievalMAP,
    RetrievalMetric,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
)
from metrics_tpu.audio import PIT, SI_SDR, SI_SNR, SNR
from metrics_tpu.core.checkpoint import (
    load_checkpoint,
    prune_checkpoints,
    save_checkpoint,
)
from metrics_tpu.text import BERTScore, BLEUScore, ROUGEScore, WER
from metrics_tpu.wrappers import BootStrapper, MetricTracker

__all__ = [
    "CatBuffer",
    "load_checkpoint",
    "prune_checkpoints",
    "save_checkpoint",
    "BERTScore",
    "BLEUScore",
    "ROUGEScore",
    "WER",
    "PIT",
    "SI_SDR",
    "SI_SNR",
    "SNR",
    "AUC",
    "AUROC",
    "Accuracy",
    "AverageMeter",
    "BootStrapper",
    "MetricTracker",
    "AveragePrecision",
    "CalibrationError",
    "Hinge",
    "KLDivergence",
    "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve",
    "BinnedRecallAtFixedPrecision",
    "PrecisionRecallCurve",
    "ROC",
    "CohenKappa",
    "CosineSimilarity",
    "ExplainedVariance",
    "MeanAbsoluteError",
    "MeanAbsolutePercentageError",
    "MeanSquaredError",
    "MeanSquaredLogError",
    "PearsonCorrcoef",
    "R2Score",
    "RetrievalCollection",
    "RetrievalFallOut",
    "RetrievalMAP",
    "RetrievalMetric",
    "RetrievalMRR",
    "RetrievalNormalizedDCG",
    "RetrievalPrecision",
    "RetrievalRecall",
    "SpearmanCorrcoef",
    "SymmetricMeanAbsolutePercentageError",
    "TweedieDevianceScore",
    "CompositionalMetric",
    "ConfusionMatrix",
    "FID",
    "IS",
    "KID",
    "LPIPS",
    "PSNR",
    "SSIM",
    "F1",
    "FBeta",
    "HammingDistance",
    "IoU",
    "MatthewsCorrcoef",
    "Metric",
    "MetricCollection",
    "Precision",
    "Recall",
    "Specificity",
    "StatScores",
]
