"""SSIM module — analogue of reference ``torchmetrics/image/ssim.py`` (105 LoC).

TPU-first redesign of the state: the reference keeps ALL preds/targets in
cat-list buffers (``ssim.py:79-80``) because ``data_range=None`` needs the
global min/max before any window statistic can be taken. Here, when
``data_range`` IS given (the common, recommended case) the per-pixel SSIM map
is reduced **per batch** into two scalar sum states — constant memory,
psum-able, and the whole update jit-fuses. Only the ``data_range=None`` path
falls back to the reference's buffer-everything design.
"""
from typing import Any, Callable, Optional, Sequence

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.image.ssim import _ssim_compute, _ssim_map, _ssim_update
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn


class SSIM(Metric):
    r"""Structural Similarity Index Measure, accumulated over batches.

    Args:
        kernel_size: gaussian window size (h, w).
        sigma: gaussian window std (h, w).
        reduction: 'elementwise_mean' | 'sum' | 'none'.
        data_range: value range; if ``None`` it is inferred from the data at
            compute time (forces full input buffering, see module docstring).
        k1 / k2: SSIM stability constants.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SSIM
        >>> preds = jnp.ones((1, 1, 16, 16)) * 0.5
        >>> target = jnp.ones((1, 1, 16, 16)) * 0.5
        >>> ssim = SSIM(data_range=1.0)
        >>> print(round(float(ssim(preds, target)), 4))
        1.0
    """

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: str = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.reduction = reduction
        # constant-memory streaming is possible iff the SSIM map of each batch
        # is independent of other batches (fixed data_range) and the final
        # reduction distributes over batches.
        self._streaming = data_range is not None and reduction in ("elementwise_mean", "sum")
        if self._streaming:
            self.add_state("similarity_sum", jnp.zeros(()), dist_reduce_fx="sum")
            # pixel counts overflow int32 on large datasets; float32 accumulates safely
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            rank_zero_warn(
                "Metric `SSIM` will save all targets and predictions in buffer"
                " (data_range=None or reduction='none'). For large datasets this"
                " may lead to large memory footprint."
            )
            self.add_state("preds", [], dist_reduce_fx="cat")
            self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target = _ssim_update(preds, target)
        if self._streaming:
            sim = _ssim_map(
                preds, target, self.kernel_size, self.sigma, self.data_range, self.k1, self.k2
            )
            self.similarity_sum = self.similarity_sum + sim.sum()
            self.total = self.total + sim.size
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Array:
        if self._streaming:
            if self.reduction == "sum":
                return self.similarity_sum
            return self.similarity_sum / self.total
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _ssim_compute(
            preds, target, self.kernel_size, self.sigma, self.reduction, self.data_range, self.k1, self.k2
        )
