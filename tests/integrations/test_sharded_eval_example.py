"""The sharded-eval example (docs/distributed.md companion) must run and
match the single-device reference — it is the acceptance demo for the
distributed story."""
import os
import subprocess
import sys
from pathlib import Path


def test_example_runs_and_matches():
    repo = Path(__file__).resolve().parents[2]
    r = subprocess.run(
        [sys.executable, str(repo / "examples" / "sharded_eval.py")],
        capture_output=True,
        text=True,
        timeout=420,
        env={**os.environ,
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
             "PYTHONPATH": str(repo) + os.pathsep + os.environ.get("PYTHONPATH", "")},
    )
    assert r.returncode == 0, r.stderr[-1500:]
    assert "matches single-device reference" in r.stdout
