"""metricslint fixture: asymmetric-schedule-decision violations — execution-plan
invalidations that would legally desynchronize the fleet one plan generation
at a time.

A ``plan_invalidate`` bumps the owner's binding generation, which retraces
fused programs and re-keys the bucketed sync layout: a rank that invalidates
while its peers do not soon dispatches a differently-shaped collective
schedule. The CI gate asserts the CLI exits NONZERO on this file. The call
names mirror ``core/plan.py``'s conventions (that is what the schedule pass
keys on); the stubs keep the module import-safe.
"""
import jax


def plan_invalidate(owner, reason="state-mutated", schema_changed=False, groups_stale=False):
    return None  # stand-in


def channel_is_suspect():  # stand-in per-process latch
    return False


def rank_dependent_invalidation(owner):
    """finding: asymmetric-schedule-decision — only rank 0 drops its plan, so
    rank 0 retraces and re-buckets while its peers keep the old layout."""
    if jax.process_index() == 0:
        plan_invalidate(owner, "rank0-refresh", schema_changed=True)


def data_dependent_invalidation(owner, state):
    """finding: asymmetric-schedule-decision — ranks whose local state grew
    large invalidate their plan while their peers keep the cached one."""
    if len(state) > 1000:
        plan_invalidate(owner, "big-state", groups_stale=True)


def data_derived_reason(owner, value):
    """finding: asymmetric-schedule-decision — the committed reason string is
    computed from per-rank data, so rank telemetries (and any policy keyed on
    the reason) diverge with the data."""
    plan_invalidate(owner, f"threshold-{int(value > 0.5)}")


def latch_governed_invalidation(owner):
    """finding: asymmetric-schedule-decision — the per-process suspect latch
    differs across ranks; an invalidation gated on it diverges with it."""
    if channel_is_suspect():
        plan_invalidate(owner, "suspect-channel", groups_stale=True)


def clean_symmetric_invalidation(owner, world):
    """No findings: the invalidation derives from symmetric inputs (world
    size is a collective-round fact every rank observes identically)."""
    if world > 1:
        plan_invalidate(owner, "membership-changed", schema_changed=True)
