"""MetricCollection tests — analogue of reference `tests/bases/test_collections.py`."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, MetricCollection, Precision, Recall
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum


def test_from_list_and_naming():
    mc = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    assert set(mc.keys()) == {"DummyMetricSum", "DummyMetricDiff"}


def test_duplicate_names_raise():
    with pytest.raises(ValueError, match="Encountered two metrics both named"):
        MetricCollection([DummyMetricSum(), DummyMetricSum()])


def test_from_dict_and_kwarg_filtering():
    mc = MetricCollection({"sum": DummyMetricSum(), "diff": DummyMetricDiff()})
    out = mc(x=jnp.asarray(5.0), y=jnp.asarray(2.0))
    np.testing.assert_allclose(np.asarray(out["sum"]), 5.0)
    np.testing.assert_allclose(np.asarray(out["diff"]), -2.0)


def test_prefix_postfix():
    mc = MetricCollection([DummyMetricSum()], prefix="pre_", postfix="_post")
    out = mc(x=jnp.asarray(1.0))
    assert list(out.keys()) == ["pre_DummyMetricSum_post"]
    mc2 = mc.clone(prefix="new_")
    out2 = mc2(x=jnp.asarray(1.0))
    assert list(out2.keys()) == ["new_DummyMetricSum_post"]


def test_update_compute_reset():
    mc = MetricCollection({"sum": DummyMetricSum(), "diff": DummyMetricDiff()})
    mc.update(x=jnp.asarray(2.0), y=jnp.asarray(3.0))
    mc.update(x=jnp.asarray(1.0), y=jnp.asarray(1.0))
    out = mc.compute()
    np.testing.assert_allclose(np.asarray(out["sum"]), 3.0)
    np.testing.assert_allclose(np.asarray(out["diff"]), -4.0)
    mc.reset()
    np.testing.assert_allclose(np.asarray(mc["sum"].x), 0.0)


def test_invalid_inputs():
    with pytest.raises(ValueError):
        MetricCollection([DummyMetricSum(), "not-a-metric"])
    with pytest.raises(ValueError):
        MetricCollection("bogus")


def test_real_metrics_shared_update():
    import numpy as np

    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(64, 5).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 5, (64,)))
    mc = MetricCollection(
        {
            "acc": Accuracy(num_classes=5),
            "prec_macro": Precision(num_classes=5, average="macro"),
            "rec_macro": Recall(num_classes=5, average="macro"),
        }
    )
    out = mc(preds, target)
    assert set(out.keys()) == {"acc", "prec_macro", "rec_macro"}


def test_fused_pure_forward():
    """One jitted program for the whole collection."""
    import jax

    mc = MetricCollection({"sum": DummyMetricSum(), "diff": DummyMetricDiff()})
    state = mc.init_state()
    fused = jax.jit(lambda s, x, y: mc.pure_forward(s, x=x, y=y))
    state, vals = fused(state, jnp.asarray(2.0), jnp.asarray(1.0))
    state, vals = fused(state, jnp.asarray(3.0), jnp.asarray(1.0))
    np.testing.assert_allclose(np.asarray(vals["sum"]), 3.0)
    final = mc.pure_compute(state)
    np.testing.assert_allclose(np.asarray(final["sum"]), 5.0)
    np.testing.assert_allclose(np.asarray(final["diff"]), -2.0)


def test_state_dict_roundtrip():
    mc = MetricCollection({"sum": DummyMetricSum()})
    mc["sum"].persistent(True)
    mc.update(x=jnp.asarray(4.0))
    sd = mc.state_dict()
    mc2 = MetricCollection({"sum": DummyMetricSum()})
    mc2.load_state_dict(sd)
    np.testing.assert_allclose(np.asarray(mc2.compute()["sum"]), 4.0)
