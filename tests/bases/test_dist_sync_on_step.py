"""Per-step DDP sync semantics (``dist_sync_on_step=True``).

Mirror of the reference's per-step assertion (``tests/helpers/testers.py:
172-181``): a rank's ``forward`` at step *s* must return the metric computed
over the concatenation of ALL ranks' step-*s* batches, while accumulation
stays local. Ranks are simulated with injected ``dist_sync_fn`` gathers —
the same seam Lightning uses (reference ``metric.py:78``).
"""
import numpy as np
import pytest
from sklearn.metrics import accuracy_score, mean_squared_error, roc_auc_score

from metrics_tpu import AUROC, Accuracy, ConfusionMatrix, MeanSquaredError

from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester, THRESHOLD

rng = np.random.RandomState(44)


class TestDistSyncOnStepAccuracy(MetricTester):
    def test_accuracy_per_step_sync(self):
        preds = rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
        target = rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
        self.run_class_metric_test(
            ddp=True,
            preds=preds,
            target=target,
            metric_class=Accuracy,
            sk_metric=lambda p, t: accuracy_score(t, (p >= THRESHOLD).astype(int)),
            dist_sync_on_step=True,
        )


class TestDistSyncOnStepMSE(MetricTester):
    atol = 1e-6

    def test_mse_per_step_sync(self):
        preds = rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
        target = rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
        self.run_class_metric_test(
            ddp=True,
            preds=preds,
            target=target,
            metric_class=MeanSquaredError,
            sk_metric=mean_squared_error,
            dist_sync_on_step=True,
        )


class TestDistSyncOnStepAUROC(MetricTester):
    atol = 1e-6

    def test_auroc_cat_state_per_step_sync(self):
        """Cat-list states gather in rank order before the per-step compute."""
        preds = rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
        target = rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
        target[:, 0] = 0  # both classes present in every gathered group
        target[:, 1] = 1
        self.run_class_metric_test(
            ddp=True,
            preds=preds,
            target=target,
            metric_class=AUROC,
            sk_metric=lambda p, t: roc_auc_score(t, p),
            dist_sync_on_step=True,
        )


class TestDistSyncOnStepConfusionMatrix(MetricTester):
    def test_confmat_per_step_sync(self):
        from sklearn.metrics import confusion_matrix

        preds = rng.randint(0, 3, (NUM_BATCHES, BATCH_SIZE))
        target = rng.randint(0, 3, (NUM_BATCHES, BATCH_SIZE))
        self.run_class_metric_test(
            ddp=True,
            preds=preds,
            target=target,
            metric_class=ConfusionMatrix,
            sk_metric=lambda p, t: confusion_matrix(t, p, labels=[0, 1, 2]),
            dist_sync_on_step=True,
            metric_args={"num_classes": 3},
        )


def test_gather_states_handles_catbuffer():
    """_gather_states must concatenate fixed-capacity CatBuffer states in
    rank order into one buffer, not return a python list of buffers."""
    import jax.numpy as jnp

    from metrics_tpu.core.cat_buffer import CatBuffer
    from tests.helpers.testers import _gather_states

    a = CatBuffer(8).append(jnp.asarray([1.0, 2.0]))
    b = CatBuffer(8).append(jnp.asarray([3.0, 4.0, 5.0]))
    out = _gather_states([{"x": a}, {"x": b}], {"x": None})
    assert isinstance(out["x"], CatBuffer)
    np.testing.assert_array_equal(np.asarray(out["x"].values()), [1.0, 2.0, 3.0, 4.0, 5.0])


def test_forward_accumulation_stays_local():
    """dist_sync_on_step syncs only the per-step value: after the loop, each
    rank's accumulated state covers just its own batches."""
    preds = rng.rand(4, BATCH_SIZE).astype(np.float32)
    target = rng.randint(0, 2, (4, BATCH_SIZE))
    import jax.numpy as jnp

    from tests.helpers.testers import _gather_states

    m0 = Accuracy(dist_sync_on_step=True)
    m1 = Accuracy(dist_sync_on_step=True)
    for i in range(0, 4, 2):
        scratch = Accuracy()
        scratch.update(jnp.asarray(preds[i + 1]), jnp.asarray(target[i + 1]))
        other_state = dict(scratch._state)

        def gather(state, reductions):
            return _gather_states([state, other_state], reductions)

        m0.dist_sync_fn = gather
        m0.distributed_available_fn = lambda: True
        m0(jnp.asarray(preds[i]), jnp.asarray(target[i]))
        m1.update(jnp.asarray(preds[i + 1]), jnp.asarray(target[i + 1]))
    m0.dist_sync_fn = None
    m0.distributed_available_fn = lambda: False
    # rank 0 accumulated ONLY batches 0 and 2
    own = np.concatenate([preds[0], preds[2]]), np.concatenate([target[0], target[2]])
    exp = accuracy_score(own[1], (own[0] >= THRESHOLD).astype(int))
    np.testing.assert_allclose(float(m0.compute()), exp, atol=1e-6)
    # the non-syncing rank's accumulation stayed local too (batches 1 and 3)
    own1 = np.concatenate([preds[1], preds[3]]), np.concatenate([target[1], target[3]])
    exp1 = accuracy_score(own1[1], (own1[0] >= THRESHOLD).astype(int))
    np.testing.assert_allclose(float(m1.compute()), exp1, atol=1e-6)
