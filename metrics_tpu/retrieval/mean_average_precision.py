"""RetrievalMAP — analogue of reference
``torchmetrics/retrieval/mean_average_precision.py``."""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.segment import GroupedByQuery, segment_cumsum, segment_sum
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries (vectorized over all groups)."""

    def _segment_metric(self, g: GroupedByQuery) -> Array:
        rel = (g.target > 0).astype(jnp.float32)
        cum_rel = segment_cumsum(rel, g)
        contrib = jnp.where(rel > 0, cum_rel / g.rank, 0.0)
        npos = segment_sum(rel, g)
        return segment_sum(contrib, g) / jnp.maximum(npos, 1.0)
