"""LPIPS module — analogue of reference
``torchmetrics/image/lpip_similarity.py`` (159 LoC), with the perceptual
network as an in-framework XLA graph (:mod:`metrics_tpu.models.lpips_net`)
instead of a wrapped third-party torch package."""
from typing import Any, Callable, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.models.lpips_net import LPIPSNetwork


def _valid_img(img: Array) -> bool:
    """[N, 3, H, W] with values in [-1, 1] (reference ``lpip_similarity.py:36-38``).

    Range check is a single device-side reduction (one scalar transfer), not a
    host copy of the batch.
    """
    shape_ok = img.ndim == 4 and img.shape[1] == 3
    if not shape_ok:
        return False
    return bool(jnp.all(jnp.abs(img) <= 1.0))


class LPIPS(Metric):
    r"""Learned Perceptual Image Patch Similarity, accumulated over batches.

    Args:
        net_type: 'alex' | 'vgg' feature tower.
        reduction: 'mean' | 'sum' over all scored pairs.
        net: optional custom callable ``(img0, img1) -> [N] distances``
            (replaces the built-in tower, e.g. one with loaded weights).

    Example:
        >>> import numpy as np, jax.numpy as jnp
        >>> from metrics_tpu import LPIPS
        >>> rng = np.random.RandomState(0)
        >>> def dist_fn(x, y):                       # custom perceptual distance
        ...     return jnp.mean((x - y) ** 2, axis=(1, 2, 3))
        >>> lpips = LPIPS(net=dist_fn)
        >>> a = jnp.asarray(rng.rand(2, 3, 8, 8).astype(np.float32)) * 2 - 1
        >>> b = jnp.asarray(rng.rand(2, 3, 8, 8).astype(np.float32)) * 2 - 1
        >>> print(round(float(lpips(a, b)), 4))
        0.6495
    """

    is_differentiable = True

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        net: Optional[Union[LPIPSNetwork, Callable]] = None,
        weights: Optional[Tuple[Any, Any]] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.net = net if net is not None else LPIPSNetwork(net=net_type, weights=weights)
        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction
        self.add_state("sum_scores", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:  # type: ignore[override]
        if not (_valid_img(img1) and _valid_img(img2)):
            raise ValueError(
                "Expected both input arguments to be normalized tensors (all values in range [-1,1])"
                f" and to have shape [N, 3, H, W] but `img1` have shape {img1.shape}"
                f" and `img2` have shape {img2.shape}"
            )
        loss = self.net(img1, img2)
        self.sum_scores = self.sum_scores + loss.sum()
        self.total = self.total + img1.shape[0]

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
