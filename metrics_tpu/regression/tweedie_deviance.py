"""TweedieDevianceScore module — analogue of reference
``torchmetrics/regression/tweedie_deviance.py`` (119 LoC)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.tweedie_deviance import (
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)


class TweedieDevianceScore(Metric):
    r"""Mean Tweedie deviance — the deviance family that interpolates the
    classic GLM losses through one ``power`` parameter:

    - ``power = 0``: squared error (normal)
    - ``power = 1``: Poisson deviance (counts)
    - ``power = 2``: Gamma deviance (strictly positive, multiplicative)
    - other values: compound Poisson–Gamma / stable families

    Accumulates a deviance-sum and count ("sum" leaves). Input-domain
    rules follow the power (e.g. ``power=1`` needs strictly positive
    preds and non-negative targets, ``power=2`` strictly positive both);
    violations raise eagerly, and ``0 < power < 1`` is undefined.

    Args:
        power: the family selector above.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TweedieDevianceScore
        >>> preds = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        >>> target = jnp.asarray([1.5, 2.5, 3.5, 4.5])
        >>> deviance = TweedieDevianceScore(power=1.0)
        >>> print(round(float(deviance(preds, target)), 4))
        0.1178
    """

    is_differentiable = True

    def __init__(
        self,
        power: float = 0.0,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("num_observations", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, targets: Array) -> None:  # type: ignore[override]
        sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, self.power)
        self.sum_deviance_score = self.sum_deviance_score + sum_deviance_score
        self.num_observations = self.num_observations + num_observations

    def compute(self) -> Array:
        return _tweedie_deviance_score_compute(self.sum_deviance_score, self.num_observations)
