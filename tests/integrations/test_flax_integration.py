"""Flax-integration tests — the analogue of the reference's Lightning suite
(``integrations/test_lightning.py``): custom metrics inside a real flax/optax
training loop, Lightning-style deferred logging with epoch-end auto-reset,
metric state checkpointed with the train state, and the data-parallel path."""
from functools import partial
from typing import Any

import flax.linen as nn
import flax.serialization
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu import Accuracy, AveragePrecision, Metric, MetricCollection
from metrics_tpu.integrations import MetricLogger, MetricTrainState
from metrics_tpu.utils.exceptions import MetricsTPUUserError


class SumMetric(Metric):
    """Reference ``integrations/test_lightning.py:27-36``."""

    def __init__(self):
        super().__init__()
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class DiffMetric(Metric):
    """Reference ``integrations/test_lightning.py:39-48``."""

    def __init__(self):
        super().__init__()
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, x):
        self.x = self.x - x

    def compute(self):
        return self.x


class BoringModel(nn.Module):
    """The reference suite's minimal trainable module (`boring_model.py`)."""

    features: int = 1

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.features)(x)


def _make_state(metrics, features_in=32, features_out=1, seed=0, **kwargs):
    model = BoringModel(features=features_out)
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, features_in)))
    return MetricTrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1), metrics=metrics, **kwargs
    )


def test_metric_in_train_state():
    """Analogue of reference ``test_metric_lightning``: a SumMetric updated
    inside the jitted train step equals the python-side accumulation, and
    reset_metrics isolates epochs."""
    state = _make_state(MetricCollection({"sum": SumMetric(), "diff": DiffMetric()}))

    @jax.jit
    def train_step(state, x, y):
        def loss_fn(p):
            out = state.apply_fn(p, x)
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        state = state.apply_gradients(grads=grads)
        return state.update_metrics(x.sum()), loss

    rng = np.random.RandomState(0)
    for _epoch in range(2):
        expected = 0.0
        losses = []
        for _ in range(3):
            x = jnp.asarray(rng.randn(4, 32).astype(np.float32))
            y = jnp.zeros((4, 1), jnp.float32)
            state, loss = train_step(state, x, y)
            expected += float(x.sum())
            losses.append(float(loss))
        values = state.compute_metrics()
        np.testing.assert_allclose(float(values["sum"]), expected, rtol=1e-5)
        np.testing.assert_allclose(float(values["diff"]), -expected, rtol=1e-5)
        state = state.reset_metrics()
    # the model actually trained (loss decreased over the run)
    assert losses[-1] < losses[0] * 1.5  # noqa: loose — sgd on random targets


def test_single_metric_promoted_to_collection():
    state = _make_state(SumMetric())
    state = state.update_metrics(jnp.asarray(3.0))
    assert float(state.compute_metrics()["summetric"]) == 3.0
    with pytest.raises(MetricsTPUUserError):
        _make_state(metrics="not-a-metric")


def test_forward_metrics_batch_values():
    """``forward_metrics`` returns the batch-local value while accumulating —
    the analogue of Lightning's ``on_step=True`` logging."""
    state = _make_state(MetricCollection({"sum": SumMetric()}))
    state, step1 = state.forward_metrics(jnp.asarray(2.0))
    state, step2 = state.forward_metrics(jnp.asarray(5.0))
    assert float(step1["sum"]) == 2.0
    assert float(step2["sum"]) == 5.0
    assert float(state.compute_metrics()["sum"]) == 7.0


def test_metrics_reset_at_epoch_end_only():
    """Analogue of reference ``test_metrics_reset`` (test_lightning.py:86-202):
    metrics logged through the logger reset exactly once per epoch end and
    never mid-epoch, across train/val/test stages."""
    resets = {}
    metrics = {}
    for stage in ("train", "val", "test"):
        acc = Accuracy()
        ap = AveragePrecision(pos_label=1)
        for name, m in ((f"acc_{stage}", acc), (f"ap_{stage}", ap)):
            resets[name] = 0
            orig, nm = m.reset, name

            def counted(orig=orig, nm=nm):
                resets[nm] += 1
                return orig()

            m.reset = counted
            metrics[name] = m

    logger = MetricLogger()
    rng = np.random.RandomState(3)

    def run_stage(stage):
        acc, ap = metrics[f"acc_{stage}"], metrics[f"ap_{stage}"]
        for _ in range(2):
            probs = jnp.asarray(rng.rand(8).astype(np.float32))
            labels = jnp.asarray(rng.randint(0, 2, (8,)))
            acc(probs, labels)
            ap(probs, labels)
            logger.log(f"{stage}/accuracy", acc)
            logger.log(f"{stage}/ap", ap)
            # mid-epoch: nothing reset
            assert resets[f"acc_{stage}"] == 0 and resets[f"ap_{stage}"] == 0
        out = logger.epoch_end()
        assert resets[f"acc_{stage}"] == 1 and resets[f"ap_{stage}"] == 1
        assert 0.0 <= float(out[f"{stage}/accuracy"]) <= 1.0
        resets[f"acc_{stage}"] = resets[f"ap_{stage}"] = 0

    for stage in ("train", "val", "test"):
        run_stage(stage)
    run_stage("val")  # trainer.validate()
    run_stage("test")  # trainer.test()


def test_epoch_end_exception_leaves_state_retryable():
    """A compute() failure mid-epoch_end must not consume any epoch state
    (ADVICE r2: earlier metrics were reset before the raise, so a retry
    double-counted plain values and recomputed reset metrics as empty)."""

    class BoomMetric(SumMetric):
        fail = True

        def compute(self):
            if self.fail:
                raise RuntimeError("boom")
            return super().compute()

    logger = MetricLogger()
    good = SumMetric()
    good.update(jnp.asarray(4.0))
    bad = BoomMetric()
    bad.update(jnp.asarray(7.0))
    logger.log("good", good)  # computed before 'boom' in dict order
    logger.log("boom", bad)
    logger.log("loss", 1.0)
    with pytest.raises(RuntimeError, match="boom"):
        logger.epoch_end()
    # nothing was reset or cleared: the retry sees the full epoch
    bad.fail = False
    out = logger.epoch_end()
    assert float(out["good"]) == 4.0
    assert float(out["boom"]) == 7.0
    assert out["loss"] == 1.0
    assert float(good.x) == 0.0  # reset happened after success


def test_logger_plain_values_and_conflicts():
    logger = MetricLogger()
    logger.log("loss", 1.0)
    logger.log("loss", 3.0)
    m = SumMetric()
    m.update(jnp.asarray(4.0))
    logger.log("sum", m)
    with pytest.raises(MetricsTPUUserError):
        logger.log("sum", SumMetric())  # different object under same name
    out = logger.epoch_end()
    assert out["loss"] == 2.0  # mean over the epoch
    assert float(out["sum"]) == 4.0
    assert logger.history == [out]
    # collections expand into name/key entries
    mc = MetricCollection({"acc": Accuracy(num_classes=2)})
    mc.update(jnp.asarray([[0.9, 0.1], [0.2, 0.8]]), jnp.asarray([0, 1]))
    logger.log("train", mc)
    out2 = logger.epoch_end()
    assert float(out2["train/acc"]) == 1.0


def test_metric_state_checkpoints_with_train_state():
    """Metric accumulators serialize/restore atomically with params/opt-state —
    the analogue of metric states inside ``nn.Module.state_dict``."""
    state = _make_state(MetricCollection({"acc": Accuracy(num_classes=3)}))
    preds = jnp.asarray(np.eye(3)[[0, 1, 2, 0]].astype(np.float32))
    target = jnp.asarray([0, 1, 2, 1])
    state = state.update_metrics(preds, target)

    blob = flax.serialization.to_bytes(state)
    fresh = _make_state(MetricCollection({"acc": Accuracy(num_classes=3)}))
    restored = flax.serialization.from_bytes(fresh, blob)
    np.testing.assert_allclose(
        float(restored.compute_metrics()["acc"]), float(state.compute_metrics()["acc"])
    )
    # restored state keeps accumulating correctly
    restored = restored.update_metrics(preds, jnp.asarray([0, 1, 2, 0]))
    assert float(restored.compute_metrics()["acc"]) == pytest.approx(7 / 8)


def test_data_parallel_train_step():
    """DP analogue of the reference's DDP Lightning run: per-device metric
    update inside shard_map, collective sync at epoch end, one XLA program."""
    n = 8
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    mc = MetricCollection({"acc": Accuracy(num_classes=4)})
    summ = SumMetric()
    state = _make_state(mc, features_in=4, features_out=4)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(n * 4, 4).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 4, (n * 4,)))

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def epoch(metric_states, sum_state, xs, ys):
        def loss_fn(p):
            logits = state.apply_fn(p, xs)
            return optax.softmax_cross_entropy_with_integer_labels(logits, ys).mean()

        jax.grad(loss_fn)(state.params)  # the model step traces alongside
        logits = state.apply_fn(state.params, xs)
        ms = mc.pure_update(metric_states, jax.nn.softmax(logits), ys)
        ss = summ.pure_update(sum_state, xs.sum())
        return mc.pure_sync(ms, "dp"), summ.pure_sync(ss, "dp")

    with mesh:
        synced, sum_synced = jax.jit(epoch)(
            state.metric_states,
            summ.init_state(),
            jax.device_put(x, NamedSharding(mesh, P("dp"))),
            jax.device_put(y, NamedSharding(mesh, P("dp"))),
        )
    state = state.replace(metric_states=synced)
    values = state.compute_metrics()

    # global-batch reference
    logits = state.apply_fn(state.params, x)
    expected_acc = float((jnp.argmax(logits, -1) == y).mean())
    np.testing.assert_allclose(float(values["acc"]), expected_acc, rtol=1e-6)
    np.testing.assert_allclose(float(summ.pure_compute(sum_synced)), float(x.sum()), rtol=1e-5)


def test_distinct_metric_configs_do_not_share_jit_cache():
    """Metric.__hash__/__eq__ can't key the jit cache (operator-overload
    parity), so the static collection is identity-keyed: two differently
    configured metrics with identical state shapes must NOT reuse one trace."""
    s_lo = _make_state(MetricCollection({"acc": Accuracy(threshold=0.5)}))
    s_hi = _make_state(MetricCollection({"acc": Accuracy(threshold=0.9)}))

    @jax.jit
    def step(state, p, t):
        return state.update_metrics(p, t)

    probs = jnp.asarray([0.6, 0.7, 0.8, 0.2])
    labels = jnp.asarray([1, 1, 1, 0])
    lo = float(step(s_lo, probs, labels).compute_metrics()["acc"])
    hi = float(step(s_hi, probs, labels).compute_metrics()["acc"])
    assert lo == 1.0   # all three positives clear 0.5
    assert hi == 0.25  # only the negative is classified correctly at 0.9


def test_logger_name_collisions_between_kinds_raise():
    logger = MetricLogger()
    m = SumMetric()
    m.update(jnp.asarray(1.0))
    logger.log("a", m)
    with pytest.raises(MetricsTPUUserError, match="metric object was already logged"):
        logger.log("a", 0.5)
    logger.log("b", 0.5)
    with pytest.raises(MetricsTPUUserError, match="plain values were already logged"):
        logger.log("b", m)
    # collection expansion colliding with a plain value is loud, not silent
    mc = MetricCollection({"acc": Accuracy(num_classes=2)})
    mc.update(jnp.asarray([[0.9, 0.1]]), jnp.asarray([0]))
    logger2 = MetricLogger()
    logger2.log("train", mc)
    logger2.log("train/acc", 0.0)
    with pytest.raises(MetricsTPUUserError, match="collide"):
        logger2.epoch_end()
