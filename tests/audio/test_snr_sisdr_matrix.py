"""SNR / SI-SDR per-step sync, sharded-mesh, and bf16 axes.

Extends `tests/audio/test_audio.py` (which already covers class ddp ×
zero_mean and per-sample functional parity, using the shared numpy oracles
imported here) with the axes the reference's `tests/audio/test_si_sdr.py`
exercises and that file does not: dist_sync_on_step, real shard_map
collectives, and bfloat16.
"""
from collections import namedtuple
from functools import partial

import numpy as np
import pytest

from metrics_tpu import SI_SDR, SNR
from tests.audio.test_audio import _np_si_sdr, _np_snr
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

TIME = 100
rng = np.random.RandomState(2020)

Input = namedtuple("Input", ["preds", "target"])
inputs = Input(
    preds=rng.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32),
    target=rng.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32),
)


def _avg_oracle(fn, zero_mean):
    return lambda p, t: fn(p, t, zero_mean=zero_mean).mean()


@pytest.mark.parametrize("zero_mean", [True, False])
@pytest.mark.parametrize(
    "metric_class, oracle",
    [(SNR, _np_snr), (SI_SDR, _np_si_sdr)],
    ids=["snr", "si_sdr"],
)
class TestSNRFamilyDistAxes(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [True, False])
    def test_per_step_sync(self, metric_class, oracle, zero_mean, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=inputs.preds,
            target=inputs.target,
            metric_class=metric_class,
            sk_metric=_avg_oracle(oracle, zero_mean),
            dist_sync_on_step=True,
            metric_args={"zero_mean": zero_mean},
        )

    def test_sharded(self, metric_class, oracle, zero_mean):
        self.run_sharded_metric_test(
            preds=inputs.preds,
            target=inputs.target,
            metric_class=metric_class,
            sk_metric=_avg_oracle(oracle, zero_mean),
            metric_args={"zero_mean": zero_mean},
        )

    def test_bf16(self, metric_class, oracle, zero_mean):
        self.run_precision_test(
            inputs.preds, inputs.target, metric_class, None, {"zero_mean": zero_mean}, atol=0.5
        )
