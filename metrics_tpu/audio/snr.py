"""SNR module — analogue of reference ``torchmetrics/audio/snr.py`` (113 LoC)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.snr import snr


class SNR(Metric):
    r"""Signal-to-noise ratio, averaged over all accumulated signals.

    Forward accepts ``preds``/``target`` of shape ``[..., time]``.

    Args:
        zero_mean: subtract the time-mean from both signals first.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> float(SNR()(preds, target))  # doctest: +ELLIPSIS
        16.18...
    """

    def __init__(
        self,
        zero_mean: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.zero_mean = zero_mean
        self.add_state("sum_snr", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        batch_vals = snr(preds=preds, target=target, zero_mean=self.zero_mean)
        self.sum_snr = self.sum_snr + jnp.sum(batch_vals)
        self.total = self.total + batch_vals.size

    def compute(self) -> Array:
        return self.sum_snr / self.total

    is_differentiable = True
