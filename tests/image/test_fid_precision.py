"""FID precision story: f32 streaming moments with Kahan compensation must
match a float64 scipy reference at the reference's tolerance (atol=1e-3,
``/root/reference`` ``tests/image/test_fid.py:28-40``) — including on
ill-conditioned covariances and long streams — and must not spew
float64-truncation warnings (round-1 VERDICT item 7).
"""
import warnings

import jax.numpy as jnp
import numpy as np
import pytest
from scipy import linalg as scipy_linalg

from metrics_tpu import FID
from metrics_tpu.ops.linalg import kahan_add, trace_sqrtm_product


def _np_fid_f64(real: np.ndarray, fake: np.ndarray) -> float:
    r = real.astype(np.float64)
    f = fake.astype(np.float64)
    mu1, mu2 = r.mean(0), f.mean(0)
    c1 = np.cov(r, rowvar=False)
    c2 = np.cov(f, rowvar=False)
    covmean = scipy_linalg.sqrtm(c1 @ c2)
    if np.iscomplexobj(covmean):
        covmean = covmean.real
    return float(((mu1 - mu2) ** 2).sum() + np.trace(c1 + c2 - 2.0 * covmean))


def _ill_conditioned_features(rng, n, d, mean_scale=30.0):
    """Features with a large common offset and variances spanning ~5 decades —
    the cancellation-prone regime for E[xx^T] - mu mu^T in f32."""
    stds = np.logspace(-2.5, 1.0, d)
    mean = mean_scale * (1.0 + rng.rand(d))
    return (mean + stds * rng.randn(n, d)).astype(np.float32)


def test_streaming_fid_matches_scipy_f64_ill_conditioned():
    rng = np.random.RandomState(0)
    d, n, batch = 12, 20_000, 100
    real = _ill_conditioned_features(rng, n, d)
    fake = _ill_conditioned_features(rng, n, d, mean_scale=30.5)

    feat = lambda x: x  # noqa: E731 — feed features directly
    fid = FID(feature=feat, feature_dim=d, streaming=True)
    for i in range(0, n, batch):
        fid.update(jnp.asarray(real[i : i + batch]), real=True)
        fid.update(jnp.asarray(fake[i : i + batch]), real=False)

    got = float(fid.compute())
    exp = _np_fid_f64(real, fake)
    np.testing.assert_allclose(got, exp, rtol=1e-3, atol=1e-3)


def test_streaming_equals_buffered_long_stream():
    """Compensated streaming moments agree with the two-pass buffered path
    over a long stream (the regime where naive f32 sums drift)."""
    rng = np.random.RandomState(1)
    d, n, batch = 8, 50_000, 200
    real = (5.0 + rng.randn(n, d)).astype(np.float32)
    fake = (5.2 + rng.randn(n, d)).astype(np.float32)

    feat = lambda x: x  # noqa: E731
    fid_s = FID(feature=feat, feature_dim=d, streaming=True)
    fid_b = FID(feature=feat, feature_dim=d)
    for i in range(0, n, batch):
        for f, is_real in ((real, True), (fake, False)):
            fid_s.update(jnp.asarray(f[i : i + batch]), real=is_real)
            fid_b.update(jnp.asarray(f[i : i + batch]), real=is_real)
    np.testing.assert_allclose(
        float(fid_s.compute()), float(fid_b.compute()), rtol=1e-3, atol=1e-3
    )
    np.testing.assert_allclose(float(fid_s.compute()), _np_fid_f64(real, fake), rtol=1e-3, atol=1e-3)


def test_merge_driven_accumulation_keeps_rescue():
    """forward()'s accumulation path is merge_states(acc, batch); the
    Kahan-aware FID merge must preserve compensated precision over a long
    merge chain (naive `a + b` sum-merge drifts like uncompensated f32)."""
    rng = np.random.RandomState(4)
    d, n, batch = 8, 40_000, 100
    real = (30.0 + rng.randn(n, d)).astype(np.float32)
    fake = (30.3 + rng.randn(n, d)).astype(np.float32)

    feat = lambda x: x  # noqa: E731
    fid = FID(feature=feat, feature_dim=d, streaming=True)
    scratch = FID(feature=feat, feature_dim=d, streaming=True)
    state = fid.init_state()
    for i in range(0, n, batch):
        batch_state = scratch.pure_update(scratch.init_state(), jnp.asarray(real[i : i + batch]), True)
        batch_state = scratch.pure_update(batch_state, jnp.asarray(fake[i : i + batch]), False)
        state = fid.merge_states(state, batch_state)
    got = float(fid.pure_compute(state))
    np.testing.assert_allclose(got, _np_fid_f64(real, fake), rtol=1e-3, atol=1e-3)


def test_kahan_merge_preserves_compensation():
    from metrics_tpu.ops.linalg import kahan_merge

    a_t, a_c = jnp.asarray(1e8, jnp.float32), jnp.asarray(-512.0, jnp.float32)
    b_t, b_c = jnp.asarray(3.0, jnp.float32), jnp.asarray(0.25, jnp.float32)
    t, c = kahan_merge(a_t, a_c, b_t, b_c)
    exp = (float(a_t) - float(a_c)) + (float(b_t) - float(b_c))
    assert abs((float(t) - float(c)) - exp) < 16.0  # few ulps at 1e8


def test_kahan_add_rescues_f32_sum():
    """A canonical Kahan check: summing many small values into a large total
    in f32 loses everything naively, survives with compensation."""
    total = jnp.asarray(1e8, jnp.float32)
    comp = jnp.asarray(0.0, jnp.float32)
    naive = total
    small = jnp.asarray(1.0, jnp.float32)  # below f32 resolution at 1e8
    for _ in range(1000):
        total, comp = kahan_add(total, comp, small)
        naive = naive + small
    corrected = float(total - comp)
    assert abs(corrected - (1e8 + 1000)) < 64.0  # few ulps at 1e8
    assert abs(float(naive) - 1e8) < 1.0  # naive sum dropped every addend


@pytest.mark.parametrize("cond_exponent", [4, 8])
def test_trace_sqrtm_product_ill_conditioned(cond_exponent):
    rng = np.random.RandomState(2)
    d = 24
    for _ in range(2):
        q1, _ = np.linalg.qr(rng.randn(d, d))
        q2, _ = np.linalg.qr(rng.randn(d, d))
        e1 = np.logspace(-cond_exponent / 2, cond_exponent / 2, d)
        e2 = np.logspace(-cond_exponent / 2, cond_exponent / 2, d)[::-1]
        s1 = (q1 * e1) @ q1.T
        s2 = (q2 * e2) @ q2.T
        exp = np.trace(scipy_linalg.sqrtm(s1 @ s2).real)
        got = float(trace_sqrtm_product(jnp.asarray(s1, jnp.float32), jnp.asarray(s2, jnp.float32)))
        np.testing.assert_allclose(got, exp, rtol=2e-3, atol=1e-3)


def test_no_float64_truncation_warnings():
    """Constructing + updating + computing a streaming FID emits no
    float64-truncation warning spam (explicit canonical-dtype choice)."""
    rng = np.random.RandomState(3)
    feat = lambda x: x  # noqa: E731
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        fid = FID(feature=feat, feature_dim=4, streaming=True)
        for _ in range(3):
            fid.update(jnp.asarray(rng.rand(16, 4).astype(np.float32)), real=True)
            fid.update(jnp.asarray(rng.rand(16, 4).astype(np.float32)), real=False)
        fid.compute()
    spam = [w for w in caught if "float64" in str(w.message)]
    assert not spam, f"float64 truncation warnings emitted: {spam[:3]}"


class TestNewtonSchulzTrace:
    """The TPU dispatch path: monitored Newton-Schulz trace(sqrtm(S1@S2))
    must hit the reference's FID parity bar (rtol 1e-3 vs scipy float64,
    reference tests/test_image/test_fid.py:28-40) including ill-conditioned
    covariances, and must not NaN from post-convergence f32 divergence."""

    def _cov(self, rng, d, cond):
        q, _ = np.linalg.qr(rng.randn(d, d))
        ev = np.logspace(0, -np.log10(cond), d)
        return (q * ev) @ q.T

    @pytest.mark.parametrize("cond", [1e2, 1e5, 1e8])
    def test_ns_matches_scipy(self, cond):
        import scipy.linalg

        from metrics_tpu.ops.linalg import trace_sqrtm_product

        rng = np.random.RandomState(17)
        d = 256
        s1 = self._cov(rng, d, cond)
        s2 = self._cov(rng, d, cond) + 0.05 * self._cov(rng, d, cond)
        ref = np.trace(scipy.linalg.sqrtm(s1.astype(np.float64) @ s2)).real
        ns = float(
            trace_sqrtm_product(
                jnp.asarray(s1, jnp.float32), jnp.asarray(s2, jnp.float32), method="ns"
            )
        )
        assert np.isfinite(ns)
        np.testing.assert_allclose(ns, ref, rtol=1e-3)

    def test_ns_jits_and_agrees_with_eigh(self):
        import jax

        from metrics_tpu.ops.linalg import trace_sqrtm_product

        rng = np.random.RandomState(3)
        f = rng.randn(64, 32).astype(np.float32)
        s1 = jnp.asarray(f.T @ f / 63)
        s2 = s1 + 0.1 * jnp.eye(32, dtype=jnp.float32)
        ns = jax.jit(lambda a, b: trace_sqrtm_product(a, b, method="ns"))(s1, s2)
        eigh = trace_sqrtm_product(s1, s2, method="eigh")
        np.testing.assert_allclose(float(ns), float(eigh), rtol=1e-4)

    def test_unknown_method_raises(self):
        from metrics_tpu.ops.linalg import trace_sqrtm_product

        with pytest.raises(ValueError, match="unknown sqrtm method"):
            trace_sqrtm_product(jnp.eye(4), jnp.eye(4), method="qr")

    def test_fid_end_to_end_ns_vs_eigh(self):
        """Full FID value with the NS path matches the eigh path (both f32)."""
        from metrics_tpu import FID

        rng = np.random.RandomState(5)
        real = jnp.asarray(rng.rand(96, 48).astype(np.float32))
        fake = jnp.asarray(rng.rand(96, 48).astype(np.float32) * 1.3 + 0.1)

        def feats(x):
            return x.reshape(x.shape[0], -1)[:, :48]

        vals = {}
        for method in ("eigh", "ns"):
            fid = FID(feature=feats, feature_dim=48, streaming=True, sqrtm_method=method)
            fid.update(real, real=True)
            fid.update(fake, real=False)
            vals[method] = float(fid.compute())
        np.testing.assert_allclose(vals["ns"], vals["eigh"], rtol=1e-3)


def test_ns_beats_eigh_f32_on_extreme_rank_deficiency():
    """N=8 samples in D=256 (rank-7 covariance): the monitored NS trace is an
    order of magnitude closer to scipy float64 than f32 eigh — evidence for
    the TPU default, not just a compile-time workaround."""
    import scipy.linalg

    from metrics_tpu.ops.linalg import trace_sqrtm_product

    rng = np.random.RandomState(11)
    n, d = 8, 256
    s1 = np.cov(rng.randn(n, d).T)
    s2 = np.cov((rng.randn(n, d) * 1.2 + 0.3).T)
    ref = np.trace(scipy.linalg.sqrtm(s1 @ s2)).real
    ns = float(trace_sqrtm_product(jnp.asarray(s1, jnp.float32), jnp.asarray(s2, jnp.float32), method="ns"))
    np.testing.assert_allclose(ns, ref, rtol=1e-3)


def test_ns_zero_covariance_is_zero_not_nan():
    """Constant features -> zero covariance: NS must return 0 like eigh, not
    NaN from normalizing by a zero Frobenius norm (TPU auto-dispatch path)."""
    from metrics_tpu.ops.linalg import trace_sqrtm_product

    z = jnp.zeros((8, 8), jnp.float32)
    assert float(trace_sqrtm_product(z, z, method="ns")) == 0.0
    assert float(trace_sqrtm_product(z, z, method="eigh")) == 0.0
