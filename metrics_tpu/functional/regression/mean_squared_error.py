"""MSE / RMSE — analogue of reference
``torchmetrics/functional/regression/mean_squared_error.py``."""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _mean_squared_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    diff = preds - target
    return jnp.sum(diff * diff), preds.size


def _mean_squared_error_compute(sum_squared_error: Array, n_obs, squared: bool = True) -> Array:
    mse = sum_squared_error / n_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """Mean squared error (RMSE with ``squared=False``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_squared_error
        >>> print(round(float(mean_squared_error(jnp.asarray([0.0, 1.0]), jnp.asarray([1.0, 1.0]))), 4))
        0.5
    """
    sum_squared_error, n_obs = _mean_squared_error_update(preds, target)
    return _mean_squared_error_compute(sum_squared_error, n_obs, squared)
