"""Chrome-trace / Perfetto exporter for the event journal.

Renders the recorded journal (``observability/journal.py``) as a Chrome
trace-event JSON timeline (the format ``chrome://tracing`` and
https://ui.perfetto.dev load directly), so "did the collective actually
hide behind the step?" is answerable by looking at two tracks instead of
instrumenting ``bench.py`` by hand:

- each **rank** is one trace *process* (``pid`` = rank);
- per rank, the **step lane** (``tid`` 0) carries compiled dispatches
  (duration events), sync launches/resolves (the resolve span is the time
  the host actually *blocked* — ≈0 when the overlap worked), and every
  instantaneous fact (fallbacks, watchdogs, degradations, checkpoints,
  group churn);
- per rank, the **sync-background lane** (``tid`` 1) carries each
  overlapped round's gather as its own span — from the moment the
  background worker started the collectives to their completion — which is
  exactly the bar that should sit UNDER the step lane's work when the
  overlap hides the sync;
- cross-rank correlation rides ``args.sync_epoch``: the same epoch tags
  the launch, background-gather and resolve events of one round on every
  rank, so sorting/filtering by it in Perfetto lines the ranks up.

Timestamps are the journal's monotonic clock in microseconds (Chrome's
unit), re-based to the earliest event so traces start near zero.
"""
import json
from typing import Any, Dict, Iterable, List, Optional

from metrics_tpu.observability import journal

__all__ = ["chrome_trace", "export_chrome_trace"]

#: tid of the foreground (step) lane and the background sync lane.
STEP_LANE = 0
SYNC_LANE = 1

#: Event classes rendered as instants on the step lane (everything that is
#: a fact, not a span).
_INSTANT_CLASSES = ("health", "degrade", "checkpoint", "group")


def _args(ev: journal.Event) -> Dict[str, Any]:
    out = {"step": ev.step, **{k: v for k, v in ev.fields.items()}}
    return {k: (v if isinstance(v, (int, float, str, bool)) or v is None else str(v))
            for k, v in out.items()}


def chrome_trace(events: Optional[Iterable[journal.Event]] = None) -> Dict[str, Any]:
    """Build the Chrome trace-event dict from ``events`` (defaults to the
    full recorded journal). Returns ``{"traceEvents": [...], ...}`` — pass
    through :func:`export_chrome_trace` to write it to disk."""
    evs: List[journal.Event] = list(journal.events() if events is None else events)
    trace: List[Dict[str, Any]] = []
    if not evs:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(e.ts for e in evs)
    # spans are recorded at their END (dispatch durations, resolve waits,
    # background gathers) — include every span's start in the re-base so no
    # trace event goes negative
    for e in evs:
        if e.kind == "sync.resolve" and "gather_start" in e.fields:
            base = min(base, float(e.fields["gather_start"]))
        if e.kind == "compiled.dispatch":
            base = min(base, e.ts - float(e.fields.get("dur_s", 0.0)))
        if e.kind == "sync.resolve":
            base = min(base, e.ts - float(e.fields.get("wait_s", 0.0)))
        if e.kind == "sync.hop":
            base = min(base, e.ts - float(e.fields.get("dur_s", 0.0)))

    def us(ts: float) -> float:
        return (ts - base) * 1e6

    ranks = sorted({e.rank for e in evs})
    for rank in ranks:
        trace.append({
            "ph": "M", "name": "process_name", "pid": rank, "tid": 0,
            "args": {"name": f"rank {rank}"},
        })
        trace.append({
            "ph": "M", "name": "thread_name", "pid": rank, "tid": STEP_LANE,
            "args": {"name": "step"},
        })
        trace.append({
            "ph": "M", "name": "thread_name", "pid": rank, "tid": SYNC_LANE,
            "args": {"name": "sync-background"},
        })

    for ev in evs:
        cls = ev.kind.partition(".")[0]
        args = _args(ev)
        if ev.kind == "compiled.dispatch":
            dur = float(ev.fields.get("dur_s", 0.0)) * 1e6
            trace.append({
                "ph": "X", "name": f"dispatch {ev.label}", "cat": "compiled",
                "pid": ev.rank, "tid": STEP_LANE,
                "ts": us(ev.ts) - dur, "dur": dur, "args": args,
            })
        elif ev.kind == "sync.resolve":
            epoch = ev.fields.get("sync_epoch")
            gather_start = ev.fields.get("gather_start")
            gather_s = float(ev.fields.get("gather_s", 0.0))
            if gather_start is not None:
                # the background lane's span: the collectives themselves
                trace.append({
                    "ph": "X", "name": f"gather {ev.label}", "cat": "sync",
                    "pid": ev.rank, "tid": SYNC_LANE,
                    "ts": us(float(gather_start)), "dur": gather_s * 1e6,
                    "args": args,
                })
            wait_us = float(ev.fields.get("wait_s", 0.0)) * 1e6
            trace.append({
                "ph": "X", "name": f"resolve {ev.label}", "cat": "sync",
                "pid": ev.rank, "tid": STEP_LANE,
                "ts": us(ev.ts) - wait_us, "dur": wait_us,
                "args": args,
            })
            if epoch is not None:
                # flow step ties the cross-rank round together visually
                trace.append({
                    "ph": "f", "bp": "e", "id": int(epoch), "cat": "sync-epoch",
                    "name": f"epoch {epoch}", "pid": ev.rank, "tid": SYNC_LANE,
                    "ts": us(ev.ts),
                })
        elif ev.kind == "sync.launch":
            trace.append({
                "ph": "i", "s": "t", "name": f"launch {ev.label}", "cat": "sync",
                "pid": ev.rank, "tid": STEP_LANE, "ts": us(ev.ts), "args": args,
            })
            epoch = ev.fields.get("sync_epoch")
            if epoch is not None:
                trace.append({
                    "ph": "s", "id": int(epoch), "cat": "sync-epoch",
                    "name": f"epoch {epoch}", "pid": ev.rank, "tid": SYNC_LANE,
                    "ts": us(ev.ts),
                })
        elif ev.kind == "sync.hop":
            # the tiered schedule's two hop classes render as their own
            # categories so the fast (intra-tier) and slow (inter-tier)
            # wires are distinguishable (color + filter) in Perfetto
            dur = float(ev.fields.get("dur_s", 0.0)) * 1e6
            trace.append({
                "ph": "X",
                "name": f"{ev.label}-tier hop (tier {ev.fields.get('tier', -1)})",
                "cat": f"sync-{ev.label}-tier",
                "pid": ev.rank, "tid": SYNC_LANE,
                "ts": us(ev.ts) - dur, "dur": dur, "args": args,
            })
        elif ev.kind in ("sync.gather", "sync.plan", "sync.drain"):
            trace.append({
                "ph": "i", "s": "t", "name": f"{ev.kind.partition('.')[2]} {ev.label}",
                "cat": "sync", "pid": ev.rank, "tid": STEP_LANE,
                "ts": us(ev.ts), "args": args,
            })
        elif ev.kind in ("compiled.trace", "compiled.fallback"):
            trace.append({
                "ph": "i", "s": "t", "name": f"{ev.kind} {ev.label}",
                "cat": "compiled", "pid": ev.rank, "tid": STEP_LANE,
                "ts": us(ev.ts), "args": args,
            })
        elif cls in _INSTANT_CLASSES:
            scope = "p" if cls == "health" else "t"  # process-wide health marks
            trace.append({
                "ph": "i", "s": scope, "name": f"{ev.kind} {ev.label}".strip(),
                "cat": cls, "pid": ev.rank, "tid": STEP_LANE,
                "ts": us(ev.ts), "args": args,
            })
        else:  # unknown/future kinds degrade to generic instants
            trace.append({
                "ph": "i", "s": "t", "name": ev.kind, "cat": cls,
                "pid": ev.rank, "tid": STEP_LANE, "ts": us(ev.ts), "args": args,
            })
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def export_chrome_trace(
    path: Optional[str] = None,
    events: Optional[Iterable[journal.Event]] = None,
) -> Dict[str, Any]:
    """Render the journal as Chrome-trace JSON; write it to ``path`` when
    given. Returns the trace dict either way. Load the file in
    ``chrome://tracing`` or https://ui.perfetto.dev (see
    ``docs/observability.md`` for the walkthrough)."""
    trace = chrome_trace(events)
    if path is not None:
        with open(path, "w") as f:
            json.dump(trace, f)
    return trace
