"""Regression metrics parity vs sklearn/scipy, mirroring the reference's
`tests/regression/` strategy."""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import pearsonr, spearmanr
from sklearn.metrics import (
    explained_variance_score as sk_explained_variance,
    mean_absolute_error as sk_mae,
    mean_absolute_percentage_error as sk_mape,
    mean_squared_error as sk_mse,
    mean_squared_log_error as sk_msle,
    mean_tweedie_deviance as sk_tweedie,
    r2_score as sk_r2,
)

from metrics_tpu import (
    CosineSimilarity,
    ExplainedVariance,
    MeanAbsoluteError,
    MeanAbsolutePercentageError,
    MeanSquaredError,
    MeanSquaredLogError,
    PearsonCorrcoef,
    R2Score,
    SpearmanCorrcoef,
    SymmetricMeanAbsolutePercentageError,
    TweedieDevianceScore,
)
from metrics_tpu.functional import (
    cosine_similarity,
    explained_variance,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    pearson_corrcoef,
    r2_score,
    spearman_corrcoef,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
)
from tests.helpers import seed_all
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

seed_all(42)

_preds = np.random.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) + 0.05
_target = np.random.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32) + 0.05
_preds_2d = np.random.rand(NUM_BATCHES, BATCH_SIZE, 3).astype(np.float32)
_target_2d = np.random.rand(NUM_BATCHES, BATCH_SIZE, 3).astype(np.float32)


@pytest.mark.parametrize(
    "metric_class, metric_fn, sk_fn, metric_args",
    [
        (MeanSquaredError, mean_squared_error, sk_mse, {}),
        (MeanSquaredError, mean_squared_error, lambda t, p: np.sqrt(sk_mse(t, p)), {"squared": False}),
        (MeanAbsoluteError, mean_absolute_error, sk_mae, {}),
        (MeanSquaredLogError, mean_squared_log_error, sk_msle, {}),
        (MeanAbsolutePercentageError, mean_absolute_percentage_error, sk_mape, {}),
    ],
)
class TestMeanErrors(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp, metric_class, metric_fn, sk_fn, metric_args):
        self.run_class_metric_test(
            ddp=ddp, preds=_preds, target=_target, metric_class=metric_class,
            sk_metric=lambda p, t: sk_fn(t, p), metric_args=metric_args,
        )

    def test_fn(self, metric_class, metric_fn, sk_fn, metric_args):
        self.run_functional_metric_test(
            _preds, _target, metric_functional=metric_fn,
            sk_metric=lambda p, t: sk_fn(t, p), metric_args=metric_args,
        )

    @pytest.mark.nightly  # full fixture breadth; CI keeps a representative slice elsewhere
    def test_sharded(self, metric_class, metric_fn, sk_fn, metric_args):
        self.run_sharded_metric_test(
            preds=_preds, target=_target, metric_class=metric_class,
            sk_metric=lambda p, t: sk_fn(t, p), metric_args=metric_args,
        )


def test_smape():
    p, t = _preds[0], _target[0]
    expected = np.mean(2 * np.abs(p - t) / (np.abs(p) + np.abs(t)))
    res = symmetric_mean_absolute_percentage_error(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)
    m = SymmetricMeanAbsolutePercentageError()
    m.update(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize("multioutput", ["uniform_average", "raw_values", "variance_weighted"])
def test_explained_variance(multioutput):
    p = np.concatenate(list(_preds_2d))
    t = np.concatenate(list(_target_2d))
    res = explained_variance(jnp.asarray(p), jnp.asarray(t), multioutput=multioutput)
    expected = sk_explained_variance(t, p, multioutput=multioutput)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-4)


def test_explained_variance_class_accumulation():
    m = ExplainedVariance()
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    expected = sk_explained_variance(np.concatenate(list(_target)), np.concatenate(list(_preds)))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-4)


@pytest.mark.parametrize("multioutput", ["uniform_average", "raw_values", "variance_weighted"])
def test_r2(multioutput):
    p = np.concatenate(list(_preds_2d))
    t = np.concatenate(list(_target_2d))
    res = r2_score(jnp.asarray(p), jnp.asarray(t), multioutput=multioutput)
    expected = sk_r2(t, p, multioutput=multioutput)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-4)


def test_r2_adjusted():
    p, t = _preds[0], _target[0]
    res = r2_score(jnp.asarray(p), jnp.asarray(t), adjusted=5)
    n = len(p)
    base = sk_r2(t, p)
    expected = 1 - (1 - base) * (n - 1) / (n - 5 - 1)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-4)


def test_r2_class_multioutput():
    m = R2Score(num_outputs=3)
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_preds_2d[i]), jnp.asarray(_target_2d[i]))
    expected = sk_r2(np.concatenate(list(_target_2d)), np.concatenate(list(_preds_2d)))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-4)


def test_pearson_fn_and_class():
    p = np.concatenate(list(_preds))
    t = np.concatenate(list(_target))
    expected = pearsonr(t, p)[0]
    np.testing.assert_allclose(np.asarray(pearson_corrcoef(jnp.asarray(p), jnp.asarray(t))), expected, atol=1e-4)
    m = PearsonCorrcoef()
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-4)


def test_pearson_merge_states():
    """The pairwise moment merge must equal single-stream accumulation."""
    a, b = PearsonCorrcoef(), PearsonCorrcoef()
    for i in range(0, NUM_BATCHES, 2):
        a.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    for i in range(1, NUM_BATCHES, 2):
        b.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    a.merge_state(b)
    expected = pearsonr(np.concatenate(list(_target)), np.concatenate(list(_preds)))[0]
    np.testing.assert_allclose(np.asarray(a.compute()), expected, atol=1e-4)


def test_pearson_forward_batch_value():
    m = PearsonCorrcoef()
    v = m(jnp.asarray(_preds[0]), jnp.asarray(_target[0]))
    expected = pearsonr(_target[0], _preds[0])[0]
    np.testing.assert_allclose(np.asarray(v), expected, atol=1e-4)


def test_spearman():
    p = np.concatenate(list(_preds))
    t = np.concatenate(list(_target))
    expected = spearmanr(t, p)[0]
    np.testing.assert_allclose(np.asarray(spearman_corrcoef(jnp.asarray(p), jnp.asarray(t))), expected, atol=1e-4)


def test_spearman_with_ties():
    rng = np.random.RandomState(0)
    p = rng.randint(0, 5, 100).astype(np.float32)  # heavy ties
    t = rng.randint(0, 5, 100).astype(np.float32)
    expected = spearmanr(t, p)[0]
    np.testing.assert_allclose(np.asarray(spearman_corrcoef(jnp.asarray(p), jnp.asarray(t))), expected, atol=1e-4)
    m = SpearmanCorrcoef()
    m.update(jnp.asarray(p[:50]), jnp.asarray(t[:50]))
    m.update(jnp.asarray(p[50:]), jnp.asarray(t[50:]))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-4)


@pytest.mark.parametrize("reduction", ["sum", "mean", "none"])
def test_cosine_similarity(reduction):
    p, t = _preds_2d[0], _target_2d[0]
    dot = (p * t).sum(-1)
    sim = dot / (np.linalg.norm(p, axis=-1) * np.linalg.norm(t, axis=-1))
    expected = {"sum": sim.sum(), "mean": sim.mean(), "none": sim}[reduction]
    res = cosine_similarity(jnp.asarray(p), jnp.asarray(t), reduction=reduction)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-5)
    m = CosineSimilarity(reduction=reduction)
    m.update(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize("power", [0, 1, 2, 3, 1.5, -1.5])
def test_tweedie(power):
    p = np.concatenate(list(_preds))
    t = np.concatenate(list(_target))
    res = tweedie_deviance_score(jnp.asarray(p), jnp.asarray(t), power=power)
    expected = sk_tweedie(t, p, power=power)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-4, rtol=1e-4)
    m = TweedieDevianceScore(power=power)
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_preds[i]), jnp.asarray(_target[i]))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-4, rtol=1e-4)


def test_tweedie_invalid():
    with pytest.raises(ValueError, match="not defined for power"):
        tweedie_deviance_score(jnp.asarray([1.0]), jnp.asarray([1.0]), power=0.5)
    with pytest.raises(ValueError, match="strictly positive"):
        tweedie_deviance_score(jnp.asarray([-1.0]), jnp.asarray([1.0]), power=1)


def test_pearson_sharded():
    """Pearson's None-reduce states gather correctly over the mesh and fold
    through _final_aggregation."""
    tester = MetricTester()
    tester.atol = 1e-4
    tester.run_sharded_metric_test(
        preds=_preds,
        target=_target,
        metric_class=PearsonCorrcoef,
        sk_metric=lambda p, t: pearsonr(t.ravel(), p.ravel())[0],
        metric_args={},
    )


def test_sharded_ci_representative():
    """CI twin of the nightly sharded mean-error sweep (MSE row)."""
    t = TestMeanErrors()
    t.run_sharded_metric_test(
        preds=_preds, target=_target, metric_class=MeanSquaredError,
        sk_metric=lambda p, tt: sk_mse(tt, p), metric_args={},
    )
