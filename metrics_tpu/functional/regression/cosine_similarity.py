"""Cosine similarity — analogue of reference
``torchmetrics/functional/regression/cosine_similarity.py``."""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    return preds.astype(jnp.float32), target.astype(jnp.float32)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot_product = jnp.sum(preds * target, axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    if reduction == "sum":
        return jnp.sum(similarity)
    if reduction == "mean":
        return jnp.mean(similarity)
    if reduction in ("none", None):
        return similarity
    raise ValueError(f"Expected reduction to be one of ['sum', 'mean', 'none'] but got {reduction}")


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    r"""Cosine similarity between rows of preds and target.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cosine_similarity
        >>> preds = jnp.asarray([[3.0, 4.0], [1.0, 0.0]])
        >>> target = jnp.asarray([[6.0, 8.0], [0.0, 1.0]])
        >>> print(cosine_similarity(preds, target, reduction=None))
        [1. 0.]
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
