"""Lockstep multi-rank collective simulator for host-sync equivalence tests.

``EchoAllgather`` (tests/parallel/test_fault_injection.py) fakes a world
where every peer contributes *this* rank's value — enough for divergence
injection, but it cannot express genuinely uneven per-rank states. This
module runs the REAL sync code for every rank concurrently (one thread per
rank) and turns each ``_raw_process_allgather`` call into a barrier
rendezvous that stacks what every rank actually contributed — a faithful
single-process model of the multi-process collective, so bucketed-vs-
per-leaf results can be compared bit-for-bit over mixed-dtype, uneven
states.

Collectives must be issued with the watchdog disabled (``timeout=0`` →
inline execution): the watchdog's worker thread would lose the rank's
thread-local identity.

Overlapped (non-blocking) sync rounds need one more seam: in production
every rank is its own process with its own ``parallel/async_sync.py``
executor, but here all fake ranks share one module, so each rank must get
its own background lane whose worker thread *carries the rank's identity*
(``executor_for_current_rank`` + an initializer propagating the
thread-local) — monkeypatch it over ``async_sync._get_executor``.
"""
import threading
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from metrics_tpu.parallel.async_sync import SerialExecutor

__all__ = ["LockstepWorld"]


class LockstepWorld:
    """Run ``fn(rank)`` on ``world`` threads with rendezvous collectives.

    Install with::

        monkeypatch.setattr(jax, "process_count", lambda: w.world)
        monkeypatch.setattr(sync_mod, "_raw_process_allgather", w.allgather)

    ``calls`` counts collective *rounds* (one per rendezvous, not per rank).
    A rank that raises aborts the barrier so peers fail fast instead of
    deadlocking; the first rank's exception is re-raised from :meth:`run`.
    """

    def __init__(self, world: int = 2) -> None:
        self.world = world
        self.calls = 0
        self._barrier = threading.Barrier(world)
        self._slots: List[Optional[np.ndarray]] = [None] * world
        self._rank = threading.local()
        self._executors: Dict[int, SerialExecutor] = {}
        self._executors_lock = threading.Lock()

    def executor_for_current_rank(self) -> SerialExecutor:
        """Per-rank single-worker executor whose thread carries this rank's
        thread-local identity — the ``async_sync._get_executor`` seam for
        simulated worlds. One worker per rank preserves the production
        property that a rank's rounds execute in launch order."""
        rank = self._rank.value
        with self._executors_lock:
            ex = self._executors.get(rank)
            if ex is None:

                def _adopt_rank(r: int = rank) -> None:
                    self._rank.value = r

                ex = SerialExecutor(
                    f"lockstep-async-rank{rank}", initializer=_adopt_rank
                )
                self._executors[rank] = ex
            return ex

    def rank_domain(self):
        """This thread's rank identity (or ``None`` off-rank) — the
        ``async_sync._current_domain`` seam: a fake rank must drain only its
        OWN launched rounds, as a real per-process rank would."""
        return getattr(self._rank, "value", None)

    def shutdown_executors(self) -> None:
        with self._executors_lock:
            for ex in self._executors.values():
                ex.shutdown(wait=False)
            self._executors.clear()

    def allgather(self, x: Any):
        rank = self._rank.value
        self._slots[rank] = np.asarray(x).copy()
        if self._barrier.wait() == 0:
            self.calls += 1
        out = jnp.asarray(np.stack(self._slots))
        # second rendezvous: every rank reads before the next round overwrites.
        # A break HERE is tolerated: the gather itself completed (every rank
        # contributed and this rank already stacked its copy), so a peer that
        # raised right after reading — e.g. a symmetric typed SyncError from
        # verifying the gathered header — may abort() before this rank drains
        # the guard barrier. Its only job (ordering vs a next round) is moot
        # once a peer aborted: an aborted peer never starts another round, and
        # a still-healthy peer can't pass this same barrier early. The FIRST
        # wait above still propagates the break — a rank dying before
        # contributing is a genuine protocol divergence.
        try:
            self._barrier.wait()
        except threading.BrokenBarrierError:
            pass
        return out

    def run(self, fn: Callable[[int], Any], timeout: float = 120.0) -> List[Any]:
        results: List[Any] = [None] * self.world
        errors: List[Optional[BaseException]] = [None] * self.world

        def body(rank: int) -> None:
            self._rank.value = rank
            try:
                results[rank] = fn(rank)
            except BaseException as err:  # noqa: BLE001 - re-raised below
                errors[rank] = err
                self._barrier.abort()

        threads = [
            threading.Thread(target=body, args=(r,), daemon=True, name=f"lockstep-rank{r}")
            for r in range(self.world)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
        if any(t.is_alive() for t in threads):
            self._barrier.abort()
            raise RuntimeError("LockstepWorld deadlocked: a rank never reached the barrier")
        for err in errors:
            if err is not None:
                raise err
        return results
