"""Sharded-mesh tests for retrieval, image (streaming FID), and audio.

Closes BASELINE config 5 (ragged query groups under collective sync) and the
distributed story of the reference's ``retrieval/retrieval_metric.py:93-139``:
per-device update, in-jit collective sync over the 'dp' axis, compute equal to
the reference on ALL data.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import FID, SI_SDR, SNR, RetrievalMAP, RetrievalMRR, RetrievalNormalizedDCG
from tests.helpers import seed_all
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester
from tests.retrieval.test_retrieval import _np_ap, _np_ndcg, _np_rr, _per_query_mean
from tests.audio.test_audio import _np_si_sdr, _np_snr

seed_all(42)

N_QUERIES = 17  # not a divisor of the row count: ragged group sizes


def _retrieval_batches():
    """[NUM_BATCHES, BATCH_SIZE] rows whose query groups are ragged and span
    batch (and therefore rank) boundaries."""
    rng = np.random.RandomState(11)
    indexes = rng.randint(0, N_QUERIES, (NUM_BATCHES, BATCH_SIZE))
    preds = rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32)
    target = rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE))
    return indexes, preds, target


class TestShardedRetrieval(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize(
        "metric_class, np_fn",
        [(RetrievalMAP, _np_ap), (RetrievalMRR, _np_rr)],
    )
    def test_sharded_ragged_groups(self, metric_class, np_fn):
        indexes, preds, target = _retrieval_batches()
        flat_idx = indexes.reshape(-1)

        def sk(total_preds, total_target):
            # row multisets match the sharded order: per-query metrics only
            # depend on (index, pred, target) triples, which move together
            return _per_query_mean(flat_idx, total_preds, total_target, np_fn)

        self.run_sharded_metric_test(
            preds, target, metric_class, sk, indexes=indexes
        )

    def test_sharded_ndcg_nonbinary(self):
        rng = np.random.RandomState(12)
        indexes, preds, _ = _retrieval_batches()
        target = rng.randint(0, 5, (NUM_BATCHES, BATCH_SIZE))
        flat_idx = indexes.reshape(-1)

        def sk(total_preds, total_target):
            return _per_query_mean(
                flat_idx, total_preds, total_target, lambda p, t: _np_ndcg(p, t, k=None)
            )

        self.run_sharded_metric_test(
            preds, target, RetrievalNormalizedDCG, sk, indexes=indexes
        )


class TestShardedAudio(MetricTester):
    atol = 1e-3  # float32 log-domain accumulation

    @pytest.mark.parametrize(
        "metric_class, np_fn", [(SNR, _np_snr), (SI_SDR, _np_si_sdr)]
    )
    def test_sharded_ratio_metrics(self, metric_class, np_fn):
        rng = np.random.RandomState(13)
        preds = rng.randn(NUM_BATCHES, BATCH_SIZE, 100).astype(np.float32)
        target = rng.randn(NUM_BATCHES, BATCH_SIZE, 100).astype(np.float32)
        self.run_sharded_metric_test(
            preds, target, metric_class, lambda p, t: np_fn(p, t).mean()
        )


def test_streaming_fid_psum_over_mesh():
    """Streaming FID: per-device moment accumulation, ONE psum sync, on-device
    sqrtm compute — the whole pipeline in a single jitted program, equal to
    the single-device value on all data."""
    world, per_rank, batch = 4, 2, 8
    feat = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :16]  # noqa: E731

    rng = np.random.RandomState(5)
    real = rng.rand(world * per_rank, batch, 3, 8, 8).astype(np.float32)
    fake = (rng.rand(world * per_rank, batch, 3, 8, 8) * 0.8 + 0.1).astype(np.float32)

    # single-device reference over all data
    fid_ref = FID(feature=feat, feature_dim=16, streaming=True)
    for i in range(world * per_rank):
        fid_ref.update(jnp.asarray(real[i]), real=True)
        fid_ref.update(jnp.asarray(fake[i]), real=False)
    expected = float(fid_ref.compute())

    fid = FID(feature=feat, feature_dim=16, streaming=True)
    mesh = Mesh(np.array(jax.devices()[:world]), ("dp",))

    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def sharded_fid(r, f):
        st = fid.init_state()
        for i in range(per_rank):
            st = fid.pure_update(st, r[0, i], True)
            st = fid.pure_update(st, f[0, i], False)
        synced = fid.pure_sync(st, "dp")
        return fid.pure_compute(synced)

    got = jax.jit(sharded_fid)(
        jnp.asarray(real.reshape(world, per_rank, batch, 3, 8, 8)),
        jnp.asarray(fake.reshape(world, per_rank, batch, 3, 8, 8)),
    )
    np.testing.assert_allclose(float(got), expected, rtol=1e-4, atol=1e-4)
    assert np.isfinite(expected) and expected > 0


class TestShardedText:
    """Text metrics: tokenization stays on host (strings can't trace), but the
    numeric states are plain sum-states — per-rank eager accumulation over
    corpus shards, one in-jit psum over the mesh, replicated compute. Closes
    the text row of the sharded-domain matrix (reference text metrics rely on
    the generic DDP gather, `text/wer.py:87-89`)."""

    CORPUS = [
        ("the quick brown fox", "the quick brown fox"),
        ("jumps over a lazy dog", "jumped over the lazy dog"),
        ("hello world again", "hello there world"),
        ("jax on tpu is fast", "jax on tpus is very fast"),
        ("metrics should sync", "metrics must sync"),
        ("one more sentence here", "one more sentences here"),
        ("short", "short"),
        ("the final pair of words", "a final pair of word"),
    ]

    def _sharded_value(self, make_metric, update_one):
        world = 4
        mesh = Mesh(np.array(jax.devices()[:world]), ("dp",))
        # per-rank eager accumulation over a disjoint corpus shard
        rank_states = []
        scratch = None
        for rank in range(world):
            m = make_metric()
            for i in range(rank, len(self.CORPUS), world):
                update_one(m, *self.CORPUS[i])
            rank_states.append(m._state)
            scratch = scratch or m  # an updated instance hosts the pure calls
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rank_states)

        @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P(), check_vma=False)
        def sync_and_compute(state):
            local = jax.tree_util.tree_map(lambda x: x[0], state)
            return scratch.pure_compute(scratch.pure_sync(local, "dp"))

        return float(sync_and_compute(stacked))

    def test_wer_psum_equals_full_corpus(self):
        from metrics_tpu import WER

        got = self._sharded_value(WER, lambda m, p, t: m.update(p, t))
        full = WER()
        for p, t in self.CORPUS:
            full.update(p, t)
        np.testing.assert_allclose(got, float(full.compute()), atol=1e-6)

    def test_bleu_psum_equals_full_corpus(self):
        from metrics_tpu import BLEUScore

        def upd(m, p, t):
            m.update([[t.split()]], [p.split()])

        got = self._sharded_value(lambda: BLEUScore(n_gram=2), upd)
        full = BLEUScore(n_gram=2)
        for p, t in self.CORPUS:
            upd(full, p, t)
        np.testing.assert_allclose(got, float(full.compute()), atol=1e-6)
