from metrics_tpu.audio.pit import PIT
from metrics_tpu.audio.si_sdr import SI_SDR
from metrics_tpu.audio.si_snr import SI_SNR
from metrics_tpu.audio.snr import SNR

__all__ = ["PIT", "SI_SDR", "SI_SNR", "SNR"]
