"""metricslint fixture: collective-schedule violations — every way a rank
can end up emitting a different collective sequence than its peers.

The CI gate asserts the CLI exits NONZERO on this file. The collective and
helper names mirror ``parallel/sync.py``'s conventions (that is what the
pass keys on); the stubs keep the module import-safe.
"""
import jax
import jax.numpy as jnp


def _process_allgather(x, timeout=None):  # stand-in collective
    return jnp.asarray(x)[None]


def state_has_nonfinite(state):  # stand-in local-data predicate
    return False


def rank_zero_extra_gather(x, state):
    """finding: rank-dependent-collective — only rank 0 emits the gather."""
    if jax.process_index() == 0:
        return _process_allgather(x)
    return x


def data_dependent_gather(state, x):
    """finding: data-dependent-collective — ranks with empty local state
    skip the collective their peers emit."""
    if len(state) > 0:
        return _process_allgather(x)
    return x


def early_exit_desync(state, x):
    """finding: data-dependent-collective — a local-data raise ahead of the
    gather means a poisoned rank leaves its peers hanging in the gather."""
    if state_has_nonfinite(state):
        raise RuntimeError("poisoned")
    return _process_allgather(x)


def collective_in_handler(x):
    """finding: collective-in-handler — a locally-caught failure is not a
    symmetric event; the retry gather pairs with nothing on healthy ranks."""
    try:
        return _process_allgather(x)
    except Exception:
        return _process_allgather(jnp.zeros_like(x))


def set_iteration_order(state):
    """finding: nondeterministic-collective-order — set iteration order
    differs across processes, so the gather sequence does too."""
    out = {}
    for name in set(state):
        out[name] = _process_allgather(state[name])
    return out


def transitive_rank_dependence(x, flag):
    """finding: rank-dependent-collective — the collective hides one call
    away, behind a rank-dependent branch."""
    if jax.process_index() > 0:
        return _emitting_helper(x)
    return x


def _emitting_helper(x):
    return _process_allgather(x)


def clean_symmetric_paths(state, x, world):
    """No findings: unconditional gathers, branches only on symmetric data
    (the gathered result, world size, schema)."""
    counts = _process_allgather(jnp.asarray(len(state)))
    if (jnp.asarray(counts) == 0).any():
        raise RuntimeError("symmetric failure on every rank")
    if world == 1:
        return x
    if x.ndim == 0:
        x = x[None]
    return _process_allgather(x)
