"""MetricCollection — many metrics, one update call, one fused sync.

Behavioral analogue of the reference's ``torchmetrics/collections.py:26-235``.
TPU upgrade: :meth:`pure_forward` traces *all* member metrics' update + sync +
compute into a single XLA program, so a collection costs one fused reduction
over the mesh instead of one gather per metric (the BASELINE north star).
"""
from contextlib import contextmanager
from copy import deepcopy
from typing import Any, Callable, Dict, Hashable, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.exceptions import MetricsTPUUserError


class MetricCollection(dict):
    """An ordered dict of metrics sharing a single ``update``/``forward``
    call — pass the superset of inputs once and each member picks the
    keyword arguments its ``update`` signature accepts.

    Beyond convenience, the collection is the performance seam: its
    ``pure_forward``/``pure_update`` trace every member into ONE XLA
    program, so a whole collection's update costs one fused kernel launch
    and its distributed sync batches into one collective round — the
    design BASELINE's north-star (<1% metric overhead) is built on.
    ``clone(prefix=...)`` gives cheap train/val/test copies.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricCollection, Precision
        >>> mc = MetricCollection({
        ...     "acc": Accuracy(num_classes=3),
        ...     "prec": Precision(num_classes=3, average="macro"),
        ... })
        >>> vals = mc(jnp.asarray([0, 2, 1]), jnp.asarray([0, 1, 1]))
        >>> print({k: round(float(v), 4) for k, v in sorted(vals.items())})
        {'acc': 0.6667, 'prec': 0.6667}

    Args:
        metrics: one Metric, a list/tuple of Metrics, or a dict name->Metric.
        prefix / postfix: added to every key in the output dict.
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def add_metrics(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
    ) -> None:
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible"
                f" with first passed dictionary {metrics} so they will be ignored."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of `Metric` or `MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = type(metric).__name__
                    if name in self:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def items(self, keep_base: bool = True) -> Iterable[Tuple[str, Metric]]:  # type: ignore[override]
        """Default keeps base keys (dict protocol — deepcopy/pickle iterate
        this); pass ``keep_base=False`` for the prefixed/postfixed view."""
        if keep_base:
            return super().items()
        return [(self._set_name(k), v) for k, v in super().items()]

    def keys(self, keep_base: bool = True) -> Iterable[str]:  # type: ignore[override]
        if keep_base:
            return super().keys()
        return [self._set_name(k) for k in super().keys()]

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return {
            self._set_name(k): m(*args, **m._filter_kwargs(**kwargs))
            for k, m in super().items()
        }

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:  # type: ignore[override]
        for m in self.values():
            m.update(*args, **m._filter_kwargs(**kwargs))

    def compute(self) -> Dict[str, Any]:
        return {self._set_name(k): m.compute() for k, m in super().items()}

    def reset(self) -> None:
        for m in self.values():
            m.reset()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self.values():
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, m in super().items():
            out.update(m.state_dict(prefix=f"{k}."))
        return out

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        for k, m in super().items():
            m.load_state_dict(state_dict, prefix=f"{k}.")

    # ---------------- host sync (fault-tolerance aware) ----------------

    def sync(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        distributed_available: Optional[Callable] = None,
        on_error: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> None:
        """Host-sync every member, threading the fault-tolerance knobs.

        All-or-nothing under ``on_error="raise"``: if a member's sync raises
        a typed ``SyncError`` mid-way, the members already synced are rolled
        back to their local state before the error propagates, so the
        collection is never left half-synced. Under ``"local"``/``"warn"``
        each member degrades independently (``Metric.sync`` swallows the
        error per member) and healthy members still report global values.
        """
        synced: List[Metric] = []
        try:
            for m in self.values():
                m.sync(
                    dist_sync_fn=dist_sync_fn,
                    should_sync=should_sync,
                    distributed_available=distributed_available,
                    on_error=on_error,
                    timeout=timeout,
                )
                if m._is_synced:
                    synced.append(m)
        except Exception:
            for m in synced:
                m.unsync()
            raise

    def unsync(self, should_unsync: bool = True) -> None:
        """Restore every synced member's pre-sync local state.

        Members that degraded to local-only state (``on_error="local"``)
        were never marked synced and are skipped rather than raising."""
        if not should_unsync:
            return
        for m in self.values():
            if m._is_synced:
                m.unsync()

    @contextmanager
    def sync_context(
        self,
        dist_sync_fn: Optional[Callable] = None,
        should_sync: bool = True,
        should_unsync: bool = True,
        distributed_available: Optional[Callable] = None,
        on_error: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> Iterator["MetricCollection"]:
        """Collection-wide sync-on-enter / restore-on-exit (the consistent-
        checkpoint pattern), with ``on_error`` graceful degradation."""
        self.sync(
            dist_sync_fn=dist_sync_fn,
            should_sync=should_sync,
            distributed_available=distributed_available,
            on_error=on_error,
            timeout=timeout,
        )
        try:
            yield self
        finally:
            self.unsync(should_unsync=should_unsync)

    # ---------------- pure-functional fused path ----------------

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        return {k: m.init_state() for k, m in super().items()}

    def pure_update(self, state: Dict[str, Any], *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return {
            k: m.pure_update(state[k], *args, **m._filter_kwargs(**kwargs))
            for k, m in super().items()
        }

    def pure_sync(self, state: Dict[str, Any], axis_name: Optional[Any] = None) -> Dict[str, Any]:
        """Collective-sync member states over ``axis_name``.

        ``axis_name=None``: each member syncs over its own declared
        ``process_group``; members without one keep their local state (what
        their standalone ``pure_forward`` would do). Raises if no member
        declares a group — there would be nothing to sync."""
        if axis_name is not None:
            return {k: m.pure_sync(state[k], axis_name) for k, m in super().items()}
        if all(m.process_group is None for m in super().values()):
            raise MetricsTPUUserError(
                "pure_sync needs a mesh axis: pass `axis_name=` or construct "
                "at least one member with `process_group=<axis or tuple>`."
            )
        return {
            k: m.pure_sync(state[k]) if m.process_group is not None else state[k]
            for k, m in super().items()
        }

    def pure_compute(self, state: Dict[str, Any]) -> Dict[str, Any]:
        return {self._set_name(k): m.pure_compute(state[k]) for k, m in super().items()}

    def merge_states(self, a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
        return {k: m.merge_states(a[k], b[k]) for k, m in super().items()}

    def pure_forward(
        self, state: Dict[str, Any], *args: Any, axis_name: Optional[str] = None, **kwargs: Any
    ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """One fused jittable step for the WHOLE collection: all member
        updates, one round of collectives, all computes — a single XLA graph.

        With ``axis_name=None`` each member syncs over its own declared
        ``process_group`` (members without one stay local) — exactly what the
        member's standalone ``pure_forward`` would do, so mixed-group
        collections neither skip a declared sync nor force one on a
        group-less member."""
        batch = self.pure_update(self.init_state(), *args, **kwargs)
        any_group = any(m.process_group is not None for m in super().values())
        if axis_name is not None or any_group:
            value_state = self.pure_sync(batch, axis_name)
        else:
            value_state = batch
        values = self.pure_compute(value_state)
        new_state = self.merge_states(state, batch)
        return new_state, values

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "(\n"
        for k, v in super().items():
            repr_str += f"  ({k}): {repr(v)}\n"
        return repr_str + ")"
