"""Flax/optax training-loop integration — the Lightning-integration analogue.

Reference behavior being matched (``integrations/test_lightning.py``,
``docs/source/pages/lightning.rst``):

- metrics live on the training module and are updated per step inside the
  training loop (reference ``test_lightning.py:58-63``);
- logging a *metric object* (``self.log(name, metric)``) defers ``compute``
  to epoch end and auto-resets the metric exactly once per epoch
  (reference ``test_lightning.py:86-202`` asserts reset-at-epoch-end and
  no-reset-mid-epoch);
- metric state checkpoints with the model (``nn.Module.state_dict``).

TPU-native redesign: instead of module-system hooks, metric state is an
explicit pytree field on the flax ``TrainState``. The train step stays a pure
function ``state -> state`` — model forward, loss, grads, optimizer update and
metric update all trace into ONE XLA program, and the state (including metric
accumulators) checkpoints atomically with params/opt-state via orbax.
"""
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import flax.struct
from flax.training import train_state

from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.exceptions import MetricsTPUUserError


class _StaticRef:
    """Identity-keyed static holder for the metric collection.

    ``Metric.__eq__`` builds a ``CompositionalMetric`` (reference operator
    parity) and ``Metric.__hash__`` covers only class + state bytes, so metric
    objects must NOT serve as jit-cache keys directly: two differently
    configured metrics (threshold, average, top_k, ...) with identical state
    shapes would collide in the cache and silently reuse the wrong trace.
    Identity equality makes distinct collections distinct cache entries.
    """

    __slots__ = ("value",)

    def __init__(self, value: Any) -> None:
        self.value = value

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, _StaticRef) and self.value is other.value

    def __hash__(self) -> int:
        return id(self.value)


class MetricTrainState(train_state.TrainState):
    """A flax ``TrainState`` that carries metric state through the jitted step.

    The :class:`MetricCollection` itself is static (identity-keyed, not
    traced); its accumulator pytree ``metric_states`` is a regular dataclass
    field, so it is donated/updated/checkpointed exactly like ``params`` and
    ``opt_state``.

    Usage::

        metrics = MetricCollection({"acc": Accuracy(num_classes=10)})
        state = MetricTrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adam(1e-3),
            metrics=metrics)

        @jax.jit
        def train_step(state, x, y):
            ...grads, new_params...
            state = state.apply_gradients(grads=grads)
            return state.update_metrics(jax.nn.softmax(logits), y)

        # epoch end (host side):
        values = state.compute_metrics()
        state = state.reset_metrics()
    """

    metrics_ref: _StaticRef = flax.struct.field(pytree_node=False)
    metric_states: Dict[str, Dict[str, Any]] = flax.struct.field(default_factory=dict)

    @property
    def metrics(self) -> MetricCollection:
        return self.metrics_ref.value

    @classmethod
    def create(cls, *, apply_fn: Callable, params: Any, tx: Any, metrics: Union[MetricCollection, Metric], **kwargs: Any) -> "MetricTrainState":
        if isinstance(metrics, Metric):
            metrics = MetricCollection({type(metrics).__name__.lower(): metrics})
        if not isinstance(metrics, MetricCollection):
            raise MetricsTPUUserError(
                f"`metrics` must be a Metric or MetricCollection, got {type(metrics)}"
            )
        return super().create(
            apply_fn=apply_fn,
            params=params,
            tx=tx,
            metrics_ref=_StaticRef(metrics),
            metric_states=metrics.init_state(),
            **kwargs,
        )

    # -- jit-traceable (pure pytree -> pytree) ---------------------------
    def update_metrics(self, *args: Any, **kwargs: Any) -> "MetricTrainState":
        """Accumulate one batch into the carried metric states (traceable)."""
        return self.replace(metric_states=self.metrics.pure_update(self.metric_states, *args, **kwargs))

    def forward_metrics(
        self, *args: Any, axis_name: Optional[Any] = None, **kwargs: Any
    ) -> Tuple["MetricTrainState", Dict[str, Any]]:
        """Accumulate AND return batch-local values (traceable), optionally
        synced over a mesh axis — the analogue of logging ``on_step=True``."""
        new_states, values = self.metrics.pure_forward(
            self.metric_states, *args, axis_name=axis_name, **kwargs
        )
        return self.replace(metric_states=new_states), values

    def sync_metrics(self, axis_name: Any) -> "MetricTrainState":
        """Collective-reduce metric states over ``axis_name`` (inside
        shard_map/pmap only)."""
        return self.replace(metric_states=self.metrics.pure_sync(self.metric_states, axis_name))

    # -- host side -------------------------------------------------------
    def compute_metrics(self) -> Dict[str, Any]:
        """Epoch-end values from the accumulated states."""
        return self.metrics.pure_compute(self.metric_states)

    def reset_metrics(self) -> "MetricTrainState":
        """Fresh accumulators for the next epoch."""
        return self.replace(metric_states=self.metrics.init_state())


class MetricLogger:
    """Lightning-style ``self.log`` semantics for eager/stateful metrics.

    Mirrors the behavior the reference's Lightning integration relies on
    (``integrations/test_lightning.py:123-127``): logging a *metric object*
    defers ``compute()`` to epoch end and resets the metric exactly once per
    epoch; logging a plain value records it immediately (mean over the epoch).

    Usage::

        logger = MetricLogger()
        for batch in epoch:
            acc(preds, target)                 # stateful update
            logger.log("train/acc", acc)       # deferred: computed at epoch end
            logger.log("train/loss", loss)     # immediate: averaged at epoch end
        values = logger.epoch_end()            # {'train/acc': ..., 'train/loss': ...}
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, Union[Metric, MetricCollection]] = {}
        self._values: Dict[str, List[float]] = {}
        self.history: List[Dict[str, float]] = []

    def log(self, name: str, value: Any) -> None:
        if isinstance(value, (Metric, MetricCollection)):
            if name in self._values:
                raise MetricsTPUUserError(
                    f"plain values were already logged under {name!r}"
                )
            prev = self._metrics.setdefault(name, value)
            if prev is not value:
                raise MetricsTPUUserError(
                    f"a different metric object was already logged under {name!r}"
                )
        else:
            if name in self._metrics:
                raise MetricsTPUUserError(
                    f"a metric object was already logged under {name!r}"
                )
            self._values.setdefault(name, []).append(float(value))

    def log_dict(self, values: Dict[str, Any]) -> None:
        for name, value in values.items():
            self.log(name, value)

    def epoch_end(self) -> Dict[str, Any]:
        """Compute deferred metrics, auto-reset them, average plain values."""
        # compute everything BEFORE any reset/clear: if a later compute()
        # raises, no epoch state has been consumed and epoch_end can be
        # retried without double-counting
        out: Dict[str, Any] = {}
        for name, metric in self._metrics.items():
            value = metric.compute()
            if isinstance(value, dict):
                for k, v in value.items():
                    out[f"{name}/{k}"] = v
            else:
                out[name] = value
        for name, vals in self._values.items():
            if name in out:  # e.g. a collection logged as 'train' expanded to this key
                raise MetricsTPUUserError(
                    f"plain values logged under {name!r} collide with a computed metric entry"
                )
            out[name] = sum(vals) / len(vals)
        for metric in self._metrics.values():
            metric.reset()
        self._metrics.clear()
        self._values.clear()
        self.history.append(out)
        return out
