"""Hardware proof for the pallas binned-stats kernel (VERDICT r2 item 2).

Runs the kernel COMPILED (interpret=False) on the real TPU chip, asserts
parity against the fused-XLA path on the same device, and times both at the
bench config-6 shape (65k rows). Appends a JSON line per run to
``scripts/pallas_tpu_proof.log`` so the result survives tunnel flapping.

Usage: python scripts/pallas_tpu_proof.py   (requires the axon TPU tunnel)
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

# runnable as `python scripts/pallas_tpu_proof.py`: the script dir, not the
# repo root, lands on sys.path, so metrics_tpu would be unimportable
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _median_time(fn, *args, reps: int = 20) -> float:
    # end every rep with a data-dependent device->host scalar fetch:
    # block_until_ready can return before execution completes over the
    # remote-TPU tunnel (same reason bench.py forces scalar readback)
    float(np.asarray(fn(*args)[0].sum()))  # compile + settle
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        _ = float(np.asarray(out[0].sum()))
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> int:
    from _tunnel import probe_tunnel

    if not probe_tunnel():
        return 2

    from metrics_tpu.utils import compile_cache

    compile_cache.enable(str(Path(__file__).resolve().parent.parent / ".jax_cache"), min_compile_seconds=2)
    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon") and "TPU" not in str(dev):
        print(f"not a TPU: {dev}", file=sys.stderr)
        return 2

    from metrics_tpu.ops.pallas_binned import _binned_stats_pallas, _binned_stats_xla

    results = {"metric": "pallas_proof", "device": str(dev), "parity": [], "bench": None}

    # Parity grid: same shapes as the interpreter-mode suite, now compiled.
    # A Mosaic compile failure IS a result (VERDICT item 2: prove OR drop) —
    # record it in the evidence line rather than dying lineless.
    rng = np.random.RandomState(42)
    for n, c, t in [(37, 3, 100), (256, 10, 5), (5, 1, 1), (1000, 17, 130), (64, 130, 20)]:
        preds = jnp.asarray(rng.rand(n, c).astype(np.float32))
        target = jnp.asarray((rng.rand(n, c) > 0.5).astype(np.float32))
        thresholds = jnp.linspace(0.0, 1.0, t)
        try:
            got = _binned_stats_pallas(preds, target, thresholds, interpret=False)
            want = _binned_stats_xla(preds, target, thresholds)
            ok = all(np.allclose(np.asarray(g), np.asarray(w)) for g, w in zip(got, want))
            entry = {"shape": [n, c, t], "ok": bool(ok)}
        except Exception as e:  # noqa: BLE001 — failure is evidence too
            entry = {"shape": [n, c, t], "ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
        results["parity"].append(entry)
        if not entry["ok"]:
            print(f"PARITY FAIL at {(n, c, t)}: {entry.get('error', 'value mismatch')}", file=sys.stderr)

    # Bench config-6 shape: 65k rows x 20 classes x 200 thresholds.
    n, c, t = 65536, 20, 200
    preds = jnp.asarray(rng.rand(n, c).astype(np.float32))
    target = jnp.asarray((rng.rand(n, c) > 0.5).astype(np.float32))
    thresholds = jnp.linspace(0.0, 1.0, t)
    big_ok = False
    try:
        xla_jit = jax.jit(_binned_stats_xla)
        t_xla = _median_time(xla_jit, preds, target, thresholds)
        t_pallas = _median_time(
            lambda p, tg, th: _binned_stats_pallas(p, tg, th, interpret=False),
            preds, target, thresholds,
        )
        got = _binned_stats_pallas(preds, target, thresholds, interpret=False)
        want = xla_jit(preds, target, thresholds)
        big_ok = all(np.allclose(np.asarray(g), np.asarray(w)) for g, w in zip(got, want))
        results["bench"] = {
            "shape": [n, c, t],
            "parity_ok": bool(big_ok),
            "xla_ms": round(t_xla * 1e3, 3),
            "pallas_ms": round(t_pallas * 1e3, 3),
            "pallas_speedup_vs_xla": round(t_xla / t_pallas, 3) if t_pallas else None,
        }
    except Exception as e:  # noqa: BLE001 — failure is evidence too
        results["bench"] = {"shape": [n, c, t], "parity_ok": False,
                           "error": f"{type(e).__name__}: {e}"[:300]}

    results["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    line = json.dumps(results)
    print(line)
    log = Path(__file__).with_name("pallas_tpu_proof.log")
    with log.open("a") as f:
        f.write(line + "\n")
    all_ok = big_ok and all(p["ok"] for p in results["parity"])
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
