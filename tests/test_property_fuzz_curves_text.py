"""Property-based fuzzing, part 5: curve structure and text identities.

Curves have shape-level invariants independent of any oracle: ROC moves
monotonically from (0,0) to (1,1), precision-recall endpoints are pinned,
calibration error lives in [0,1]. Text metrics have exact self-identities.
Hypothesis searches values; shapes stay fixed.
"""
import jax.numpy as jnp
import os

import numpy as np
import pytest

# gate, don't crash collection: environments without the fuzzing dep still
# run the rest of the suite (the driver image does not guarantee hypothesis)
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from metrics_tpu.functional import (
    bleu_score,
    calibration_error,
    precision_recall_curve,
    roc,
    rouge_score,
    wer,
)

N = 24
# CI runs a reduced draw budget to stay inside the 45-min envelope;
# nightly (and any local run without the var) keeps the full budget
_EXAMPLES = int(os.environ.get("METRICS_TPU_FUZZ_EXAMPLES", 30))
COMMON = dict(max_examples=_EXAMPLES, deadline=None)

_scores = st.lists(
    st.floats(0.0, 1.0, allow_nan=False, allow_infinity=False, width=32).filter(
        lambda x: x == 0.0 or x > 1.2e-38  # XLA FTZ
    ),
    min_size=N,
    max_size=N,
)
_bin_target = st.lists(st.integers(0, 1), min_size=N, max_size=N)


@settings(**COMMON)
@given(scores=_scores, target=_bin_target)
def test_roc_monotone_between_corners(scores, target):
    t = np.asarray(target)
    if t.min() == t.max():
        return
    s = jnp.asarray(np.asarray(scores, np.float32))
    fpr, tpr, _ = roc(s, jnp.asarray(t), pos_label=1)
    fpr, tpr = np.asarray(fpr), np.asarray(tpr)
    assert np.all(np.diff(fpr) >= -1e-7), "fpr must be nondecreasing"
    assert np.all(np.diff(tpr) >= -1e-7), "tpr must be nondecreasing"
    assert fpr[0] == pytest.approx(0.0) and tpr[0] == pytest.approx(0.0)
    assert fpr[-1] == pytest.approx(1.0) and tpr[-1] == pytest.approx(1.0)
    assert np.all((fpr >= -1e-7) & (fpr <= 1 + 1e-7))
    assert np.all((tpr >= -1e-7) & (tpr <= 1 + 1e-7))


@settings(**COMMON)
@given(scores=_scores, target=_bin_target)
def test_pr_curve_bounds_and_endpoint(scores, target):
    t = np.asarray(target)
    if t.sum() == 0:  # no positives: precision undefined everywhere
        return
    s = jnp.asarray(np.asarray(scores, np.float32))
    precision, recall, _ = precision_recall_curve(s, jnp.asarray(t), pos_label=1)
    precision, recall = np.asarray(precision), np.asarray(recall)
    assert np.all((precision >= -1e-7) & (precision <= 1 + 1e-7))
    assert np.all((recall >= -1e-7) & (recall <= 1 + 1e-7))
    # reference convention: curve ends at (recall=0, precision=1)
    assert precision[-1] == pytest.approx(1.0)
    assert recall[-1] == pytest.approx(0.0)
    assert np.all(np.diff(recall) <= 1e-7), "recall is nonincreasing along the curve"


@settings(**COMMON)
@given(scores=_scores, target=_bin_target, n_bins=st.sampled_from([5, 10, 15]))
def test_calibration_error_in_unit_interval(scores, target, n_bins):
    t = np.asarray(target)
    s = jnp.asarray(np.asarray(scores, np.float32))
    for norm in ("l1", "max"):
        v = float(calibration_error(s, jnp.asarray(t), n_bins=n_bins, norm=norm))
        assert -1e-7 <= v <= 1.0 + 1e-7, f"{norm}: {v}"


_sentence = st.lists(
    st.sampled_from("the a cat dog runs jumps blue red".split()), min_size=4, max_size=12
)


@settings(**COMMON)
@given(sents=st.lists(_sentence, min_size=1, max_size=3))
def test_text_self_identities(sents):
    """Any corpus scored against itself: BLEU=1, ROUGE-1/L F=1, WER=0."""
    texts = [" ".join(s) for s in sents]
    refs = [[t] for t in texts]
    np.testing.assert_allclose(float(bleu_score(refs, texts)), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(wer(texts, texts)), 0.0, atol=1e-9)
    r = rouge_score(texts, texts)
    np.testing.assert_allclose(float(np.asarray(r["rouge1_fmeasure"])), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(np.asarray(r["rougeL_fmeasure"])), 1.0, atol=1e-6)


@settings(**COMMON)
@given(sents=st.lists(_sentence, min_size=2, max_size=4), data=st.data())
def test_bleu_corpus_order_invariance(sents, data):
    """Corpus BLEU is a ratio of corpus-summed counts: permuting the corpus
    order must not change it."""
    hyps = [" ".join(s) for s in sents]
    refs = [[" ".join(data.draw(_sentence))] for _ in sents]
    base = float(bleu_score(refs, hyps))
    perm = data.draw(st.permutations(list(range(len(hyps)))))
    shuffled = float(bleu_score([refs[i] for i in perm], [hyps[i] for i in perm]))
    np.testing.assert_allclose(base, shuffled, atol=1e-6)
