"""Static-report consumers inside the runtime — probe pre-classification
and compute-group declaration validation.

``core/compiled.py``'s eligibility probe (``jax.eval_shape`` + instance-
``__dict__`` diffing) exists to answer two questions before any state buffer
is donated: *is update traceable?* and *does update latch undeclared
instance attributes?* For most shipped metric classes both answers are
static properties of the source. This module evaluates the metric-class
pass against a **live** class — declared states come from the instance's
runtime ``_defaults`` (exact, even for dynamically-named states the AST
cannot resolve) while the write/host-sync facts come from the AST of the
class's actual MRO — and caches one verdict per class:

- ``CLEAN``: every attribute written by update (helpers included) is a
  declared state / shared latch / runtime-bookkeeping attr, the scan is
  fully resolved, and no host-sync antipattern (the usual cause of
  trace-time ``ConcretizationTypeError``) appears. The probe may be
  skipped: the compiled dispatch produces results bit-identical to the
  probed path, and a residual trace failure still falls back to eager via
  ``dispatch_program``'s recovery (state buffers survive a trace error).
- ``DIRTY``: the scan is fully resolved and update writes an undeclared
  attribute — the probe's conclusion, known at class-definition time. The
  dispatcher can mark the fallback immediately, naming the attribute and
  source line instead of the generic probe message.
- ``UNKNOWN``: anything less than full resolution (dynamic writes,
  unresolvable helpers, source unavailable). The runtime probe keeps the
  last word, exactly as before.

``METRICS_TPU_ANALYSIS_PRECLASSIFY=0`` turns consultation off process-wide
(every class probes, the pre-PR behavior — the escape hatch the equality
tests use to assert bit-identical results).
"""
import ast
import inspect
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from metrics_tpu.analysis.metric_pass import (
    RUNTIME_EXEMPT_ATTRS,
    AttrWrite,
    BodyScan,
    ClassInfo,
    Universe,
    scan_entry,
)
from metrics_tpu.analysis.report import Finding, filter_findings, parse_suppressions

#: Env escape hatch: 0/false/off disables static probe pre-classification
#: (and the planner's static-hazard screen) process-wide.
PRECLASSIFY_ENV = "METRICS_TPU_ANALYSIS_PRECLASSIFY"


def preclassify_enabled() -> bool:
    return os.environ.get(PRECLASSIFY_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


@dataclass
class ClassVerdict:
    """Cached static analysis of one live class's update/compute/merge bodies.

    Kinds: ``"update"`` (compiled update traces ``pure_update``),
    ``"compute"`` (compiled forward adds the batch-local ``pure_compute``)
    and ``"merge"`` (compiled forward also traces ``merge_states``).
    """

    resolved_update: bool = False
    resolved_compute: bool = False
    resolved_merge: bool = False
    #: every self-attr write reachable from update / compute, with locations
    update_writes: List[AttrWrite] = field(default_factory=list)
    compute_writes: List[AttrWrite] = field(default_factory=list)
    #: self-attr writes reachable from merge_states: the compiled forward
    #: runs the merge functionally on state dicts, so ANY instance write
    #: there (declared or not) would be skipped — demotes to "unknown"
    merge_writes: List[AttrWrite] = field(default_factory=list)
    #: per-kind: self attrs (or aliases) passed into non-pure callees —
    #: demote when the live value is a mutable container
    leaked: Dict[str, List[str]] = field(default_factory=dict)
    #: host-sync findings from the update scan and the merge_states scan
    host_syncs: List[Finding] = field(default_factory=list)
    merge_host_syncs: List[Finding] = field(default_factory=list)
    #: per-kind demotion signals: traced-value branches (legal eager,
    #: ConcretizationTypeError under tracing) and compute-side host syncs —
    #: never "dirty" (eager semantics are fine), but the eval_shape probe
    #: must keep the last word, so "clean" demotes to "unknown"
    demotions: Dict[str, int] = field(default_factory=dict)
    path: str = ""

    def undeclared_writes(
        self, declared: Set[str], kinds: Tuple[str, ...] = ("update",)
    ) -> List[AttrWrite]:
        out: List[AttrWrite] = []
        seen: Set[Tuple[str, int]] = set()
        for kind in kinds:
            if kind == "merge":
                continue  # merge_states is scanned for host syncs only
            for w in self.update_writes if kind == "update" else self.compute_writes:
                if w.attr in declared or w.attr in RUNTIME_EXEMPT_ATTRS or w.attr.startswith("__"):
                    continue
                key = (w.attr, w.line)
                if key not in seen:
                    seen.add(key)
                    out.append(w)
        return out

    def sync_findings(self, kinds: Tuple[str, ...]) -> List[Finding]:
        out: List[Finding] = []
        if "update" in kinds:
            out.extend(self.host_syncs)
        if "merge" in kinds:
            out.extend(self.merge_host_syncs)
        return out

    def resolved(self, kinds: Tuple[str, ...]) -> bool:
        by_kind = {
            "update": self.resolved_update,
            "compute": self.resolved_compute,
            "merge": self.resolved_merge,
        }
        return all(by_kind[k] for k in kinds)


_verdicts: Dict[type, Optional[ClassVerdict]] = {}
_module_universes: Dict[Tuple[str, ...], Tuple[Universe, Dict[Tuple[str, str], ClassInfo]]] = {}


def clear_cache() -> None:
    """Test hook: forget every cached verdict and parsed module."""
    _verdicts.clear()
    _module_universes.clear()


def _mro_universe(cls: type):
    """Parse the modules of every class on ``cls``'s MRO (the runtime MRO,
    not the textual approximation) into one Universe, and index each class
    by (source path, qualname)."""
    paths: List[str] = []
    for c in cls.__mro__:
        if c is object:
            continue
        try:
            path = inspect.getsourcefile(c)
        except TypeError:
            return None
        if path is None:
            return None
        if path not in paths:
            paths.append(path)
    key = tuple(paths)
    cached = _module_universes.get(key)
    if cached is not None:
        return cached
    universe = Universe()
    index: Dict[Tuple[str, str], ClassInfo] = {}
    for path in paths:
        try:
            with open(path, "r") as fh:
                source = fh.read()
            tree = ast.parse(source)
        except (OSError, SyntaxError):
            return None
        for ci in universe.add_module(tree, path):
            index[(path, ci.qualname)] = ci
    _module_universes[key] = (universe, index)
    return universe, index


def _class_info_for(cls: type, universe_index) -> Optional[ClassInfo]:
    universe, index = universe_index
    try:
        path = inspect.getsourcefile(cls)
    except TypeError:
        return None
    if path is None:
        return None
    return index.get((path, cls.__qualname__))


def class_verdict(cls: type) -> Optional[ClassVerdict]:
    """The cached static verdict for ``cls`` (None = source unavailable)."""
    if cls in _verdicts:
        return _verdicts[cls]
    verdict = _build_verdict(cls)
    _verdicts[cls] = verdict
    return verdict


def _build_verdict(cls: type) -> Optional[ClassVerdict]:
    uni = _mro_universe(cls)
    if uni is None:
        return None
    universe, _ = uni
    ci = _class_info_for(cls, uni)
    if ci is None:
        return None
    v = ClassVerdict(path=ci.path)
    # chain state names feed only the host-sync taint seeds; the declared
    # set the caller checks writes against comes from the live instance
    state_names: Set[str] = set()
    for c in universe.chain(ci):
        state_names |= c.state_names
    sources: Dict[str, str] = {}
    for kind in ("update", "compute", "merge_states"):
        scan = scan_entry(universe, ci, kind, state_names)
        if scan is None:
            # no visible definition anywhere on the MRO sources: stay unknown
            continue
        suppressed = _apply_suppressions(scan, sources)
        # conservative rescan: every parameter treated as traced, so a host
        # sync / value branch on an UNANNOTATED array input demotes "clean"
        # to "unknown" (the eval_shape probe then decides, as before)
        cons = scan_entry(universe, ci, kind, state_names, seed_all_params=True)
        branches = [
            vb for vb in cons.value_branches
            if not _branch_suppressed(vb, sources)
        ]
        demote = len(branches) + len(_apply_suppressions(cons, sources).host_syncs)
        if kind == "update":
            v.resolved_update = scan.resolved
            v.update_writes = suppressed.writes
            v.host_syncs = suppressed.host_syncs
            # annotation-confirmed syncs are DIRTY; conservative extras demote
            v.demotions["update"] = demote - len(suppressed.host_syncs)
            v.leaked["update"] = list(scan.leaked)
        elif kind == "compute":
            v.resolved_compute = scan.resolved
            v.compute_writes = suppressed.writes
            v.demotions["compute"] = demote
            v.leaked["compute"] = list(scan.leaked)
        else:
            v.resolved_merge = scan.resolved
            v.merge_host_syncs = suppressed.host_syncs
            v.merge_writes = [
                w for w in scan.writes
                if w.attr not in RUNTIME_EXEMPT_ATTRS and not w.attr.startswith("__")
            ]
            v.demotions["merge"] = demote - len(suppressed.host_syncs)
            v.leaked["merge"] = list(scan.leaked)
    return v


def _apply_suppressions(scan: BodyScan, sources: Dict[str, str]) -> BodyScan:
    """Honor ``# metricslint: disable=...`` comments for runtime consumers
    too: a suppressed finding must not flip a class to DIRTY (the CLI and
    the probe must agree on what counts)."""
    out = BodyScan(resolved=scan.resolved)
    for w in scan.writes:
        # writes carry no rule yet — they become undeclared-state /
        # unshared-latch depending on the consumer; honor either suppression
        src = _read_source(w.path, sources) if w.path else None
        if src is not None:
            sup = parse_suppressions(src)
            if sup.suppressed("undeclared-state", w.line) or sup.suppressed("unshared-latch", w.line):
                continue
        out.writes.append(w)
    if scan.host_syncs:
        by_path: Dict[str, List[Finding]] = {}
        for f in scan.host_syncs:
            by_path.setdefault(f.path, []).append(f)
        for path, fs in by_path.items():
            src = _read_source(path, sources)
            out.host_syncs.extend(fs if src is None else filter_findings(fs, src))
    return out


def _branch_suppressed(vb, sources: Dict[str, str]) -> bool:
    """A traced-value branch on a line carrying a host-sync suppression is
    waived (the ``is_traced``-guarded ``bool()`` in ``Metric.merge_states``
    is the canonical case: the comment vouches the branch never sees a
    tracer), keeping the CLI and the runtime verdict in agreement."""
    line, _owner, path = vb
    src = _read_source(path, sources) if path else None
    if src is None:
        return False
    return parse_suppressions(src).suppressed("host-sync-in-update", line)


def _read_source(path: str, sources: Dict[str, str]) -> Optional[str]:
    if path not in sources:
        try:
            with open(path, "r") as fh:
                sources[path] = fh.read()
        except OSError:
            return None
    return sources[path]


# ---------------------------------------------------------------------------
# probe pre-classification (core/compiled.py / core/metric.py)
# ---------------------------------------------------------------------------

def static_probe_verdict(metric, kinds: Tuple[str, ...]) -> Tuple[str, Optional[str]]:
    """Pre-classify one metric instance for the compiled-eligibility probe.

    Returns ``(verdict, detail)`` where verdict is:

    - ``"clean"`` — statically verified: skip the ``jax.eval_shape`` probe.
    - ``"dirty"`` — statically refuted: ``detail`` names the offending
      attribute(s) and source line(s); mark the fallback without probing.
    - ``"unknown"`` — run the probe, as before pre-classification existed.

    ``kinds`` is ``("update",)`` for compiled update and
    ``("update", "compute", "merge")`` for compiled forward (whose program
    also traces the batch-local compute and the ``merge_states`` fold).
    """
    if not preclassify_enabled():
        return "unknown", None
    cls = type(metric)
    v = class_verdict(cls)
    if v is None or not v.resolved(kinds):
        return "unknown", None
    declared = set(getattr(metric, "_defaults", ())) | set(
        getattr(cls, "_group_shared_attrs", ()) or ()
    )
    bad = v.undeclared_writes(declared, kinds)
    if bad:
        spots = ", ".join(
            f"self.{w.attr} ({_short(v.path)}:{w.line}, {w.owner})" for w in bad[:4]
        )
        return (
            "dirty",
            f"update mutates undeclared instance attribute(s): {spots} — "
            "statically flagged by metricslint (undeclared-state); declare the "
            "attr with add_state or list it in _group_shared_attrs",
        )
    syncs = v.sync_findings(kinds)
    if syncs:
        f = syncs[0]
        return (
            "dirty",
            f"the traced path forces a host sync on a traced value "
            f"({_short(f.path)}:{f.line}, {f.owner}) — statically flagged by "
            "metricslint (host-sync-in-update); it would fail tracing anyway",
        )
    if any(v.demotions.get(k, 0) for k in kinds):
        # a traced-value python branch (or a compute-side host sync) is fine
        # eagerly but concretizes under tracing — let the probe decide
        return "unknown", None
    if "merge" in kinds and v.merge_writes:
        # the compiled forward runs merge_states functionally on state
        # dicts: ANY instance write there (even to a declared state) would
        # be skipped by the replay — the probe must decide
        return "unknown", None
    for kind in kinds:
        for attr in v.leaked.get(kind, ()):
            value = getattr(metric, attr, None)
            if not isinstance(value, (str, int, float, bool, bytes, tuple, frozenset, type(None))) and not (
                hasattr(value, "dtype") and hasattr(value, "shape")
            ):
                # a mutable (or unknown-type) attr escaped into a callee we
                # cannot see through — an in-place mutation could hide there
                return "unknown", None
    return "clean", None


def static_probe_verdict_many(pairs) -> Tuple[str, Optional[str]]:
    """Aggregate :func:`static_probe_verdict` over ``(metric, kinds)`` pairs:
    ``dirty`` dominates (first detail), then ``unknown``, else ``clean``."""
    saw_unknown = False
    saw_any = False
    for metric, kinds in pairs:
        saw_any = True
        verdict, detail = static_probe_verdict(metric, kinds)
        if verdict == "dirty":
            return "dirty", detail
        saw_unknown = saw_unknown or verdict == "unknown"
    if not saw_any or saw_unknown:
        return "unknown", None
    return "clean", None


def _short(path: str) -> str:
    parts = path.split(os.sep)
    return os.sep.join(parts[-2:]) if len(parts) >= 2 else path


# ---------------------------------------------------------------------------
# compute-group declaration validation (core/collections.py)
# ---------------------------------------------------------------------------

def grouping_hazards(metric) -> List[str]:
    """Human-readable reasons this metric's class must not join a compute
    group, from the static report: update writes an attribute that is
    neither an ``add_state`` state nor listed in ``_group_shared_attrs``,
    so a group dispatch would not propagate it to siblings. Empty when the
    class is clean or the analysis could not fully resolve update (the
    runtime contract — declared identity — is then trusted as before)."""
    if not preclassify_enabled():
        return []
    cls = type(metric)
    v = class_verdict(cls)
    if v is None or not v.resolved(("update",)):
        return []
    declared = set(getattr(metric, "_defaults", ())) | set(
        getattr(cls, "_group_shared_attrs", ()) or ()
    )
    return [
        f"update writes self.{w.attr} ({_short(v.path)}:{w.line}, {w.owner}), "
        "which is neither an add_state state nor listed in _group_shared_attrs"
        for w in v.undeclared_writes(declared, ("update",))
    ]
