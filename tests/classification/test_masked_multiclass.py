"""masked multiclass/multilabel AUROC + AP — one-vs-rest vectorized,
static-shape (ops/ranking.py), so CatBuffer-mode multiclass curve metrics
fuse update → all_gather sync → compute into ONE jitted XLA program.

Parity references: per-class sklearn roc_auc_score / average_precision_score
composed exactly like the reference's eager multiclass paths
(``functional/classification/auroc.py:120-257``,
``average_precision.py:37-86``).
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from sklearn.metrics import average_precision_score, roc_auc_score

from metrics_tpu import AUROC, AveragePrecision
from metrics_tpu.ops.ranking import (
    masked_multiclass_auroc,
    masked_multiclass_average_precision,
    masked_multilabel_auroc,
)

rng = np.random.RandomState(33)
NUM_CLASSES = 5


def _mc_data(n, num_classes=NUM_CLASSES, quantized=False):
    p = rng.rand(n, num_classes).astype(np.float32)
    if quantized:  # heavy ties
        p = np.round(p * 4) / 4.0
    p = p / p.sum(1, keepdims=True)
    t = rng.randint(0, num_classes, n)
    return p, t


def _sk_ovr_auroc(p, t, average, num_classes=NUM_CLASSES):
    scores = np.array([roc_auc_score((t == c).astype(int), p[:, c]) for c in range(num_classes)])
    if average is None:
        return scores
    if average == "macro":
        return scores.mean()
    support = np.bincount(t, minlength=num_classes)
    return (scores * support).sum() / support.sum()


@pytest.mark.parametrize("average", [None, "macro", "weighted"])
@pytest.mark.parametrize("quantized", [False, True])
def test_multiclass_auroc_parity(average, quantized):
    p, t = _mc_data(400, quantized=quantized)
    got = np.asarray(masked_multiclass_auroc(jnp.asarray(p), jnp.asarray(t), average=average))
    np.testing.assert_allclose(got, _sk_ovr_auroc(p, t, average), atol=1e-6)


def test_multiclass_auroc_mask_equals_slice():
    p, t = _mc_data(300)
    mask = np.arange(300) < 120
    got = float(
        masked_multiclass_auroc(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask), "macro")
    )
    np.testing.assert_allclose(got, _sk_ovr_auroc(p[:120], t[:120], "macro"), atol=1e-6)


def test_multiclass_auroc_weighted_drops_unobserved_class():
    """A class with zero support contributes nothing under `weighted` —
    the static-shape analogue of the reference's column drop."""
    p, t = _mc_data(200, num_classes=4)
    t = np.where(t == 3, 0, t)  # class 3 never observed
    got = float(
        masked_multiclass_auroc(jnp.asarray(p), jnp.asarray(t), average="weighted")
    )
    scores = [roc_auc_score((t == c).astype(int), p[:, c]) for c in range(3)]
    support = np.bincount(t, minlength=4)[:3]
    np.testing.assert_allclose(got, (scores * support).sum() / support.sum(), atol=1e-6)


@pytest.mark.parametrize("average", [None, "macro", "weighted", "micro"])
def test_multilabel_auroc_parity(average):
    n, c = 300, 4
    p = rng.rand(n, c).astype(np.float32)
    t = rng.randint(0, 2, (n, c))
    got = np.asarray(
        masked_multilabel_auroc(jnp.asarray(p), jnp.asarray(t), average=average)
    )
    if average == "micro":
        exp = roc_auc_score(t.reshape(-1), p.reshape(-1))
    else:
        scores = np.array([roc_auc_score(t[:, i], p[:, i]) for i in range(c)])
        if average is None:
            exp = scores
        elif average == "macro":
            exp = scores.mean()
        else:
            support = t.sum(0)
            exp = (scores * support).sum() / support.sum()
    np.testing.assert_allclose(got, exp, atol=1e-6)


@pytest.mark.parametrize("average", [None, "macro", "weighted"])
def test_multiclass_average_precision_parity(average):
    p, t = _mc_data(400)
    got = np.asarray(
        masked_multiclass_average_precision(jnp.asarray(p), jnp.asarray(t), average=average)
    )
    scores = np.array(
        [average_precision_score((t == c).astype(int), p[:, c]) for c in range(NUM_CLASSES)]
    )
    if average is None:
        exp = scores
    elif average == "macro":
        exp = scores.mean()
    else:
        support = np.bincount(t, minlength=NUM_CLASSES)
        exp = (scores * support / support.sum()).sum()
    np.testing.assert_allclose(got, exp, atol=1e-6)


def test_multiclass_ap_nan_class_excluded_from_macro():
    """No valid positives for a class → per-class NaN, excluded from macro
    (reference `_average_precision_compute_with_precision_recall` nan-filter)."""
    p, t = _mc_data(200, num_classes=4)
    t = np.where(t == 2, 1, t)  # class 2 unobserved
    got_vec = np.asarray(
        masked_multiclass_average_precision(jnp.asarray(p), jnp.asarray(t), average=None)
    )
    assert np.isnan(got_vec[2]) and not np.isnan(np.delete(got_vec, 2)).any()
    got = float(
        masked_multiclass_average_precision(jnp.asarray(p), jnp.asarray(t), average="macro")
    )
    exp = np.nanmean(
        [average_precision_score((t == c).astype(int), p[:, c]) if (t == c).any() else np.nan
         for c in range(4)]
    )
    np.testing.assert_allclose(got, exp, atol=1e-6)


# ---------------------------------------------------------------------------
# module (CatBuffer) integration
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("average", ["macro", "weighted"])
def test_catbuffer_multiclass_auroc_matches_list_mode(average):
    p, t = _mc_data(10 * 32)
    p, t = p.reshape(10, 32, NUM_CLASSES), t.reshape(10, 32)
    m_list = AUROC(num_classes=NUM_CLASSES, average=average)
    m_cb = AUROC(num_classes=NUM_CLASSES, average=average).with_capacity(512)
    for i in range(10):
        m_list.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
        m_cb.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    np.testing.assert_allclose(float(m_cb.compute()), float(m_list.compute()), atol=1e-6)
    np.testing.assert_allclose(
        float(m_cb.compute()),
        _sk_ovr_auroc(p.reshape(-1, NUM_CLASSES), t.reshape(-1), average),
        atol=1e-6,
    )


def test_catbuffer_multiclass_ap_matches_sklearn():
    p, t = _mc_data(8 * 32)
    p, t = p.reshape(8, 32, NUM_CLASSES), t.reshape(8, 32)
    m_cb = AveragePrecision(num_classes=NUM_CLASSES, average="macro").with_capacity(512)
    for i in range(8):
        m_cb.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    flat_p, flat_t = p.reshape(-1, NUM_CLASSES), t.reshape(-1)
    exp = np.mean(
        [average_precision_score((flat_t == c).astype(int), flat_p[:, c])
         for c in range(NUM_CLASSES)]
    )
    np.testing.assert_allclose(float(m_cb.compute()), exp, atol=1e-6)


def test_catbuffer_multiclass_ap_average_none_returns_list():
    """Return type must not flip with with_capacity(): eager returns a
    per-class list, so the CatBuffer path does too."""
    p, t = _mc_data(64)
    m = AveragePrecision(num_classes=NUM_CLASSES, average=None).with_capacity(64)
    m.update(jnp.asarray(p), jnp.asarray(t))
    res = m.compute()
    assert isinstance(res, list) and len(res) == NUM_CLASSES
    exp = [average_precision_score((t == c).astype(int), p[:, c]) for c in range(NUM_CLASSES)]
    np.testing.assert_allclose([float(r) for r in res], exp, atol=1e-6)


def test_fused_multiclass_auroc_jitted():
    """update + compute both trace — the whole pipeline is one XLA program."""
    m = AUROC(num_classes=NUM_CLASSES).with_capacity(320)
    p, t = _mc_data(10 * 32)
    p, t = p.reshape(10, 32, NUM_CLASSES), t.reshape(10, 32)
    m.update(jnp.asarray(p[0]), jnp.asarray(t[0]))
    m.reset()
    step = jax.jit(m.pure_update)
    state = m.init_state()
    for i in range(10):
        state = step(state, jnp.asarray(p[i]), jnp.asarray(t[i]))
    val = jax.jit(m.pure_compute)(state)
    np.testing.assert_allclose(
        float(val), _sk_ovr_auroc(p.reshape(-1, NUM_CLASSES), t.reshape(-1), "macro"),
        atol=1e-6,
    )


def test_fully_fused_sharded_multiclass_pipeline():
    """Multiclass CatBuffer AUROC: per-device update, all_gather sync,
    vmapped one-vs-rest compute — ONE jitted program over the mesh."""
    world, per_rank, bs = 4, 2, 32
    p, t = _mc_data(world * per_rank * bs)
    p = p.reshape(world, per_rank, bs, NUM_CLASSES)
    t = t.reshape(world, per_rank, bs)
    mesh = Mesh(np.array(jax.devices()[:world]), ("dp",))
    m = AUROC(num_classes=NUM_CLASSES).with_capacity(per_rank * bs)
    m.update(jnp.asarray(p[0, 0]), jnp.asarray(t[0, 0]))
    m.reset()

    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def fused(p_sh, t_sh):
        st = m.init_state()
        for i in range(per_rank):
            st = m.pure_update(st, p_sh[0, i], t_sh[0, i])
        synced = m.pure_sync(st, "dp")
        return m.pure_compute(synced)

    out = jax.jit(fused)(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(
        float(out), _sk_ovr_auroc(p.reshape(-1, NUM_CLASSES), t.reshape(-1), "macro"),
        atol=1e-6,
    )
