"""MetricTester — the central test fixture.

Mirror of the reference's harness (`tests/helpers/testers.py:291-512`) adapted
to JAX:

- *DDP simulation*: instead of a 2-process gloo pool, each simulated rank gets
  its own metric instance fed rank-strided batches; states are combined two
  ways — (a) ``merge_states`` (the host/merge path) and (b) a real
  ``shard_map`` over a virtual device mesh with in-jit collectives
  (``pure_sync``) — both asserted against the reference metric on ALL data.
- *jit gate*: the scriptability analogue (`testers.py:154-155`) — the metric's
  pure update/compute must trace under ``jax.jit`` (skipped for metrics whose
  update is inherently host-side, e.g. text metrics).
- *pickle round-trip* (`testers.py:163-165`).
"""
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu.core.metric import Metric

NUM_PROCESSES = 2
NUM_BATCHES = 10
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5


def _assert_allclose(tm_result: Any, sk_result: Any, atol: float = 1e-8) -> None:
    """Recursively assert closeness of metric output vs reference output."""
    if isinstance(tm_result, dict):
        for k in tm_result:
            _assert_allclose(tm_result[k], sk_result[k], atol=atol)
        return
    np.testing.assert_allclose(
        np.asarray(tm_result, dtype=np.float64),
        np.asarray(sk_result, dtype=np.float64),
        atol=atol,
        rtol=1e-5,
    )


def _sort_rows(arr: np.ndarray) -> np.ndarray:
    """Canonical leading-axis order: rows sorted lexicographically."""
    flat = arr.reshape(arr.shape[0], -1)
    return arr[np.lexsort(flat.T[::-1])]


def _assert_allclose_any_row_order(tm_result: Any, sk_result: Any, atol: float = 1e-8) -> None:
    """Row-multiset closeness: per-SAMPLE outputs merged across ddp ranks
    come back rank-permuted (ranks hold strided batches), which is a
    reordering, not an error — a ddp gather has no canonical row order.
    Both sides are sorted into a canonical order before comparing, so
    values must still match one-to-one."""
    ours = np.asarray(tm_result, dtype=np.float64)
    ref = np.asarray(sk_result, dtype=np.float64)
    assert ours.shape == ref.shape, (ours.shape, ref.shape)
    np.testing.assert_allclose(_sort_rows(ours), _sort_rows(ref), atol=atol, rtol=1e-5)


def _pickle_roundtrip(metric: Metric) -> Metric:
    import pickle

    return pickle.loads(pickle.dumps(metric))


def _concat_rank_data(x: np.ndarray, world: int, rank: int) -> np.ndarray:
    """Batches strided by rank, concatenated (reference `testers.py:167`)."""
    return np.concatenate([x[i] for i in range(rank, x.shape[0], world)], axis=0)


def _gather_states(states: Sequence[Dict[str, Any]], reductions: Dict[str, Any]) -> Dict[str, Any]:
    """Rank-ordered gather+reduce of per-rank state dicts — the tester's
    stand-in for the reference's ``gather_all_tensors`` + reduction
    (``metric.py:217-242``). Used as an injected ``dist_sync_fn``."""
    from metrics_tpu.core.cat_buffer import CatBuffer

    out: Dict[str, Any] = {}
    for name, red in reductions.items():
        vals = [s[name] for s in states]
        if isinstance(vals[0], CatBuffer) and all(isinstance(v, CatBuffer) for v in vals):
            # fixed-capacity cat state on every rank
            gathered = CatBuffer(sum(v.capacity for v in vals))
            for v in vals:
                gathered = gathered.merge(v)
            out[name] = gathered
        elif isinstance(vals[0], (list, CatBuffer)):
            # cat states, possibly mixed: forward's batch state for a
            # CatBuffer metric is a plain per-batch list (O(batch) updates,
            # `core/metric.py` forward docstring) while other ranks hand over
            # CatBuffers — flatten everything to one rank-ordered chunk list
            chunks: list = []
            for v in vals:
                if isinstance(v, CatBuffer):
                    chunks.append(v.values())
                else:
                    chunks.extend(v)
            out[name] = chunks
        elif red == "sum":
            out[name] = sum(vals[1:], vals[0])
        elif red == "mean":
            out[name] = sum(vals[1:], vals[0]) / len(vals)
        elif red == "min":
            out[name] = jnp.min(jnp.stack([jnp.asarray(v) for v in vals]), axis=0)
        elif red == "max":
            out[name] = jnp.max(jnp.stack([jnp.asarray(v) for v in vals]), axis=0)
        elif red == "cat":
            out[name] = jnp.concatenate([jnp.asarray(v) for v in vals], axis=0)
        elif callable(red):
            out[name] = red(jnp.stack([jnp.asarray(v) for v in vals]))
        elif red is None:
            out[name] = list(vals)
        else:
            raise NotImplementedError(f"_gather_states: unsupported reduction {red!r}")
    return out


def _with_static_num_classes(
    metric_class: type, metric_args: dict, preds: np.ndarray, target: np.ndarray
) -> dict:
    """Add `num_classes` for label-valued inputs so formatting is jit-static.

    Data-dependent num_classes inference is eager-only; under jit a real user
    must pass it — the jitted test paths mirror that.
    """
    if (
        "num_classes" not in metric_args
        and np.issubdtype(np.asarray(preds).dtype, np.integer)
        and np.issubdtype(np.asarray(target).dtype, np.integer)
    ):
        nc = int(max(np.max(preds), np.max(target))) + 1
        try:
            candidate = {**metric_args, "num_classes": nc}
            metric_class(**candidate)
            return candidate
        except (TypeError, ValueError):
            pass
    return metric_args


class MetricTester:
    """Base tester; subclass per domain, call run_* from parametrized tests."""

    atol: float = 1e-8

    def run_functional_metric_test(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        metric_functional: Callable,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        **kwargs_update: Any,
    ) -> None:
        """Functional parity on single batches (reference `_functional_test`)."""
        metric_args = metric_args or {}
        for i in range(NUM_BATCHES):
            tm_result = metric_functional(
                jnp.asarray(preds[i]), jnp.asarray(target[i]), **metric_args, **kwargs_update
            )
            sk_result = sk_metric(preds[i], target[i])
            _assert_allclose(tm_result, sk_result, atol=self.atol)

    def run_class_metric_test(
        self,
        ddp: bool,
        preds: np.ndarray,
        target: np.ndarray,
        metric_class: type,
        sk_metric: Callable,
        dist_sync_on_step: bool = False,
        metric_args: Optional[dict] = None,
        check_dist_sync_on_step: bool = True,
        check_batch: bool = True,
        check_jit: bool = True,
        check_merge: bool = True,
        row_order_invariant: bool = False,
        **kwargs_update: Any,
    ) -> None:
        """Class-metric parity: accumulate over batches, compare vs reference.

        With ``ddp=True`` simulates NUM_PROCESSES ranks via rank-strided
        batches + state merge, then (optionally) re-checks through a real
        shard_map collective in `run_sharded_metric_test`-style.

        ``row_order_invariant=True`` compares the final ddp-merged result as
        a row multiset (sorted canonical order) — for per-sample outputs,
        whose merged row order legitimately depends on rank layout.
        """
        metric_args = metric_args or {}
        world = NUM_PROCESSES if ddp else 1

        metrics = [
            metric_class(**metric_args, dist_sync_on_step=dist_sync_on_step)
            for _ in range(world)
        ]
        # pickle gate (reference testers.py:163-165)
        metrics[0] = _pickle_roundtrip(metrics[0])

        if ddp and dist_sync_on_step:
            # per-step sync semantics (reference testers.py:172-181): every
            # rank's forward at step s must equal the reference on the
            # concatenation of ALL ranks' step-s batches. Each rank's
            # dist_sync_fn gathers the other ranks' batch states in rank order.
            assert NUM_BATCHES % world == 0
            for i in range(0, NUM_BATCHES, world):
                kw_i = lambda j: {k: jnp.asarray(v[j]) for k, v in kwargs_update.items()}  # noqa: E731
                batch_states = []
                for r in range(world):
                    scratch = metric_class(**metric_args)
                    scratch.update(jnp.asarray(preds[i + r]), jnp.asarray(target[i + r]), **kw_i(i + r))
                    batch_states.append(dict(scratch._state))
                for r in range(world):
                    m = metrics[r]

                    def gather(state, reductions, _r=r):
                        ordered = [
                            state if r2 == _r else batch_states[r2] for r2 in range(world)
                        ]
                        return _gather_states(ordered, reductions)

                    m.dist_sync_fn = gather
                    m.distributed_available_fn = lambda: True
                    batch_result = m(
                        jnp.asarray(preds[i + r]), jnp.asarray(target[i + r]), **kw_i(i + r)
                    )
                    if check_dist_sync_on_step:
                        group_preds = np.concatenate([preds[i + r2] for r2 in range(world)], axis=0)
                        group_target = np.concatenate([target[i + r2] for r2 in range(world)], axis=0)
                        _assert_allclose(batch_result, sk_metric(group_preds, group_target), atol=self.atol)
            for m in metrics:  # final compute below uses the merge path
                m.dist_sync_fn = None
                m.distributed_available_fn = lambda: False
        else:
            for i in range(NUM_BATCHES):
                rank = i % world
                batch_result = metrics[rank](
                    jnp.asarray(preds[i]), jnp.asarray(target[i]), **{k: jnp.asarray(v[i]) for k, v in kwargs_update.items()}
                )
                if check_batch and not dist_sync_on_step:
                    sk_batch_result = sk_metric(preds[i], target[i])
                    _assert_allclose(batch_result, sk_batch_result, atol=self.atol)

        total_preds = np.concatenate([preds[i] for i in range(NUM_BATCHES)], axis=0)
        total_target = np.concatenate([target[i] for i in range(NUM_BATCHES)], axis=0)
        sk_result = sk_metric(total_preds, total_target)

        if world == 1:
            _assert_allclose(metrics[0].compute(), sk_result, atol=self.atol)
        elif check_merge:
            merged = metrics[0]
            for m in metrics[1:]:
                merged.merge_state(m)
            if row_order_invariant:
                _assert_allclose_any_row_order(merged.compute(), sk_result, atol=self.atol)
            else:
                _assert_allclose(merged.compute(), sk_result, atol=self.atol)

        if check_jit and not ddp:
            self._run_jit_gate(metric_class, preds, target, metric_args, **kwargs_update)

    def _run_jit_gate(
        self,
        metric_class: type,
        preds: np.ndarray,
        target: np.ndarray,
        metric_args: dict,
        **kwargs_update: Any,
    ) -> None:
        """The metric's pure update+compute must trace under jax.jit."""
        metric_args = _with_static_num_classes(metric_class, metric_args, preds, target)
        metric = metric_class(**metric_args)
        # warm the python-side case detection with one eager batch so static
        # config (e.g. Accuracy.mode) is known before tracing
        metric.update(jnp.asarray(preds[0]), jnp.asarray(target[0]),
                      **{k: jnp.asarray(v[0]) for k, v in kwargs_update.items()})
        metric.reset()

        step = jax.jit(metric.pure_update)
        state = metric.init_state()
        has_list_state = any(isinstance(v, list) for v in state.values())
        if has_list_state:
            # list-states retrace as they grow; jit a single-batch step only
            state = step(state, jnp.asarray(preds[0]), jnp.asarray(target[0]),
                         **{k: jnp.asarray(v[0]) for k, v in kwargs_update.items()})
        else:
            for i in range(2):
                state = step(state, jnp.asarray(preds[i]), jnp.asarray(target[i]),
                             **{k: jnp.asarray(v[i]) for k, v in kwargs_update.items()})
        metric.pure_compute(state)  # must not raise

    def run_precision_test(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        metric_class: type,
        metric_functional: Optional[Callable] = None,
        metric_args: Optional[dict] = None,
        dtype=jnp.bfloat16,
        atol: float = 1e-2,
        **kwargs_update: Any,
    ) -> None:
        """Half-precision axis (reference ``run_precision_test_cpu/_gpu``,
        `testers.py:431-477`): the metric must accept bf16/f16 float inputs and
        produce a finite value close to the float32 result. bf16 is the TPU-
        native half type (MXU accumulates in f32), so it is the default here.
        """
        metric_args = metric_args or {}

        def cast(x: np.ndarray):
            arr = jnp.asarray(x)
            return arr.astype(dtype) if jnp.issubdtype(arr.dtype, jnp.floating) else arr

        m_full = metric_class(**metric_args)
        m_half = metric_class(**metric_args)
        for i in range(2):
            m_full.update(jnp.asarray(preds[i]), jnp.asarray(target[i]),
                          **{k: jnp.asarray(v[i]) for k, v in kwargs_update.items()})
            m_half.update(cast(preds[i]), cast(target[i]),
                          **{k: cast(v[i]) for k, v in kwargs_update.items()})
        full = np.asarray(m_full.compute(), dtype=np.float64)
        half = np.asarray(jnp.asarray(m_half.compute(), dtype=jnp.float32), dtype=np.float64)
        assert np.all(np.isfinite(half)), "half-precision compute produced non-finite values"
        np.testing.assert_allclose(half, full, atol=atol, rtol=5e-2)

        if metric_functional is not None:
            f_half = metric_functional(cast(preds[0]), cast(target[0]), **metric_args)
            assert np.all(np.isfinite(np.asarray(jnp.asarray(f_half, dtype=jnp.float32)))), (
                "half-precision functional produced non-finite values"
            )

    def run_differentiability_test(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        metric_class: type,
        metric_functional: Optional[Callable] = None,
        metric_args: Optional[dict] = None,
    ) -> None:
        """Differentiability axis (reference ``run_differentiability_test`` +
        ``torch.autograd.gradcheck``, `testers.py:479-509`).

        JAX computes a gradient for any float function, so the declared
        ``is_differentiable`` flag is checked *semantically*:

        - ``True``  → ``jax.grad`` w.r.t. preds is finite, somewhere nonzero,
          and matches a central finite difference along a random direction
          (the gradcheck analogue, run in x64).
        - ``False`` → the metric is piecewise constant in preds (argmax/
          threshold based): the gradient is identically zero.
        """
        metric_args = metric_args or {}
        metric = metric_class(**metric_args)
        p0 = np.asarray(preds[0])
        if not np.issubdtype(p0.dtype, np.floating) or metric.is_differentiable is None:
            return
        t0 = jnp.asarray(target[0])

        if metric_functional is not None:
            fn = metric_functional
        else:
            # class-based fallback: warm the eager input-mode detection once so
            # the pure path traces with static config under jax.grad
            warm = metric_class(**metric_args)
            warm.update(jnp.asarray(p0), t0)
            warm.reset()

            def fn(p, t, **kw):
                return warm.pure_compute(warm.pure_update(warm.init_state(), p, t))

        def scalar_fn(p):
            out = fn(p, t0, **metric_args)
            leaves = jax.tree_util.tree_leaves(out)
            return sum(jnp.sum(leaf) for leaf in leaves if jnp.issubdtype(leaf.dtype, jnp.floating))

        grad = jax.grad(scalar_fn)(jnp.asarray(p0))
        assert np.all(np.isfinite(np.asarray(grad))), "gradient has non-finite entries"

        if metric.is_differentiable:
            assert np.any(np.asarray(grad) != 0), (
                f"{metric_class.__name__} declares is_differentiable=True but "
                "grad w.r.t. preds is identically zero"
            )
            # gradcheck analogue: directional derivative vs central difference.
            # x64 is enabled for the probe; eps balances truncation error
            # against round-off for metrics that compute internally in f32
            # (a float64 input does not force every intermediate to f64).
            rng_dir = np.random.RandomState(3)
            direction = rng_dir.randn(*p0.shape)
            direction /= np.linalg.norm(direction) + 1e-12
            eps = 1e-4
            with jax.enable_x64():
                p64 = np.asarray(p0, dtype=np.float64)
                f_plus = float(scalar_fn(jnp.asarray(p64 + eps * direction)))
                f_minus = float(scalar_fn(jnp.asarray(p64 - eps * direction)))
                grad64 = jax.grad(scalar_fn)(jnp.asarray(p64))
            fd = (f_plus - f_minus) / (2 * eps)
            analytic = float(np.sum(np.asarray(grad64, dtype=np.float64) * direction))
            np.testing.assert_allclose(analytic, fd, rtol=2e-2, atol=1e-4)
        else:
            assert not np.any(np.asarray(grad) != 0), (
                f"{metric_class.__name__} declares is_differentiable=False but "
                "has a nonzero gradient w.r.t. preds"
            )

    def run_sharded_metric_test(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        metric_class: type,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        world: int = 2,
        **kwargs_update: Any,
    ) -> None:
        """The REAL distributed path: shard_map over a virtual mesh.

        Each device runs pure_update on its shard of batches, then pure_sync
        (psum / all_gather collectives over the mesh axis) + pure_compute —
        all inside ONE jitted program. Result must equal the reference on all
        data, on every device.
        """
        metric_args = metric_args or {}
        assert NUM_BATCHES % world == 0
        per_rank = NUM_BATCHES // world

        metric_args = _with_static_num_classes(metric_class, metric_args, preds, target)
        metric = metric_class(**metric_args)
        # warm python-side static config (e.g. input mode) eagerly
        metric.update(jnp.asarray(preds[0]), jnp.asarray(target[0]),
                      **{k: jnp.asarray(v[0]) for k, v in kwargs_update.items()})
        metric.reset()

        devices = np.array(jax.devices()[:world])
        mesh = Mesh(devices, axis_names=("dp",))

        p_sh = stride_by_rank(preds, world)
        t_sh = stride_by_rank(target, world)
        kw_sh = {k: stride_by_rank(np.asarray(v), world) for k, v in kwargs_update.items()}

        # metrics with only fixed-shape states run the FULL fused pipeline
        # (update + collectives + compute) inside the traced program; cat-state
        # metrics return the synced state and compute eagerly, since their
        # compute is dynamic-shape by design (curves)
        fused_compute = not any(isinstance(v, list) for v in metric.init_state().values())

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P("dp"), P("dp")) + tuple(P("dp") for _ in kw_sh),
            out_specs=P(),
            check_vma=False,  # all_gather'd cat-states are replicated, but the
            # static varying-axes check can't always infer it
        )
        def sharded_eval(p, t, *kws):
            state = metric.init_state()
            for i in range(per_rank):
                state = metric.pure_update(
                    state, p[0, i], t[0, i], **{k: kw[0, i] for k, kw in zip(kw_sh, kws)}
                )
            synced = metric.pure_sync(state, "dp")
            return metric.pure_compute(synced) if fused_compute else synced

        out = sharded_eval(p_sh, t_sh, *kw_sh.values())
        result = out if fused_compute else metric.pure_compute(out)
        total_preds = np.concatenate([preds[i] for i in range(NUM_BATCHES)], axis=0)
        total_target = np.concatenate([target[i] for i in range(NUM_BATCHES)], axis=0)
        # order across ranks differs from plain concat for cat-states; reference
        # metrics used here must be permutation-invariant over samples
        sk_result = sk_metric(total_preds, total_target)
        _assert_allclose(result, sk_result, atol=self.atol)


def stride_by_rank(x: np.ndarray, world: int, num_batches: int = NUM_BATCHES) -> jnp.ndarray:
    """Rank-strided batch assignment ``[world, num_batches // world, ...]``:
    rank r gets batches r, r+world, ... (shared by `run_sharded_metric_test`
    and the sharded-collection tests)."""
    return jnp.asarray(np.stack([
        np.stack([x[i] for i in range(r, num_batches, world)]) for r in range(world)
    ]))


def accumulate_and_merge(metric_factory, preds, target, world, num_batches=NUM_BATCHES):
    """Round-robin batch updates over `world` instances, merge, compute —
    the shared merge-semantics harness for curve/binned matrices."""
    ms = [metric_factory() for _ in range(world)]
    for i in range(num_batches):
        ms[i % world].update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    merged = ms[0]
    for m in ms[1:]:
        merged.merge_state(m)
    return merged.compute()


class DummyMetric(Metric):
    name = "Dummy"

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("x", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, *args: Any, **kwargs: Any) -> None:
        pass

    def compute(self) -> Any:
        pass


class DummyListMetric(Metric):
    name = "DummyList"

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx="cat")

    def update(self, *args: Any, **kwargs: Any) -> None:
        pass

    def compute(self) -> Any:
        pass


class DummyMetricSum(DummyMetric):
    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class DummyMetricDiff(DummyMetric):
    def update(self, y):
        self.x = self.x - y

    def compute(self):
        return self.x
