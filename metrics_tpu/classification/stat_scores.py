"""StatScores module metric — stateful tp/fp/tn/fn accumulator.

Behavioral analogue of the reference's
``torchmetrics/classification/stat_scores.py:43-271``. States are sum-reduced
int32 leaves (``psum`` across the mesh) unless ``reduce='samples'`` /
``mdmc_reduce='samplewise'``, which accumulate per-batch arrays as "cat" list
states (``all_gather`` across the mesh), mirroring reference
``stat_scores.py:178-191``.
"""
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.stat_scores import (
    _stat_scores_compute,
    _stat_scores_update,
)
from metrics_tpu.utils.data import dim_zero_cat


class StatScores(Metric):
    """True/false positives/negatives and support — the shared accumulator
    the whole Accuracy/Precision/Recall/F-beta/Specificity/Hamming family
    derives from.

    Each update formats the inputs into a canonical binary ``[N, C]`` (or
    ``[N, C, X]``) layout and adds boolean-sum counters; states are int32
    "sum" leaves (``psum`` across the mesh), except under
    ``reduce="samples"`` / ``mdmc_reduce="samplewise"`` where per-batch
    arrays accumulate as "cat" states (``all_gather`` across the mesh).

    Args:
        threshold: binarization cut for binary/multilabel probabilities.
        top_k: with multiclass probabilities, one-hot the k best classes
            instead of the argmax only.
        reduce: counter granularity — ``"micro"`` keeps one global
            tp/fp/tn/fn quartet; ``"macro"`` keeps a ``[C]`` quartet per
            class; ``"samples"`` keeps a quartet per sample.
        num_classes: number of classes ``C``; required for ``"macro"``.
        ignore_index: class label whose rows/columns drop out of every
            counter.
        mdmc_reduce: multidim policy — ``"global"`` flattens the extra
            sample dimension into the batch, ``"samplewise"`` keeps a
            counter row per sample, ``None`` rejects multidim input.
        multiclass: force/forbid multiclass interpretation of ambiguous
            inputs (e.g. binary-looking int preds with ``num_classes=2``).
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    :meth:`compute` returns the counters stacked along the last axis in
    the order ``[tp, fp, tn, fn, support]`` (support = tp + fn), shaped by
    ``reduce``/``mdmc_reduce`` — e.g. ``[5]`` for micro, ``[C, 5]`` for
    macro, ``[N, 5]`` for samplewise.

    Raises:
        ValueError: unknown ``reduce``/``mdmc_reduce``, ``"macro"`` without
            ``num_classes``, multidim input without ``mdmc_reduce``, or a
            ``threshold`` outside ``(0, 1)``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import StatScores
        >>> preds = jnp.asarray([1, 0, 1, 1])
        >>> target = jnp.asarray([1, 0, 0, 1])
        >>> stat_scores = StatScores(reduce="micro", num_classes=2)
        >>> print(stat_scores(preds, target).tolist())  # tp, fp, tn, fn, support
        [3, 1, 3, 1, 4]
        >>> per_class = StatScores(reduce="macro", num_classes=2)
        >>> print(per_class(preds, target).tolist())
        [[1, 0, 2, 1, 2], [2, 1, 1, 0, 2]]
    """

    is_differentiable = False

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.multiclass = multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if reduce not in ["micro", "macro", "samples"]:
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in [None, "samplewise", "global"]:
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        default: Any
        if mdmc_reduce != "samplewise" and reduce != "samples":
            zeros_shape = () if reduce == "micro" else (num_classes,)
            default, reduce_fn = jnp.zeros(zeros_shape, dtype=jnp.int32), "sum"
        else:
            default, reduce_fn = [], None

        for s in ("tp", "fp", "tn", "fn"):
            self.add_state(s, default=[] if isinstance(default, list) else default, dist_reduce_fx=reduce_fn)

    def update_identity(self) -> Optional[Tuple]:
        """Compute-group key of the stat-score family.

        Every metric that inherits this ``update`` unchanged — Precision,
        Recall, FBeta/F1, Specificity, StatScores itself — folds batches
        through the identical ``_stat_scores_update`` call, parameterized
        only by the arguments below. Members of a ``MetricCollection`` with
        equal keys therefore run ONE tp/fp/tn/fn accumulation per step and
        share one copy of the counters; each still reduces its own value at
        ``compute``. Subclasses that override ``update`` (e.g. ``Accuracy``,
        whose update latches an input-mode attribute and takes a subset-
        accuracy branch) are automatically excluded unless they re-declare
        their own key (see ``Metric._effective_update_identity``).
        """
        return (
            "stat_scores",
            self.reduce,
            self.mdmc_reduce,
            self.threshold,
            self.num_classes,
            self.top_k,
            self.multiclass,
            self.ignore_index,
        )

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        """Accumulate tp/fp/tn/fn from a batch of (preds, target)."""
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            multiclass=self.multiclass,
            ignore_index=self.ignore_index,
        )
        if isinstance(self.tp, list):
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)
        else:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn

    def _get_final_stats(self) -> Tuple[Array, Array, Array, Array]:
        """Concatenate list states (samplewise) or pass through sum states."""
        if isinstance(self.tp, list):
            return (
                dim_zero_cat(self.tp),
                dim_zero_cat(self.fp),
                dim_zero_cat(self.tn),
                dim_zero_cat(self.fn),
            )
        return self.tp, self.fp, self.tn, self.fn

    def compute(self) -> Array:
        """Return the ``(..., 5)`` array of ``[tp, fp, tn, fn, support]``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)
