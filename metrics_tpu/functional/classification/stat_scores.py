"""tp/fp/tn/fn statistics — the backbone of the classification family.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/stat_scores.py:28-397``. All shape
dispatch is static, so every function here jits cleanly (given ``num_classes``);
the boolean-product sums XLA fuses into a single pass over the inputs.
"""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod


def _del_column(data: Array, idx: int) -> Array:
    """Drop class column ``idx`` (static index)."""
    return jnp.concatenate([data[:, :idx], data[:, idx + 1:]], axis=1)


def _stat_scores(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
) -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn over binary ``(N, C)`` or ``(N, C, X)`` inputs.

    Output shapes per ``reduce`` follow the reference contract
    (``stat_scores.py:43-56``): micro → scalar / (N,), macro → (C,) / (N,C),
    samples → (N,) / (N,X).
    """
    if reduce == "micro":
        axis: Tuple[int, ...] = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        axis = (0,) if preds.ndim == 2 else (2,)
    else:  # samples
        axis = (1,)

    true_pred = target == preds
    pos_pred = preds == 1

    tp = jnp.sum(true_pred & pos_pred, axis=axis)
    fp = jnp.sum(~true_pred & pos_pred, axis=axis)
    tn = jnp.sum(true_pred & ~pos_pred, axis=axis)
    fn = jnp.sum(~true_pred & ~pos_pred, axis=axis)
    return (
        tp.astype(jnp.int32),
        fp.astype(jnp.int32),
        tn.astype(jnp.int32),
        fn.astype(jnp.int32),
    )


def _stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
    validate: bool = True,
) -> Tuple[Array, Array, Array, Array]:
    """Format inputs and count statistics (reference ``stat_scores.py:76-145``)."""
    preds, target, _ = _input_format_classification(
        preds, target, threshold=threshold, num_classes=num_classes,
        multiclass=multiclass, top_k=top_k, validate=validate,
    )

    if ignore_index is not None and not 0 <= ignore_index < preds.shape[1]:
        raise ValueError(
            f"The `ignore_index` {ignore_index} is not valid for inputs with {preds.shape[1]} classes"
        )
    if ignore_index is not None and preds.shape[1] == 1:
        raise ValueError("You can not use `ignore_index` with binary data.")

    if preds.ndim == 3:
        if not mdmc_reduce:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the `mdmc_reduce` parameter"
            )
        if mdmc_reduce == "global":
            preds = jnp.swapaxes(preds, 1, 2).reshape(-1, preds.shape[1])
            target = jnp.swapaxes(target, 1, 2).reshape(-1, target.shape[1])

    if ignore_index is not None and reduce != "macro":
        preds = _del_column(preds, ignore_index)
        target = _del_column(target, ignore_index)

    tp, fp, tn, fn = _stat_scores(preds, target, reduce=reduce)

    if ignore_index is not None and reduce == "macro":
        tp = tp.at[..., ignore_index].set(-1)
        fp = fp.at[..., ignore_index].set(-1)
        tn = tn.at[..., ignore_index].set(-1)
        fn = fn.at[..., ignore_index].set(-1)

    return tp, fp, tn, fn


def _stat_scores_compute(tp: Array, fp: Array, tn: Array, fn: Array) -> Array:
    """Stack tp/fp/tn/fn/support into one ``(..., 5)`` output."""
    outputs = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    return jnp.where(outputs < 0, -1, outputs)


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Turn numerator/denominator statistics into a final averaged score.

    Handles zero-division, ignored classes (denominator < 0 → masked out), and
    the micro/macro/weighted/samples/none axes exactly as the reference's
    ``_reduce_stat_scores`` (``stat_scores.py:183-237``) — but branch-free so
    it fuses into one XLA kernel.
    """
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else weights.astype(jnp.float32)
    numerator = jnp.where(zero_div_mask, float(zero_division), numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    scores = jnp.where(jnp.isnan(scores), float(zero_division), scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        scores = jnp.mean(scores, axis=0)
        ignore_mask = jnp.sum(ignore_mask, axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        return jnp.where(ignore_mask, jnp.nan, scores)
    return jnp.sum(scores)


def stat_scores(
    preds: Array,
    target: Array,
    reduce: str = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Count tp/fp/tn/fn/support with flexible reduction — the stateless
    primitive underneath the whole precision/recall/accuracy family.

    Contract identical to the reference's ``stat_scores``
    (``functional/classification/stat_scores.py:240-397``).

    Args:
        preds: predictions — labels, probabilities, or logits in any
            supported classification shape.
        target: ground-truth labels of the matching shape.
        reduce: counter granularity — ``"micro"`` one global quartet,
            ``"macro"`` a ``[C]`` quartet per class, ``"samples"`` one per
            sample.
        mdmc_reduce: multidim policy (``"global"``/``"samplewise"``/
            ``None``).
        num_classes: class count; required for ``"macro"``.
        top_k: one-hot the k best multiclass scores instead of the argmax.
        threshold: binarization cut for probabilistic input.
        multiclass: force/forbid multiclass interpretation.
        ignore_index: class label whose rows/columns drop from every count.

    Returns:
        ``[..., 5]`` stacked ``[tp, fp, tn, fn, support]`` — ``[5]`` for
        micro, ``[C, 5]`` macro, ``[N, 5]`` samplewise.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import stat_scores
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> print(stat_scores(preds, target, reduce="micro"))
        [3 1 3 1 4]
    """
    if reduce not in ["micro", "macro", "samples"]:
        raise ValueError(f"The `reduce` {reduce} is not valid.")
    if mdmc_reduce not in [None, "samplewise", "global"]:
        raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
    if reduce == "macro" and (not num_classes or num_classes < 1):
        raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_reduce, top_k=top_k,
        threshold=threshold, num_classes=num_classes, multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _stat_scores_compute(tp, fp, tn, fn)
