"""PSNR grid: data_range/base/dim axes × ddp × dist_sync_on_step.

Mirror of the reference's `tests/image/test_psnr.py:77-138` matrix, with the
sk reference hand-rolled in numpy (the formula is closed-form; the reference
leans on skimage, which this image does not ship).
"""
from collections import namedtuple
from functools import partial

import numpy as np
import pytest

from metrics_tpu import PSNR
from metrics_tpu.functional import psnr
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

rng = np.random.RandomState(42)

Input = namedtuple("Input", ["preds", "target"])

_input_size = (NUM_BATCHES, BATCH_SIZE, 4, 4)
_inputs = [
    Input(
        preds=(rng.randint(n_cls_pred, size=_input_size) / n_cls_pred).astype(np.float32),
        target=(rng.randint(n_cls_target, size=_input_size) / n_cls_target).astype(np.float32),
    )
    for n_cls_pred, n_cls_target in [(10, 10), (5, 10), (10, 5)]
]


def _np_psnr(preds, target, data_range, base):
    mse = np.mean((preds.astype(np.float64) - target) ** 2)
    return 10 * np.log10(data_range**2 / mse) / np.log10(base)


def _np_psnr_dim(preds, target, data_range, base):
    """dim=(1,2) on [B,H,W] batches: per-image PSNR, mean-reduced (matches
    reduction 'elementwise_mean' over the kept batch axis)."""
    p = preds.reshape(preds.shape[0], -1).astype(np.float64)
    t = target.reshape(target.shape[0], -1)
    mse = np.mean((p - t) ** 2, axis=1)
    vals = 10 * np.log10(data_range**2 / mse) / np.log10(base)
    return vals.mean()


@pytest.mark.parametrize(
    "preds, target, data_range, dim",
    [
        (_inputs[0].preds, _inputs[0].target, 1.0, None),
        (_inputs[1].preds, _inputs[1].target, 1.0, None),
        (_inputs[2].preds, _inputs[2].target, 0.5, None),
        (_inputs[2].preds, _inputs[2].target, 0.5, (1, 2)),
    ],
)
@pytest.mark.parametrize("base", [10.0, 2.718281828459045])
class TestPSNRMatrix(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_psnr_class(self, preds, target, data_range, dim, base, ddp, dist_sync_on_step):
        sk = _np_psnr if dim is None else _np_psnr_dim
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=PSNR,
            sk_metric=partial(sk, data_range=data_range, base=base),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"data_range": data_range, "base": base, "dim": dim},
            check_jit=False,  # jit covered in test_image.py
        )

    def test_psnr_functional(self, preds, target, data_range, dim, base):
        sk = _np_psnr if dim is None else _np_psnr_dim
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=psnr,
            sk_metric=partial(sk, data_range=data_range, base=base),
            metric_args={"data_range": data_range, "base": base, "dim": dim},
        )


@pytest.mark.parametrize("reduction", ["none", "sum"])
def test_reduction_for_dim_none_warns(reduction):
    """Reference `test_psnr.py:134-138`."""
    with pytest.warns(UserWarning, match="will not have any effect"):
        PSNR(reduction=reduction, dim=None)
