"""ROC module metric.

Behavioral analogue of the reference's ``torchmetrics/classification/roc.py``
(172 LoC).
"""
from typing import Any, Callable, List, Optional, Tuple, Union

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.roc import _roc_compute, _roc_update
from metrics_tpu.utils.data import dim_zero_cat


class ROC(Metric):
    """(fpr, tpr, thresholds) over all distinct thresholds."""

    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(
        self,
    ) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _roc_compute(preds, target, self.num_classes, self.pos_label)
