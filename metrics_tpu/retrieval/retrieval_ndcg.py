"""RetrievalNormalizedDCG — analogue of reference
``torchmetrics/retrieval/retrieval_ndcg.py`` (non-binary targets allowed)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.segment import GroupedByQuery, relevance_sorted, segment_sum
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric
from metrics_tpu.utils.checks import _check_retrieval_k


class RetrievalNormalizedDCG(RetrievalMetric):
    """Mean nDCG@k over queries; linear gain, log2 discount.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalNormalizedDCG
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> ndcg = RetrievalNormalizedDCG()
        >>> print(round(float(ndcg(preds, target, indexes=indexes)), 4))
        0.9599
    """

    allow_non_binary_target = True

    def __init__(
        self,
        empty_target_action: str = "neg",
        k: Optional[int] = None,
        num_queries: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            empty_target_action=empty_target_action,
            num_queries=num_queries,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        _check_retrieval_k(k)
        self.k = k

    def _segment_metric(self, g: GroupedByQuery) -> Array:
        in_topk = jnp.ones_like(g.rank, dtype=bool) if self.k is None else g.rank <= self.k
        discount = jnp.log2(g.rank + 1.0)
        dcg = segment_sum(jnp.where(in_topk, g.target / discount, 0.0), g)

        ideal_target, ideal_rank = relevance_sorted(g)
        ideal_in_topk = jnp.ones_like(ideal_rank, dtype=bool) if self.k is None else ideal_rank <= self.k
        ideal_discount = jnp.log2(ideal_rank + 1.0)
        idcg = segment_sum(jnp.where(ideal_in_topk, ideal_target / ideal_discount, 0.0), g)

        return jnp.where(idcg == 0, 0.0, dcg / jnp.where(idcg == 0, 1.0, idcg))
