"""Binned PR-curve hot op: bucket-histogram default + opt-in pallas kernel.

The binned family (reference ``torchmetrics/classification/
binned_precision_recall.py:147-174``) accumulates TP/FP/FN counts of shape
``[num_classes, num_thresholds]`` from ``[N, C]`` probability batches.

Three mechanisms live here; :func:`binned_stat_scores` dispatches by
BACKEND, because the winner is decided by the memory system, not the math
(all three are bit-identical, ties included — tested):

- **bucket-histogram (default off-TPU)** — each element is bucketized ONCE
  against the sorted thresholds (``searchsorted``, O(log T)), bucket counts
  are scatter-added into a ``[C, T+1, 2]`` histogram (one scatter carries
  both the weighted and raw counts), and ``TP(t) = #{bucket > t}`` falls
  out of one reverse cumulative sum. ~T/log T less work than comparing
  against every threshold; measured **61x faster** than the fused compare
  on the CPU host (14.5 ms vs 883 ms at N=65k, C=8, T=128). On TPU the
  scatter-add serializes and this path measures ~42 ms — ~20x WORSE than
  the dense compare — so it is never auto-picked there.
- **fused-XLA compare (default on TPU)** — broadcast ``[N, C, T]`` compare
  + reduce; dense VPU work XLA fuses to ~0.5-1.4 ms on the v5e. Also the
  oracle the other mechanisms are validated against.
- **pallas kernel** (``use_pallas=True``, OPT-IN only) — class-major VMEM
  streaming of the compare formulation. Paired back-to-back hardware
  measurement (r4, 20-40 samples/shape): **1.1-1.7x** over fused XLA
  depending on shape and chip window (1.67x at N=262k/C=8; parity-or-slower
  for binary C=1) — BENCH.md row 6 is the measurement of record. A real but
  <2x scheduling win that does not justify auto-dispatch maintenance; kept
  as an opt-in and a validation target.
"""
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

__all__ = ["binned_stat_scores"]

_LANE = 128  # TPU lane width
_SUBLANE = 8  # float32 sublane tile
_BLOCK_N = 2048  # batch elements per grid step (lane-dim tiles)
_THRESH_CHUNK = 16  # thresholds per inner-loop step


def _ceil_to(x: int, m: int) -> int:
    return -(-x // m) * m


def _binned_stats_bucket(preds: Array, target: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """Bucket-histogram path (default): O(N*C*log T) instead of O(N*C*T).

    ``bucket = searchsorted(thresholds, pred, side='right')`` counts the
    thresholds <= pred in float32 — exactly the set the compare formulation
    marks positive — so ``TP(t) = sum of target where bucket > t`` is a
    suffix sum of a ``[C, T+1, 2]`` bucket histogram — the weighted and raw
    counts ride ONE scatter (the scatter dominates this path's cost), one
    reverse cumsum per class. Every intermediate is O(N*C + C*T), nothing
    of size ``N*T`` exists anywhere, and the result is bit-identical to the
    compare paths (ties and NaN preds included).
    """
    preds = preds.astype(jnp.float32)
    thresholds = thresholds.astype(jnp.float32)
    n, c = preds.shape
    t = thresholds.shape[0]
    flat_p = preds.reshape(-1)
    bucket = jnp.searchsorted(thresholds, flat_p, side="right")
    # NaN preds: searchsorted places NaN past every threshold (positive
    # everywhere) but `pred >= thr` is False for NaN (negative everywhere) —
    # force bucket 0 so all three mechanisms stay bit-identical
    bucket = jnp.where(jnp.isnan(flat_p), 0, bucket)
    w = target.astype(jnp.float32)
    cls = jnp.broadcast_to(jnp.arange(c)[None, :], (n, c)).reshape(-1)
    # ONE scatter for both histograms: the scatter is this path's dominant
    # cost, so the weighted and unweighted counts ride the same indices
    vals = jnp.stack([w.reshape(-1), jnp.ones((n * c,), jnp.float32)], axis=-1)
    hist = jnp.zeros((c, t + 1, 2), jnp.float32).at[cls, bucket].add(vals)
    suffix = jnp.cumsum(hist[:, ::-1, :], axis=1)[:, ::-1, :]
    tp = suffix[:, 1:, 0]
    cnt = suffix[:, 1:, 1]
    pos = w.sum(0)[:, None]
    return tp, cnt - tp, pos - tp


def _binned_stats_xla(preds: Array, target: Array, thresholds: Array) -> Tuple[Array, Array, Array]:
    """Fused-XLA brute-force compare path: broadcast compare + reduce — the
    oracle the bucket and pallas paths are validated against.

    Compares in float32 like the pallas kernel does, so inputs lying exactly
    at a threshold classify identically on both backends."""
    preds = preds.astype(jnp.float32)
    thresholds = thresholds.astype(jnp.float32)
    predictions = preds[:, :, None] >= thresholds[None, None, :]
    t = target[:, :, None].astype(bool)
    tp = jnp.sum(t & predictions, axis=0).astype(jnp.float32)
    fp = jnp.sum(~t & predictions, axis=0).astype(jnp.float32)
    fn = jnp.sum(t & ~predictions, axis=0).astype(jnp.float32)
    return tp, fp, fn


def _kernel(x_ref, w_ref, thr_ref, tp_ref, cnt_ref, pos_ref, *, t_chunks: int):
    """One grid step: a [C, block] tile of the class-major stream.

    x_ref/w_ref: [Cp, BN] probabilities / {0,1} weights.
    thr_ref:     [Tp, 1] thresholds.
    tp_ref/cnt_ref: [Tp, Cp] accumulators; pos_ref: [1, Cp].
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        tp_ref[:] = jnp.zeros_like(tp_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        pos_ref[:] = jnp.zeros_like(pos_ref)

    x = x_ref[:]  # [Cp, BN]
    w = w_ref[:]

    def body(k, _):
        i0 = k * _THRESH_CHUNK
        thr_c = thr_ref[pl.ds(i0, _THRESH_CHUNK), :]  # [TC, 1]
        # [TC, Cp, BN] compare lives only in registers/VMEM for this chunk
        cmp = (x[None, :, :] >= thr_c[:, :, None]).astype(jnp.float32)
        tp_ref[pl.ds(i0, _THRESH_CHUNK), :] += jnp.sum(w[None, :, :] * cmp, axis=2)
        cnt_ref[pl.ds(i0, _THRESH_CHUNK), :] += jnp.sum(cmp, axis=2)
        return 0

    jax.lax.fori_loop(0, t_chunks, body, 0)
    pos_ref[0, :] += jnp.sum(w, axis=1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_stats_pallas(
    preds: Array, target: Array, thresholds: Array, interpret: bool = False
) -> Tuple[Array, Array, Array]:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, c = preds.shape
    t = thresholds.shape[0]
    tp_pad = _ceil_to(t, max(_THRESH_CHUNK, _SUBLANE))
    cp = _ceil_to(c, _SUBLANE)
    block = min(_BLOCK_N, _ceil_to(n, _LANE))
    np_ = _ceil_to(n, block)

    # class-major stream; batch padding gets -inf probs (matches no finite
    # threshold) / 0 weights, threshold padding is +inf (matches no element)
    x = jnp.full((cp, np_), -jnp.inf, jnp.float32)
    x = x.at[:c, :n].set(preds.T.astype(jnp.float32))
    w = jnp.zeros((cp, np_), jnp.float32).at[:c, :n].set(target.T.astype(jnp.float32))
    thr = jnp.full((tp_pad, 1), jnp.inf, jnp.float32).at[:t, 0].set(thresholds.astype(jnp.float32))

    kernel = functools.partial(_kernel, t_chunks=tp_pad // _THRESH_CHUNK)
    tp, cnt, pos = pl.pallas_call(
        kernel,
        grid=(np_ // block,),
        in_specs=[
            pl.BlockSpec((cp, block), lambda i: (0, i)),
            pl.BlockSpec((cp, block), lambda i: (0, i)),
            pl.BlockSpec((tp_pad, 1), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tp_pad, cp), lambda i: (0, 0)),
            pl.BlockSpec((tp_pad, cp), lambda i: (0, 0)),
            pl.BlockSpec((1, cp), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((tp_pad, cp), jnp.float32),
            jax.ShapeDtypeStruct((tp_pad, cp), jnp.float32),
            jax.ShapeDtypeStruct((1, cp), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, thr)

    tp = tp[:t, :c].T  # [C, T]
    fp = cnt[:t, :c].T - tp
    fn = pos[0, :c, None] - tp
    return tp, fp, fn


def _vmem_budget_ok(n: int, c: int, t: int) -> bool:
    """Live VMEM: in tiles + [Tp,Cp] accumulators + one [TC,Cp,block] chunk.

    Guards the OPT-IN pallas path: exceeding the ~8 MB working-set bound
    would fail deep inside mosaic at compile time; raising here names the
    actual problem and the fix."""
    cp = _ceil_to(c, _SUBLANE)
    tp_pad = _ceil_to(t, max(_THRESH_CHUNK, _SUBLANE))
    block = min(_BLOCK_N, _ceil_to(n, _LANE))
    live = (2 * cp * block + 2 * tp_pad * cp + 2 * _THRESH_CHUNK * cp * block) * 4
    return live < 8 * 1024 * 1024


def binned_stat_scores(
    preds: Array,
    target: Array,
    thresholds: Array,
    use_pallas: Optional[bool] = None,
    interpret: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-class, per-threshold (TP, FP, FN) counts for binned PR metrics.

    Args:
        preds: ``[N, C]`` probabilities.
        target: ``[N, C]`` binary labels.
        thresholds: ``[T]`` decision thresholds.
        use_pallas: ``True`` opts into the hand-tiled pallas kernel (1.1-1.7x
            vs fused XLA on v5e depending on shape — BENCH.md row 6; never
            auto-picked); ``False`` forces the fused-XLA compare; ``None``
            (default) picks by backend — fused compare on TPU, the
            bucket-histogram path elsewhere (25x on the CPU host; TPU
            scatters serialize). Caveat: the bucket path needs CONCRETE
            ascending thresholds (the sortedness check runs on the host);
            passing thresholds as a traced jit argument falls back to the
            compare path. Metrics close over fixed threshold arrays, so
            they always get the bucket path off-TPU.
        interpret: run the pallas kernel in interpreter mode (CPU testing).

    Returns:
        Three ``[C, T]`` float32 arrays: true/false positives and false
        negatives at each (class, threshold).
    """
    if use_pallas is False and interpret:
        raise ValueError(
            "contradictory flags: use_pallas=False forces the fused-XLA compare "
            "but interpret=True requests the pallas interpreter"
        )
    if use_pallas or interpret:
        n, c = preds.shape
        if not interpret and not _vmem_budget_ok(n, c, thresholds.shape[0]):
            raise ValueError(
                f"binned_stat_scores(use_pallas=True): shape (N={n}, C={c}, "
                f"T={thresholds.shape[0]}) exceeds the kernel's ~8 MB VMEM "
                "working-set budget; use the default dispatch instead."
            )
        return _binned_stats_pallas(preds, target, thresholds, interpret=interpret)
    if use_pallas is False:
        return _binned_stats_xla(preds, target, thresholds)
    if jax.default_backend() == "tpu":
        return _binned_stats_xla(preds, target, thresholds)
    # bucket-histogram needs ascending thresholds (searchsorted); Binned*
    # metrics build linspace or pass user arrays through unchanged, so check
    # on the HOST when concrete (a jnp.all here would stage into an ambient
    # trace and produce an unreadable tracer even for constants) and keep
    # compare semantics otherwise
    from metrics_tpu.utils.data import is_traced

    if not is_traced(thresholds):
        import numpy as np

        thr = np.asarray(thresholds)
        if bool(np.all(thr[1:] >= thr[:-1])):
            return _binned_stats_bucket(preds, target, thresholds)
    return _binned_stats_xla(preds, target, thresholds)
