"""ROC module metric.

Behavioral analogue of the reference's ``torchmetrics/classification/roc.py``
(172 LoC).
"""
from typing import Any, Callable, List, Optional, Tuple, Union

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.roc import _roc_compute, _roc_update
from metrics_tpu.utils.data import dim_zero_cat


class ROC(Metric):
    """Full receiver-operating-characteristic curve: ``(fpr, tpr,
    thresholds)`` at every distinct score (reference ``roc.py``).

    Scores/targets accumulate as "cat" states; :meth:`compute` sorts once
    and cumulative-sums (the XLA-friendly `_binary_clf_curve`), prepending
    the conventional (0, 0) point. Binary input ``[N]`` yields three
    arrays; multiclass ``[N, C]`` (with ``num_classes``) yields
    per-class lists. For a constant-memory alternative with fixed
    thresholds, see :class:`~metrics_tpu.BinnedPrecisionRecallCurve` — on
    TPU it is the recommended default for large streams.

    Args:
        num_classes: number of classes for multiclass scores; ``None``
            for binary.
        pos_label: the label treated as positive in binary input.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ROC
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> roc = ROC(pos_label=1)
        >>> fpr, tpr, thresholds = roc(preds, target)
        >>> print(fpr.tolist())
        [0.0, 0.0, 0.5, 0.5, 1.0]
        >>> print(tpr.tolist())
        [0.0, 0.5, 0.5, 1.0, 1.0]
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    #: the shared clf-curve preprocessing infers num_classes/pos_label; a
    #: grouped dispatch copies the inference to every sibling
    _group_shared_attrs = ("num_classes", "pos_label")

    def update_identity(self):
        """Compute-group key of the clf-curve family: ``_roc_update`` IS
        ``_precision_recall_curve_update``, so ROC, PrecisionRecallCurve and
        (non-micro) AveragePrecision instances with equal
        ``(num_classes, pos_label)`` append bit-identical preds/target rows
        — a ``MetricCollection`` holds ONE shared preds/target accumulation
        (list or CatBuffer) for the whole group instead of one per metric.
        """
        return ("clf_curve", self.num_classes, self.pos_label)

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target, num_classes, pos_label = _roc_update(preds, target, self.num_classes, self.pos_label)
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(
        self,
    ) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _roc_compute(preds, target, self.num_classes, self.pos_label)
