"""Single-query reciprocal rank — analogue of reference
``torchmetrics/functional/retrieval/reciprocal_rank.py``."""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """1 / rank of the first relevant document; 0 if none.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_reciprocal_rank
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([False, True, False])
        >>> print(round(float(retrieval_reciprocal_rank(preds, target)), 4))
        0.5
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not jnp.sum(target):
        return jnp.asarray(0.0)
    target = target[jnp.argsort(-preds)]
    first = jnp.argmax(target > 0)
    return 1.0 / (first + 1.0)
