"""Shared example bootstrap: default to the CPU platform.

Under a site-preloaded jax the ambient accelerator plugin initializes on
first use — and hangs outright when its tunnel is down — so examples run on
CPU unless ``--real`` is passed. Must be called before any jax backend use.
"""
import sys


def pin_cpu_unless_real() -> None:
    import jax

    if "--real" not in sys.argv:
        jax.config.update("jax_platforms", "cpu")
