"""Synced-vs-local state_dict semantics (reference `tests/bases/test_ddp.py:
106-238` `_test_state_dict_is_synced`, run here with an injected 2-rank
gather instead of a process pool).

The contract: while synced, ``state_dict`` snapshots the GLOBAL (reduced)
state; after ``unsync`` it snapshots the LOCAL accumulation again, and the
sync/unsync state machine raises on double transitions exactly like the
reference.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.core.metric import Metric
from metrics_tpu.utils.exceptions import MetricsTPUUserError
from tests.helpers.testers import _gather_states


class DummyCatMetric(Metric):
    """Reference `test_ddp.py:109-120`: a sum state + a count state."""

    def __init__(self):
        super().__init__()
        self.add_state("x", jnp.zeros(()), dist_reduce_fx="sum", persistent=True)
        self.add_state("c", jnp.zeros(()), dist_reduce_fx="sum", persistent=True)

    def update(self, x):
        self.x = self.x + x
        self.c = self.c + 1

    def compute(self):
        return self.x


def _make_two_ranks(steps):
    """Rank 0 is the metric under test; rank 1's states are gathered in."""
    m = DummyCatMetric()
    other = DummyCatMetric()
    for i in range(steps):
        m.update(jnp.asarray(float(i)))
        other.update(jnp.asarray(float(i)))

    def gather(state, reductions):
        return _gather_states([state, dict(other._state)], reductions)

    m.dist_sync_fn = gather
    m.distributed_available_fn = lambda: True
    return m


def test_state_dict_synced_vs_local():
    steps = 5
    exp_sum = sum(range(steps))
    m = _make_two_ranks(steps)

    # local snapshot
    sd = m.state_dict()
    assert float(sd["x"]) == exp_sum and float(sd["c"]) == steps

    # synced snapshot carries the 2-rank global state
    m.sync()
    assert m._is_synced
    sd = m.state_dict()
    assert float(sd["x"]) == 2 * exp_sum and float(sd["c"]) == 2 * steps

    # reload of the synced snapshot resumes from GLOBAL totals
    m2 = DummyCatMetric()
    m2.load_state_dict(sd)
    assert float(m2.compute()) == 2 * exp_sum

    # unsync restores the local accumulation
    m.unsync()
    assert not m._is_synced
    sd = m.state_dict()
    assert float(sd["x"]) == exp_sum and float(sd["c"]) == steps


def test_sync_state_machine_guards():
    m = _make_two_ranks(3)
    m.sync()
    with pytest.raises(MetricsTPUUserError, match="already been synced"):
        m.sync()
    with pytest.raises(MetricsTPUUserError, match="shouldn't be synced"):
        m(jnp.asarray(1.0))
    m.unsync()
    with pytest.raises(MetricsTPUUserError, match="already been un-synced"):
        m.unsync()


def test_sync_context_snapshots_then_restores():
    steps = 4
    exp_sum = sum(range(steps))
    m = _make_two_ranks(steps)
    with m.sync_context():
        assert m._is_synced
        assert float(m.state_dict()["x"]) == 2 * exp_sum
    assert not m._is_synced
    assert float(m.state_dict()["x"]) == exp_sum

    with m.sync_context(should_unsync=False):
        assert m._is_synced
    assert m._is_synced  # stays synced when asked
    m.unsync()

    # accumulation continues correctly after the round-trips
    m.update(jnp.asarray(10.0))
    assert float(m.state_dict()["x"]) == exp_sum + 10


def test_unsync_without_cache_raises():
    m = _make_two_ranks(2)
    m.sync()
    cache = m._cache
    m._cache = None
    with pytest.raises(MetricsTPUUserError, match="cache"):
        m.unsync()
    m._cache = cache
    m.unsync()
    np.testing.assert_allclose(float(m.state_dict()["x"]), 1.0)
