"""CosineSimilarity module — analogue of reference
``torchmetrics/regression/cosine_similarity.py`` (108 LoC)."""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.utils.data import dim_zero_cat


class CosineSimilarity(Metric):
    r"""Cosine similarity between paired vectors — the angle, not the
    magnitude. 1-D inputs are treated as ONE vector pair (flattened
    across batches at compute); N-D inputs score one similarity per
    last-axis row.

    Args:
        reduction: ``"sum"`` / ``"mean"`` over the per-row similarities,
            or ``"none"`` for the vector.

    Values accumulate as "cat" states so the flattened-pair semantics
    stay exact across batches.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> preds = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
        >>> target = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
        >>> cosine = CosineSimilarity(reduction="mean")
        >>> print(round(float(cosine(preds, target)), 4))
        1.0
    """

    is_differentiable = True

    def __init__(
        self,
        reduction: str = "sum",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)
