"""Binned (constant-memory) PR-curve family — the TPU-preferred design.

Behavioral analogue of the reference's
``torchmetrics/classification/binned_precision_recall.py:45-324``, with one
TPU-first change: the reference iterates one threshold at a time to conserve
memory (``binned_precision_recall.py:163-168``); here the [N, C] × [T]
comparison is vectorized into a single fused XLA kernel — states stay
O(C × T), fully static shapes, jit/shard_map native.
"""
from typing import Any, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute_with_precision_recall,
)
from metrics_tpu.ops.pallas_binned import binned_stat_scores
from metrics_tpu.utils.data import METRIC_EPS, to_onehot


def _recall_at_precision(
    precision: Array, recall: Array, thresholds: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Highest recall (and its threshold) where precision >= min_precision.

    Ties broken like the reference's lexicographic ``max((r, p, t))``
    (``binned_precision_recall.py:25-42``): max recall, then max precision,
    then max threshold — expressed as three staged reductions so it jits.
    """
    n = thresholds.shape[0]
    prec, rec = precision[:n], recall[:n]
    ok = prec >= min_precision
    max_recall = jnp.max(jnp.where(ok, rec, -1.0))
    tie = ok & (rec == max_recall)
    max_prec = jnp.max(jnp.where(tie, prec, -1.0))
    tie = tie & (prec == max_prec)
    best_threshold = jnp.max(jnp.where(tie, thresholds, -jnp.inf))
    max_recall = jnp.maximum(max_recall, 0.0)
    best_threshold = jnp.where(
        max_recall == 0.0, jnp.asarray(1e6, dtype=thresholds.dtype), best_threshold
    ).astype(thresholds.dtype)
    return max_recall, best_threshold


class BinnedPrecisionRecallCurve(Metric):
    """Precision–recall curve over FIXED thresholds — the constant-memory
    alternative to :class:`~metrics_tpu.PrecisionRecallCurve` and the
    recommended default on TPU.

    Instead of storing every score, the state is TP/FP/FN sum counters of
    shape ``[C, T]``: update bins the batch against the thresholds via a
    backend-aware mechanism (fused-XLA compare on TPU, bucket-histogram
    elsewhere; a hand-tiled pallas kernel stays available via
    ``ops.pallas_binned.binned_stat_scores(use_pallas=True)`` — all three
    hardware-proven bit-exact, see BENCH.md row 6), so memory never grows
    with the stream, the update is one fixed-shape jitted op, and
    distributed sync is a single ``psum``. The price is curve resolution:
    precision/recall are exact *at the chosen thresholds* rather than at
    every distinct score.

    Args:
        num_classes: number of classes (1 for binary-style scores).
        thresholds: an int ``T`` (evenly spaced in [0, 1]), an explicit
            1-D array of thresholds, or a python list.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    :meth:`compute` returns ``(precision, recall, thresholds)`` with the
    conventional (1, 0) endpoint appended.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedAveragePrecision
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> binned_ap = BinnedAveragePrecision(num_classes=1, thresholds=5)
        >>> binned_ap.update(preds, target)
        >>> print(round(float(binned_ap.compute()), 4))
        0.8333
    """

    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Array, List[float], None] = 100,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
        )
        self.num_classes = num_classes
        if isinstance(thresholds, int):
            self.num_thresholds = thresholds
            self.thresholds = jnp.linspace(0, 1.0, thresholds)
        elif thresholds is not None:
            if not isinstance(thresholds, (list, jnp.ndarray)):
                raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")
            self.thresholds = jnp.asarray(thresholds, dtype=jnp.float32)
            self.num_thresholds = self.thresholds.size
        else:
            raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")

        for name in ("TPs", "FPs", "FNs"):
            self.add_state(
                name,
                default=jnp.zeros((num_classes, self.num_thresholds), dtype=jnp.float32),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        """[N] or [N, C] probabilities vs integer / one-hot targets."""
        if preds.ndim == target.ndim == 1:
            preds = preds.reshape(-1, 1)
            target = target.reshape(-1, 1)
        if preds.ndim == target.ndim + 1:
            target = to_onehot(target, num_classes=self.num_classes)
        target = (target == 1).astype(jnp.float32)
        # bucket-histogram stats: each element bucketized once against the
        # sorted thresholds instead of compared against all T of them
        # (ops/pallas_binned.py; compare-path and pallas remain as opt-ins)
        tp, fp, fn = binned_stat_scores(preds, target, self.thresholds)
        self.TPs = self.TPs + tp
        self.FPs = self.FPs + fp
        self.FNs = self.FNs + fn

    def compute(
        self,
    ) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        precisions = (self.TPs + METRIC_EPS) / (self.TPs + self.FPs + METRIC_EPS)
        recalls = self.TPs / (self.TPs + self.FNs + METRIC_EPS)
        # guarantee the curve ends at precision=1, recall=0
        precisions = jnp.concatenate([precisions, jnp.ones((self.num_classes, 1), dtype=precisions.dtype)], axis=1)
        recalls = jnp.concatenate([recalls, jnp.zeros((self.num_classes, 1), dtype=recalls.dtype)], axis=1)
        if self.num_classes == 1:
            return precisions[0, :], recalls[0, :], self.thresholds
        return list(precisions), list(recalls), [self.thresholds for _ in range(self.num_classes)]


class BinnedAveragePrecision(BinnedPrecisionRecallCurve):
    """Constant-memory average precision from binned PR pairs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedAveragePrecision
        >>> m = BinnedAveragePrecision(num_classes=1, thresholds=5)
        >>> m.update(jnp.asarray([0.1, 0.85, 0.4, 0.8]), jnp.asarray([0, 1, 0, 1]))
        >>> print(round(float(m.compute()), 4))
        1.0
    """

    def compute(self) -> Union[List[Array], Array]:  # type: ignore[override]
        precisions, recalls, _ = super().compute()
        return _average_precision_compute_with_precision_recall(
            precisions, recalls, self.num_classes, average=None
        )


class BinnedRecallAtFixedPrecision(BinnedPrecisionRecallCurve):
    """Highest recall at a minimum precision, from binned PR pairs.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedRecallAtFixedPrecision
        >>> m = BinnedRecallAtFixedPrecision(num_classes=1, min_precision=0.5, thresholds=5)
        >>> m.update(jnp.asarray([0.1, 0.85, 0.4, 0.8]), jnp.asarray([0, 1, 0, 1]))
        >>> recall, threshold = m.compute()
        >>> print(round(float(recall), 4), round(float(threshold), 2))
        1.0 0.75
    """

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Union[int, Array, List[float], None] = 100,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            thresholds=thresholds,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
        )
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:  # type: ignore[override]
        precisions, recalls, thresholds = super().compute()
        if self.num_classes == 1:
            return _recall_at_precision(precisions, recalls, thresholds, self.min_precision)
        recalls_at_p = []
        thresholds_at_p = []
        for i in range(self.num_classes):
            r, t = _recall_at_precision(precisions[i], recalls[i], thresholds[i], self.min_precision)
            recalls_at_p.append(r)
            thresholds_at_p.append(t)
        return jnp.stack(recalls_at_p), jnp.stack(thresholds_at_p)
