"""Bucketed (fused) host-sync equivalence suite (ISSUE 2 tentpole).

The contract under test: the bucketed planner (``parallel/bucketing.py``)
syncs a whole state dict — or a whole ``MetricCollection`` — in
O(#dtypes × #fx-classes) ``_raw_process_allgather`` calls and produces
**bit-identical** results to the per-leaf path, across mixed dtypes, mixed
reductions, uneven cat lengths, list states, CatBuffers and callable-``fx``
fallbacks. Real two-rank payloads run through :class:`LockstepWorld`
(``tests/helpers/fake_world.py``): every rank executes the production sync
code on its own thread and each collective is a barrier rendezvous over the
ranks' actual contributions.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.parallel.sync as sync_mod
from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel.bucketing import (
    build_sync_plan,
    clear_sync_plan_cache,
    fused_sync_enabled,
    sync_plan_cache_info,
)
from metrics_tpu.parallel.health import CAT_LENGTH_SLOTS, build_health_word, header_cat_lengths
from metrics_tpu.parallel.sync import gather_all_arrays, host_sync_state, sync_in_jit
from metrics_tpu.utils.exceptions import SyncError
from tests.helpers.fake_world import LockstepWorld

WORLD = 2


@pytest.fixture(autouse=True)
def _fresh_plan_cache():
    clear_sync_plan_cache()
    yield
    clear_sync_plan_cache()


@pytest.fixture
def lockstep(monkeypatch):
    """A real two-rank world: production sync code per rank, rendezvous
    collectives, ``calls`` counting collective rounds."""
    world = LockstepWorld(WORLD)
    monkeypatch.setattr(jax, "process_count", lambda: world.world)
    monkeypatch.setattr(sync_mod, "_raw_process_allgather", world.allgather)
    return world


def _custom_fx(gathered):
    return jnp.sum(gathered, axis=0) * 2.0


def _mixed_state(rank: int):
    """Uneven, mixed-dtype, mixed-fx state — every leaf family at once."""
    buf = CatBuffer(16)
    buf.append(jnp.arange(3 + 2 * rank, dtype=jnp.float32) + 100.0 * rank)
    ibuf = CatBuffer(8)
    ibuf.append(jnp.asarray([[1 + rank, 2], [3, 4 + rank]], jnp.int32)[: 1 + rank])
    state = {
        "sum_f32": jnp.asarray([[1.5, 2.5], [3.5, 4.5]]) * (rank + 1),
        "sum_scalar": jnp.asarray(2.0 + rank),
        "sum_i32": jnp.asarray([2, 3], jnp.int32) + rank,
        "mean_f32": jnp.asarray([0.25, 0.75]) + rank,
        "max_f32": jnp.asarray([[1.0 + 3 * rank, 2.0], [5.0, 4.0 - rank]]),
        "min_i32": jnp.asarray(5 - 2 * rank, jnp.int32),
        "cat_f32": jnp.arange(3 + rank, dtype=jnp.float32) + 10.0 * rank,  # uneven rows
        "cat_2d": (jnp.arange(2 * (2 - rank), dtype=jnp.float32).reshape(2 - rank, 2) - rank),
        "cat_i32": jnp.arange(4 - rank, dtype=jnp.int32) + 20 * rank,
        "none_scalar": jnp.asarray(7.0 + rank),  # fx=None → cat family
        "lst": [jnp.asarray([1.0, 2.0]) + rank, jnp.asarray(3.0 + rank)],
        "buf": buf,  # uneven CatBuffer fill
        "ibuf": ibuf,  # int CatBuffer, uneven rows
        "cust": jnp.asarray([1.0 + rank, 2.0]),  # callable fx → fallback
    }
    reductions = {
        "sum_f32": "sum", "sum_scalar": "sum", "sum_i32": "sum",
        "mean_f32": "mean", "max_f32": "max", "min_i32": "min",
        "cat_f32": "cat", "cat_2d": "cat", "cat_i32": "cat",
        "none_scalar": None, "lst": "cat", "buf": "cat", "ibuf": "cat",
        "cust": _custom_fx,
    }
    return state, reductions


def _assert_leaf_equal(a, b, name):
    """Bit-for-bit: same type, dtype, shape, bytes."""
    if isinstance(a, CatBuffer):
        assert isinstance(b, CatBuffer), name
        assert a.capacity == b.capacity, name
        assert int(np.asarray(a.count)) == int(np.asarray(b.count)), name
        assert bool(np.asarray(a.overflowed)) == bool(np.asarray(b.overflowed)), name
        assert np.asarray(a.buffer).tobytes() == np.asarray(b.buffer).tobytes(), name
    elif isinstance(a, list):
        assert isinstance(b, list) and len(a) == len(b), name
        for x, y in zip(a, b):
            _assert_leaf_equal(x, y, name)
    else:
        a, b = np.asarray(a), np.asarray(b)
        assert a.dtype == b.dtype, (name, a.dtype, b.dtype)
        assert a.shape == b.shape, (name, a.shape, b.shape)
        assert a.tobytes() == b.tobytes(), name


def _assert_state_equal(sa, sb):
    assert sorted(sa) == sorted(sb)
    for name in sa:
        _assert_leaf_equal(sa[name], sb[name], name)


def _run_sync(world, fused, state_fn=_mixed_state):
    def body(rank):
        state, reds = state_fn(rank)
        # timeout=0: watchdog inline, so the rank's thread-local survives
        return host_sync_state(state, reds, update_count=3, timeout=0, fused=fused)

    return world.run(body)


# ---------------------------------------------------------------------------
# bit-for-bit equivalence over genuinely uneven two-rank states
# ---------------------------------------------------------------------------

def test_fused_equals_per_leaf_bit_for_bit(lockstep):
    fused = _run_sync(lockstep, fused=True)
    per_leaf = _run_sync(lockstep, fused=False)
    for rank in range(WORLD):
        _assert_state_equal(fused[rank], per_leaf[rank])
    # collectives are symmetric: every rank computes the identical result
    _assert_state_equal(fused[0], fused[1])


def test_fused_merges_uneven_cat_rows_correctly(lockstep):
    out = _run_sync(lockstep, fused=True)[0]
    # cat_f32: rank0 has 3 rows, rank1 has 4 — concatenated in rank order
    expected = np.concatenate([np.arange(3, dtype=np.float32), np.arange(4, dtype=np.float32) + 10.0])
    np.testing.assert_array_equal(np.asarray(out["cat_f32"]), expected)
    # CatBuffer: 3 + 5 uneven rows, world*capacity merged buffer
    assert len(out["buf"]) == 3 + 5 and out["buf"].capacity == WORLD * 16
    np.testing.assert_array_equal(
        np.asarray(out["buf"].values()),
        np.concatenate([np.arange(3, dtype=np.float32), np.arange(5, dtype=np.float32) + 100.0]),
    )
    # list state: one trimmed piece per rank
    assert len(out["lst"]) == WORLD and out["lst"][0].shape == (3,)
    # callable fx fallback still honored
    np.testing.assert_array_equal(np.asarray(out["cust"]), np.asarray([1.0 + 2.0, 4.0]) * 2.0)


def test_fused_collective_budget(lockstep):
    _run_sync(lockstep, fused=True)
    fused_calls = lockstep.calls
    lockstep.calls = 0
    _run_sync(lockstep, fused=False)
    per_leaf_calls = lockstep.calls

    state, reds = _mixed_state(0)
    plan = build_sync_plan(state, reds)
    # 1 header + one collective per bucket + the callable fallback's payload
    # (its shape gather is skipped: schema-verified static shape)
    assert fused_calls == 1 + plan.n_buckets + len(plan.fallback)
    assert fused_calls < len(state), (fused_calls, len(state))
    assert fused_calls < per_leaf_calls, (fused_calls, per_leaf_calls)


def test_header_carries_cat_lengths(lockstep):
    state, reds = _mixed_state(1)
    word = build_health_word(state, reds)
    plan = build_sync_plan(state, reds)
    lengths = header_cat_lengths(np.stack([word, word]), len(plan.cat_leaves))
    # header column order == planner cat-leaf order; values are row counts
    for j, spec in enumerate(plan.cat_leaves):
        from metrics_tpu.parallel.health import _state_kinds, cat_row_count

        _, kinds = _state_kinds(state)
        assert lengths[0, j] == cat_row_count(state[spec.name], kinds[spec.name]), spec.name


def test_fused_beyond_length_slots_gathers_one_length_vector(lockstep):
    """> CAT_LENGTH_SLOTS cat states: one extra length-vector collective,
    still O(#buckets) overall and bit-identical to per-leaf."""
    n = CAT_LENGTH_SLOTS + 2

    def big_state(rank):
        state = {f"c{j:02d}": jnp.arange(j % 3 + 1 + rank, dtype=jnp.float32) + j for j in range(n)}
        reds = {k: "cat" for k in state}
        return state, reds

    fused = _run_sync(lockstep, fused=True, state_fn=big_state)
    fused_calls = lockstep.calls
    lockstep.calls = 0
    per_leaf = _run_sync(lockstep, fused=False, state_fn=big_state)
    _assert_state_equal(fused[0], per_leaf[0])
    # 1 header + 1 length vector + 1 f32 cat bucket
    assert fused_calls == 3


def test_plan_cache_hits_on_same_schema(lockstep):
    state0, reds = _mixed_state(0)
    plan_a = build_sync_plan(state0, reds)
    # same schema, different data (uneven leading dims hash equal)
    state1, _ = _mixed_state(1)
    plan_b = build_sync_plan(state1, reds)
    assert plan_a is plan_b
    info = sync_plan_cache_info()
    assert info["size"] == 1 and info["hits"] == 1 and info["misses"] == 1
    # a schema change (dtype) misses
    changed = dict(state0)
    changed["sum_scalar"] = jnp.asarray(2, jnp.int32)
    assert build_sync_plan(changed, reds) is not plan_a
    assert sync_plan_cache_info()["misses"] == 2


def test_repeated_syncs_replan_zero_times(lockstep):
    _run_sync(lockstep, fused=True)
    misses = sync_plan_cache_info()["misses"]
    _run_sync(lockstep, fused=True)
    _run_sync(lockstep, fused=True)
    assert sync_plan_cache_info()["misses"] == misses  # plan reused, 0 replans


# ---------------------------------------------------------------------------
# escape hatches
# ---------------------------------------------------------------------------

def test_env_escape_hatch(monkeypatch):
    assert fused_sync_enabled()  # default on
    monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", "0")
    assert not fused_sync_enabled()
    monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", "off")
    assert not fused_sync_enabled()
    monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", "1")
    assert fused_sync_enabled()


def test_env_escape_hatch_routes_per_leaf(lockstep, monkeypatch):
    monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", "0")
    _run_sync(lockstep, fused=None)  # env decides
    env_calls = lockstep.calls
    monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", "1")
    lockstep.calls = 0
    _run_sync(lockstep, fused=None)
    assert lockstep.calls < env_calls  # fused default issues fewer collectives


# ---------------------------------------------------------------------------
# MetricCollection fused path
# ---------------------------------------------------------------------------

class _SumMetric(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)
        self.count = self.count + jnp.asarray(jnp.size(x), jnp.int32)

    def compute(self):
        return self.total / self.count


class _MaxMetric(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("mx", jnp.full((2,), -jnp.inf), dist_reduce_fx="max")

    def update(self, x):
        self.mx = jnp.maximum(self.mx, x)

    def compute(self):
        return self.mx


class _CatMetric(Metric):
    def __init__(self):
        super().__init__()
        self.add_state("vals", [], dist_reduce_fx="cat")

    def update(self, x):
        self.vals.append(x)

    def compute(self):
        return jnp.concatenate([v[None] if v.ndim == 0 else v for v in self.vals])


def _make_collection(rank):
    mc = MetricCollection({"avg": _SumMetric(), "mx": _MaxMetric(), "cat": _CatMetric()})
    for m in mc.values():
        m.distributed_available_fn = lambda: True
    mc["avg"].update(jnp.asarray([1.0 + rank, 2.0]))
    mc["mx"].update(jnp.asarray([3.0 + rank, 1.0 - rank]))
    mc["cat"].update(jnp.arange(2 + rank, dtype=jnp.float32) + 5.0 * rank)
    return mc


def test_collection_fused_sync_one_plan(lockstep):
    """≥3 metrics, ≥6 leaves: the whole collection syncs in ≤ 1 header +
    #dtypes·#fx-classes collectives with per-member-identical results."""

    def body(rank):
        mc = _make_collection(rank)
        mc.sync(timeout=0)
        synced = {
            "avg_total": np.asarray(mc["avg"].total).copy(),
            "avg_count": np.asarray(mc["avg"].count).copy(),
            "mx": np.asarray(mc["mx"].mx).copy(),
            "cat": [np.asarray(v).copy() for v in mc["cat"].vals],
            "synced": [m._is_synced for m in mc.values()],
        }
        mc.unsync()
        synced["local_total"] = float(np.asarray(mc["avg"].total))
        return synced

    r0, r1 = lockstep.run(body)
    # buckets for 3 metrics / 4 leaves: (f32,sum), (i32,sum), (f32,max), f32-cat
    assert lockstep.calls == 1 + 4, lockstep.calls
    n_leaves = 4
    assert lockstep.calls <= 1 + 4 and lockstep.calls > 0
    assert lockstep.calls < 1 + n_leaves + 1  # strictly better than ≥1/leaf
    assert all(r0["synced"]) and all(r1["synced"])
    np.testing.assert_allclose(r0["avg_total"], (1.0 + 2.0) + (2.0 + 2.0))
    assert int(r0["avg_count"]) == 4
    np.testing.assert_array_equal(r0["mx"], [4.0, 1.0])
    assert len(r0["cat"]) == WORLD  # one gathered piece per rank
    np.testing.assert_array_equal(r0["cat"][1], [5.0, 6.0, 7.0])
    # unsync restored rank-local state
    assert r0["local_total"] == 3.0 and r1["local_total"] == 4.0
    # symmetric across ranks
    np.testing.assert_array_equal(r0["mx"], r1["mx"])


def test_collection_fused_matches_per_member(lockstep, monkeypatch):
    def run(env_value):
        monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", env_value)

        def body(rank):
            mc = _make_collection(rank)
            mc.sync(timeout=0)
            return {k: {n: v for n, v in m._state.items()} for k, m in mc.items()}

        return lockstep.run(body)

    fused = run("1")
    per_member = run("0")
    for rank in range(WORLD):
        for key in fused[rank]:
            _assert_state_equal(fused[rank][key], per_member[rank][key])


def test_collection_member_optout_disables_fused(lockstep):
    def body(rank):
        mc = _make_collection(rank)
        mc["avg"].sync_fused = False  # one member opts out → whole collection per-member
        mc.sync(timeout=0)
        return float(np.asarray(mc["avg"].total))

    totals = lockstep.run(body)
    # per-member loop: 3 headers + payloads > the fused path's 5 rounds
    assert lockstep.calls > 5
    assert totals[0] == totals[1] == 7.0


def test_collection_strict_member_keeps_per_member_semantics(lockstep):
    """A strict member must NOT ride the fused header: its summed
    update-count column would escalate strictness onto non-strict members
    with legitimately ragged counts (and opposite skews could cancel)."""

    def body(rank):
        mc = _make_collection(rank)
        mc["avg"].sync_strict_update_count = True
        # ragged but legal: the non-strict members saw one extra batch on
        # rank 1 — per-member semantics warn, they must not raise
        mc["mx"]._update_count = 2 + rank
        mc["cat"]._update_count = 2 + rank
        mc.sync(timeout=0)
        return [m._is_synced for m in mc.values()]

    # the warning fires in worker threads; the filter/record machinery is
    # process-global, so one outer recorder sees it (pytest.warns inside
    # each thread would race on the global filter state)
    with pytest.warns(RuntimeWarning, match="update-count skew"):
        results = lockstep.run(body)
    for synced in results:
        assert all(synced)
    # per-member loop ran (3 headers), not the fused single-header path
    assert lockstep.calls > 5


def test_collection_fused_failure_all_or_nothing(lockstep):
    """A sick member (empty cat state) fails the fused header: under
    on_error='raise' NO member is left synced or mutated."""

    def body(rank):
        mc = MetricCollection({"good": _SumMetric(), "bad": _CatMetric()})
        for m in mc.values():
            m.distributed_available_fn = lambda: True
        mc["good"].update(jnp.asarray(1.0 + rank))
        try:
            mc.sync(timeout=0)
            raise AssertionError("sync should have raised")
        except SyncError:
            pass
        return (
            float(np.asarray(mc["good"].total)),
            [m._is_synced for m in mc.values()],
        )

    for total, synced in lockstep.run(body, timeout=120.0):
        assert not any(synced)
        assert total in (1.0, 2.0)  # untouched local state


def test_collection_fused_check_finite_raises_at_header(lockstep):
    """A NaN-poisoned check_finite member must fail the FUSED header too:
    the combined state's key-prefixed ``_nonfinite`` flag still reaches the
    health word's poison verdict (member-grouped lookup in health.py)."""
    from metrics_tpu.utils.exceptions import NonFiniteStateError

    def body(rank):
        mc = MetricCollection(
            {"clean": _SumMetric(), "sick": _SumMetric().enable_check_finite()}
        )
        for m in mc.values():
            m.distributed_available_fn = lambda: True
        mc["clean"].update(jnp.asarray(1.0))
        mc["sick"].update(jnp.asarray(jnp.nan if rank == 0 else 1.0))
        try:
            mc.sync(timeout=0)
            raise AssertionError("fused sync of a poisoned member did not raise")
        except NonFiniteStateError:
            pass
        return [m._is_synced for m in mc.values()]

    for synced in lockstep.run(body):
        assert not any(synced)  # all-or-nothing: raised before any mutation


def test_collection_fused_unscreened_member_not_screened(lockstep):
    """Per-member parity: a member that never opted into check_finite may
    hold NaN and still sync — another member's poison flag must not screen
    states outside its own group."""

    def body(rank):
        mc = MetricCollection(
            {"nan": _SumMetric(), "screened": _SumMetric().enable_check_finite()}
        )
        for m in mc.values():
            m.distributed_available_fn = lambda: True
        mc["nan"].update(jnp.asarray(jnp.nan))  # unscreened, legal
        mc["screened"].update(jnp.asarray(2.0))  # clean
        mc.sync(timeout=0)
        total = float(np.asarray(mc["screened"].total))
        mc.unsync()
        return total

    assert lockstep.run(body) == [4.0, 4.0]


# ---------------------------------------------------------------------------
# gather_all_arrays all_shapes (satellite)
# ---------------------------------------------------------------------------

def test_gather_all_arrays_skips_shape_gather_with_known_shapes(lockstep):
    def body(rank):
        x = jnp.arange(4, dtype=jnp.float32) + rank
        shapes = np.tile(np.asarray([4], np.int32), (WORLD, 1))
        return gather_all_arrays(x, timeout=0, all_shapes=shapes)

    out = lockstep.run(body)
    assert lockstep.calls == 1  # payload only, no shape pre-gather
    np.testing.assert_array_equal(np.asarray(out[0][1]), np.arange(4, dtype=np.float32) + 1)
    lockstep.calls = 0

    def body_unknown(rank):
        return gather_all_arrays(jnp.arange(4.0) + rank, timeout=0)

    lockstep.run(body_unknown)
    assert lockstep.calls == 2  # shape gather + payload


def test_gather_all_arrays_validates_all_shapes():
    with pytest.raises(ValueError, match="all_shapes"):
        # world == 1 short-circuits, so fake a 2-process world via the arg check
        import unittest.mock as mock

        with mock.patch.object(jax, "process_count", lambda: 2):
            gather_all_arrays(jnp.zeros((3,)), all_shapes=np.zeros((3, 1), np.int32))


# ---------------------------------------------------------------------------
# in-jit fused mode + callable list fx (satellites)
# ---------------------------------------------------------------------------

def _mesh(n):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n]), axis_names=("dp",))


def test_sync_in_jit_fused_matches_per_leaf():
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(4)
    reds = {"a": "sum", "b": "mean", "c": "sum", "mx": "max", "mn": "min"}

    def run(fused):
        @partial(
            shard_map, mesh=mesh,
            in_specs=(P("dp"),) * 5, out_specs=P(),
        )
        def step(a, b, c, mx, mn):
            local = {
                "a": a.reshape(1, 2), "b": b.reshape(()), "c": c.reshape(2),
                "mx": mx.reshape(()), "mn": mn.reshape(()),
            }
            return sync_in_jit(local, reds, "dp", fused=fused)

        return jax.jit(step)(
            jnp.ones((4, 2)), jnp.asarray([0.5] * 4),
            jnp.asarray([[1, 2], [3, 4], [5, 6], [7, 8]], jnp.int32),
            jnp.arange(4.0), jnp.arange(4.0) + 10,
        )

    per_leaf, fused = run(False), run(True)
    for name in per_leaf:
        a, b = np.asarray(per_leaf[name]), np.asarray(fused[name])
        assert a.dtype == b.dtype and np.array_equal(a, b), name


def test_sync_in_jit_list_state_respects_callable_fx():
    from functools import partial

    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _mesh(4)

    def custom(v, axis_name):
        return jax.lax.psum(v, axis_name)

    @partial(shard_map, mesh=mesh, in_specs=(P("dp"),), out_specs=P())
    def step(x):
        out = sync_in_jit({"l": [x.reshape(2)]}, {"l": custom}, "dp")
        return out["l"][0]

    result = jax.jit(step)(jnp.arange(8.0))
    # psum keeps the local shape; the old code forced "cat" (all_gather → (8,))
    assert result.shape == (2,)
    np.testing.assert_array_equal(np.asarray(result), [0 + 2 + 4 + 6, 1 + 3 + 5 + 7])


# ---------------------------------------------------------------------------
# compile cache env knob (satellite)
# ---------------------------------------------------------------------------

def test_compile_cache_env_knob(monkeypatch, tmp_path):
    from metrics_tpu.utils import compile_cache

    monkeypatch.delenv(compile_cache.ENV_VAR, raising=False)
    assert compile_cache.enable_from_env() is None
    monkeypatch.setenv(compile_cache.ENV_VAR, "0")
    assert compile_cache.enable_from_env() is None
    monkeypatch.setenv(compile_cache.ENV_VAR, "off")
    assert compile_cache.enable_from_env() is None
    target = str(tmp_path / "xla-cache")
    monkeypatch.setenv(compile_cache.ENV_VAR, target)
    path = compile_cache.enable_from_env()
    assert path == os.path.abspath(target) and os.path.isdir(path)
