"""Fault-injection harness for the fault-tolerant host sync path (ISSUE 1).

Single-process simulation of dead, slow, and divergent peers: the bare
collective seam ``metrics_tpu.parallel.sync._raw_process_allgather`` is
monkeypatched (while ``jax.process_count`` reports a fake world) so every
divergence class travels the REAL production path — sync-header build,
the single health-word ``process_allgather``, symmetric verification,
watchdog, and ``on_error`` degradation — without spawning processes.
The 2-process end-to-end complement lives in ``__graft_entry__
.dryrun_multihost`` (a real divergent rank + ``on_error="local"``).

Covers the acceptance matrix: every divergence class (empty state,
overflow, schema mismatch, update-count skew, non-finite state, dead rank
via injected timeout) raises the same typed exception on all ranks — zero
hangs — and ``on_error="local"`` returns the local-only ``compute()``
result with a warning instead of raising.
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.parallel.sync as sync_mod
from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.parallel.health import (
    CAT_LENGTH_SLOTS,
    COUNT_SLOTS,
    HEALTH_PROTOCOL_VERSION,
    NONFINITE_STATE,
    WORD_WIDTH,
    _F_EPOCH,
    _F_FIXED,
    _F_LENGTHS,
    _F_NONFINITE,
    _F_NSTATES,
    _F_OVERFLOW,
    _F_SCHEMA,
    _F_UPDATES,
    _F_VERSION,
    build_health_word,
    call_with_sync_watchdog,
    channel_is_suspect,
    distributed_initialize_with_retry,
    reset_channel_health,
    state_has_nonfinite,
    state_schema_hash,
    verify_health_words,
)
from metrics_tpu.parallel.sync import host_sync_leaf, host_sync_state
from metrics_tpu.utils.exceptions import (
    MetricsTPUUserError,
    NonFiniteStateError,
    StateDivergenceError,
    SyncError,
    SyncTimeoutError,
)
from tests.helpers.testers import DummyListMetric, DummyMetricSum

WORLD = 2


class EchoAllgather:
    """Fake ``process_allgather``: every peer contributes this rank's value.

    ``mutate_first(rank1_word)`` (optional) edits what "rank 1" contributed
    to the FIRST gather only — in ``host_sync_state`` that is always the
    health-word collective, so a scenario can diverge the header while the
    payload gathers (which must not run after a failed verify) stay honest.
    ``delay_s`` simulates a slow (but live) interconnect.
    """

    def __init__(self, world=WORLD, mutate_first=None, delay_s=0.0):
        self.world = world
        self.mutate_first = mutate_first
        self.delay_s = delay_s
        self.calls = 0

    def __call__(self, x):
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        rows = [np.asarray(x).copy() for _ in range(self.world)]
        if self.calls == 1 and self.mutate_first is not None:
            rows[1] = self.mutate_first(rows[1])
        return jnp.asarray(np.stack(rows))


@pytest.fixture(autouse=True)
def _fresh_channel():
    # watchdog-timeout scenarios latch the process-wide channel-suspect
    # flag by design; isolate it per test
    reset_channel_health()
    yield
    reset_channel_health()


@pytest.fixture
def fake_world(monkeypatch):
    """Install a fake 2-process world over the single-process test runner."""

    def _install(allgather):
        monkeypatch.setattr(jax, "process_count", lambda: allgather.world)
        monkeypatch.setattr(sync_mod, "_raw_process_allgather", allgather)
        return allgather

    return _install


def _sum_state():
    return {"x": jnp.ones(())}, {"x": "sum"}


def _catbuf_state(rows=3, capacity=8):
    buf = CatBuffer(capacity)
    buf.append(jnp.arange(rows, dtype=jnp.float32))
    return {"preds": buf}, {"preds": "cat"}


# ---------------------------------------------------------------------------
# health word: build + schema hash
# ---------------------------------------------------------------------------

def test_health_word_layout():
    state, reds = _catbuf_state(rows=3)
    word = build_health_word(state, reds, update_count=7)
    assert word.dtype == np.int32 and word.shape == (WORD_WIDTH,)
    # fixed width for EVERY metric: v2 = fixed cols + count slots + the
    # bucketed planner's per-cat-state row-length slots
    assert WORD_WIDTH == _F_FIXED + COUNT_SLOTS + CAT_LENGTH_SLOTS
    assert word[_F_VERSION] == HEALTH_PROTOCOL_VERSION
    assert word[_F_UPDATES] == 7
    assert word[_F_OVERFLOW] == 0 and word[_F_NONFINITE] == 0
    assert word[_F_NSTATES] == 1
    assert word[_F_FIXED] == 3  # CatBuffer fill count in the first count slot
    assert (word[_F_FIXED + 1 : _F_LENGTHS] == -1).all()  # unused count slots
    assert word[_F_LENGTHS] == 3  # CatBuffer row count in the first length slot
    assert (word[_F_LENGTHS + 1 :] == -1).all()  # unused length slots

    state["preds"].overflowed = jnp.ones((), jnp.bool_)
    assert build_health_word(state, reds)[_F_OVERFLOW] == 1


def test_schema_hash_ignores_batch_raggedness_not_config():
    # uneven per-rank batches (leading dim) must hash equal...
    a = {"v": jnp.zeros((3, 4))}
    b = {"v": jnp.zeros((9, 4))}
    reds = {"v": "cat"}
    assert state_schema_hash(a, reds) == state_schema_hash(b, reds)
    # ...but a mis-configured metric (different item shape / dtype /
    # reduction / state names) must not
    assert state_schema_hash({"v": jnp.zeros((3, 5))}, reds) != state_schema_hash(a, reds)
    assert state_schema_hash(a, {"v": "sum"}) != state_schema_hash(a, reds)
    assert state_schema_hash({"w": jnp.zeros((3, 4))}, {"w": "cat"}) != state_schema_hash(a, reds)


# ---------------------------------------------------------------------------
# symmetric verification: every divergence class, same typed raise on
# every rank (verification is deterministic over the shared gathered matrix)
# ---------------------------------------------------------------------------

def _assert_symmetric_raise(exc_type, words, state, reds, **kwargs):
    """Both simulated ranks verify the SAME gathered matrix → same raise."""
    for _rank in range(WORLD):
        with pytest.raises(exc_type):
            verify_health_words(np.array(words), state, reds, **kwargs)


@pytest.mark.parametrize(
    "col, value, exc_type",
    [
        (_F_VERSION, 999, StateDivergenceError),  # software-version skew
        (_F_SCHEMA, 12345, StateDivergenceError),  # num_classes-style mis-config
        (_F_OVERFLOW, 1, SyncError),  # CatBuffer overflow on a peer
        (_F_NONFINITE, 1, NonFiniteStateError),  # NaN/Inf-poisoned peer
        (_F_EPOCH, 7, StateDivergenceError),  # overlapped-round skew (v3)
    ],
    ids=["version-skew", "schema-mismatch", "overflow", "non-finite", "epoch-skew"],
)
def test_divergence_classes_raise_symmetrically(col, value, exc_type):
    state, reds = _catbuf_state()
    word = build_health_word(state, reds, update_count=1)
    words = np.stack([word, word.copy()])
    words[1, col] = value
    _assert_symmetric_raise(exc_type, words, state, reds)


def test_empty_peer_state_raises_before_schema():
    # an empty rank's unknown item spec perturbs its schema hash too; the
    # count check must win so the message says "no update()", not "schema"
    state, reds = _catbuf_state()
    word = build_health_word(state, reds)
    empty = word.copy()
    empty[_F_SCHEMA] = 0
    empty[_F_FIXED] = 0
    with pytest.raises(StateDivergenceError, match="empty state"):
        verify_health_words(np.stack([word, empty]), state, reds)


def test_update_count_skew_warns_by_default_raises_strict():
    state, reds = _sum_state()
    word = build_health_word(state, reds, update_count=4)
    skew = word.copy()
    skew[_F_UPDATES] = 3  # last-batch raggedness: one rank saw fewer steps
    words = np.stack([word, skew])
    with pytest.warns(RuntimeWarning, match="update-count skew"):
        verify_health_words(words, state, reds)
    _assert_symmetric_raise(
        StateDivergenceError, words, state, reds, strict_update_count=True
    )


def test_healthy_words_verify_clean():
    state, reds = _catbuf_state()
    word = build_health_word(state, reds, update_count=2)
    verify_health_words(np.stack([word, word]), state, reds)  # no raise


# ---------------------------------------------------------------------------
# host_sync_state through the injected collective: one header gather,
# typed raise BEFORE any payload gather
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("fused", [True, False], ids=["fused", "per-leaf"])
def test_divergent_rank_raises_before_payload_gather(fake_world, fused):
    def diverge(word):
        word[_F_SCHEMA] = (int(word[_F_SCHEMA]) + 1) & 0x7FFFFFFF
        return word

    ag = fake_world(EchoAllgather(mutate_first=diverge))
    state, reds = _catbuf_state()
    with pytest.raises(StateDivergenceError):
        host_sync_state(state, reds, update_count=1, fused=fused)
    # symmetric-failure contract: the raise happened on the header gather,
    # so no rank can be stranded inside a later payload collective — on the
    # fused path included (the planner only runs after a verified header)
    assert ag.calls == 1


def test_healthy_sync_collapses_per_leaf_prechecks(fake_world, monkeypatch):
    ag = fake_world(EchoAllgather())
    state, reds = _catbuf_state(rows=3)
    state["n"], reds["n"] = jnp.ones(()), "sum"
    out = host_sync_state(state, reds, update_count=1)
    # fused default: 1 header + 1 f32 reduce bucket + 1 f32 cat bucket,
    # and ZERO per-leaf count/flag/shape gathers
    assert ag.calls == 3
    assert len(out["preds"]) == WORLD * 3  # both ranks' rows merged
    np.testing.assert_allclose(np.asarray(out["n"]), WORLD * 1.0)

    # escape hatch: per-leaf payloads (CatBuffer pays a shape gather; the
    # sum leaf's shape is schema-verified so its shape gather is skipped),
    # still zero per-leaf prechecks — the old protocol cost up to 2 extra
    monkeypatch.setenv("METRICS_TPU_FUSED_SYNC", "0")
    ag.calls = 0
    out = host_sync_state(state, reds, update_count=1)
    assert ag.calls == 1 + 2 + 1
    assert len(out["preds"]) == WORLD * 3
    np.testing.assert_allclose(np.asarray(out["n"]), WORLD * 1.0)


def test_slow_but_live_peer_completes_within_timeout(fake_world):
    fake_world(EchoAllgather(delay_s=0.05))
    state, reds = _sum_state()
    out = host_sync_state(state, reds, timeout=30.0)
    np.testing.assert_allclose(np.asarray(out["x"]), WORLD * 1.0)


@pytest.mark.parametrize("fused", [True, False], ids=["fused", "per-leaf"])
def test_dead_peer_raises_sync_timeout(fake_world, fused):
    fake_world(EchoAllgather(delay_s=3.0))  # "dead" at the watchdog's scale
    state, reds = _sum_state()
    t0 = time.perf_counter()
    with pytest.raises(SyncTimeoutError, match="dead or stalled"):
        host_sync_state(state, reds, timeout=0.2, fused=fused)
    assert time.perf_counter() - t0 < 2.0  # raised, did not block out the call


def test_watchdog_env_knob(fake_world, monkeypatch):
    fake_world(EchoAllgather(delay_s=3.0))
    monkeypatch.setenv("METRICS_TPU_SYNC_TIMEOUT_S", "0.2")
    state, reds = _sum_state()
    with pytest.raises(SyncTimeoutError):
        host_sync_state(state, reds)


def test_timeout_latches_channel_suspect_and_refuses_new_collectives(fake_world):
    # after a watchdog fires, the abandoned worker may still sit inside the
    # timed-out gather — a fresh collective could pair with a peer's stale
    # one and "succeed" with wrong data. Further syncs must refuse up front.
    ag = fake_world(EchoAllgather(delay_s=3.0))
    state, reds = _sum_state()
    with pytest.raises(SyncTimeoutError):
        host_sync_state(state, reds, timeout=0.2)
    assert channel_is_suspect()
    calls_after_timeout = ag.calls
    with pytest.raises(SyncTimeoutError, match="refused"):
        host_sync_state(state, reds, timeout=30.0)
    assert ag.calls == calls_after_timeout  # refused BEFORE any collective
    # a re-established process group clears the latch explicitly
    reset_channel_health()
    assert not channel_is_suspect()


def test_channel_suspect_degrades_under_on_error_local(fake_world):
    # a collection syncing after one member timed out: remaining members
    # degrade to local-only state instead of gambling on a desynced channel
    ag = fake_world(EchoAllgather(delay_s=3.0))
    first, second = DummyMetricSum(), DummyMetricSum()
    for m in (first, second):
        m.distributed_available_fn = lambda: True
        m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        first.sync(on_error="local", timeout=0.2)
    calls_after_timeout = ag.calls
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        second.sync(on_error="local", timeout=30.0)
    assert ag.calls == calls_after_timeout  # no new collective was issued
    np.testing.assert_allclose(np.asarray(second.x), 1.0)  # local state kept


# ---------------------------------------------------------------------------
# host_sync_leaf: single-process paths + standalone typed prechecks
# (the satellite replacing the old bare-RuntimeError coverage)
# ---------------------------------------------------------------------------

def test_host_sync_leaf_single_process_passthrough():
    # world == 1: no collectives; scalar/list pass through, CatBuffer copies
    out = host_sync_leaf(jnp.asarray(2.0), "sum")
    np.testing.assert_allclose(np.asarray(out), 2.0)
    out = host_sync_leaf([jnp.asarray([1.0, 2.0])], "cat")
    assert isinstance(out, list) and len(out) == 1
    buf = CatBuffer(4)
    buf.append(jnp.asarray([1.0]))
    out = host_sync_leaf(buf, "cat")
    assert isinstance(out, CatBuffer) and out is not buf and len(out) == 1


def test_host_sync_leaf_empty_catbuffer_typed(fake_world):
    fake_world(EchoAllgather())
    with pytest.raises(StateDivergenceError, match="empty state"):
        host_sync_leaf(CatBuffer(4), "cat")


def test_host_sync_leaf_overflowed_catbuffer_typed(fake_world):
    fake_world(EchoAllgather())
    buf = CatBuffer(2)
    buf.append(jnp.asarray([1.0, 2.0]))
    buf.overflowed = jnp.ones((), jnp.bool_)
    with pytest.raises(SyncError, match="overflowed"):
        host_sync_leaf(buf, "cat")


def test_host_sync_leaf_empty_list_typed(fake_world):
    fake_world(EchoAllgather())
    with pytest.raises(StateDivergenceError, match="empty state"):
        host_sync_leaf([], "cat")


def test_typed_errors_remain_runtime_errors():
    # back-compat: callers catching the pre-typed bare RuntimeError keep
    # working across the whole hierarchy
    for exc in (SyncError, SyncTimeoutError, StateDivergenceError, NonFiniteStateError):
        assert issubclass(exc, RuntimeError) and issubclass(exc, SyncError)


# ---------------------------------------------------------------------------
# Metric-level graceful degradation: on_error = raise | local | warn
# ---------------------------------------------------------------------------

def _distributed_metric(fake_world, allgather, metric=None):
    fake_world(allgather)
    m = metric if metric is not None else DummyMetricSum()
    m.distributed_available_fn = lambda: True
    return m


def _schema_diverge(word):
    word[_F_SCHEMA] = (int(word[_F_SCHEMA]) + 1) & 0x7FFFFFFF
    return word


def test_metric_sync_on_error_raise_default(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=_schema_diverge))
    m.update(jnp.asarray(1.0))
    with pytest.raises(StateDivergenceError):
        m.sync()
    assert not m._is_synced and m._cache is None  # no half-synced residue


def test_metric_on_error_local_degrades_to_local_compute(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=_schema_diverge))
    m.sync_on_error = "local"
    m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        val = m.compute()  # compute()-time auto-sync threads on_error through
    np.testing.assert_allclose(np.asarray(val), 1.0)  # local, not world-summed
    assert not m._is_synced


@pytest.mark.parametrize("fused", [None, True, False], ids=["env-default", "fused", "per-leaf"])
def test_metric_on_error_local_timeout_degrades(fake_world, fused):
    m = _distributed_metric(fake_world, EchoAllgather(delay_s=3.0))
    m.sync_fused = fused  # the per-metric knob threads through _run_dist_sync
    m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        m.sync(on_error="local", timeout=0.2)
    assert not m._is_synced
    np.testing.assert_allclose(np.asarray(m.x), 1.0)


def test_overflowed_peer_raises_before_fused_payload(fake_world):
    # a corrupt CatBuffer poisons the merge on both payload strategies; the
    # header raises before the planner ever builds a payload buffer
    ag = fake_world(EchoAllgather())
    state, reds = _catbuf_state()
    state["preds"].overflowed = jnp.ones((), jnp.bool_)
    with pytest.raises(SyncError, match="overflowed"):
        host_sync_state(state, reds, update_count=1, fused=True)
    assert ag.calls == 1


def test_metric_on_error_warn_warns_on_every_rank(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=_schema_diverge))
    m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        m.sync(on_error="warn")
    assert not m._is_synced


def test_metric_healthy_sync_still_works(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather())
    m.update(jnp.asarray(1.0))
    m.sync()
    assert m._is_synced
    np.testing.assert_allclose(np.asarray(m.x), WORLD * 1.0)
    m.unsync()
    np.testing.assert_allclose(np.asarray(m.x), 1.0)


def test_metric_strict_update_count_skew(fake_world):
    def skew(word):
        word[_F_UPDATES] = int(word[_F_UPDATES]) + 1
        return word

    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=skew))
    m.sync_strict_update_count = True
    m.update(jnp.asarray(1.0))
    with pytest.raises(StateDivergenceError, match="update-count skew"):
        m.sync()


def test_sync_on_error_validation():
    with pytest.raises(MetricsTPUUserError, match="sync_on_error"):
        DummyMetricSum(sync_on_error="ignore")
    m = DummyMetricSum()
    with pytest.raises(MetricsTPUUserError, match="on_error"):
        m.sync(on_error="ignore", distributed_available=lambda: True)


def test_sync_context_on_error_local_skips_unsync(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=_schema_diverge))
    m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        with m.sync_context(on_error="local") as synced:
            np.testing.assert_allclose(np.asarray(synced.x), 1.0)
    # exiting after a degraded sync must not raise "already un-synced"
    assert not m._is_synced


# ---------------------------------------------------------------------------
# MetricCollection: all-or-nothing rollback / per-member degradation
# ---------------------------------------------------------------------------

def test_collection_rolls_back_on_member_failure(fake_world):
    from metrics_tpu.core.collections import MetricCollection

    fake_world(EchoAllgather())
    good, bad = DummyMetricSum(), DummyListMetric()  # bad: empty cat state
    mc = MetricCollection({"good": good, "bad": bad})
    for m in mc.values():
        m.distributed_available_fn = lambda: True
    good.update(jnp.asarray(1.0))
    with pytest.raises(StateDivergenceError):
        mc.sync()
    # the already-synced member was rolled back to local state
    assert not good._is_synced and not bad._is_synced
    np.testing.assert_allclose(np.asarray(good.x), 1.0)


def test_collection_on_error_local_degrades_members_independently(fake_world):
    from metrics_tpu.core.collections import MetricCollection

    fake_world(EchoAllgather())
    good, bad = DummyMetricSum(), DummyListMetric()
    mc = MetricCollection({"good": good, "bad": bad})
    for m in mc.values():
        m.distributed_available_fn = lambda: True
    good.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        mc.sync(on_error="local")
    # the healthy member still reports the global value; the sick one
    # degraded to local-only instead of taking the job down
    assert good._is_synced and not bad._is_synced
    np.testing.assert_allclose(np.asarray(good.x), WORLD * 1.0)
    mc.unsync()  # degraded members are skipped, synced ones restored
    np.testing.assert_allclose(np.asarray(good.x), 1.0)


# ---------------------------------------------------------------------------
# check_finite screening
# ---------------------------------------------------------------------------

def test_check_finite_latches_and_refuses_compute():
    m = DummyMetricSum(check_finite=True)
    m.update(jnp.asarray(1.0))
    assert int(np.asarray(m._nonfinite)) == 0
    m.update(jnp.asarray(jnp.nan))
    assert int(np.asarray(m._nonfinite)) == 1
    m.update(jnp.asarray(1.0))  # the flag latches: later finite updates
    assert int(np.asarray(m._nonfinite)) == 1  # cannot clear the poison
    with pytest.raises(NonFiniteStateError, match="non-finite"):
        m.compute()


def test_check_finite_clean_path_unaffected():
    m = DummyMetricSum(check_finite=True)
    m.update(jnp.asarray(2.0))
    np.testing.assert_allclose(np.asarray(m.compute()), 2.0)
    m.reset()
    assert int(np.asarray(m._nonfinite)) == 0


def test_enable_check_finite_after_update_rejected():
    m = DummyMetricSum()
    m.update(jnp.asarray(1.0))
    with pytest.raises(MetricsTPUUserError, match="before the first"):
        m.enable_check_finite()


def test_check_finite_poisoned_rank_fails_symmetrically(fake_world):
    # the local rank itself is poisoned: its own health word carries the
    # flag, so the header gather raises the typed error on every rank
    m = _distributed_metric(fake_world, EchoAllgather(), DummyMetricSum(check_finite=True))
    m.update(jnp.asarray(jnp.inf))
    with pytest.raises(NonFiniteStateError):
        m.sync()


def test_check_finite_enforced_with_custom_dist_sync_fn():
    # a custom transport bypasses the health header, but the poison flag
    # rides it anyway (fx="sum"): every rank sees the same world-summed
    # value post-sync and compute() must still refuse symmetrically
    def seam(state, reductions):
        # fake 2-rank transport: a poisoned peer contributes flag=1
        out = dict(state)
        out[NONFINITE_STATE] = jnp.asarray(state[NONFINITE_STATE], jnp.int32) + 1
        out["x"] = jnp.asarray(state["x"]) * 2
        return out

    m = DummyMetricSum(check_finite=True, dist_sync_fn=seam)
    m.distributed_available_fn = lambda: True
    m.update(jnp.asarray(1.0))  # locally finite — only the "peer" is poisoned
    with pytest.raises(NonFiniteStateError, match="participating process"):
        m.compute()


def test_update_count_ignores_trace_time_invocations():
    # pure_update under jit re-enters _wrap_update with tracer args; retraces
    # are a compilation artifact and must not skew the health word's counter
    m = DummyMetricSum()
    m.update(jnp.asarray(1.0))
    assert m._update_count == 1

    @jax.jit
    def step(state, x):
        return m.pure_update(state, x)

    state = m.init_state()
    for i in range(3):  # first call traces; all three go through pure_update
        state = step(state, jnp.asarray(float(i)))
    assert m._update_count == 1  # eager count only
    np.testing.assert_allclose(np.asarray(state["x"]), 3.0)
    # eager pure_update (warm-ups, bench loops) operates on an explicit
    # state pytree — it must not skew the stateful accumulation's counter
    m.pure_update(m.init_state(), jnp.asarray(5.0))
    assert m._update_count == 1


def test_unsync_tolerated_after_degraded_sync(fake_world):
    # the documented sync -> state_dict -> unsync checkpoint pattern must
    # not crash the very job on_error="local" just saved
    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=_schema_diverge))
    m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        m.sync(on_error="local")
    m.unsync()  # tolerated no-op, not "already un-synced"
    np.testing.assert_allclose(np.asarray(m.x), 1.0)
    # ...but the guard still fires for a genuinely unpaired unsync
    with pytest.raises(MetricsTPUUserError, match="already been un-synced"):
        m.unsync()


# ---------------------------------------------------------------------------
# async (overlapped) sync path: the same divergence classes surface at
# RESOLVE time with identical typed errors and on_error degradation, and the
# channel-suspect latch covers the background thread
# ---------------------------------------------------------------------------


def test_async_dead_rank_mid_flight_times_out_at_resolve(fake_world):
    # the peer dies while the round is in flight: the background thread's
    # watchdog fires, the typed timeout surfaces at the next read
    m = _distributed_metric(fake_world, EchoAllgather(delay_s=3.0))
    m.sync_timeout = 0.2
    m.update(jnp.asarray(1.0))
    m.sync(blocking=False)
    with pytest.raises(SyncTimeoutError):
        m.sync()
    # the accumulation survived the failed round
    np.testing.assert_allclose(np.asarray(m.x), 1.0)


def test_async_watchdog_fire_latches_channel_suspect(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather(delay_s=3.0))
    m.sync_timeout = 0.2
    m.update(jnp.asarray(1.0))
    m.sync(blocking=False)
    with pytest.raises(SyncTimeoutError):
        m.sync()
    # the background watchdog poisoned collective ordering process-wide:
    # a NEW blocking sync refuses up front, exactly like the foreground case
    assert channel_is_suspect()
    m2 = DummyMetricSum()
    m2.distributed_available_fn = lambda: True
    m2.update(jnp.asarray(2.0))
    with pytest.raises(SyncTimeoutError, match="refused"):
        m2.sync()
    reset_channel_health()


def test_async_divergent_header_at_resolve(fake_world):
    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=_schema_diverge))
    m.update(jnp.asarray(1.0))
    m.sync(blocking=False)
    with pytest.raises(StateDivergenceError):
        m.sync()
    np.testing.assert_allclose(np.asarray(m.x), 1.0)  # fold-back before raise


def test_async_degrades_local_then_blocking_sync_recovers(fake_world):
    # round 1 hits a divergent peer; on_error="local" keeps the local
    # accumulation; once the divergence clears, a LATER blocking sync of the
    # same metric recovers the global view
    echo = EchoAllgather(mutate_first=_schema_diverge)
    m = _distributed_metric(fake_world, echo)
    m.sync_on_error = "local"
    m.update(jnp.asarray(1.0))
    m.sync(blocking=False)
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        m.sync()
    assert not m._is_synced and m._sync_degraded
    assert m.sync_stats()["degraded"] == 1
    np.testing.assert_allclose(np.asarray(m.x), 1.0)
    m.unsync()  # tolerated no-op after the degradation
    # the transient divergence clears (mutate_first hit only the first
    # gather): blocking sync now succeeds and reports the world value
    m.sync()
    assert m._is_synced
    np.testing.assert_allclose(np.asarray(m.x), 2.0)  # echo world of 2
    m.unsync()


def test_custom_dist_sync_fn_drains_pending_rounds(fake_world):
    # the foreground-drains-first ordering invariant applies to custom
    # transports too: a blocking custom-fn sync must not issue collectives
    # while another metric's background round is still running
    slow = EchoAllgather(delay_s=0.3)
    a = _distributed_metric(fake_world, slow)
    a.sync_timeout = 0  # watchdog off; the background gather takes ~0.3 s
    a.update(jnp.asarray(1.0))
    a.sync(blocking=False)
    b = DummyMetricSum()
    b.distributed_available_fn = lambda: True
    b.update(jnp.asarray(2.0))
    seen = {}

    def fn(state, reds):
        seen["a_round_done"] = a.__dict__["_inflight"].future.done()
        return state

    b.sync(dist_sync_fn=fn)
    assert seen["a_round_done"]  # b's transport ran only after a's round
    b.unsync()
    a.unsync()  # drain/cancel a's (already finished) round


def test_async_update_while_in_flight_then_snapshot_policy(fake_world):
    # updates during the window accumulate into the delta buffer; a
    # "snapshot" resolve serves the consistent cut and unsync restores the
    # full accumulation — nothing is silently mixed
    m = _distributed_metric(fake_world, EchoAllgather())
    m.update(jnp.asarray(1.0))
    m.sync(blocking=False)
    m.update(jnp.asarray(10.0))
    m.sync()
    assert m.sync_stats()["stale_resolves"] == 1
    np.testing.assert_allclose(np.asarray(m.x), 2.0)  # echo world of snapshot 1.0
    m.unsync()
    np.testing.assert_allclose(np.asarray(m.x), 11.0)


def test_catbuffer_has_nonfinite():
    buf = CatBuffer(4)
    buf.append(jnp.asarray([1.0, 2.0]))
    assert not bool(np.asarray(buf.has_nonfinite()))
    buf.append(jnp.asarray([jnp.nan]))
    assert bool(np.asarray(buf.has_nonfinite()))
    ints = CatBuffer(4)
    ints.append(jnp.asarray([1, 2]))
    assert not bool(np.asarray(ints.has_nonfinite()))  # ints always finite


# ---------------------------------------------------------------------------
# watchdog + coordinator-bind retry primitives
# ---------------------------------------------------------------------------

def test_watchdog_passthrough_and_error_propagation():
    assert call_with_sync_watchdog(lambda: 41 + 1, timeout=5.0) == 42

    def boom():
        raise ValueError("inner")

    with pytest.raises(ValueError, match="inner"):
        call_with_sync_watchdog(boom, timeout=5.0)


def test_watchdog_disabled_runs_inline():
    tid = call_with_sync_watchdog(threading.get_ident, timeout=0)
    assert tid == threading.get_ident()  # no worker thread when disabled


def test_watchdog_times_out():
    with pytest.raises(SyncTimeoutError, match="did not complete"):
        call_with_sync_watchdog(lambda: time.sleep(3.0), timeout=0.1, what="test gather")


def test_initialize_retry_absorbs_transient_port_race():
    attempts = []

    def flaky(**kwargs):
        attempts.append(kwargs)
        if len(attempts) < 3:
            raise RuntimeError("Address already in use: 127.0.0.1:9999")

    distributed_initialize_with_retry(
        "localhost:9999", 2, 0, base_backoff_s=0.001, initialize_fn=flaky
    )
    assert len(attempts) == 3
    assert attempts[0]["coordinator_address"] == "localhost:9999"


def test_initialize_retry_nontransient_raises_immediately():
    calls = []

    def broken(**kwargs):
        calls.append(1)
        raise ValueError("invalid process_id")

    with pytest.raises(ValueError):
        distributed_initialize_with_retry(
            "localhost:9999", 2, 0, base_backoff_s=0.001, initialize_fn=broken
        )
    assert len(calls) == 1


def test_initialize_retry_exhaustion_is_typed_and_chained():
    def always_down(**kwargs):
        raise RuntimeError("failed to connect to coordinator")

    with pytest.raises(SyncTimeoutError, match="failed after 3 attempts") as ei:
        distributed_initialize_with_retry(
            "localhost:9999", 2, 1, max_retries=2, base_backoff_s=0.001,
            initialize_fn=always_down,
        )
    assert isinstance(ei.value.__cause__, RuntimeError)


# ---------------------------------------------------------------------------
# on_missing matrix (ISSUE 16): the missing-rank policy composes with the
# on_error ladder — "raise" keeps the pre-quorum behavior bit-for-bit,
# "local" degrades ONLY the missing-rank class, "quorum" shrinks the
# membership over an installed transport and re-runs the gather.
# The fleet-scale end-to-end complement lives in test_resilience.py.
# ---------------------------------------------------------------------------


@pytest.fixture(autouse=True)
def _fresh_resilience():
    from metrics_tpu.parallel import resilience

    resilience.reset_resilience()
    yield
    resilience.reset_resilience()


class _EchoTransport:
    """Quorum transport over the Echo world: ``probe()`` reports ``live``
    (mutable — a scenario script), and negotiation/subset gathers echo this
    rank's contribution for every live peer (symmetric agreement)."""

    def __init__(self, live=(0,)):
        self.live = tuple(live)
        self.subset_calls = 0

    def probe(self):
        return self.live

    def negotiate_allgather(self, vec, live):
        return np.stack([np.asarray(vec)] * len(live))

    def subset_allgather(self, x, live):
        self.subset_calls += 1
        return jnp.asarray(np.stack([np.asarray(x)] * len(live)))


def test_on_missing_validation():
    from metrics_tpu.core.metric import Metric  # noqa: F401 - import check

    with pytest.raises(MetricsTPUUserError, match="sync_on_missing"):
        DummyMetricSum(sync_on_missing="bogus")
    m = DummyMetricSum()
    with pytest.raises(MetricsTPUUserError, match="on_missing"):
        m.sync(on_missing="bogus", distributed_available=lambda: True)


def test_on_missing_local_degrades_dead_rank_only(fake_world):
    # a dead peer degrades to local state WITHOUT on_error="local" ...
    m = _distributed_metric(fake_world, EchoAllgather(delay_s=3.0))
    m.sync_on_missing = "local"
    m.update(jnp.asarray(1.0))
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        m.sync(timeout=0.2)
    assert not m._is_synced and m._sync_degraded
    np.testing.assert_allclose(np.asarray(m.x), 1.0)


def test_on_missing_local_still_raises_non_missing_errors(fake_world):
    # ... but a poisoned peer is NOT a missing rank: the typed raise stands
    def poison(word):
        word[_F_NONFINITE] = 1
        return word

    m = _distributed_metric(fake_world, EchoAllgather(mutate_first=poison))
    m.sync_on_missing = "local"
    m.update(jnp.asarray(1.0))
    with pytest.raises(NonFiniteStateError):
        m.sync(timeout=0.2)


def test_on_missing_quorum_without_transport_falls_through(fake_world):
    from metrics_tpu.observability import diagnostics

    diagnostics.reset("quorum-no-transport")
    fake_world(EchoAllgather(delay_s=3.0))
    state, reds = _sum_state()
    with pytest.raises(SyncTimeoutError, match="dead or stalled"):
        host_sync_state(state, reds, timeout=0.2, on_missing="quorum")
    assert diagnostics.seen("quorum-no-transport")
    diagnostics.reset("quorum-no-transport")


def test_on_missing_quorum_shrinks_dead_rank_to_survivors(fake_world):
    from metrics_tpu.parallel import resilience

    transport = _EchoTransport(live=(0,))  # only this rank is reachable
    resilience.set_quorum_transport(transport)
    fake_world(EchoAllgather(delay_s=3.0))  # the full-world gather is dead
    state, reds = _sum_state()
    out = host_sync_state(state, reds, timeout=0.2, on_missing="quorum")
    # shrank to a quorum of one and re-ran the gather over the survivor set
    np.testing.assert_allclose(np.asarray(out["x"]), 1.0)
    assert resilience.membership_epoch() == 1
    assert resilience.live_ranks() == (0,)
    assert resilience.effective_world() == 1
    assert transport.subset_calls > 0
    # the quorum retry readmitted the channel: no latched refusal afterwards
    assert not channel_is_suspect()


def test_on_missing_quorum_readmits_recovered_rank(fake_world):
    from metrics_tpu.parallel import resilience

    transport = _EchoTransport(live=(0,))
    resilience.set_quorum_transport(transport)
    fake_world(EchoAllgather(delay_s=3.0))
    state, reds = _sum_state()
    host_sync_state(state, reds, timeout=0.2, on_missing="quorum")
    assert resilience.membership_epoch() == 1 and resilience.live_ranks() == (0,)

    # the lost peer comes back: the next quorum-mode sync renegotiates the
    # full membership and gathers over the full world again
    transport.live = (0, 1)
    fake_world(EchoAllgather())  # transport healed
    out = host_sync_state(state, reds, timeout=0.2, on_missing="quorum")
    assert resilience.membership_epoch() == 2
    assert resilience.live_ranks() == (0, 1)
    assert resilience.effective_world() == WORLD
    np.testing.assert_allclose(np.asarray(out["x"]), WORLD * 1.0)


def test_on_missing_quorum_all_live_is_invisible(fake_world):
    from metrics_tpu.parallel import resilience

    transport = _EchoTransport(live=(0, 1))
    resilience.set_quorum_transport(transport)
    ag = fake_world(EchoAllgather())
    state, reds = _sum_state()
    out = host_sync_state(state, reds, update_count=1, on_missing="quorum")
    # all-live: identical collectives to on_missing="raise", no negotiation,
    # no subset routing, membership untouched
    np.testing.assert_allclose(np.asarray(out["x"]), WORLD * 1.0)
    assert resilience.membership_epoch() == 0
    assert transport.subset_calls == 0
    assert ag.calls == 2  # header + one fused payload bucket, as ever


def test_async_on_missing_local_degrades_at_resolve(fake_world):
    # overlapped round: the peer dies mid-flight; the launch-time policy
    # rides the round and degrades the resolve instead of raising
    m = _distributed_metric(fake_world, EchoAllgather(delay_s=3.0))
    m.sync_timeout = 0.2
    m.sync_on_missing = "local"
    m.update(jnp.asarray(1.0))
    m.sync(blocking=False)
    with pytest.warns(RuntimeWarning, match="LOCAL-ONLY"):
        m.sync()
    assert not m._is_synced and m._sync_degraded
    np.testing.assert_allclose(np.asarray(m.x), 1.0)
