"""Persistent compilation cache: enabling it must actually write cache
entries that a second process can hit (the eigh/Inception compile cost is
paid once per machine, not per process)."""
import os
import subprocess
import sys

CHILD = """
import sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from metrics_tpu.utils import compile_cache
compile_cache.enable({cache!r}, min_compile_seconds=0.0)
import jax.numpy as jnp
import numpy as np
t0 = time.perf_counter()
# a compile that is unique to this test but identical across both children
f = jax.jit(lambda x: jnp.tanh(x @ x.T) * 1.25 + jnp.cos(x).sum())
out = f(jnp.arange(64.0).reshape(8, 8))
out.block_until_ready()
print("COMPILE_S", time.perf_counter() - t0)
"""


def test_cache_dir_populated_and_hit(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    cache = str(tmp_path / "xla")
    code = CHILD.format(repo=repo, cache=cache)
    r1 = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, timeout=240)
    assert r1.returncode == 0, r1.stderr[-800:]
    entries = []
    for root, _, files in os.walk(cache):
        entries += files
    assert entries, "cache dir is empty after a jit compile"
    r2 = subprocess.run([sys.executable, "-c", code], capture_output=True, text=True, timeout=240)
    assert r2.returncode == 0, r2.stderr[-800:]


def test_enable_returns_default_dir(monkeypatch, tmp_path):
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    import importlib

    from metrics_tpu.utils import compile_cache

    importlib.reload(compile_cache)
    try:
        got = compile_cache.enable()
        assert got.startswith(str(tmp_path))
        assert os.path.isdir(got)
    finally:
        importlib.reload(compile_cache)  # restore module-level default
