"""masked_binary_auroc — static-shape Mann-Whitney AUROC with tie handling.

Parity vs sklearn's trapezoidal roc_auc_score (exact, including ties), plus
the design goal it unlocks: a CatBuffer AUROC whose update + collective sync
+ compute trace into ONE jitted XLA program.
"""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from sklearn.metrics import roc_auc_score

from sklearn.metrics import average_precision_score

from metrics_tpu import AUROC, AveragePrecision
from metrics_tpu.ops.ranking import (
    masked_binary_auroc,
    masked_binary_average_precision,
    tie_averaged_ranks,
)

rng = np.random.RandomState(21)


def test_tie_averaged_ranks_matches_scipy():
    from scipy.stats import rankdata

    vals = np.array([3.0, 1.0, 3.0, 2.0, 3.0, 1.0], np.float32)
    got = np.asarray(tie_averaged_ranks(jnp.asarray(vals), jnp.ones(6, bool)))
    np.testing.assert_allclose(got, rankdata(vals), atol=1e-6)


def test_ranks_with_mask_ignore_padding():
    vals = np.array([0.5, 0.2, 9.9, 0.8, 9.9], np.float32)  # rows 2,4 padded
    valid = np.array([True, True, False, True, False])
    got = np.asarray(tie_averaged_ranks(jnp.asarray(vals), jnp.asarray(valid)))
    np.testing.assert_allclose(got[valid], [2.0, 1.0, 3.0], atol=1e-6)


@pytest.mark.parametrize("n", [16, 321, 2048])
def test_auroc_parity_continuous(n):
    p = rng.rand(n).astype(np.float32)
    t = rng.randint(0, 2, n)
    got = float(masked_binary_auroc(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got, roc_auc_score(t, p), atol=1e-6)


def test_auroc_parity_heavy_ties():
    # quantized scores: many tied groups — the case where naive trapz breaks
    p = (rng.randint(0, 5, 400) / 4.0).astype(np.float32)
    t = rng.randint(0, 2, 400)
    got = float(masked_binary_auroc(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got, roc_auc_score(t, p), atol=1e-6)


def test_auroc_mask_equals_slice():
    p = rng.rand(256).astype(np.float32)
    t = rng.randint(0, 2, 256)
    mask = np.arange(256) < 100
    got = float(masked_binary_auroc(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask)))
    np.testing.assert_allclose(got, roc_auc_score(t[:100], p[:100]), atol=1e-6)


def test_ranks_with_valid_neg_inf_scores():
    """A valid -inf score must rank below every other valid row, not collide
    with the padding (regression: sentinel-value sorting)."""
    vals = np.array([-np.inf, 0.5, 0.2, 1.0], np.float32)
    valid = np.array([True, True, False, True])
    got = np.asarray(tie_averaged_ranks(jnp.asarray(vals), jnp.asarray(valid)))
    np.testing.assert_allclose(got[valid], [1.0, 2.0, 3.0], atol=1e-6)
    # sklearn rejects -inf inputs; by hand: positives {0.9, 0.1} vs negatives
    # {-inf, 0.5} win 3 of 4 pairs -> AUROC 0.75
    p = np.array([-np.inf, 0.9, 0.1, 0.5], np.float32)
    t = np.array([0, 1, 1, 0])
    got_auc = float(masked_binary_auroc(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got_auc, 0.75, atol=1e-6)


def test_auroc_pos_label_zero_not_fast_pathed():
    """pos_label=0 must keep curve-path semantics (class 0 scored positive)."""
    p = rng.rand(6, 32).astype(np.float32)
    t = rng.randint(0, 2, (6, 32))
    m_list, m_cb = AUROC(pos_label=0), AUROC(pos_label=0).with_capacity(256)
    for i in range(6):
        m_list.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
        m_cb.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    np.testing.assert_allclose(float(m_cb.compute()), float(m_list.compute()), atol=1e-6)


def test_auroc_degenerate_single_class():
    p = rng.rand(32).astype(np.float32)
    assert float(masked_binary_auroc(jnp.asarray(p), jnp.zeros(32))) == 0.5
    assert float(masked_binary_auroc(jnp.asarray(p), jnp.ones(32))) == 0.5


@pytest.mark.parametrize("n", [16, 321, 2048])
def test_average_precision_parity_continuous(n):
    p = rng.rand(n).astype(np.float32)
    t = rng.randint(0, 2, n)
    got = float(masked_binary_average_precision(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got, average_precision_score(t, p), atol=1e-6)


def test_average_precision_parity_heavy_ties():
    p = (rng.randint(0, 5, 400) / 4.0).astype(np.float32)
    t = rng.randint(0, 2, 400)
    got = float(masked_binary_average_precision(jnp.asarray(p), jnp.asarray(t)))
    np.testing.assert_allclose(got, average_precision_score(t, p), atol=1e-6)


def test_average_precision_mask_equals_slice():
    p = rng.rand(256).astype(np.float32)
    t = rng.randint(0, 2, 256)
    mask = np.arange(256) < 100
    got = float(
        masked_binary_average_precision(jnp.asarray(p), jnp.asarray(t), jnp.asarray(mask))
    )
    np.testing.assert_allclose(got, average_precision_score(t[:100], p[:100]), atol=1e-6)


def test_average_precision_no_positives_nan():
    p = rng.rand(32).astype(np.float32)
    assert np.isnan(float(masked_binary_average_precision(jnp.asarray(p), jnp.zeros(32))))


def test_catbuffer_average_precision_matches_list_mode():
    p = rng.rand(10, 32).astype(np.float32)
    t = rng.randint(0, 2, (10, 32))
    m_list, m_cb = AveragePrecision(), AveragePrecision().with_capacity(512)
    for i in range(10):
        m_list.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
        m_cb.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    np.testing.assert_allclose(float(m_cb.compute()), float(m_list.compute()), atol=1e-6)
    np.testing.assert_allclose(
        float(m_cb.compute()), average_precision_score(t.reshape(-1), p.reshape(-1)), atol=1e-6
    )


def test_catbuffer_ap_binarizes_nonbinary_targets():
    """Raw targets outside {0,1} must binarize via pos_label like the curve
    path (one-vs-rest over raw labels), not act as weights."""
    p = rng.rand(200).astype(np.float32)
    t = rng.randint(0, 3, 200)  # values {0,1,2}
    m_list, m_cb = AveragePrecision(), AveragePrecision().with_capacity(256)
    m_list.update(jnp.asarray(p), jnp.asarray(t))
    m_cb.update(jnp.asarray(p), jnp.asarray(t))
    np.testing.assert_allclose(float(m_cb.compute()), float(m_list.compute()), atol=1e-6)
    np.testing.assert_allclose(
        float(m_cb.compute()), average_precision_score((t == 1).astype(int), p), atol=1e-6
    )


def test_fused_average_precision_jitted():
    m = AveragePrecision().with_capacity(320)
    p = rng.rand(10, 32).astype(np.float32)
    t = rng.randint(0, 2, (10, 32))
    m.update(jnp.asarray(p[0]), jnp.asarray(t[0]))
    m.reset()
    step = jax.jit(m.pure_update)
    state = m.init_state()
    for i in range(10):
        state = step(state, jnp.asarray(p[i]), jnp.asarray(t[i]))
    val = jax.jit(m.pure_compute)(state)  # compute itself traces
    np.testing.assert_allclose(
        float(val), average_precision_score(t.reshape(-1), p.reshape(-1)), atol=1e-6
    )


def test_catbuffer_auroc_compute_matches_list_mode():
    p = rng.rand(10, 32).astype(np.float32)
    t = rng.randint(0, 2, (10, 32))
    m_list, m_cb = AUROC(), AUROC().with_capacity(512)
    for i in range(10):
        m_list.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
        m_cb.update(jnp.asarray(p[i]), jnp.asarray(t[i]))
    np.testing.assert_allclose(float(m_cb.compute()), float(m_list.compute()), atol=1e-6)


def test_fully_fused_sharded_pipeline():
    """update + all_gather sync + compute in ONE traced program, multi-device."""
    world = 4
    per_rank = 2
    p = rng.rand(world * per_rank, 32).astype(np.float32)
    t = rng.randint(0, 2, (world * per_rank, 32))
    mesh = Mesh(np.array(jax.devices()[:world]), ("dp",))
    m = AUROC().with_capacity(per_rank * 32)
    m.update(jnp.asarray(p[0]), jnp.asarray(t[0]))
    m.reset()

    @partial(jax.shard_map, mesh=mesh, in_specs=(P("dp"), P("dp")), out_specs=P(), check_vma=False)
    def fused(p_sh, t_sh):
        st = m.init_state()
        for i in range(per_rank):
            st = m.pure_update(st, p_sh[0, i], t_sh[0, i])
        synced = m.pure_sync(st, "dp")
        return m.pure_compute(synced)  # masked rank formula — traces fine

    out = jax.jit(fused)(
        jnp.asarray(p.reshape(world, per_rank, 32)),
        jnp.asarray(t.reshape(world, per_rank, 32)),
    )
    # rank-strided vs contiguous order doesn't matter: AUROC is permutation-invariant
    np.testing.assert_allclose(float(out), roc_auc_score(t.reshape(-1), p.reshape(-1)), atol=1e-6)


def test_fused_forward_jitted():
    """pure_forward (state, batch) -> (state, batch_auroc) under jit."""
    m = AUROC().with_capacity(320)
    p = rng.rand(10, 32).astype(np.float32)
    t = rng.randint(0, 2, (10, 32))
    m.update(jnp.asarray(p[0]), jnp.asarray(t[0]))
    m.reset()
    fwd = jax.jit(m.pure_forward)
    state = m.init_state()
    # materialize buffers once (first trace), then steady state
    for i in range(10):
        state, batch_val = fwd(state, jnp.asarray(p[i]), jnp.asarray(t[i]))
        np.testing.assert_allclose(float(batch_val), roc_auc_score(t[i], p[i]), atol=1e-6)
    np.testing.assert_allclose(
        float(m.pure_compute(state)), roc_auc_score(t.reshape(-1), p.reshape(-1)), atol=1e-6
    )
