"""IoU / CohenKappa / MatthewsCorrcoef input-type matrices vs sklearn.

Mirror of the reference's `tests/classification/test_iou.py`,
`test_cohen_kappa.py`, and `test_matthews_corrcoef.py`: binary / prob /
multilabel / multiclass / mdmc fixtures through class (eager + ddp +
per-step sync) and functional paths against jaccard_score /
cohen_kappa_score / matthews_corrcoef, plus IoU's hand-worked
ignore_index / absent_score edge tables.
"""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import cohen_kappa_score as sk_cohen_kappa
from sklearn.metrics import jaccard_score as sk_jaccard
from sklearn.metrics import matthews_corrcoef as sk_matthews

from metrics_tpu import CohenKappa, IoU, MatthewsCorrcoef
from metrics_tpu.functional import cohen_kappa, iou, matthews_corrcoef
from tests.classification.inputs import (
    _input_binary,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_prob as _input_mcls_prob,
    _input_multidim_multiclass as _input_mdmc,
    _input_multilabel as _input_mlb,
    _input_multilabel_prob as _input_mlb_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _flat_labels(preds, target, num_classes):
    """Collapse any accepted input pair to flat label vectors (argmax probs /
    threshold binaries), mirroring the reference's per-case sk wrappers."""
    p, t = np.asarray(preds), np.asarray(target)
    if p.dtype.kind == "f":
        if p.ndim == t.ndim + 1:  # class dim present → argmax
            p = np.argmax(p, axis=1)
        else:  # probabilities → threshold
            p = (p >= THRESHOLD).astype(int)
    return p.reshape(-1), t.reshape(-1)


def _sk_iou(preds, target, num_classes, average="macro"):
    p, t = _flat_labels(preds, target, num_classes)
    return sk_jaccard(t, p, average=average, labels=list(range(num_classes)))


def _sk_kappa(preds, target, num_classes, weights=None):
    p, t = _flat_labels(preds, target, num_classes)
    return sk_cohen_kappa(y1=t, y2=p, weights=weights)


def _sk_mcc(preds, target, num_classes):
    p, t = _flat_labels(preds, target, num_classes)
    return sk_matthews(t, p)


_GRID = [
    (_input_binary_prob.preds, _input_binary_prob.target, 2),
    (_input_binary.preds, _input_binary.target, 2),
    (_input_mlb_prob.preds, _input_mlb_prob.target, 2),
    (_input_mlb.preds, _input_mlb.target, 2),
    (_input_mcls_prob.preds, _input_mcls_prob.target, NUM_CLASSES),
    (_input_multiclass.preds, _input_multiclass.target, NUM_CLASSES),
    (_input_mdmc.preds, _input_mdmc.target, NUM_CLASSES),
]
_GRID_IDS = ["binary_prob", "binary", "multilabel_prob", "multilabel", "mcls_prob", "mcls", "mdmc"]


@pytest.mark.parametrize("preds, target, num_classes", _GRID, ids=_GRID_IDS)
class TestConfmatDerivedMatrix(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_iou_class(self, preds, target, num_classes, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=IoU,
            sk_metric=partial(_sk_iou, num_classes=num_classes),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"num_classes": num_classes, "threshold": THRESHOLD},
            check_jit=False,
        )

    def test_iou_fn(self, preds, target, num_classes):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=iou,
            sk_metric=partial(_sk_iou, num_classes=num_classes),
            metric_args={"num_classes": num_classes, "threshold": THRESHOLD},
        )

    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    @pytest.mark.parametrize("ddp", [True, False])
    def test_kappa_class(self, preds, target, num_classes, weights, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=CohenKappa,
            sk_metric=partial(_sk_kappa, num_classes=num_classes, weights=weights),
            metric_args={"num_classes": num_classes, "threshold": THRESHOLD, "weights": weights},
            check_jit=False,
        )

    @pytest.mark.parametrize("weights", [None, "linear", "quadratic"])
    def test_kappa_fn(self, preds, target, num_classes, weights):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=cohen_kappa,
            sk_metric=partial(_sk_kappa, num_classes=num_classes, weights=weights),
            metric_args={"num_classes": num_classes, "threshold": THRESHOLD, "weights": weights},
        )

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_mcc_class(self, preds, target, num_classes, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=MatthewsCorrcoef,
            sk_metric=partial(_sk_mcc, num_classes=num_classes),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"num_classes": num_classes, "threshold": THRESHOLD},
            check_jit=False,
        )

    def test_mcc_fn(self, preds, target, num_classes):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=matthews_corrcoef,
            sk_metric=partial(_sk_mcc, num_classes=num_classes),
            metric_args={"num_classes": num_classes, "threshold": THRESHOLD},
        )


@pytest.mark.parametrize(
    "pred, target, ignore_index, absent_score, num_classes, expected",
    [
        # the reference's absent_score table (`test_iou.py:165-198`)
        ([0], [0], None, -1.0, 2, [1.0, -1.0]),
        ([0, 0], [0, 0], None, -1.0, 2, [1.0, -1.0]),
        ([0], [0], None, -1.0, 1, [1.0]),
        ([1], [1], None, -1.0, 2, [-1.0, 1.0]),
        ([1], [1], 0, -1.0, 2, [1.0]),
        ([0, 2], [0, 2], None, -1.0, 3, [1.0, -1.0, 1.0]),
        ([0, 1], [0, 1], None, -1.0, 3, [1.0, 1.0, -1.0]),
        ([0, 1], [0, 0], None, -1.0, 3, [0.5, 0.0, -1.0]),
        ([0, 0], [0, 1], None, -1.0, 3, [0.5, 0.0, -1.0]),
        ([0, 2], [0, 2], None, 1.0, 3, [1.0, 1.0, 1.0]),
        ([0, 2], [0, 2], 0, 1.0, 3, [1.0, 1.0]),
    ],
)
def test_iou_absent_score(pred, target, ignore_index, absent_score, num_classes, expected):
    out = iou(
        jnp.asarray(pred), jnp.asarray(target),
        ignore_index=ignore_index, absent_score=absent_score,
        num_classes=num_classes, reduction="none",
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-6)


@pytest.mark.parametrize(
    "pred, target, ignore_index, num_classes, reduction, expected",
    [
        # the reference's ignore_index table (`test_iou.py:211-226`)
        ([0, 1, 1, 2, 2], [0, 1, 2, 2, 2], None, 3, "none", [1, 1 / 2, 2 / 3]),
        ([0, 1, 1, 2, 2], [0, 1, 2, 2, 2], 0, 3, "none", [1 / 2, 2 / 3]),
        ([0, 1, 1, 2, 2], [0, 1, 2, 2, 2], 1, 3, "none", [1, 2 / 3]),
        ([0, 1, 1, 2, 2], [0, 1, 2, 2, 2], 2, 3, "none", [1, 1]),
        ([0, 1, 1, 2, 2], [0, 1, 2, 2, 2], 0, 3, "elementwise_mean", [7 / 12]),
        ([0, 1, 1, 2, 2], [0, 1, 2, 2, 2], 0, 3, "sum", [7 / 6]),
    ],
)
def test_iou_ignore_index(pred, target, ignore_index, num_classes, reduction, expected):
    out = iou(
        jnp.asarray(pred), jnp.asarray(target),
        ignore_index=ignore_index, num_classes=num_classes, reduction=reduction,
    )
    np.testing.assert_allclose(np.asarray(out).reshape(-1), np.asarray(expected), atol=1e-6)
