"""Fixed-capacity "cat"-state ring buffers — TPU-native list states.

The reference accumulates curve/retrieval inputs in *growing python lists*
(``add_state(default=[], dist_reduce_fx="cat")``, reference ``metric.py:112-176``)
and concatenates at ``compute()``. Growing shapes are hostile to XLA: every new
batch count retraces the jitted step, and collectives need static shapes
(reference pads ad hoc at ``utilities/distributed.py:122-145``).

:class:`CatBuffer` replaces the list with a **pre-allocated
``[capacity, ...]`` buffer + a fill count**:

- ``append`` is a ``lax.dynamic_update_slice`` — static shapes, O(1) memory,
  the jitted update step never retraces as data accumulates and the buffer can
  be donated. The compiled eager hot path (``core/compiled.py``) relies on
  both properties: a CatBuffer-state metric's ``update()`` auto-JITs into one
  donated-buffer program per step, where a growing list state would retrace
  every step (and is therefore routed to eager).
- cross-device sync is a plain ``lax.all_gather`` of buffers + counts
  followed by a static-shape compaction of contiguous
  ``dynamic_update_slice`` copies (:func:`sync_cat_buffer_in_jit`) — the
  uneven-per-rank protocol with no host round-trip and no row scatter
  (TPU scatters serialize; measured 256x slower).
- ``merge`` (checkpoint resume / ``forward`` accumulation) is one
  contiguous ``dynamic_update_slice`` at the fill offset, also static-shape.

Opt in per metric via ``metric.with_capacity(n)``: every declared list state
becomes a ``CatBuffer``; the metric's ``update``/``compute`` code is unchanged
(``.append`` and ``dim_zero_cat`` dispatch on the type). In a
``MetricCollection`` compute group (``core/collections.py``), curve metrics
with equal capacities share ONE CatBuffer object for the whole group — a
K-metric ROC/PR/AP collection holds one ``[capacity, ...]`` buffer instead
of K, and a stray out-of-group ``update`` copies the buffer wrapper
(``copy()`` — the underlying array is immutable, so the copy is O(1) until
the next append replaces it) before diverging.

Eager appends past capacity raise. Inside jit (no exceptions possible) an
overflowing write clamps at the end of the buffer, the fill count saturates
at ``capacity``, and a persistent ``overflowed`` flag is raised; the flag is
a pytree leaf, so it survives ``scan`` carries, sync (OR across devices) and
``merge``, eager reads (``values()``) raise on it, and consumers NaN-poison
their compute result through :meth:`CatBuffer.poison` — overflow is loud
everywhere instead of silently overwriting rows. Size ``capacity`` to your
eval set.

Checkpointing (``core/checkpoint.py``, ``docs/checkpointing.md``): a
CatBuffer serializes as ``(capacity, buffer rows, count, overflowed)`` with
a CRC per leaf, and the sticky ``overflowed`` flag round-trips — a corrupt
accumulation stays loud across a preemption boundary. Elastic resume folds
shards through :meth:`CatBuffer.merge`, so scale-down (several saved shards
landing on one rank) needs ``capacity`` sized for the combined row counts.
"""
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array, lax

from metrics_tpu.utils.data import is_traced as _is_traced
from metrics_tpu.utils.exceptions import MetricsTPUUserError
from metrics_tpu.utils.prints import rank_zero_warn

__all__ = ["CatBuffer", "sync_cat_buffer_in_jit"]


class CatBuffer:
    """A bounded, jit-friendly accumulation buffer for "cat" metric states.

    XLA needs static shapes, so the reference's grow-as-you-go list states
    (preds/targets for AUROC, PR curves, Spearman, ...) become a
    fixed-capacity ring: ``append`` is a constant-shape
    ``dynamic_update_slice`` at the current ``count`` — traceable inside a
    jitted/scanned step with zero retracing — and consumers mask rows
    ``>= count`` out of the computation instead of slicing them away.
    Registered as a pytree, so it flows through ``jit``/``scan``/
    ``shard_map`` carries; the cross-device gather compacts valid rows
    from every device's buffer. Overflow raises eagerly; under tracing
    (where the count check cannot run) the write clamps, ``count``
    saturates at ``capacity`` and ``overflowed`` latches True — surfaced
    at compute via :meth:`poison` / eager ``values()``.

    Attributes:
        capacity: max number of rows (static).
        buffer: ``[capacity, *item_shape]`` array, or ``None`` until the first
            ``append`` fixes the item shape/dtype.
        count: scalar int32 — number of valid rows (saturates at capacity).
        overflowed: scalar bool — True once any append/merge tried to write
            past capacity; sticky through copy/merge/sync/checkpoint.
    """

    __slots__ = ("capacity", "buffer", "count", "overflowed")

    def __init__(
        self,
        capacity: int,
        buffer: Optional[Array] = None,
        count: Optional[Array] = None,
        overflowed: Optional[Array] = None,
    ) -> None:
        if capacity <= 0:
            raise ValueError(f"CatBuffer capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.buffer = buffer
        self.count = jnp.zeros((), jnp.int32) if count is None else count
        self.overflowed = jnp.zeros((), jnp.bool_) if overflowed is None else overflowed

    # -- accumulation ---------------------------------------------------
    def append(self, batch: Array) -> "CatBuffer":
        """Write a batch of rows at the fill offset (in place; returns self)."""
        batch = jnp.asarray(batch)
        if batch.ndim == 0:
            batch = batch[None]
        n = batch.shape[0]
        if self.buffer is None:
            self.buffer = jnp.zeros((self.capacity,) + batch.shape[1:], batch.dtype)
        if n > self.capacity:
            raise MetricsTPUUserError(
                f"Batch of {n} rows exceeds CatBuffer capacity {self.capacity}."
            )
        if batch.shape[1:] != self.buffer.shape[1:]:
            # the item spec freezes at the first append (and persists through
            # reset() — defaults materialize); be loud instead of letting
            # dynamic_update_slice fail opaquely
            raise MetricsTPUUserError(
                f"CatBuffer item shape mismatch: buffer holds {self.buffer.shape[1:]} "
                f"rows but got {batch.shape[1:]}. One metric instance cannot mix "
                "item shapes; create a fresh metric for differently-shaped inputs."
            )
        if not _is_traced(self.count):
            if int(self.count) + n > self.capacity:
                raise MetricsTPUUserError(
                    f"CatBuffer overflow: {int(self.count)} + {n} > capacity {self.capacity}. "
                    "Construct the metric with a larger `with_capacity(...)`."
                )
        start = (self.count,) + (jnp.zeros((), jnp.int32),) * (batch.ndim - 1)
        self.buffer = lax.dynamic_update_slice(self.buffer, batch.astype(self.buffer.dtype), start)
        new_total = self.count + jnp.asarray(n, jnp.int32)
        # under tracing the eager check above cannot run: saturate the count
        # (dynamic_update_slice already clamped the write) and latch the flag
        # so the corruption is detectable at compute instead of silent
        self.overflowed = jnp.logical_or(self.overflowed, new_total > self.capacity)
        self.count = jnp.minimum(new_total, self.capacity)
        return self

    # -- reads ----------------------------------------------------------
    def values(self) -> Array:
        """The valid rows ``buffer[:count]`` (eager only: dynamic shape)."""
        if self.buffer is None:
            return jnp.zeros((0,))
        if _is_traced(self.count) or _is_traced(self.buffer):
            raise MetricsTPUUserError(
                "CatBuffer.values() needs a concrete fill count and is eager-only; "
                "inside jit use `.buffer` with `.mask()` (padding-aware compute), "
                "or a Binned* metric for a fully-fused constant-shape pipeline."
            )
        if not _is_traced(self.overflowed) and bool(self.overflowed):
            raise MetricsTPUUserError(
                f"CatBuffer overflowed inside jit: more than capacity={self.capacity} "
                "rows were appended, and late rows overwrote earlier ones. The data "
                "is corrupt — construct the metric with a larger `with_capacity(...)` "
                "and re-run."
            )
        return self.buffer[: int(self.count)]

    def mask(self) -> Array:
        """``[capacity]`` bool validity mask — jit-safe padding awareness."""
        return jnp.arange(self.capacity) < self.count

    def poison(self, value: Array) -> Array:
        """NaN-poison ``value`` if this buffer has overflowed — jit-safe.

        Compute paths that consume ``.buffer``/``.mask()`` inside jit cannot
        raise; routing their result through ``poison`` turns a corrupted
        accumulation into NaN (loud) instead of a plausible wrong number
        (silent). Eagerly, a concrete overflow also emits a rank-zero
        warning pointing at ``with_capacity``. Reference list states never
        drop data (``metric.py:112-176``) — this is the TPU-native contract:
        bounded memory, but corruption is always detectable.

        Dtype: a floating ``value`` keeps its dtype. An integer ``value`` is
        widened to float32 only when an overflow is *possible* (traced flag,
        or concretely overflowed) — NaN needs a float carrier; when the flag
        is concretely False the value passes through untouched (ADVICE r4).
        """
        value = jnp.asarray(value)
        if not _is_traced(self.overflowed):
            if not bool(self.overflowed):
                return value
            rank_zero_warn(
                f"CatBuffer overflowed (capacity {self.capacity}): compute returns "
                "NaN. Construct the metric with a larger `with_capacity(...)`."
            )
        out_dtype = value.dtype if jnp.issubdtype(value.dtype, jnp.floating) else jnp.float32
        return jnp.where(self.overflowed, jnp.asarray(jnp.nan, out_dtype), value.astype(out_dtype))

    def has_nonfinite(self) -> Array:
        """Scalar bool: any NaN/Inf among the accumulated rows — jit-safe.

        The ``check_finite`` screening hook (``Metric.enable_check_finite``):
        padding rows are zero by construction (append writes into a zeroed
        buffer; merge/sync re-zero their tails), so the whole-buffer check is
        exact without a mask reduction. Integer buffers are always finite.
        """
        if self.buffer is None or not jnp.issubdtype(self.buffer.dtype, jnp.inexact):
            return jnp.zeros((), jnp.bool_)
        return jnp.logical_not(jnp.all(jnp.isfinite(self.buffer)))

    def __len__(self) -> int:
        return int(self.count)

    # -- functional structure -------------------------------------------
    def copy(self) -> "CatBuffer":
        return CatBuffer(self.capacity, self.buffer, self.count, self.overflowed)

    def fresh_copy(self) -> "CatBuffer":
        """A copy whose array leaves are *newly allocated* buffers.

        Unlike :meth:`copy` (an O(1) wrapper copy sharing the immutable
        leaves), every leaf here is privately owned by the result — the
        copy-on-first-donation primitive of the compiled eager hot path
        (``core/compiled.py``): a donated buffer is invalidated in place, so
        a CatBuffer about to enter a ``donate_argnums`` program must not
        share leaves with defaults, compute-group siblings, sync caches or
        user-held references.
        """
        return CatBuffer(
            self.capacity,
            None if self.buffer is None else jnp.array(self.buffer, copy=True),
            jnp.array(self.count, copy=True),
            jnp.array(self.overflowed, copy=True),
        )

    def reset(self) -> "CatBuffer":
        return CatBuffer(self.capacity)

    def merge(self, other: "CatBuffer") -> "CatBuffer":
        """New CatBuffer = self's rows then other's rows (capacity = self's).

        Static-shape: other's rows scatter at offset ``self.count`` with
        out-of-bounds rows dropped (eager overflow raises; traced overflow
        saturates the count and latches ``overflowed``, like ``append``).
        """
        if other.buffer is None:
            out = self.copy()
            out.overflowed = jnp.logical_or(self.overflowed, other.overflowed)
            return out
        if self.buffer is None:
            base = CatBuffer(self.capacity, overflowed=self.overflowed)
            base.buffer = jnp.zeros((self.capacity,) + other.buffer.shape[1:], other.buffer.dtype)
            base.count = jnp.zeros((), jnp.int32)
            return base.merge(other)
        if not (_is_traced(self.count) or _is_traced(other.count)):
            if int(self.count) + int(other.count) > self.capacity:
                raise MetricsTPUUserError(
                    f"CatBuffer overflow on merge: {int(self.count)} + {int(other.count)} "
                    f"> capacity {self.capacity}."
                )
        # one contiguous dynamic_update_slice instead of a row scatter (same
        # trick as sync_cat_buffer_in_jit's compaction — TPU scatters
        # serialize): other's whole buffer lands at self's fill offset, with
        # a scratch tail preventing start clamping; rows past the merged
        # count are re-zeroed so padding stays deterministic
        item_shape = self.buffer.shape[1:]
        zero_starts = (jnp.zeros((), jnp.int32),) * len(item_shape)
        padded = jnp.concatenate(
            [self.buffer, jnp.zeros((other.capacity,) + item_shape, self.buffer.dtype)]
        )
        padded = lax.dynamic_update_slice(
            padded, other.buffer.astype(self.buffer.dtype), (self.count,) + zero_starts
        )
        new_total = self.count + other.count
        count = jnp.minimum(new_total, self.capacity)
        valid = jnp.arange(self.capacity) < count
        buffer = jnp.where(
            valid.reshape((self.capacity,) + (1,) * len(item_shape)),
            padded[: self.capacity],
            jnp.zeros((), padded.dtype),  # dtype-preserving zero (bool buffers!)
        )
        overflowed = jnp.logical_or(
            jnp.logical_or(self.overflowed, other.overflowed), new_total > self.capacity
        )
        return CatBuffer(self.capacity, buffer, count, overflowed)

    def __repr__(self) -> str:
        item = None if self.buffer is None else self.buffer.shape[1:]
        return f"CatBuffer(capacity={self.capacity}, count={self.count}, item_shape={item})"


def _catbuffer_flatten(cb: CatBuffer) -> Tuple[Sequence[Any], int]:
    return (cb.buffer, cb.count, cb.overflowed), cb.capacity


def _catbuffer_unflatten(capacity: int, children: Sequence[Any]) -> CatBuffer:
    buffer, count, overflowed = children
    return CatBuffer(capacity, buffer, count, overflowed)


jax.tree_util.register_pytree_node(CatBuffer, _catbuffer_flatten, _catbuffer_unflatten)


def sync_cat_buffer_in_jit(cb: CatBuffer, axis_name: str) -> CatBuffer:
    """All-gather a CatBuffer across a mesh axis into one compacted buffer.

    Static-shape replacement for the reference's uneven-shape gather protocol
    (``utilities/distributed.py:122-145``): gather ``[W, capacity, ...]``
    buffers plus one packed ``[W, 2]`` (count, overflow-flag) vector, then
    compact each rank's valid rows at its exclusive-cumsum offset into a
    ``[W*capacity, ...]`` result. Two ``all_gather`` collectives per state,
    riding ICI inside the jitted program.

    The compaction is W contiguous ``dynamic_update_slice`` copies in
    ascending rank order — rank r+1's block starts exactly where rank r's
    valid rows end, so each copy overwrites the previous rank's padding
    tail. No scratch tail is needed: counts saturate at ``capacity``, so
    the last offset is at most ``(W-1)*capacity`` — exactly the clamp
    limit, never past it. Contiguous DMA instead of a row scatter:
    measured **0.445 ms vs 113.8 ms (256x)** on v5e at 8x2M f32 rows (TPU
    scatters serialize at ~150M rows/s; gather-reindex and stable-argsort
    formulations measured worse — BENCH.md config 2 sync term).
    """
    if cb.buffer is None:
        raise MetricsTPUUserError("Cannot sync an empty CatBuffer (no item shape yet).")
    bufs = lax.all_gather(cb.buffer, axis_name)  # [W, cap, ...]
    # the scalar overflow flag rides the counts gather (one packed int32
    # vector) instead of costing a third collective launch
    meta = lax.all_gather(
        jnp.stack([cb.count, cb.overflowed.astype(jnp.int32)]), axis_name
    )  # [W, 2]
    counts = meta[:, 0]
    # per-rank counts saturate at capacity, so sum(counts) <= W*cap = new_cap:
    # the gather itself cannot overflow — only carry the ranks' OR'd flags
    overflowed = jnp.any(meta[:, 1] > 0)
    world = bufs.shape[0]
    new_cap = world * cb.capacity
    offsets = jnp.cumsum(counts) - counts
    item_shape = bufs.shape[2:]
    zero_starts = (jnp.zeros((), jnp.int32),) * len(item_shape)
    out = jnp.zeros((new_cap,) + item_shape, cb.buffer.dtype)
    for r in range(world):
        out = lax.dynamic_update_slice(out, bufs[r], (offsets[r],) + zero_starts)
    total = jnp.sum(counts).astype(jnp.int32)
    # zero the garbage tail (last rank's padding rows) so buffer contents
    # stay deterministic for direct comparisons/checkpoints
    valid = jnp.arange(new_cap) < total
    out = jnp.where(
        valid.reshape((new_cap,) + (1,) * len(item_shape)), out, jnp.zeros((), out.dtype)
    )
    return CatBuffer(new_cap, out, total, overflowed)
