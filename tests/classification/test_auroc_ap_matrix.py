"""AUROC / AveragePrecision input-type × average (× max_fpr) matrices.

Mirror of the reference's `tests/classification/test_auroc.py` and
`test_average_precision.py`: binary / multiclass / mdmc / multilabel /
multilabel-multidim probability fixtures, average ∈ {macro, weighted, micro},
max_fpr ∈ {None, 0.8, 0.5} (binary only, McClish correction), against
sklearn's roc_auc_score / average_precision_score, through class
(eager + ddp + per-step sync) and functional paths.
"""
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_average_precision
from sklearn.metrics import roc_auc_score as sk_roc_auc_score

from metrics_tpu import AUROC, AveragePrecision
from metrics_tpu.functional import auroc, average_precision
from tests.classification.inputs import (
    _input_binary_prob,
    _input_multiclass_prob as _input_mcls_prob,
    _input_multidim_multiclass_prob as _input_mdmc_prob,
    _input_multilabel_multidim_prob as _input_mlmd_prob,
    _input_multilabel_prob as _input_mlb_prob,
)
from tests.helpers.testers import NUM_CLASSES, MetricTester


# -- sk wrappers (reference test_auroc.py:34-87) ----------------------------
def _sk_auroc_binary(preds, target, num_classes, average="macro", max_fpr=None):
    return sk_roc_auc_score(target.reshape(-1), preds.reshape(-1), average=average, max_fpr=max_fpr)


def _sk_auroc_multiclass(preds, target, num_classes, average="macro", max_fpr=None):
    return sk_roc_auc_score(
        target.reshape(-1), preds.reshape(-1, num_classes), average=average, max_fpr=max_fpr, multi_class="ovr"
    )


def _sk_auroc_mdmc(preds, target, num_classes, average="macro", max_fpr=None):
    p = np.moveaxis(preds, 1, -1).reshape(-1, num_classes)
    return sk_roc_auc_score(target.reshape(-1), p, average=average, max_fpr=max_fpr, multi_class="ovr")


def _sk_auroc_multilabel(preds, target, num_classes, average="macro", max_fpr=None):
    return sk_roc_auc_score(
        target.reshape(-1, num_classes), preds.reshape(-1, num_classes), average=average, max_fpr=max_fpr
    )


def _sk_auroc_mlmd(preds, target, num_classes, average="macro", max_fpr=None):
    p = np.moveaxis(preds, 1, -1).reshape(-1, num_classes)
    t = np.moveaxis(target, 1, -1).reshape(-1, num_classes)
    return sk_roc_auc_score(t, p, average=average, max_fpr=max_fpr)


def _sk_ap_binary(preds, target, num_classes):
    return sk_average_precision(target.reshape(-1), preds.reshape(-1))


def _sk_ap_multiclass(preds, target, num_classes):
    p = preds.reshape(-1, num_classes)
    t = target.reshape(-1)
    return np.mean([sk_average_precision((t == c).astype(int), p[:, c]) for c in range(num_classes)])


@pytest.mark.parametrize("average", ["macro", "weighted", "micro"])
@pytest.mark.parametrize(
    "preds, target, sk_metric, num_classes",
    [
        (_input_binary_prob.preds, _input_binary_prob.target, _sk_auroc_binary, 1),
        (_input_mcls_prob.preds, _input_mcls_prob.target, _sk_auroc_multiclass, NUM_CLASSES),
        (_input_mdmc_prob.preds, _input_mdmc_prob.target, _sk_auroc_mdmc, NUM_CLASSES),
        (_input_mlb_prob.preds, _input_mlb_prob.target, _sk_auroc_multilabel, NUM_CLASSES),
        (_input_mlmd_prob.preds, _input_mlmd_prob.target, _sk_auroc_mlmd, NUM_CLASSES),
    ],
    ids=["binary", "multiclass", "mdmc", "multilabel", "mlmd"],
)
class TestAUROCMatrix(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_auroc_class(self, preds, target, sk_metric, num_classes, average, ddp, dist_sync_on_step):
        if average == "micro" and preds.ndim > 2 and preds.ndim == target.ndim + 1:
            pytest.skip("micro average is undefined for multiclass AUROC")

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=AUROC,
            sk_metric=partial(sk_metric, num_classes=num_classes, average=average, max_fpr=None),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={
                "num_classes": None if num_classes == 1 else num_classes,
                "average": average,
            },
            check_batch=False,  # rank-based: per-batch value differs from accumulated
            check_jit=False,
        )

    def test_auroc_fn(self, preds, target, sk_metric, num_classes, average):
        if average == "micro" and preds.ndim > 2 and preds.ndim == target.ndim + 1:
            pytest.skip("micro average is undefined for multiclass AUROC")

        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=auroc,
            sk_metric=partial(sk_metric, num_classes=num_classes, average=average, max_fpr=None),
            metric_args={
                "num_classes": None if num_classes == 1 else num_classes,
                "average": average,
            },
        )


@pytest.mark.parametrize("max_fpr", [0.8, 0.5])
@pytest.mark.parametrize("ddp", [True, False])
def test_auroc_binary_max_fpr(max_fpr, ddp):
    """McClish-corrected partial AUROC is a binary-only argument, so it gets
    its own binary grid instead of 4/5 skipped fixture rows."""

    class _T(MetricTester):
        atol = 1e-6

    _T().run_class_metric_test(
        ddp=ddp,
        preds=_input_binary_prob.preds,
        target=_input_binary_prob.target,
        metric_class=AUROC,
        sk_metric=partial(_sk_auroc_binary, num_classes=1, average="macro", max_fpr=max_fpr),
        metric_args={"max_fpr": max_fpr},
        check_batch=False,
        check_jit=False,
    )


def test_auroc_wrong_max_fpr():
    """Invalid max_fpr values raise (reference `test_auroc.py:141-151`)."""
    import jax.numpy as jnp

    for bad in (-0.5, 0.0, 1.5, "x"):
        with pytest.raises(ValueError):
            auroc(jnp.asarray(_input_binary_prob.preds[0]), jnp.asarray(_input_binary_prob.target[0]), max_fpr=bad)


@pytest.mark.parametrize(
    "preds, target, sk_metric, num_classes",
    [
        (_input_binary_prob.preds, _input_binary_prob.target, _sk_ap_binary, 1),
        (_input_mcls_prob.preds, _input_mcls_prob.target, _sk_ap_multiclass, NUM_CLASSES),
    ],
    ids=["binary", "multiclass"],
)
class TestAveragePrecisionMatrix(MetricTester):
    atol = 1e-6

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_ap_class(self, preds, target, sk_metric, num_classes, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=AveragePrecision,
            sk_metric=partial(sk_metric, num_classes=num_classes),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"num_classes": None if num_classes == 1 else num_classes},
            check_batch=False,
            check_jit=False,
        )

    def test_ap_fn(self, preds, target, sk_metric, num_classes):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=average_precision,
            sk_metric=partial(sk_metric, num_classes=num_classes),
            metric_args={"num_classes": None if num_classes == 1 else num_classes},
        )
