"""metrics_tpu.observability — unified telemetry for the metric runtime.

Three layers, one instrumentation surface (``docs/observability.md``):

- :mod:`~metrics_tpu.observability.journal` — the structured event journal:
  an off-by-default, lock-free per-thread ring recorder of typed runtime
  events (compiled trace/dispatch/fallback, sync launch/resolve/drain with
  ``sync_epoch`` and staleness verdict, health-word failures, watchdog
  fires, channel-suspect latches, degradations, checkpoint save/load/prune,
  compute-group form/detach), each carrying monotonic time, rank and step;
  plus the :func:`on_event` subscriber hook for fleet loggers.
- :mod:`~metrics_tpu.observability.trace_export` — renders the journal as
  a Chrome-trace/Perfetto JSON timeline: one process per rank, the
  overlapped-sync background lane as its own track, rounds correlated
  across ranks by ``sync_epoch``.
- :mod:`~metrics_tpu.observability.registry` — the unified stats registry
  behind ``Metric.telemetry()`` / ``MetricCollection.telemetry()``:
  compile + sync + checkpoint + health counters in one schema'd snapshot
  (``compile_stats()``/``sync_stats()`` are views over it), with
  delta-since-last-call and JSON-lines / Prometheus exporters.

Quick start::

    from metrics_tpu import observability as obs

    obs.enable()                        # start recording
    ... training loop ...
    obs.export_chrome_trace("trace.json")   # open in ui.perfetto.dev
    print(obs.telemetry_prometheus(metric.telemetry()))

    sub = obs.on_event(print, classes=("health", "degrade"))
    ... sub.close()
"""
from metrics_tpu.observability import diagnostics, journal, registry, trace_export
from metrics_tpu.observability.diagnostics import diag, warn_once
from metrics_tpu.observability.journal import (
    EVENT_KINDS,
    Event,
    clear,
    disable,
    enable,
    enabled,
    events,
    on_event,
    record,
)
from metrics_tpu.observability.registry import (
    TELEMETRY_SCHEMA,
    StatsRegistry,
    telemetry_jsonl,
    telemetry_prometheus,
)
from metrics_tpu.observability.trace_export import chrome_trace, export_chrome_trace

__all__ = [
    "EVENT_KINDS",
    "TELEMETRY_SCHEMA",
    "Event",
    "StatsRegistry",
    "chrome_trace",
    "clear",
    "diag",
    "diagnostics",
    "disable",
    "enable",
    "enabled",
    "events",
    "export_chrome_trace",
    "journal",
    "on_event",
    "record",
    "registry",
    "telemetry_jsonl",
    "telemetry_prometheus",
    "trace_export",
    "warn_once",
]
