"""Tier topology for the two-level (hierarchical) host-sync schedule.

A real fleet is not flat: ranks inside one slice/host talk over fast ICI,
ranks across slices over slow DCN — FastUSP-style multi-level collaborative
collectives (PAPERS.md) win exactly because the slow hop carries the fewest
possible participants and bytes. This module owns the *topology* side of
that schedule:

- **Configuration.** A tier map assigns every rank a tier id. Two seams:
  the ``METRICS_TPU_TIER_SIZE`` env knob (ranks ``[k*size, (k+1)*size)``
  share tier ``k`` — matching ``tests/helpers/fake_world.FaultProfile``'s
  latency model) and the explicit :func:`set_tier_map` override (an int
  tier size or a ``rank -> tier`` callable) for irregular fleets. No map
  configured = the flat world, and the sync path is bit-identical to the
  untiered code with zero extra collectives.
- **Negotiation.** The topology is a *pure function* of the negotiated
  live-rank set (``parallel/resilience.py``) and the configured map, so
  every rank derives the identical :class:`TierTopology` with no extra
  collectives — including in the same epoch as a quorum shrink, where the
  survivor set changed under it. The health word (protocol v5,
  ``parallel/health.py``) carries each rank's self-reported tier id and
  payload-precision code; :func:`expected_tier_column` is what the
  verifier compares the gathered column against, so an asymmetric tier map
  (ranks disagreeing who lives in which tier) or a mixed-precision fleet
  raises a typed ``StateDivergenceError`` on every rank *before* any
  payload collective.
- **Transport.** Tiered hops are subset collectives. The seam is the same
  ``subset_allgather(x, ranks)`` interface quorum mode rides
  (``resilience.set_quorum_transport``): :func:`active_tier_transport`
  prefers an explicitly installed tier transport and falls back to the
  quorum transport, so a fleet (or a simulated world) wired for quorum
  sync is tier-capable for free. A tier map configured with *no* transport
  warns once and keeps the flat path — never a silent behavior change.

The schedule itself (reduce-within-tier → one inter-tier exchange per
bucket → intra-tier broadcast) lives with the bucketed execution engine
(``parallel/bucketing.py``); the per-schema schedule cache lives with the
unified plan store (``core/plan.py``).
"""
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "TIER_SIZE_ENV",
    "TierTopology",
    "active_tier_transport",
    "active_topology",
    "expected_tier_column",
    "my_tier_id",
    "reset_tiering",
    "set_tier_map",
    "set_tier_transport",
    "tier_of_rank",
    "tier_topology",
    "tiering_configured",
]

#: Env knob: int tier size — ranks ``[k*size, (k+1)*size)`` share tier ``k``.
TIER_SIZE_ENV = "METRICS_TPU_TIER_SIZE"

_LOCK = threading.Lock()
_TIER_MAP: Optional[Callable[[int], int]] = None
_TIER_MAP_TOKEN: Any = None
_TIER_TRANSPORT: Optional[Any] = None
_TOPOLOGY_CACHE: Dict[Any, "TierTopology"] = {}


def _current_rank() -> int:
    """This process's global rank — the seam simulated thread-per-rank
    worlds monkeypatch to the calling thread's identity (production: one
    rank per process, ``jax.process_index()``)."""
    import jax

    return jax.process_index()


def set_tier_map(tier_map: Any) -> None:
    """Install (or clear, with ``None``) the explicit tier map.

    ``tier_map`` is an int tier size or a ``rank -> tier id`` callable.
    The explicit map wins over the :data:`TIER_SIZE_ENV` knob. Must be
    installed identically on every rank — the health word's tier column
    verifies exactly that and raises symmetrically when it is not.
    """
    global _TIER_MAP, _TIER_MAP_TOKEN
    with _LOCK:
        if tier_map is None:
            _TIER_MAP, _TIER_MAP_TOKEN = None, None
        elif callable(tier_map):
            _TIER_MAP, _TIER_MAP_TOKEN = tier_map, ("fn", id(tier_map))
        else:
            size = int(tier_map)
            if size <= 0:
                from metrics_tpu.utils.exceptions import MetricsTPUUserError

                raise MetricsTPUUserError(
                    f"tier size must be a positive int, got {tier_map!r}"
                )
            _TIER_MAP = lambda rank, _s=size: rank // _s
            _TIER_MAP_TOKEN = ("size", size)
        _TOPOLOGY_CACHE.clear()


def set_tier_transport(transport: Optional[Any]) -> None:
    """Install (or clear) the subset-collective transport tiered hops ride
    on — same ``subset_allgather(x, ranks)`` interface as the quorum
    transport, which :func:`active_tier_transport` falls back to."""
    global _TIER_TRANSPORT
    with _LOCK:
        _TIER_TRANSPORT = transport


def reset_tiering() -> None:
    """Clear map, transport and topology cache (tests)."""
    global _TIER_MAP, _TIER_MAP_TOKEN, _TIER_TRANSPORT
    with _LOCK:
        _TIER_MAP, _TIER_MAP_TOKEN, _TIER_TRANSPORT = None, None, None
        _TOPOLOGY_CACHE.clear()


def _configured_map() -> Tuple[Optional[Callable[[int], int]], Any]:
    """(tier_of callable, cache token) — explicit map, else env size, else
    ``(None, None)`` (flat world)."""
    with _LOCK:
        if _TIER_MAP is not None:
            return _TIER_MAP, _TIER_MAP_TOKEN
    raw = os.environ.get(TIER_SIZE_ENV, "").strip()
    if not raw:
        return None, None
    try:
        size = int(raw)
    except ValueError:
        from metrics_tpu.observability.diagnostics import warn_once

        warn_once(
            "tier-size-invalid",
            f"{TIER_SIZE_ENV}={raw!r} is not an int — tiered sync disabled, "
            "falling back to the flat world gather.",
        )
        return None, None
    if size <= 0:
        return None, None
    return (lambda rank, _s=size: rank // _s), ("size", size)


def tiering_configured() -> bool:
    """Is any tier map (explicit or env) configured on this rank?"""
    return _configured_map()[0] is not None


def tier_of_rank(rank: int) -> int:
    """Tier id of ``rank`` under the configured map; ``-1`` when no map is
    configured (the flat world). The value every rank self-reports in its
    health word's tier column — negotiated, not trusted: the verifier
    compares the gathered column against :func:`expected_tier_column`."""
    fn, _ = _configured_map()
    return -1 if fn is None else int(fn(int(rank)))


def my_tier_id() -> int:
    """This rank's tier id (``-1`` unconfigured) — the health-word column."""
    return tier_of_rank(_current_rank())


class TierTopology:
    """The negotiated two-level layout over one live-rank set.

    Pure data, derived identically on every rank from ``(live, tier map)``:

    - ``live`` — sorted live ranks (the gather's global row order);
    - ``tiers`` — ``tier id -> sorted member ranks`` (tier ids sorted);
    - ``leaders`` — one leader (min rank) per tier, in tier order: the
      inter-tier exchange's participant set;
    - ``assembly`` — for each live rank (in global sorted order) the row
      index ``tier_pos * max_tier + member_pos`` into the concatenated
      padded tier blocks, so every rank reconstructs the exact ``[world,
      n]`` matrix the flat gather would have produced — bit-identical,
      whatever the tier map's rank interleaving;
    - per-rank views (``my_tier_ranks`` / ``is_leader`` / ``leader_pos``)
      for the executing rank.

    ``degenerate`` (one tier, or one rank per tier) means the schedule
    cannot beat the flat gather; callers keep the flat path.
    """

    __slots__ = (
        "key",
        "live",
        "tiers",
        "leaders",
        "max_tier",
        "assembly",
        "rank",
        "my_tier",
        "my_tier_ranks",
        "is_leader",
        "leader_pos",
        "tier_pos",
        "expected_tiers",
    )

    def __init__(self, live: Tuple[int, ...], tier_of: Callable[[int], int], rank: int, key: Any) -> None:
        self.key = key
        self.live = tuple(sorted(int(r) for r in live))
        members: Dict[int, list] = {}
        for r in self.live:
            members.setdefault(int(tier_of(r)), []).append(r)
        self.tiers = {tid: tuple(members[tid]) for tid in sorted(members)}
        self.leaders = tuple(ranks[0] for ranks in self.tiers.values())
        self.max_tier = max(len(ranks) for ranks in self.tiers.values())
        tier_order = {tid: i for i, tid in enumerate(self.tiers)}
        self.expected_tiers = np.asarray([tier_of(r) for r in self.live], np.int32)
        pos: Dict[int, int] = {}
        for tid, ranks in self.tiers.items():
            for j, r in enumerate(ranks):
                pos[r] = tier_order[tid] * self.max_tier + j
        self.assembly = np.asarray([pos[r] for r in self.live], np.int64)
        self.rank = int(rank)
        my_tid = int(tier_of(self.rank)) if self.rank in pos else None
        self.my_tier = my_tid
        self.my_tier_ranks = self.tiers.get(my_tid, ())
        self.is_leader = bool(self.my_tier_ranks) and self.my_tier_ranks[0] == self.rank
        self.leader_pos = 0  # the leader is the min rank = row 0 of its tier block
        self.tier_pos = tier_order.get(my_tid, -1)

    @property
    def n_tiers(self) -> int:
        return len(self.tiers)

    @property
    def degenerate(self) -> bool:
        """One tier (pure-fast-hop world) or one rank per tier (the tiered
        schedule degenerates to the flat gather plus overhead)."""
        return self.n_tiers <= 1 or self.max_tier <= 1

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"TierTopology(n_tiers={self.n_tiers}, live={len(self.live)}, "
            f"rank={self.rank}, tier={self.my_tier}, leader={self.is_leader})"
        )


def tier_topology(live: Any, rank: int, tier_of: Optional[Callable[[int], int]] = None) -> TierTopology:
    """Derive (memoized) the :class:`TierTopology` for one live set.

    Keyed on ``(live tuple, map token, rank)`` so a quorum shrink — which
    changes ``live`` — re-derives the topology in the *same* membership
    epoch with zero extra collectives: survivors agree on ``live`` by
    negotiation and on the map by configuration, hence on the topology.
    """
    token: Any = None
    if tier_of is None:
        tier_of, token = _configured_map()
        if tier_of is None:
            from metrics_tpu.utils.exceptions import MetricsTPUUserError

            raise MetricsTPUUserError(
                "tier_topology: no tier map configured (set_tier_map or "
                f"{TIER_SIZE_ENV})"
            )
    else:
        token = ("fn", id(tier_of))
    live_t = tuple(sorted(int(r) for r in live))
    key = (live_t, token, int(rank))
    with _LOCK:
        topo = _TOPOLOGY_CACHE.get(key)
        if topo is None:
            topo = TierTopology(live_t, tier_of, int(rank), key)
            if len(_TOPOLOGY_CACHE) > 64:  # membership changes are rare
                _TOPOLOGY_CACHE.clear()
            _TOPOLOGY_CACHE[key] = topo
        return topo


def expected_tier_column(world: int) -> Optional[np.ndarray]:
    """The tier-id column this rank EXPECTS every live rank to report —
    ``None`` when no map is configured (peers must then report ``-1``).
    Row order matches the gathered health words (sorted live ranks). Pads
    with the configured map when the gathered world disagrees with the
    local live view (the membership-skew check fires first anyway)."""
    fn, _ = _configured_map()
    if fn is None:
        return None
    from metrics_tpu.parallel.resilience import live_ranks

    live = tuple(sorted(live_ranks()))
    if len(live) != world:
        live = tuple(range(world))
    return np.asarray([int(fn(r)) for r in live], np.int32)


def active_tier_transport() -> Optional[Any]:
    """The subset-collective transport tiered hops run over: the explicitly
    installed one, else the quorum transport (``parallel/resilience.py``),
    else ``None`` (tiered sync stays off)."""
    with _LOCK:
        if _TIER_TRANSPORT is not None:
            return _TIER_TRANSPORT
    from metrics_tpu.parallel import resilience

    return getattr(resilience, "_TRANSPORT", None)


def active_topology() -> Optional[TierTopology]:
    """The topology the NEXT bucketed sync should schedule over, or ``None``
    for the flat path: no map configured, no subset transport installed
    (warned once — never a silent change), or a degenerate layout (single
    tier / one rank per tier, where flat is already optimal).
    """
    fn, token = _configured_map()
    if fn is None:
        return None
    if active_tier_transport() is None:
        from metrics_tpu.observability.diagnostics import warn_once

        warn_once(
            "tier-no-transport",
            "a tier map is configured but no subset-collective transport is "
            "installed (tiering.set_tier_transport / "
            "resilience.set_quorum_transport) — the two-level schedule "
            "cannot issue tier-local collectives, so syncs keep the flat "
            "world gather.",
        )
        return None
    from metrics_tpu.parallel.resilience import live_ranks

    topo = tier_topology(live_ranks(), _current_rank(), None)
    return None if topo.degenerate else topo
