"""Benchmarks on the available accelerator.

Default (driver contract): runs BASELINE config 1 and prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}``.

``python bench.py --all`` additionally runs BASELINE configs 2-5 (one JSON
line each; see BASELINE.md for the config table and BENCH.md for recorded
numbers).

The baseline proxy for config 1 is a faithful torch-CPU implementation of the
same accumulation (the reference publishes no performance numbers —
BASELINE.md), timed in-process.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = 2048
NUM_CLASSES = 10
STEPS = 200
WARM = 20


def _ensure_backend(probe_timeout: int = 240, attempts: int = 2) -> str:
    """Make sure jax can actually initialize a backend before benching.

    The ambient accelerator plugin (JAX_PLATFORMS=axon tunnel) can fail or
    hang at first contact (round-1 failure: BENCH_r01 rc=1, 'Unable to
    initialize backend'). Probe it in a subprocess with a timeout; on
    persistent failure fall back to cpu so the contract JSON line is still
    emitted with a real (cpu) measurement plus a diagnostic.

    Must run before jax creates a backend in THIS process. Returns the
    platform name actually in use.
    """
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats == "cpu":
        import jax

        return jax.devices()[0].platform
    # empty JAX_PLATFORMS still auto-detects accelerator plugins, so it gets
    # the same timeout-guarded probe as an explicit accelerator setting

    code = "import jax; d = jax.devices(); print('PROBE_OK', d[0].platform)"
    last_err = None
    for _ in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=probe_timeout,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                import jax

                return jax.devices()[0].platform
            last_err = (r.stdout + r.stderr).strip()[-500:]
        except subprocess.TimeoutExpired:
            last_err = f"backend probe timed out after {probe_timeout}s"
        time.sleep(5)

    print(
        json.dumps({"diagnostic": "accelerator backend unavailable, falling back to cpu", "error": last_err}),
        file=sys.stderr,
    )
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax.devices()[0].platform


def _time_steps(fn, *args, steps=STEPS, warm=WARM):
    """Median-free simple wall-clock: warm the dispatch path, then average."""
    import jax

    out = None
    for _ in range(warm):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps


def bench_ours() -> float:
    """Config 1: Accuracy + StatScores fused update step."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricCollection, StatScores

    mc = MetricCollection(
        {"acc": Accuracy(num_classes=NUM_CLASSES), "stats": StatScores(reduce="macro", num_classes=NUM_CLASSES)}
    )
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (BATCH,)))

    # donate the state pytree: accumulators update in place in HBM
    step = jax.jit(mc.pure_update, donate_argnums=(0,))

    state = mc.init_state()
    state = step(state, preds, target)  # compile
    jax.block_until_ready(state)

    class _Loop:
        def __init__(self):
            self.state = state

        def __call__(self, p, t):
            self.state = step(self.state, p, t)
            return self.state

    loop = _Loop()
    dt = _time_steps(loop, preds, target)
    # sanity: value must be finite
    vals = mc.pure_compute(loop.state)
    assert np.isfinite(float(np.asarray(vals["acc"]))), "bench produced non-finite metric"
    return dt


def bench_torch_baseline() -> float:
    """Reference-style accumulation in torch (CPU), same math, same shapes."""
    import torch

    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, (BATCH,)))

    def step(tp, fp, tn, fn, correct, total):
        p1 = preds.argmax(1)
        oh_p = torch.nn.functional.one_hot(p1, NUM_CLASSES)
        oh_t = torch.nn.functional.one_hot(target, NUM_CLASSES)
        true_pred = oh_t == oh_p
        pos_pred = oh_p == 1
        tp = tp + (true_pred & pos_pred).sum(0)
        fp = fp + (~true_pred & pos_pred).sum(0)
        tn = tn + (true_pred & ~pos_pred).sum(0)
        fn = fn + (~true_pred & ~pos_pred).sum(0)
        correct = correct + (p1 == target).sum()
        total = total + target.numel()
        return tp, fp, tn, fn, correct, total

    z = torch.zeros(NUM_CLASSES, dtype=torch.long)
    st = (z, z.clone(), z.clone(), z.clone(), torch.zeros((), dtype=torch.long), 0)
    st = step(*st)  # warm
    t0 = time.perf_counter()
    for _ in range(STEPS):
        st = step(*st)
    return (time.perf_counter() - t0) / STEPS


def _emit(metric, value, unit, vs=None):
    print(json.dumps({"metric": metric, "value": value, "unit": unit, "vs_baseline": vs}))


def bench_config2() -> None:
    """Config 2: AUROC (CatBuffer cat-state) + ConfusionMatrix collection."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import AUROC, ConfusionMatrix, MetricCollection

    batch, steps_cap = 1024, 64
    mc = MetricCollection(
        {
            "auroc": AUROC().with_capacity(batch * steps_cap),
            "confmat": ConfusionMatrix(num_classes=2),
        }
    )
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(batch).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, (batch,)))
    mc.update(preds, target)  # warm eager mode detection
    state0 = mc.init_state()
    step = jax.jit(mc.pure_update, donate_argnums=(0,))
    state = step(state0, preds, target)
    jax.block_until_ready(state)

    holder = {"s": state}

    def loop(p, t):
        holder["s"] = step(holder["s"], p, t)
        return holder["s"]

    # buffer capacity = batch * steps_cap rows; 1 compile step + `warm`
    # warmup steps already consumed rows, so the timed loop takes the rest —
    # derived from capacity so changing WARM cannot overflow the CatBuffer.
    steps = steps_cap - WARM - 1
    assert steps > 0, f"WARM={WARM} leaves no timed steps for capacity {steps_cap}"
    dt = _time_steps(loop, preds, target, steps=steps, warm=WARM)
    val = mc.pure_compute(holder["s"])
    n_rows = int(np.asarray(holder["s"]["auroc"]["preds"].count))
    assert n_rows == batch * steps_cap, f"CatBuffer row count {n_rows} != capacity {batch * steps_cap}"
    assert np.isfinite(float(np.asarray(val["auroc"])))
    _emit("auroc_confmat_fused_step", round(dt * 1e6, 2), "us/step")


def bench_config3() -> None:
    """Config 3: FID — Inception-v3 forward + streaming moments on device."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import FID

    fid = FID(feature=2048, streaming=True)
    batch = 32
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.rand(batch, 3, 299, 299).astype(np.float32))

    fid.update(imgs, real=True)  # compile both paths
    fid.update(imgs, real=False)

    def step(im):
        fid.update(im, real=True)
        return fid.real_n

    dt = _time_steps(step, imgs, steps=8, warm=2)
    t0 = time.perf_counter()
    val = fid.compute()
    jax.block_until_ready(val)
    dt_compute = time.perf_counter() - t0
    _emit("fid_inception_forward", round(batch / dt, 1), "imgs/s")
    _emit("fid_compute_sqrtm", round(dt_compute, 3), "s")


def bench_config4() -> None:
    """Config 4: BERTScore — in-framework BERT forward as the scoring engine."""
    import jax

    from metrics_tpu import BERTScore

    sents_per_batch = 64
    bs = BERTScore(max_length=64, batch_size=sents_per_batch)
    preds = ["the quick brown fox jumps over the lazy dog"] * sents_per_batch
    refs = ["a quick brown fox jumped over lazy dogs"] * sents_per_batch
    for _ in range(4):
        bs.update(preds, refs)
    t0 = time.perf_counter()
    out = bs.compute()
    jax.block_until_ready(out["f1"])
    dt = time.perf_counter() - t0
    _emit("bertscore_compute", round(4 * sents_per_batch / dt, 1), "sentences/s")


def bench_config5() -> None:
    """Config 5: RetrievalMAP + NDCG over ragged query groups (segment ops)."""
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP, RetrievalNormalizedDCG

    n, queries = 65536, 1024
    rng = np.random.RandomState(0)
    idx = jnp.asarray(rng.randint(0, queries, (n,)))
    preds = jnp.asarray(rng.rand(n).astype(np.float32))
    target = jnp.asarray(rng.randint(0, 2, (n,)))

    m_map = RetrievalMAP()
    m_ndcg = RetrievalNormalizedDCG()
    m_map.update(preds, target, idx)
    m_ndcg.update(preds, target, idx)

    t0 = time.perf_counter()
    v1 = m_map.compute()
    v2 = m_ndcg.compute()
    dt = time.perf_counter() - t0
    assert np.isfinite(float(np.asarray(v1))) and np.isfinite(float(np.asarray(v2)))
    _emit("retrieval_map_ndcg_compute", round(dt * 1e3, 2), "ms/65536-docs")


def main() -> None:
    try:
        platform = _ensure_backend()
        print(json.dumps({"diagnostic": f"benching on platform={platform}"}), file=sys.stderr)
        ours = bench_ours()
    except Exception as e:  # noqa: BLE001 — contract line must appear no matter what
        print(
            json.dumps(
                {
                    "metric": "fused_metric_step_time",
                    "value": None,
                    "unit": "us/step",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {e}"[:500],
                }
            )
        )
        raise SystemExit(0)
    try:
        base = bench_torch_baseline()
        vs = base / ours
    except Exception:
        vs = None
    _emit("fused_metric_step_time", round(ours * 1e6, 2), "us/step", round(vs, 3) if vs else None)
    if "--all" in sys.argv:
        for cfg in (bench_config2, bench_config3, bench_config4, bench_config5):
            try:
                cfg()
            except Exception as e:  # noqa: BLE001 — keep later configs running
                print(json.dumps({"diagnostic": f"{cfg.__name__} failed", "error": str(e)[:500]}), file=sys.stderr)


if __name__ == "__main__":
    main()
