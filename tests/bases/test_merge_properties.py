"""Algebraic properties of merge_state across metric families.

The merge-based forward (`core/metric.py:261-304`) replaces the reference's
double-update with `merged = merge_states(accumulated, batch)` — that is only
sound if merging is associative and agrees with plain accumulation over the
concatenated data. Pin both properties for one metric per state algebra:
sum (Accuracy), running moments with pairwise merge (PearsonCorrcoef),
cat-list (SpearmanCorrcoef), CatBuffer (AUROC.with_capacity), min/max (PSNR),
and dict-of-counters (ROUGEScore).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import AUROC, Accuracy, PSNR, PearsonCorrcoef, ROUGEScore, SpearmanCorrcoef

rng = np.random.RandomState(31)


def _chunks(n):
    out = []
    for _ in range(n):
        preds = rng.rand(64).astype(np.float32)
        target = rng.randint(0, 2, 64)
        target[0], target[1] = 0, 1  # both classes for AUROC
        out.append((preds, target))
    return out


CASES = {
    "accuracy_sum": (lambda: Accuracy(), _chunks(3)),
    "pearson_moments": (
        lambda: PearsonCorrcoef(),
        [(rng.rand(64).astype(np.float32), rng.rand(64).astype(np.float32)) for _ in range(3)],
    ),
    "spearman_catlist": (
        lambda: SpearmanCorrcoef(),
        [(rng.rand(64).astype(np.float32), rng.rand(64).astype(np.float32)) for _ in range(3)],
    ),
    "auroc_catbuffer": (lambda: AUROC().with_capacity(1024), _chunks(3)),
    "psnr_minmax": (
        lambda: PSNR(),
        [((rng.rand(64) * 3).astype(np.float32), (rng.rand(64) * 3).astype(np.float32)) for _ in range(3)],
    ),
    "rouge_counterdict": (
        lambda: ROUGEScore(),
        [(["the cat sat on the mat"], ["a cat sat there"]),
         (["tiny dog barks"], ["a tiny dog barked loudly"]),
         (["metrics on tpus"], ["metrics running on tpus"])],
    ),
}


def _leaf_close(a, b, atol=1e-6):
    import jax

    la = [np.asarray(jnp.asarray(x, jnp.float32), np.float64) for x in jax.tree_util.tree_leaves(a)]
    lb = [np.asarray(jnp.asarray(x, jnp.float32), np.float64) for x in jax.tree_util.tree_leaves(b)]
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(x, y, atol=atol, rtol=1e-5)


def _metric_with(make, chunks):
    m = make()
    for args in chunks:
        m.update(*(jnp.asarray(a) if not isinstance(a, list) else a for a in args))
    return m


@pytest.mark.parametrize("name", sorted(CASES), ids=sorted(CASES))
def test_merge_agrees_with_plain_accumulation(name):
    """compute(merge(A, B, C)) == compute(single metric fed all chunks)."""
    make, chunks = CASES[name]
    parts = [_metric_with(make, [c]) for c in chunks]
    merged = parts[0]
    for p in parts[1:]:
        merged.merge_state(p)
    whole = _metric_with(make, chunks)
    _leaf_close(merged.compute(), whole.compute(), atol=1e-5)


@pytest.mark.parametrize("name", sorted(CASES), ids=sorted(CASES))
def test_merge_is_associative(name):
    """(A ⊕ B) ⊕ C == A ⊕ (B ⊕ C) at the compute level."""
    make, chunks = CASES[name]

    def build(i):
        return _metric_with(make, [chunks[i]])

    left = build(0)
    left.merge_state(build(1))
    left.merge_state(build(2))

    right_tail = build(1)
    right_tail.merge_state(build(2))
    right = build(0)
    right.merge_state(right_tail)

    _leaf_close(left.compute(), right.compute(), atol=1e-5)
