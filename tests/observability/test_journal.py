"""Event-journal unit suite: recording, filtering, subscribers, the ring,
the rank seam, and the never-from-traced-code assertion."""
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.observability import journal


def test_disabled_recorder_records_nothing():
    journal.record("sync.gather", label="m")
    assert journal.events() == []
    assert journal.ACTIVE is False


def test_enable_record_clear():
    journal.enable()
    journal.record("sync.gather", label="m", sync_epoch=3)
    journal.record("checkpoint.save", label="m", step=7)
    evs = journal.events()
    assert [e.kind for e in evs] == ["sync.gather", "checkpoint.save"]
    assert evs[0].fields["sync_epoch"] == 3
    assert evs[1].step == 7
    assert evs[0].ts <= evs[1].ts
    journal.clear()
    assert journal.events() == []


def test_every_emitted_kind_is_catalogued():
    journal.enable()
    for kind in journal.EVENT_KINDS:
        journal.record(kind, label="x")
    assert len(journal.events()) == len(journal.EVENT_KINDS)


def test_kind_and_class_filtering():
    journal.enable()
    journal.record("sync.launch", sync_epoch=1)
    journal.record("sync.resolve", sync_epoch=1)
    journal.record("health.watchdog")
    assert [e.kind for e in journal.events(kinds=("sync",))] == [
        "sync.launch", "sync.resolve",
    ]
    assert [e.kind for e in journal.events(kinds=("health.watchdog",))] == [
        "health.watchdog"
    ]


def test_ring_overwrites_oldest():
    journal.enable(capacity=8)
    try:
        for i in range(20):
            journal.record("sync.gather", step=i)
        steps = [e.step for e in journal.events()]
        assert steps == list(range(12, 20))
    finally:
        journal.enable(capacity=None)
        journal.clear()
        journal.disable()
        # restore the default capacity for later tests
        journal._capacity = journal._DEFAULT_CAPACITY


def test_threads_record_into_their_own_rings_and_merge_sorted():
    journal.enable()

    def emit(tag):
        for i in range(5):
            journal.record("sync.gather", label=tag, step=i)

    threads = [threading.Thread(target=emit, args=(f"t{i}",)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    emit("main")
    evs = journal.events()
    assert len(evs) == 20
    assert all(evs[i].ts <= evs[i + 1].ts for i in range(len(evs) - 1))
    assert {e.label for e in evs} == {"t0", "t1", "t2", "main"}


def test_rank_provider_seam():
    journal.set_rank_provider(lambda: 7)
    journal.enable()
    journal.record("sync.gather")
    assert journal.events()[0].rank == 7
    assert journal.events(rank=3) == []
    assert len(journal.events(rank=7)) == 1


def test_subscriber_receives_without_recorder():
    got = []
    sub = journal.on_event(got.append, classes=("degrade", "health"))
    try:
        assert journal.ACTIVE is True  # subscriber keeps emission live
        journal.record("degrade.local", label="m", error="SyncError")
        journal.record("sync.gather", label="m")  # filtered out
        journal.record("health.watchdog")
        assert [e.kind for e in got] == ["degrade.local", "health.watchdog"]
        assert journal.events() == []  # ring recorder still off
    finally:
        sub.close()
    assert journal.ACTIVE is False
    journal.record("degrade.local")
    assert got[-1].kind == "health.watchdog"  # detached: nothing new


def test_subscriber_exceptions_never_propagate():
    def boom(ev):
        raise RuntimeError("fleet logger died")

    with journal.on_event(boom):
        journal.record("health.watchdog")  # must not raise


def test_record_inside_trace_raises():
    journal.enable()

    def traced(x):
        journal.record("sync.gather", label="m")
        return x + 1

    with pytest.raises(RuntimeError, match="inside traced code"):
        jax.jit(traced)(jnp.zeros(()))


def test_event_as_dict_roundtrip():
    journal.enable()
    journal.record("sync.resolve", label="m", step=2, sync_epoch=4, stale=False)
    d = journal.events()[0].as_dict()
    assert d["kind"] == "sync.resolve" and d["sync_epoch"] == 4
    assert set(d) >= {"ts", "rank", "step", "kind", "label"}


def test_compiled_step_loop_journals_dispatches():
    """The compiled hot path emits one dispatch event per step (plus one
    trace event per compilation), attributed to the metric label."""
    from metrics_tpu.core.metric import Metric

    class _Sum(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def update(self, x):
            self.total = self.total + jnp.sum(x)

        def compute(self):
            return self.total

    journal.enable()
    m = _Sum(compiled_update=True)
    x = jnp.asarray(np.ones((4,), np.float32))
    for _ in range(4):
        m.update(x)
    kinds = [e.kind for e in journal.events(kinds=("compiled",))]
    assert kinds.count("compiled.dispatch") == 4
    assert kinds.count("compiled.trace") == 1
    ev = journal.events(kinds=("compiled.dispatch",))[0]
    assert ev.label == "_Sum" and ev.fields["op"] == "update"
    assert ev.fields["dur_s"] >= 0.0


def test_fallback_event_carries_reason():
    from metrics_tpu.core.metric import Metric

    class _Latch(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
            self.seen = []

        def update(self, x):
            self.seen.append(1)  # metricslint: disable=undeclared-state
            self.total = self.total + jnp.sum(x)

        def compute(self):
            return self.total

    journal.enable()
    m = _Latch(compiled_update=True)
    m.update(jnp.ones((2,)))
    evs = journal.events(kinds=("compiled.fallback",))
    assert len(evs) == 1
    assert evs[0].fields["op"] == "update"
    assert "seen" in evs[0].fields["reason"]
    assert m.compile_stats()["fallback"]["update"]
