"""Scale-invariant SDR — analogue of reference
``torchmetrics/functional/audio/si_sdr.py:20-64``.
"""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def si_sdr(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Scale-invariant signal-to-distortion ratio.

    Projects ``preds`` onto ``target`` (optimal scaling ``alpha``) and measures
    the residual energy ratio in dB.

    Args:
        preds: shape ``[..., time]``
        target: shape ``[..., time]``
        zero_mean: subtract the time-mean from both signals first

    Returns:
        si-sdr value of shape ``[...]``

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> float(si_sdr(preds, target))  # doctest: +ELLIPSIS
        18.40...
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target * target, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    ratio = (jnp.sum(target_scaled * target_scaled, axis=-1) + eps) / (
        jnp.sum(noise * noise, axis=-1) + eps
    )
    return 10 * jnp.log10(ratio)
