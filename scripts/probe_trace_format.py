"""Inspect what jax.profiler.trace records for a TPU program: xplane planes/
lines and the chrome-trace event names, so the bench's device-time parser
targets the right stream."""
import glob
import gzip
import json
import os
import tempfile


def main() -> None:
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        for _ in range(4):
            x = x @ x
        return x

    x = jnp.ones((1024, 1024), jnp.bfloat16)
    jax.block_until_ready(f(x))
    td = tempfile.mkdtemp(prefix="jaxprof_")
    with jax.profiler.trace(td):
        for _ in range(3):
            jax.block_until_ready(f(x))

    tj = glob.glob(os.path.join(td, "**", "*.trace.json.gz"), recursive=True)[0]
    with gzip.open(tj, "rt") as fh:
        data = json.load(fh)
    ev = data.get("traceEvents", [])
    pids = {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name")
    print("PROCESSES:", json.dumps(pids))
    by_pid = {}
    for e in ev:
        if e.get("ph") == "X":
            by_pid.setdefault(e["pid"], []).append(e)
    for pid, evs in by_pid.items():
        names = {}
        for e in evs:
            names.setdefault(e["name"], [0, 0.0])
            names[e["name"]][0] += 1
            names[e["name"]][1] += e.get("dur", 0)
        top = sorted(names.items(), key=lambda kv: -kv[1][1])[:8]
        print(f"PID {pid} ({pids.get(pid)}): {len(evs)} events; top:", json.dumps(top))

    try:
        from tensorflow.core.profiler.protobuf import xplane_pb2  # noqa: F401
        xp = glob.glob(os.path.join(td, "**", "*.xplane.pb"), recursive=True)[0]
        space = xplane_pb2.XSpace()
        with open(xp, "rb") as fh:
            space.ParseFromString(fh.read())
        for plane in space.planes:
            print("XPLANE:", plane.name, "lines:", [(l.name, len(l.events)) for l in plane.lines])
    except Exception as e:
        print("xplane parse failed:", type(e).__name__, str(e)[:200])


if __name__ == "__main__":
    main()
