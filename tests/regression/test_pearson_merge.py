"""Regression tests for the PearsonCorrcoef.merge_states host-sync fix.

The merge used to early-return on ``float(jnp.sum(...)) == 0`` — a
device→host sync inside every ``forward()`` step that also made the merge
untraceable (metricslint: host-sync-in-update). It is now a ``jnp.where``
selection: same values, traceable, and the compiled forward path can engage.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.regression.pearson import PearsonCorrcoef

RNG = np.random.RandomState(7)
PREDS = [jnp.asarray(RNG.randn(24).astype(np.float32)) for _ in range(4)]
TARGET = [jnp.asarray(RNG.randn(24).astype(np.float32)) for _ in range(4)]


def _state_after(m):
    return {k: np.asarray(v) for k, v in m._state.items()}


def _accumulated(n):
    m = PearsonCorrcoef()
    for p, t in zip(PREDS[:n], TARGET[:n]):
        m.update(p, t)
    return m


def test_merge_states_empty_side_semantics():
    full = _accumulated(2)
    empty = PearsonCorrcoef()
    # b empty -> a's values; a empty -> b's values; both empty -> defaults
    merged_b_empty = full.merge_states(dict(full._state), dict(empty._state))
    merged_a_empty = full.merge_states(dict(empty._state), dict(full._state))
    both_empty = full.merge_states(dict(empty._state), dict(empty._state))
    for k, v in full._state.items():
        np.testing.assert_array_equal(np.asarray(merged_b_empty[k]), np.asarray(v))
        np.testing.assert_array_equal(np.asarray(merged_a_empty[k]), np.asarray(v))
        np.testing.assert_array_equal(np.asarray(both_empty[k]), 0.0)
        assert not np.isnan(np.asarray(both_empty[k])).any()


def test_merge_states_nonempty_matches_sequential():
    a, b = _accumulated(2), PearsonCorrcoef()
    for p, t in zip(PREDS[2:], TARGET[2:]):
        b.update(p, t)
    merged = a.merge_states(dict(a._state), dict(b._state))
    m = a.clone()
    m._state = dict(merged)
    sequential = _accumulated(4)
    np.testing.assert_allclose(float(m.compute()), float(sequential.compute()), rtol=1e-5)


def test_merge_states_is_traceable():
    """The old float()-guard raised ConcretizationTypeError under jit."""
    m = _accumulated(2)
    other = _accumulated(4)
    jitted = jax.jit(m.merge_states)
    out = jitted(dict(m._state), dict(other._state))
    eager = m.merge_states(dict(m._state), dict(other._state))
    for k in eager:
        np.testing.assert_allclose(np.asarray(out[k]), np.asarray(eager[k]), rtol=1e-6)


def test_forward_values_unchanged_and_compiled_path_engages():
    eager = PearsonCorrcoef()
    compiled = PearsonCorrcoef()
    compiled.compiled_update = True
    for p, t in zip(PREDS, TARGET):
        v_eager = eager(p, t)
        v_compiled = compiled(p, t)
        np.testing.assert_allclose(np.asarray(v_compiled), np.asarray(v_eager), rtol=1e-6)
    stats = compiled.compile_stats()
    assert stats["fallback"] is None, stats["fallback"]
    assert stats["dispatches"] >= 1, "compiled forward must actually engage"
    s_e, s_c = _state_after(eager), _state_after(compiled)
    for k in s_e:
        np.testing.assert_allclose(s_c[k], s_e[k], rtol=1e-6)
    np.testing.assert_allclose(float(compiled.compute()), float(eager.compute()), rtol=1e-6)
