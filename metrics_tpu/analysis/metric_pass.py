"""metricslint metric-class pass — static contracts of ``Metric`` subclasses.

Every contract this pass checks is one the runtime currently enforces late
(or not at all):

- ``update()``/``compute()`` may mutate **only** ``add_state``-declared
  attributes (plus declared ``_group_shared_attrs`` latches). The runtime
  discovers violations via the ``jax.eval_shape`` probe at the first
  compiled dispatch (step ~17 with the default warm-up) and silently falls
  back to eager; here the undeclared latch is a definition-time finding
  naming the attribute and line (``undeclared-state`` / ``unshared-latch``).
- hot-path host syncs (``float()``/``.item()``/``np.asarray`` on traced
  values, ``jax.device_get``) stall the dispatch pipeline every step and
  break under tracing (``host-sync-in-update``).
- declaration hygiene: overriding ``update`` without re-declaring
  ``update_identity`` silently drops the inherited compute-group key
  (``Metric._effective_update_identity``); ``add_state`` declarations with
  statically-wrong defaults fail at construction or sync time
  (``update-identity-redeclare`` / ``state-default``).

The pass is pure AST — nothing is imported or executed — so it runs on any
source tree, including fixture files that would not survive an import. Name
resolution is therefore *textual*: a class's ancestry is resolved by base
class name within the analyzed file set, and anything unresolvable degrades
to "unknown" rather than a false finding (``ClassInfo.update_resolved`` is
how the runtime integration distinguishes "verified clean" from "cannot
tell" — only the former skips the runtime probe).
"""
import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from metrics_tpu.analysis.report import Finding

#: Runtime-bookkeeping attributes the Metric base machinery mutates around
#: update/compute — never evidence of a user side-effect latch. Must stay a
#: superset of ``metrics_tpu.core.compiled._PROBE_EXEMPT`` (pinned by
#: ``tests/analysis/test_metric_pass.py``); kept as a literal copy so the
#: AST passes import nothing from the jax-backed runtime modules.
RUNTIME_EXEMPT_ATTRS = frozenset(
    {
        "_state",
        "_defaults",
        "_computed",
        "_update_called",
        "_forward_cache",
        "_update_count",
        "_pure_mode",
        "_donation_ready",
        "_compiled",
        "_plan_binding",
        "_cache",
        "_update_kwarg_names",
        "_ckpt_suppress",
        "_to_sync",
        "_reductions",
        "_persistent",
        "_is_synced",
        "_sync_degraded",
        "_dtype",
    }
)

_ALLOWED_FX = {"sum", "mean", "cat", "max", "min"}

#: method calls that mutate their receiver in place (one container level,
#: matching the runtime probe's shallow-container snapshot). ``.update()``
#: is deliberately absent: ``self.metric_a.update(...)`` — nested-metric
#: delegation — is overwhelmingly more common than a dict-latch
#: ``self.d.update(...)`` and indistinguishable from it statically.
_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "discard", "remove", "clear",
    "setdefault", "pop", "popitem", "appendleft",
}

#: annotation text fragments that mark a parameter as a traced array input
_ARRAY_ANNOTATIONS = ("Array", "ndarray", "jnp.", "ArrayLike")

_NUMPY_MODULE_NAMES = {"np", "numpy", "onp"}


# ---------------------------------------------------------------------------
# class harvesting
# ---------------------------------------------------------------------------

@dataclass
class AddStateCall:
    node: ast.Call
    names: Tuple[str, ...]          # () when the name expression is dynamic
    default: Optional[ast.expr]
    fx: Optional[ast.expr]
    fx_given: bool
    #: declared under an if/else (e.g. list-vs-array depending on a ctor
    #: arg): two conditional declarations of one name are alternatives,
    #: not duplicates
    conditional: bool = False


@dataclass
class ClassInfo:
    name: str
    qualname: str
    path: str
    node: ast.ClassDef
    base_names: List[str] = field(default_factory=list)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    add_state_calls: List[AddStateCall] = field(default_factory=list)
    state_names: Set[str] = field(default_factory=set)
    #: UPPERCASE name references used as add_state names (imported module
    #: constants, e.g. ``NONFINITE_STATE``) — resolved against
    #: ``Universe.constants`` at check time
    state_name_refs: Set[str] = field(default_factory=set)
    dynamic_state_names: bool = False
    shared_attrs: Optional[Set[str]] = None   # None = not declared here
    shared_dynamic: bool = False              # declared, but not a literal
    defines_identity: bool = False
    identity_nontrivial: bool = False

    @property
    def defines_update(self) -> bool:
        return "update" in self.methods


def _base_name(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Subscript):  # Generic[...] style
        return _base_name(expr.value)
    return None


def _literal_names(expr: ast.expr, env: Dict[str, ast.expr]) -> Optional[Tuple[str, ...]]:
    """Constant-name extraction for an add_state first argument: a string
    literal, a loop variable bound to a literal tuple/list of strings, or a
    module-level string constant (``NONFINITE_STATE``-style)."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return (expr.value,)
    if isinstance(expr, ast.Name) and expr.id in env:
        try:
            value = ast.literal_eval(env[expr.id])
        except (ValueError, SyntaxError):
            return None
        if isinstance(value, str):
            return (value,)
        if isinstance(value, (tuple, list)) and all(isinstance(v, str) for v in value):
            return tuple(value)
    return None


def _call_kwarg(call: ast.Call, name: str, pos: int) -> Tuple[Optional[ast.expr], bool]:
    if len(call.args) > pos:
        return call.args[pos], True
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value, True
    return None, False


def _harvest_add_state(ci: ClassInfo, fn: ast.FunctionDef, module_env: Dict[str, ast.expr]) -> None:
    """Collect ``self.add_state(...)`` calls in ``fn``, resolving loop-bound
    name tuples (``for s in ("tp", "fp"): self.add_state(s, ...)``) and
    module-level constants."""
    env: Dict[str, ast.expr] = dict(module_env)
    conditional_ids: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
            env[node.target.id] = node.iter
        if isinstance(node, (ast.If, ast.Try)):
            for sub in ast.walk(node):
                if sub is not node:
                    conditional_ids.add(id(sub))
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_state"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
            and node.args
        ):
            continue
        names = _literal_names(node.args[0], env)
        default, _ = _call_kwarg(node, "default", 1)
        fx, fx_given = _call_kwarg(node, "dist_reduce_fx", 2)
        call = AddStateCall(node, names or (), default, fx, fx_given, id(node) in conditional_ids)
        ci.add_state_calls.append(call)
        if names is not None:
            ci.state_names.update(names)
        elif (
            isinstance(node.args[0], ast.Name) and node.args[0].id.isupper()
        ):
            # an imported module constant by convention (NONFINITE_STATE);
            # resolved against the cross-file constant table at check time
            ci.state_name_refs.add(node.args[0].id)
        else:
            ci.dynamic_state_names = True


def _identity_nontrivial(fn: ast.FunctionDef) -> bool:
    """False when the body is the default ``return None`` (docstring aside)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Return):
            if node.value is None:
                continue
            if isinstance(node.value, ast.Constant) and node.value.value is None:
                continue
            return True
    return False


def harvest_classes(tree: ast.Module, path: str) -> List[ClassInfo]:
    """All classes in a module (top-level and nested), with their contracts."""
    out: List[ClassInfo] = []
    # module-level string constants (NONFINITE_STATE = "_nonfinite" style)
    module_env: Dict[str, ast.expr] = {}
    for item in tree.body:
        if isinstance(item, ast.Assign) and len(item.targets) == 1:
            t = item.targets[0]
            if isinstance(t, ast.Name):
                module_env[t.id] = item.value

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                qual = f"{prefix}{child.name}"
                ci = ClassInfo(child.name, qual, path, child)
                for b in child.bases:
                    name = _base_name(b)
                    if name:
                        ci.base_names.append(name)
                for item in child.body:
                    if isinstance(item, ast.FunctionDef):
                        ci.methods.setdefault(item.name, item)
                    elif isinstance(item, (ast.Assign, ast.AnnAssign)):
                        targets = item.targets if isinstance(item, ast.Assign) else [item.target]
                        for t in targets:
                            if isinstance(t, ast.Name) and t.id == "_group_shared_attrs":
                                try:
                                    val = ast.literal_eval(item.value) if item.value else ()
                                    ci.shared_attrs = set(val)
                                except (ValueError, SyntaxError):
                                    ci.shared_dynamic = True
                for fn in ci.methods.values():
                    _harvest_add_state(ci, fn, module_env)
                if "update_identity" in ci.methods:
                    ci.defines_identity = True
                    ci.identity_nontrivial = _identity_nontrivial(ci.methods["update_identity"])
                out.append(ci)
                visit(child, qual + ".")
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit(child, prefix + child.name + ".")

    visit(tree, "")
    return out


class Universe:
    """Name-indexed class registry across every analyzed file, with textual
    ancestry resolution (first registration of a simple name wins — the
    package has no metric-class name collisions, and a miss only widens
    "unknown", never produces a finding)."""

    def __init__(self) -> None:
        self.by_name: Dict[str, ClassInfo] = {}
        self.all: List[ClassInfo] = []
        #: UPPERCASE module-level string constants across every analyzed
        #: file (resolves imported add_state name constants)
        self.constants: Dict[str, str] = {}

    def add_module(self, tree: ast.Module, path: str) -> List[ClassInfo]:
        infos = harvest_classes(tree, path)
        for ci in infos:
            self.by_name.setdefault(ci.name, ci)
            self.all.append(ci)
        for item in tree.body:
            if isinstance(item, ast.Assign) and len(item.targets) == 1:
                t = item.targets[0]
                if (
                    isinstance(t, ast.Name)
                    and t.id.isupper()
                    and isinstance(item.value, ast.Constant)
                    and isinstance(item.value.value, str)
                ):
                    self.constants.setdefault(t.id, item.value.value)
        return infos

    def chain(self, ci: ClassInfo) -> List[ClassInfo]:
        """``ci`` plus resolvable ancestors, nearest first (depth-first over
        base names — a linearization approximation that is exact for the
        package's single-inheritance metric hierarchy)."""
        out: List[ClassInfo] = []
        seen: Set[int] = set()

        def walk(c: ClassInfo) -> None:
            if id(c) in seen:
                return
            seen.add(id(c))
            out.append(c)
            for b in c.base_names:
                base = self.by_name.get(b)
                if base is not None:
                    walk(base)

        walk(ci)
        return out

    def is_metric_class(self, ci: ClassInfo) -> bool:
        """Does ``ci`` look like a Metric subclass? True when the textual
        ancestry reaches a class named ``Metric`` or any ancestor (itself
        included) declares state via ``add_state``."""
        for c in self.chain(ci):
            if c.name == "Metric" or c.add_state_calls:
                return True
        return "Metric" in ci.base_names


# ---------------------------------------------------------------------------
# update/compute reachability + attribute writes
# ---------------------------------------------------------------------------

@dataclass
class AttrWrite:
    attr: str
    line: int
    col: int
    in_place: bool
    owner: str  # "Class.method"
    path: str = ""  # source file of the method that performs the write


@dataclass
class BodyScan:
    """Everything the mutation/host-sync rules need about one entry point
    (``update`` or ``compute``) of one class, helpers included."""

    writes: List[AttrWrite] = field(default_factory=list)
    host_syncs: List[Finding] = field(default_factory=list)
    #: if/while tests that depend on traced VALUES (not shapes/dtypes):
    #: legal in eager, a guaranteed ``ConcretizationTypeError`` under
    #: tracing — their presence demotes a "clean" runtime verdict to
    #: "unknown" so the eval_shape probe keeps the last (and precise) word.
    #: Entries are ``(line, owner, path)``.
    value_branches: List[Tuple[int, str, str]] = field(default_factory=list)
    #: self attributes (or aliases of them) passed as arguments to callees
    #: that are not known-pure: an in-place mutation could hide there. The
    #: runtime verdict demotes to "unknown" when the live value is mutable.
    leaked: List[str] = field(default_factory=list)
    #: False when something prevented a complete scan: a dynamic attribute
    #: write (setattr/getattr dispatch), an unresolvable self-method call, or
    #: ``self`` escaping into a non-method call. The runtime integration only
    #: trusts fully-resolved scans.
    resolved: bool = True


def _self_attr(expr: ast.expr) -> Optional[str]:
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


#: namespaces whose functions never mutate their array/container arguments
#: in place (jax arrays are immutable; these APIs return new values)
_PURE_ARG_NAMESPACES = frozenset({"jnp", "np", "jax", "lax", "numpy", "onp"})


def _collect_writes(fn: ast.FunctionDef, owner: str, path: str, scan: BodyScan) -> None:
    # local aliases of self attributes (`buf = self.seen`): an in-place
    # mutation of the alias is a mutation of the attribute
    aliases: Dict[str, str] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Attribute):
            attr = _self_attr(node.value)
            if attr is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        aliases[t.id] = attr
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                _write_target(t, owner, path, scan)
                # rebinding an alias name to something else ends the alias —
                # but a SUBSCRIPT store through it is still an attr mutation
                if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                    attr = aliases.get(t.value.id)
                    if attr is not None:
                        scan.writes.append(AttrWrite(attr, t.lineno, t.col_offset, True, owner, path))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            _write_target(node.target, owner, path, scan)
        elif isinstance(node, ast.AugAssign):
            _write_target(node.target, owner, path, scan, aug=True)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    scan.writes.append(AttrWrite(attr, t.lineno, t.col_offset, False, owner, path))
                elif isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        scan.writes.append(AttrWrite(attr, t.lineno, t.col_offset, True, owner, path))
        elif isinstance(node, ast.Call):
            # in-place container mutation: self.attr.append(...) — or the
            # same through a local alias (`buf = self.attr; buf.append(x)`)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
            ):
                attr = _self_attr(node.func.value)
                if attr is None and isinstance(node.func.value, ast.Name):
                    attr = aliases.get(node.func.value.id)
                if attr is not None:
                    scan.writes.append(
                        AttrWrite(attr, node.lineno, node.col_offset, True, owner, path)
                    )
            # setattr(self, ...): a write we may not be able to name
            elif isinstance(node.func, ast.Name) and node.func.id == "setattr":
                if node.args and isinstance(node.args[0], ast.Name) and node.args[0].id == "self":
                    if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                        scan.writes.append(
                            AttrWrite(str(node.args[1].value), node.lineno, node.col_offset, False, owner, path)
                        )
                    else:
                        scan.resolved = False
            # a self attribute (or an alias of one) handed to an arbitrary
            # callee may be mutated in place where we cannot see it — jax
            # arrays and python scalars are immutable, container latches are
            # not. Record the leak; the runtime verdict demotes to "unknown"
            # only when the attr's LIVE value is actually mutable (so config
            # scalars like `self.reduce` passed to functional helpers keep
            # the stat-score family statically clean). jnp/np/jax-namespace
            # calls and benign builtins are known pure.
            if not _callee_is_pure(node.func):
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    leaked = None
                    if isinstance(arg, ast.Attribute):
                        leaked = _self_attr(arg)
                    elif isinstance(arg, ast.Name):
                        leaked = aliases.get(arg.id)
                    if leaked is not None:
                        scan.leaked.append(leaked)


def _write_target(t: ast.expr, owner: str, path: str, scan: BodyScan, aug: bool = False) -> None:
    attr = _self_attr(t)
    if attr is not None:
        # plain assignment rebinds; augmented assignment on a container
        # mutates in place, but either way it is a write to the attr
        scan.writes.append(AttrWrite(attr, t.lineno, t.col_offset, aug, owner, path))
        return
    if isinstance(t, ast.Subscript):
        attr = _self_attr(t.value)
        if attr is not None:
            scan.writes.append(AttrWrite(attr, t.lineno, t.col_offset, True, owner, path))
    elif isinstance(t, (ast.Tuple, ast.List)):
        for el in t.elts:
            _write_target(el, owner, path, scan, aug=aug)


# -- host-sync taint ---------------------------------------------------------

def _is_array_annotation(ann: Optional[ast.expr]) -> bool:
    if ann is None:
        return False
    text = ast.unparse(ann) if hasattr(ast, "unparse") else ""
    return any(frag in text for frag in _ARRAY_ANNOTATIONS)


class _TaintScan(ast.NodeVisitor):
    """Single-function forward taint: which local names carry traced values?

    Seeds: parameters with array-typed annotations, ``self.<state>`` reads.
    Propagation: any assignment whose RHS mentions a tainted name (or a
    tainted self-state read) taints its targets; ``for`` targets inherit
    the iterable's taint. One forward pass in source order plus a fixpoint
    loop, which is enough for the package's straight-line update bodies.
    """

    def __init__(self, fn: ast.FunctionDef, state_names: Set[str], seed_all: bool = False) -> None:
        self.state_names = state_names
        self.tainted: Set[str] = set()
        args = fn.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.arg == "self":
                continue
            # seed_all: the entry's parameters are traced by contract
            # (merge_states receives state pytrees), annotations aside
            if seed_all or _is_array_annotation(a.annotation):
                self.tainted.add(a.arg)

    def expr_tainted(self, expr: ast.expr) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return True
            attr = _self_attr(node) if isinstance(node, ast.Attribute) else None
            if attr is not None and attr in self.state_names:
                return True
        return False

    def run(self, fn: ast.FunctionDef) -> None:
        for _ in range(3):  # fixpoint for simple forward/backward dataflow
            before = set(self.tainted)
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and self.expr_tainted(node.value):
                    for t in node.targets:
                        self._taint_target(t)
                elif isinstance(node, ast.AugAssign) and (
                    self.expr_tainted(node.value) or self.expr_tainted(node.target)
                ):
                    self._taint_target(node.target)
                elif isinstance(node, ast.AnnAssign) and node.value is not None and self.expr_tainted(node.value):
                    self._taint_target(node.target)
                elif isinstance(node, ast.For) and self.expr_tainted(node.iter):
                    self._taint_target(node.target)
                elif isinstance(node, ast.withitem) and node.optional_vars is not None:
                    if self.expr_tainted(node.context_expr):
                        self._taint_target(node.optional_vars)
            if self.tainted == before:
                break

    def _taint_target(self, t: ast.expr) -> None:
        if isinstance(t, ast.Name):
            self.tainted.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._taint_target(el)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value)


#: schema reads on arrays — branching on these is static under tracing
_SHAPE_ATTRS = frozenset({"shape", "ndim", "dtype", "size"})
_SCHEMA_PREDICATES = frozenset({"isinstance", "len", "callable", "hasattr", "type", "is_traced"})


def _test_value_dependent(expr: ast.expr, taint: "_TaintScan") -> bool:
    """Does a branch test read traced *values* (vs shapes/dtypes/types)?
    ``if preds.ndim == 1`` is static under tracing; ``if preds.sum() > 0``
    concretizes a tracer and raises."""
    found = False

    def visit(node: ast.AST) -> None:
        nonlocal found
        if found:
            return
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return  # schema read — do not descend
            attr = _self_attr(node)
            if attr is not None and attr in taint.state_names:
                found = True
                return
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if name in _SCHEMA_PREDICATES:
                return
        if isinstance(node, ast.Name) and node.id in taint.tainted:
            found = True
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(expr)
    return found


def _scan_host_syncs(
    fn: ast.FunctionDef, owner: str, path: str, state_names: Set[str], scan: BodyScan,
    seed_all: bool = False,
) -> None:
    taint = _TaintScan(fn, state_names, seed_all=seed_all)
    taint.run(fn)
    for node in ast.walk(fn):
        test = None
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            test = node.test
        elif isinstance(node, ast.Assert):
            test = node.test
        if test is not None and _test_value_dependent(test, taint):
            scan.value_branches.append((node.lineno, owner, path))
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        finding: Optional[str] = None
        if isinstance(func, ast.Name) and func.id in ("float", "int", "bool"):
            if node.args and taint.expr_tainted(node.args[0]):
                finding = (
                    f"{func.id}() on a traced value forces a device->host sync "
                    "every step (and breaks under jit tracing)"
                )
        elif isinstance(func, ast.Attribute) and func.attr == "item" and not node.args:
            if taint.expr_tainted(func.value):
                finding = ".item() on a traced value forces a device->host sync every step"
        elif (
            isinstance(func, ast.Attribute)
            and func.attr in ("asarray", "array")
            and isinstance(func.value, ast.Name)
            and func.value.id in _NUMPY_MODULE_NAMES
        ):
            if node.args and taint.expr_tainted(node.args[0]):
                finding = (
                    f"np.{func.attr}() on a traced value materializes it on the "
                    "host every step — keep the hot path in jnp"
                )
        elif (
            isinstance(func, ast.Attribute) and func.attr == "device_get"
        ) or (isinstance(func, ast.Name) and func.id == "device_get"):
            finding = "jax.device_get() inside the per-step hot path blocks on the device"
        if finding:
            scan.host_syncs.append(
                Finding(
                    "host-sync-in-update",
                    path,
                    node.lineno,
                    node.col_offset,
                    f"{owner}: {finding}",
                    owner=owner,
                )
            )


# -- reachability ------------------------------------------------------------

#: builtins that take ``self`` without ever mutating its attributes
_BENIGN_SELF_CONSUMERS = frozenset({"type", "id", "repr", "str", "hash", "len", "isinstance"})

#: builtins that never mutate their arguments in place
_PURE_BUILTIN_CALLEES = frozenset(
    {
        "len", "float", "int", "bool", "str", "repr", "hash", "type", "id",
        "isinstance", "callable", "hasattr", "getattr", "list", "tuple",
        "dict", "set", "frozenset", "sorted", "reversed", "enumerate", "zip",
        "range", "min", "max", "sum", "abs", "all", "any", "print", "format",
    }
)


def _callee_is_pure(func: ast.expr) -> bool:
    """Callees that provably do not mutate their arguments in place: benign
    builtins and anything under the jnp/np/jax/lax namespaces (jax arrays
    are immutable; these APIs return new values)."""
    if isinstance(func, ast.Name):
        return func.id in _PURE_BUILTIN_CALLEES
    if isinstance(func, ast.Attribute):
        root = func.value
        while isinstance(root, ast.Attribute):
            root = root.value
        return isinstance(root, ast.Name) and root.id in _PURE_ARG_NAMESPACES
    return False


def _self_method_calls(fn: ast.FunctionDef) -> Tuple[Set[str], bool]:
    """(names of self.<m>(...) calls, self_escapes) — ``self_escapes`` is True
    when ``self`` is passed as an argument to anything non-introspective
    (the callee may then mutate attributes we cannot see)."""
    calls: Set[str] = set()
    escapes = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
            ):
                calls.add(node.func.attr)
            callee = _call_name_of(node.func)
            if callee in _BENIGN_SELF_CONSUMERS:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id == "self":
                    escapes = True
                elif isinstance(arg, ast.Starred) and isinstance(arg.value, ast.Name) and arg.value.id == "self":
                    escapes = True
    return calls, escapes


def _call_name_of(func: ast.expr) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


#: Metric-API methods update bodies legitimately call without them being
#: "helpers to scan" (they live on the runtime base and never mutate
#: non-exempt attrs from update; add_state is scanned separately)
_RUNTIME_API_METHODS = frozenset(
    {
        "add_state", "reset", "compute", "update", "forward", "clone",
        "_group_detach_if_stray", "pure_update", "pure_compute",
        "_batch_default_state", "merge_states", "_filtered_kwargs",
        "enable_check_finite", "with_capacity",
    }
)


def scan_entry(
    universe: Universe, ci: ClassInfo, entry: str, state_names: Set[str],
    seed_all_params: bool = False,
) -> Optional[BodyScan]:
    """Scan ``entry`` (``update``/``compute``/``merge_states``) of ``ci``:
    the nearest definition in the textual MRO plus every reachable
    self-method helper. Returns ``None`` when no definition is visible
    anywhere in the chain.

    ``seed_all_params=True`` taints every entry parameter regardless of
    annotation — the conservative mode the runtime probe pre-classification
    uses for its demote-to-unknown signals (an unannotated array parameter
    must not let a host sync or value branch slip past the "clean" verdict;
    the CLI keeps the annotation-based seeding so unannotated host-side
    metrics do not produce false findings)."""
    chain = universe.chain(ci)

    def find(name: str) -> Optional[Tuple[ClassInfo, ast.FunctionDef]]:
        for c in chain:
            fn = c.methods.get(name)
            if fn is not None:
                return c, fn
        return None

    start = find(entry)
    if start is None:
        return None
    scan = BodyScan()
    seen: Set[str] = set()
    queue: List[Tuple[ClassInfo, ast.FunctionDef]] = [start]
    while queue:
        owner_ci, fn = queue.pop()
        if fn.name in seen:
            continue
        seen.add(fn.name)
        owner = f"{owner_ci.name}.{fn.name}"
        _collect_writes(fn, owner, owner_ci.path, scan)
        # merge_states runs inside every forward() step (and the compiled
        # forward traces it): its state-dict parameters are traced values.
        # compute is scanned too — its host syncs are never CLI findings
        # (a one-shot compute() may legitimately leave the device), but its
        # value branches demote the runtime "clean" verdict to "unknown".
        _scan_host_syncs(
            fn, owner, owner_ci.path, state_names, scan,
            seed_all=seed_all_params or (entry == "merge_states"),
        )
        calls, escapes = _self_method_calls(fn)
        if escapes:
            scan.resolved = False
        for name in calls:
            if name in seen or name in _RUNTIME_API_METHODS:
                continue
            target = find(name)
            if target is None:
                # a self-method we cannot see (defined on an unanalyzed base
                # or built dynamically): the scan is incomplete
                scan.resolved = False
            else:
                queue.append(target)
    return scan


# ---------------------------------------------------------------------------
# the rules
# ---------------------------------------------------------------------------

def _chain_state_names(universe: Universe, ci: ClassInfo) -> Tuple[Set[str], bool]:
    names: Set[str] = set()
    dynamic = False
    for c in universe.chain(ci):
        names |= c.state_names
        dynamic = dynamic or c.dynamic_state_names
        for ref in c.state_name_refs:
            resolved = universe.constants.get(ref)
            if resolved is None:
                dynamic = True
            else:
                names.add(resolved)
    return names, dynamic


def _chain_shared_attrs(universe: Universe, ci: ClassInfo) -> Tuple[Set[str], bool]:
    """(declared shared attrs, dynamic?) — nearest declaration wins, like a
    class attribute."""
    for c in universe.chain(ci):
        if c.shared_dynamic:
            return set(), True
        if c.shared_attrs is not None:
            return set(c.shared_attrs), False
    return set(), False


def _chain_declares_identity(universe: Universe, ci: ClassInfo) -> bool:
    for c in universe.chain(ci):
        if c.defines_identity and c.identity_nontrivial:
            return True
    return False


def _is_scalar_default(expr: ast.expr) -> bool:
    """Statically-certain 0-d defaults: numeric literals, ``jnp.zeros(())``/
    ``jnp.ones(())``, ``jnp.asarray(<number>)``."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, (int, float)):
        return True
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
        if expr.func.attr in ("zeros", "ones") and expr.args:
            shape = expr.args[0]
            return isinstance(shape, ast.Tuple) and not shape.elts
        if expr.func.attr in ("asarray", "array") and expr.args:
            return isinstance(expr.args[0], ast.Constant) and isinstance(
                expr.args[0].value, (int, float)
            )
    return False


def check_class(universe: Universe, ci: ClassInfo) -> List[Finding]:
    findings: List[Finding] = []
    state_names, dynamic_states = _chain_state_names(universe, ci)
    shared, dynamic_shared = _chain_shared_attrs(universe, ci)
    declares_identity = _chain_declares_identity(universe, ci)

    # ---- state-default hygiene ------------------------------------------
    seen_names: Set[str] = set()
    for c in [ci]:  # own declarations only; ancestors report on themselves
        for call in c.add_state_calls:
            node = call.node
            for n in call.names:
                # conditional declarations (if/else list-vs-array schema
                # choices) are alternatives, never duplicates
                if n in seen_names and not call.conditional:
                    findings.append(
                        Finding(
                            "state-default", ci.path, node.lineno, node.col_offset,
                            f"{ci.name}: duplicate add_state declaration of {n!r}",
                            attr=n, owner=ci.name,
                        )
                    )
                if not call.conditional:
                    seen_names.add(n)
            if isinstance(call.default, ast.List) and call.default.elts:
                findings.append(
                    Finding(
                        "state-default", ci.path, node.lineno, node.col_offset,
                        f"{ci.name}: add_state default must be a jnp array or an "
                        "EMPTY list (non-empty list defaults are rejected at runtime)",
                        owner=ci.name,
                    )
                )
            fx_literal: Optional[object] = None
            if isinstance(call.fx, ast.Constant):
                fx_literal = call.fx.value
            if isinstance(fx_literal, str) and fx_literal not in _ALLOWED_FX:
                findings.append(
                    Finding(
                        "state-default", ci.path, node.lineno, node.col_offset,
                        f"{ci.name}: dist_reduce_fx {fx_literal!r} is not one of "
                        f"{sorted(_ALLOWED_FX)} (or a callable/None)",
                        owner=ci.name,
                    )
                )
            if (
                isinstance(call.default, ast.List)
                and not call.default.elts
                and fx_literal in ("sum", "mean", "max", "min")
            ):
                findings.append(
                    Finding(
                        "state-default", ci.path, node.lineno, node.col_offset,
                        f"{ci.name}: a growing list state cannot use the reduce-style "
                        f"dist_reduce_fx {fx_literal!r} — the host sync treats lists as "
                        "cat-family (use 'cat'/None/a callable, or an array default)",
                        owner=ci.name,
                    )
                )
            if call.default is not None and fx_literal == "cat" and _is_scalar_default(call.default):
                findings.append(
                    Finding(
                        "state-default", ci.path, node.lineno, node.col_offset,
                        f"{ci.name}: a 0-d default cannot be a 'cat' state — "
                        "concatenation needs a leading row dimension (shape/dtype "
                        "mismatch with the declared reduction)",
                        owner=ci.name,
                    )
                )

    # ---- update-identity-redeclare --------------------------------------
    if ci.defines_update and not ci.defines_identity:
        for c in universe.chain(ci)[1:]:
            if c.defines_identity and c.identity_nontrivial:
                fn = ci.methods["update"]
                findings.append(
                    Finding(
                        "update-identity-redeclare", ci.path, fn.lineno, fn.col_offset,
                        f"{ci.name} overrides update() but not update_identity(); the "
                        f"key inherited from {c.name} is silently dropped at runtime "
                        "(Metric._effective_update_identity) — re-declare the key (or "
                        "an explicit `return None`) to make the grouping contract "
                        "visible",
                        owner=f"{ci.name}.update",
                    )
                )
                break

    # ---- mutation + host-sync rules -------------------------------------
    # only report findings for code the class itself defines — inherited
    # bodies are the ancestor's findings, at its own definition site
    own_methods = {f"{ci.name}.{m}" for m in ci.methods}
    for entry in ("update", "compute", "merge_states"):
        scan = scan_entry(universe, ci, entry, state_names)
        if scan is None:
            continue
        if entry != "compute":  # compute host syncs are not hot-path findings
            findings.extend(f for f in scan.host_syncs if f.owner in own_methods)
        if entry == "merge_states":
            # merge_states is checked for host syncs only: it must not touch
            # self at all, but inherited Metric.merge_states bookkeeping and
            # super() delegation make a write rule too noisy to be useful
            continue
        if dynamic_states or dynamic_shared:
            continue  # cannot know the declared sets; stay silent
        for w in scan.writes:
            if w.owner not in own_methods:
                continue
            if w.attr in state_names or w.attr in shared or w.attr in RUNTIME_EXEMPT_ATTRS:
                continue
            if w.attr.startswith("__"):
                continue
            verb = "mutates (in place)" if w.in_place else "assigns"
            if declares_identity and entry == "update":
                findings.append(
                    Finding(
                        "unshared-latch", ci.path, w.line, w.col,
                        f"{w.owner} {verb} self.{w.attr}, which is not an add_state "
                        "state and is missing from _group_shared_attrs — a compute "
                        "group would not propagate it to siblings (declare it, or "
                        "drop the update_identity key)",
                        attr=w.attr, owner=w.owner,
                    )
                )
            else:
                findings.append(
                    Finding(
                        "undeclared-state", ci.path, w.line, w.col,
                        f"{w.owner} {verb} self.{w.attr}, which no reachable "
                        "add_state() declares — an undeclared latch: reset()/sync/"
                        "checkpoint will not cover it and the compiled hot path "
                        "must exclude this class (declare it with add_state, or "
                        "set it in __init__ and list it in _group_shared_attrs)",
                        attr=w.attr, owner=w.owner,
                    )
                )
    return findings


def run_metric_pass(universe: Universe, infos: Sequence[ClassInfo]) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[Tuple] = set()
    for ci in infos:
        if not universe.is_metric_class(ci):
            continue
        if ci.name in ("Metric", "MetricCollection"):
            continue  # the runtime bases themselves, not metric subclasses
        for f in check_class(universe, ci):
            key = (f.rule, f.path, f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                findings.append(f)
    return findings
