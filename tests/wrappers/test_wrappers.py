"""AverageMeter / BootStrapper / MetricTracker tests."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, AverageMeter, BootStrapper, MetricTracker
from tests.helpers.testers import DummyMetricSum


def test_average_meter_simple():
    avg = AverageMeter()
    avg.update(3)
    avg.update(1)
    np.testing.assert_allclose(np.asarray(avg.compute()), 2.0)


def test_average_meter_weighted():
    avg = AverageMeter()
    v = avg(jnp.asarray([1.0, 2.0]), jnp.asarray([3.0, 1.0]))
    np.testing.assert_allclose(np.asarray(v), 1.25)


def test_average_meter_vector():
    avg = AverageMeter()
    v = avg(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(v), 2.0)


def test_bootstrapper_accuracy():
    rng = np.random.RandomState(123)
    boot = BootStrapper(Accuracy(num_classes=5), num_bootstraps=20, seed=1)
    boot.update(jnp.asarray(rng.randint(0, 5, (200,))), jnp.asarray(rng.randint(0, 5, (200,))))
    out = boot.compute()
    assert set(out.keys()) == {"mean", "std"}
    # random preds vs random targets -> accuracy ~ 0.2
    assert abs(float(out["mean"]) - 0.2) < 0.1
    assert float(out["std"]) > 0


def test_bootstrapper_quantile_raw():
    rng = np.random.RandomState(5)
    boot = BootStrapper(
        Accuracy(num_classes=5), num_bootstraps=10, quantile=0.5, raw=True, sampling_strategy="multinomial"
    )
    boot.update(jnp.asarray(rng.randint(0, 5, (100,))), jnp.asarray(rng.randint(0, 5, (100,))))
    out = boot.compute()
    assert "quantile" in out and "raw" in out
    assert out["raw"].shape == (10,)


def test_bootstrapper_invalid():
    with pytest.raises(ValueError, match="Expected base metric"):
        BootStrapper("not-a-metric")
    with pytest.raises(ValueError, match="sampling_strategy"):
        BootStrapper(Accuracy(), sampling_strategy="bogus")


def test_tracker_lifecycle():
    tracker = MetricTracker(DummyMetricSum(), maximize=True)
    with pytest.raises(ValueError, match="cannot be called before"):
        tracker.update(jnp.asarray(1.0))
    vals = [1.0, 5.0, 3.0]
    for v in vals:
        tracker.increment()
        tracker.update(jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(tracker.compute_all()), vals)
    best, step = tracker.best_metric(return_step=True)
    assert best == 5.0 and step == 1
    assert tracker.n_steps == 3


def test_tracker_minimize():
    tracker = MetricTracker(DummyMetricSum(), maximize=False)
    for v in [3.0, 1.0, 2.0]:
        tracker.increment()
        tracker.update(jnp.asarray(v))
    assert tracker.best_metric() == 1.0
