"""Specificity module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/specificity.py`` (174 LoC).
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.stat_scores import StatScores
from metrics_tpu.functional.classification.specificity import _specificity_compute


class Specificity(StatScores):
    r"""Specificity :math:`\frac{TN}{TN + FP}` — the true-negative rate:
    how much of what is *negative* the model correctly left alone
    (reference ``specificity.py:28``). The mirror image of
    :class:`~metrics_tpu.Recall`, which scores the positives.

    Accumulates the shared :class:`StatScores` tp/fp/tn/fn counters;
    every constructor argument (``num_classes``, ``threshold``,
    ``average``, ``mdmc_average``, ``ignore_index``, ``top_k``,
    ``multiclass``, and the runtime quartet) behaves exactly as documented
    on :class:`~metrics_tpu.Precision` — only the compute-time ratio
    differs, dividing true negatives by all actual negatives.

    Raises:
        ValueError: unknown ``average``, per-class average without
            ``num_classes``, or multidim input without ``mdmc_average``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Specificity
        >>> preds = jnp.asarray([0, 2, 1, 3])
        >>> target = jnp.asarray([0, 1, 2, 3])
        >>> specificity = Specificity(num_classes=4, average="macro")
        >>> print(round(float(specificity(preds, target)), 4))
        0.8333
        >>> micro = Specificity(average="micro")
        >>> micro.update(jnp.asarray([0.1, 0.9, 0.6]), jnp.asarray([0, 0, 1]))
        >>> print(round(float(micro.compute()), 4))
        0.5
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        threshold: float = 0.5,
        average: str = "micro",
        mdmc_average: Optional[str] = None,
        ignore_index: Optional[int] = None,
        top_k: Optional[int] = None,
        multiclass: Optional[bool] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
        if average not in allowed_average:
            raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
        if average in ["macro", "weighted", "none", None] and (not num_classes or num_classes < 1):
            raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
        super().__init__(
            reduce="macro" if average in ["weighted", "none", None] else average,
            mdmc_reduce=mdmc_average,
            threshold=threshold,
            top_k=top_k,
            num_classes=num_classes,
            multiclass=multiclass,
            ignore_index=ignore_index,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.average = average

    def compute(self) -> Array:
        tp, fp, tn, fn = self._get_final_stats()
        return _specificity_compute(tp, fp, tn, fn, self.average, self.mdmc_reduce)
