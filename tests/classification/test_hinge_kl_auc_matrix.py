"""Hinge / KLDivergence / AUC grids vs sklearn & scipy.

Mirror of the reference's `tests/classification/test_hinge.py`,
`test_kl_divergence.py`, and `test_auc.py`: hinge over binary / single-elem /
multiclass × squared × multiclass_mode against an sklearn-adapted oracle; KL
over probs / log-probs × reduction against scipy entropy; AUC over
sorted-both-ways random curves (small + large) against sklearn auc.
"""
from collections import namedtuple
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.special import softmax
from scipy.stats import entropy
from sklearn.metrics import auc as sk_auc_fn
from sklearn.metrics import hinge_loss as sk_hinge_loss
from sklearn.preprocessing import OneHotEncoder

from metrics_tpu import AUC, Hinge, KLDivergence
from metrics_tpu.functional import auc, hinge, kl_divergence
from tests.helpers.testers import BATCH_SIZE, EXTRA_DIM, NUM_BATCHES, NUM_CLASSES, MetricTester

rng = np.random.RandomState(42)

Input = namedtuple("Input", ["preds", "target"])

_hinge_binary = Input(
    preds=rng.randn(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
    target=rng.randint(0, 2, (NUM_BATCHES, BATCH_SIZE)),
)
_hinge_multiclass = Input(
    preds=rng.randn(NUM_BATCHES, BATCH_SIZE, NUM_CLASSES).astype(np.float32),
    target=rng.randint(0, NUM_CLASSES, (NUM_BATCHES, BATCH_SIZE)),
)


def _sk_hinge(preds, target, squared, multiclass_mode):
    """Reference `test_hinge.py:42-74` (sklearn-adapted; squared and
    one-vs-all built from the margin directly)."""
    sk_preds, sk_target = np.asarray(preds, np.float64), np.asarray(target)

    if multiclass_mode == "one-vs-all":
        enc = OneHotEncoder()
        enc.fit(sk_target.reshape(-1, 1))
        sk_target = enc.transform(sk_target.reshape(-1, 1)).toarray()

    if sk_preds.ndim == 1 or multiclass_mode == "one-vs-all":
        sk_target = 2 * sk_target - 1

    if squared or sk_target.max() != 1 or sk_target.min() != -1:
        if sk_preds.ndim == 1 or multiclass_mode == "one-vs-all":
            margin = sk_target * sk_preds
        else:
            mask = np.ones_like(sk_preds, dtype=bool)
            mask[np.arange(sk_target.shape[0]), sk_target] = False
            margin = sk_preds[~mask]
            margin -= np.max(sk_preds[mask].reshape(sk_target.shape[0], -1), axis=1)
        measures = np.clip(1 - margin, 0, None)
        if squared:
            measures = measures**2
        return measures.mean(axis=0)
    if multiclass_mode == "one-vs-all":
        return np.asarray([
            sk_hinge_loss(y_true=sk_target[:, i], pred_decision=sk_preds[:, i])
            for i in range(sk_preds.shape[1])
        ])
    return sk_hinge_loss(y_true=sk_target, pred_decision=sk_preds)


@pytest.mark.parametrize(
    "preds, target, squared, multiclass_mode",
    [
        (_hinge_binary.preds, _hinge_binary.target, False, None),
        (_hinge_binary.preds, _hinge_binary.target, True, None),
        (_hinge_multiclass.preds, _hinge_multiclass.target, False, "crammer-singer"),
        (_hinge_multiclass.preds, _hinge_multiclass.target, True, "crammer-singer"),
        (_hinge_multiclass.preds, _hinge_multiclass.target, False, "one-vs-all"),
        (_hinge_multiclass.preds, _hinge_multiclass.target, True, "one-vs-all"),
    ],
    ids=["binary", "binary_sq", "cs", "cs_sq", "ova", "ova_sq"],
)
class TestHingeMatrix(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_hinge_class(self, preds, target, squared, multiclass_mode, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Hinge,
            sk_metric=partial(_sk_hinge, squared=squared, multiclass_mode=multiclass_mode),
            dist_sync_on_step=dist_sync_on_step,
            metric_args={"squared": squared, "multiclass_mode": multiclass_mode},
            check_jit=False,
        )

    def test_hinge_fn(self, preds, target, squared, multiclass_mode):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=hinge,
            sk_metric=partial(_sk_hinge, squared=squared, multiclass_mode=multiclass_mode),
            metric_args={"squared": squared, "multiclass_mode": multiclass_mode},
        )


def test_hinge_wrong_params():
    """Reference `test_hinge.py:125-155`: bad mode / shape mismatches raise."""
    with pytest.raises(ValueError):
        hinge(jnp.asarray(_hinge_multiclass.preds[0]), jnp.asarray(_hinge_multiclass.target[0]),
              multiclass_mode="bogus")
    with pytest.raises(ValueError):
        hinge(jnp.asarray([[-1.0, 1.0]]), jnp.asarray([0, 1]))  # batch mismatch


# -- KL divergence ----------------------------------------------------------
_kl_p = rng.rand(NUM_BATCHES, BATCH_SIZE, EXTRA_DIM).astype(np.float32) + 1e-3
_kl_q = rng.rand(NUM_BATCHES, BATCH_SIZE, EXTRA_DIM).astype(np.float32) + 1e-3
_kl_logp = np.log(softmax(rng.rand(NUM_BATCHES, BATCH_SIZE, EXTRA_DIM).astype(np.float32), axis=-1))
_kl_logq = np.log(softmax(rng.rand(NUM_BATCHES, BATCH_SIZE, EXTRA_DIM).astype(np.float32), axis=-1))


def _sk_kl(p, q, log_prob, reduction):
    """Reference `test_kl_divergence.py:46-56`: scipy entropy (normalizes
    unnormalized probs itself)."""
    p, q = np.asarray(p, np.float64), np.asarray(q, np.float64)
    if log_prob:
        p, q = softmax(p, axis=1), softmax(q, axis=1)
    res = entropy(p, q, axis=1)
    return {"mean": np.mean, "sum": np.sum}[reduction](res)


@pytest.mark.parametrize("reduction", ["mean", "sum"])
@pytest.mark.parametrize(
    "p, q, log_prob",
    [(_kl_p, _kl_q, False), (_kl_logp, _kl_logq, True)],
    ids=["probs", "log_probs"],
)
class TestKLDivergenceMatrix(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [True, False])
    def test_kl_class(self, p, q, log_prob, reduction, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=p,
            target=q,
            metric_class=KLDivergence,
            sk_metric=partial(_sk_kl, log_prob=log_prob, reduction=reduction),
            metric_args={"log_prob": log_prob, "reduction": reduction},
            check_jit=False,
        )

    def test_kl_fn(self, p, q, log_prob, reduction):
        self.run_functional_metric_test(
            p,
            q,
            metric_functional=kl_divergence,
            sk_metric=partial(_sk_kl, log_prob=log_prob, reduction=reduction),
            metric_args={"log_prob": log_prob, "reduction": reduction},
        )


# -- AUC --------------------------------------------------------------------
def _make_curve(n, direction):
    x = np.sort(rng.rand(n).astype(np.float64))
    y = rng.rand(n).astype(np.float64)
    if direction == "desc":
        x, y = x[::-1].copy(), y[::-1].copy()
    return x.astype(np.float32), y.astype(np.float32)


@pytest.mark.parametrize("n", [8 * NUM_BATCHES, 512 * NUM_BATCHES], ids=["small", "large"])
@pytest.mark.parametrize("direction", ["asc", "desc"])
def test_auc_matrix(n, direction):
    """Sorted-both-ways curves, accumulated batch-wise, vs sklearn auc
    (reference `test_auc.py:44-86`)."""
    x, y = _make_curve(n, direction)
    expected = sk_auc_fn(x[::-1], y[::-1]) if direction == "desc" else sk_auc_fn(x, y)

    m = AUC()
    for xb, yb in zip(x.reshape(NUM_BATCHES, -1), y.reshape(NUM_BATCHES, -1)):
        m.update(jnp.asarray(xb), jnp.asarray(yb))
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(float(auc(jnp.asarray(x), jnp.asarray(y))), expected, atol=1e-4, rtol=1e-4)


def test_auc_reorder():
    """Unsorted x needs reorder=True (reference `test_auc.py:89-100`)."""
    x = jnp.asarray([1.0, 3.0, 2.0, 4.0])
    y = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    with pytest.raises(ValueError, match="reorder"):
        auc(x, y)
    np.testing.assert_allclose(
        float(auc(x, y, reorder=True)),
        sk_auc_fn(np.sort(np.asarray(x)), np.asarray(y)[np.argsort(np.asarray(x))]),
        atol=1e-6,
    )
