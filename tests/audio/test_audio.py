"""Audio metric parity vs hand-rolled numpy references, mirroring the
reference's `tests/audio/` strategy (which compares against speechmetrics /
hand-rolled formulas)."""
from itertools import permutations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import PIT, SI_SDR, SI_SNR, SNR
from metrics_tpu.functional import pit, pit_permutate, si_sdr, si_snr, snr
from tests.helpers import seed_all
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

seed_all(42)

TIME = 100

_preds = np.random.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32)
_target = np.random.randn(NUM_BATCHES, BATCH_SIZE, TIME).astype(np.float32)


def _np_snr(preds, target, zero_mean=False):
    eps = np.finfo(np.float32).eps
    if zero_mean:
        target = target - target.mean(-1, keepdims=True)
        preds = preds - preds.mean(-1, keepdims=True)
    noise = target - preds
    return 10 * np.log10(((target**2).sum(-1) + eps) / ((noise**2).sum(-1) + eps))


def _np_si_sdr(preds, target, zero_mean=False):
    eps = np.finfo(np.float32).eps
    if zero_mean:
        target = target - target.mean(-1, keepdims=True)
        preds = preds - preds.mean(-1, keepdims=True)
    alpha = ((preds * target).sum(-1, keepdims=True) + eps) / ((target**2).sum(-1, keepdims=True) + eps)
    scaled = alpha * target
    noise = scaled - preds
    return 10 * np.log10(((scaled**2).sum(-1) + eps) / ((noise**2).sum(-1) + eps))


def _np_si_snr(preds, target):
    return _np_si_sdr(preds, target, zero_mean=True)


def _avg(fn):
    return lambda p, t: fn(p, t).mean()


@pytest.mark.parametrize(
    "metric_class, metric_fn, np_fn, metric_args",
    [
        (SNR, snr, _np_snr, {}),
        (SNR, snr, lambda p, t: _np_snr(p, t, zero_mean=True), {"zero_mean": True}),
        (SI_SDR, si_sdr, _np_si_sdr, {}),
        (SI_SDR, si_sdr, lambda p, t: _np_si_sdr(p, t, zero_mean=True), {"zero_mean": True}),
        (SI_SNR, si_snr, _np_si_snr, {}),
    ],
)
class TestAudioRatios(MetricTester):
    atol = 1e-3  # float32 log-domain accumulation

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp, metric_class, metric_fn, np_fn, metric_args):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_preds,
            target=_target,
            metric_class=metric_class,
            sk_metric=_avg(np_fn),
            metric_args=metric_args,
        )

    def test_fn(self, metric_class, metric_fn, np_fn, metric_args):
        self.run_functional_metric_test(
            _preds, _target, metric_functional=metric_fn, sk_metric=np_fn, metric_args=metric_args
        )


def _np_pit(preds, target, np_metric, eval_func="max"):
    """Exhaustive numpy PIT reference."""
    batch, spk = target.shape[:2]
    best_metric = np.empty(batch)
    best_perm = np.empty((batch, spk), dtype=np.int64)
    for b in range(batch):
        best = None
        for perm in permutations(range(spk)):
            val = np.mean([np_metric(preds[b, perm[t]], target[b, t]) for t in range(spk)])
            if best is None or (val > best if eval_func == "max" else val < best):
                best = val
                best_perm[b] = perm
            # note: perm[t] is the estimate matched to target t
        best_metric[b] = best
    return best_metric, best_perm


_pit_preds = np.random.randn(NUM_BATCHES, 4, 3, TIME).astype(np.float32)
_pit_target = np.random.randn(NUM_BATCHES, 4, 3, TIME).astype(np.float32)


@pytest.mark.parametrize(
    "metric_fn, np_fn, eval_func",
    [
        (si_sdr, _np_si_sdr, "max"),
        (si_snr, _np_si_snr, "max"),
        (snr, _np_snr, "max"),
    ],
)
def test_pit_functional(metric_fn, np_fn, eval_func):
    for i in range(NUM_BATCHES):
        best_metric, best_perm = pit(
            jnp.asarray(_pit_preds[i]), jnp.asarray(_pit_target[i]), metric_fn, eval_func
        )
        np_metric, np_perm = _np_pit(_pit_preds[i], _pit_target[i], np_fn, eval_func)
        np.testing.assert_allclose(np.asarray(best_metric), np_metric, atol=1e-3)
        np.testing.assert_array_equal(np.asarray(best_perm), np_perm)


def test_pit_permutate():
    preds = jnp.asarray(_pit_preds[0])
    best_metric, best_perm = pit(preds, jnp.asarray(_pit_target[0]), si_sdr, "max")
    permuted = pit_permutate(preds, best_perm)
    for b in range(preds.shape[0]):
        for t in range(preds.shape[1]):
            np.testing.assert_array_equal(
                np.asarray(permuted[b, t]), np.asarray(preds[b, int(best_perm[b, t])])
            )


def test_pit_jit():
    fn = jax.jit(lambda p, t: pit(p, t, si_sdr, "max"))
    best_metric, best_perm = fn(jnp.asarray(_pit_preds[0]), jnp.asarray(_pit_target[0]))
    np_metric, np_perm = _np_pit(_pit_preds[0], _pit_target[0], _np_si_sdr, "max")
    np.testing.assert_allclose(np.asarray(best_metric), np_metric, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(best_perm), np_perm)


def test_pit_hungarian_path():
    """Force the Hungarian host path by dropping the exhaustive limit."""
    import importlib

    pit_mod = importlib.import_module("metrics_tpu.functional.audio.pit")
    old = pit_mod._MAX_EXHAUSTIVE_SPK
    pit_mod._MAX_EXHAUSTIVE_SPK = 1
    try:
        best_metric, best_perm = pit(
            jnp.asarray(_pit_preds[0]), jnp.asarray(_pit_target[0]), si_sdr, "max"
        )
    finally:
        pit_mod._MAX_EXHAUSTIVE_SPK = old
    np_metric, np_perm = _np_pit(_pit_preds[0], _pit_target[0], _np_si_sdr, "max")
    np.testing.assert_allclose(np.asarray(best_metric), np_metric, atol=1e-3)
    np.testing.assert_array_equal(np.asarray(best_perm), np_perm)


def test_pit_class():
    m = PIT(si_sdr, "max")
    for i in range(NUM_BATCHES):
        m.update(jnp.asarray(_pit_preds[i]), jnp.asarray(_pit_target[i]))
    expected = np.mean(
        [_np_pit(_pit_preds[i], _pit_target[i], _np_si_sdr, "max")[0].mean() for i in range(NUM_BATCHES)]
    )
    np.testing.assert_allclose(float(m.compute()), expected, atol=1e-3)


def test_pit_errors():
    with pytest.raises(ValueError, match="eval_func"):
        pit(jnp.zeros((2, 2, 4)), jnp.zeros((2, 2, 4)), si_sdr, "best")
    with pytest.raises(ValueError, match="Inputs must be of shape"):
        pit(jnp.zeros((4,)), jnp.zeros((4,)), si_sdr, "max")
