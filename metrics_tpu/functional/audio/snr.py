"""Signal-to-noise ratio — analogue of reference
``torchmetrics/functional/audio/snr.py:21-67``.

Pure jnp, vectorized over all leading dims, jittable.
"""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def snr(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    r"""Signal-to-noise ratio: :math:`10 \log_{10}(P_{signal} / P_{noise})`.

    Args:
        preds: shape ``[..., time]``
        target: shape ``[..., time]``
        zero_mean: subtract the time-mean from both signals first

    Returns:
        snr value of shape ``[...]``

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> float(snr(preds, target))  # doctest: +ELLIPSIS
        16.18...
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    ratio = (jnp.sum(target * target, axis=-1) + eps) / (jnp.sum(noise * noise, axis=-1) + eps)
    return 10 * jnp.log10(ratio)
