"""Bucketed host-path sync: one collective per dtype/fx class, not per leaf.

The health-word protocol (``parallel/health.py``) collapsed the *precheck*
collectives into a single gather, but the *payload* path still issued one
``process_allgather`` per state leaf — plus a shape pre-gather per uneven
leaf — and a ``MetricCollection`` multiplied that by the number of metrics.
Collective fusion is exactly the lever the related work pulls (EQuARX,
arxiv 2506.17615: fused quantized AllReduce; portable collective
redistribution, arxiv 2112.01075: many small transfers batched into few
large ones): latency hides in per-collective launch overhead, so the fix is
to move the same bytes in O(#dtypes × #fx-classes) collectives.

This module is the **bucketed sync planner**. Given the state dict of one
metric — or the combined, key-prefixed states of an entire
``MetricCollection`` (``MetricCollection.sync``) — it classifies every leaf
and builds a :class:`SyncPlan`. Compute groups (``core/collections.py``)
compose with the planner upstream: the collection combines ONE state per
group (not one per member), so a grouped collection's plan carries fewer
leaves — fewer header count/length columns consumed and strictly smaller
bucket payloads — while staying rank-symmetric (grouping is deterministic
from construction, so every rank plans the identical combined schema):

- **reduce leaves** (``fx`` in ``sum``/``mean``/``max``/``min``) group by
  ``(dtype, fx)``: each bucket flattens and concatenates into one flat
  buffer, gathers once to ``[world, total]``, applies the reduction over the
  world axis, and splits back — elementwise over the same ``world`` values
  as the per-leaf path, so results are bit-identical;
- **cat-family leaves** (CatBuffer, list states, arrays with ``fx`` in
  ``("cat", None)``) group by dtype into one padded ragged buffer: each rank
  flattens its rows leaf-by-leaf, pads to the max total across ranks (known
  from the header's length columns — no shape pre-gathers), gathers once,
  and every rank slices each leaf's per-rank pieces back out;
- **callable-``fx`` leaves** cannot be planned (opaque reduction) and fall
  back to :func:`~metrics_tpu.parallel.sync.host_sync_leaf`.

The static plan (leaf order, bucket membership, item shapes/sizes) is
cached in the unified :class:`~metrics_tpu.core.plan.ExecutionPlan` store
(``core/plan.py``), keyed on the exact schema string behind the health
word's CRC (:func:`~metrics_tpu.parallel.health.state_schema_parts` — the
full string, so a CRC collision can never alias two schemas onto one
plan), so repeated ``compute()`` calls pay zero re-planning. Per-rank row
counts — the only dynamic input — ride the header gather's length columns.
The store is lock-protected and plans are immutable after construction, so
the async overlap layer (``parallel/async_sync.py``) reuses them from its
background thread across overlapped rounds — a round's snapshot has the
same schema the blocking path would sync, so rounds hit the cached plan
without re-planning. This module keeps the *classifier* (the pure layout
builder) and the execution engine; the cache itself lives with the plan.

Execution requires the caller to have *already verified* the gathered
health words: the plan trusts cross-rank schema equality (verified via the
schema hash), non-empty cat states (count columns), and un-overflowed
CatBuffers (overflow column). ``host_sync_state`` wires this up and is the
supported entry point; the ``METRICS_TPU_FUSED_SYNC=0`` env knob is the
escape hatch back to the per-leaf path.
"""
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.parallel.health import (
    cat_family_names,
    cat_row_count,
    header_cat_lengths,
)

__all__ = [
    "LeafSpec",
    "SyncPlan",
    "build_sync_plan",
    "clear_sync_plan_cache",
    "fused_sync_enabled",
    "host_sync_state_bucketed",
    "sync_plan_cache_info",
]

#: Env escape hatch: set to 0/false/off to restore the per-leaf payload path.
FUSED_SYNC_ENV = "METRICS_TPU_FUSED_SYNC"

_REDUCERS = {
    "sum": lambda g: jnp.sum(g, axis=0),
    "mean": lambda g: jnp.mean(g, axis=0),
    "max": lambda g: jnp.max(g, axis=0),
    "min": lambda g: jnp.min(g, axis=0),
}


def fused_sync_enabled() -> bool:
    """Default payload strategy: bucketed (fused) unless the env knob opts out."""
    return os.environ.get(FUSED_SYNC_ENV, "1").strip().lower() not in ("0", "false", "off", "no")


class LeafSpec:
    """Static per-leaf plan entry.

    ``kind`` ∈ ``reduce`` | ``cat`` | ``list`` | ``catbuf`` | ``fallback``.
    ``item_shape``/``item_size`` describe one *row* for cat-family leaves and
    the full (rank-invariant) array for reduce leaves. ``cat_index`` is the
    leaf's column in the header's length table (-1 for non-cat kinds).
    """

    __slots__ = ("name", "kind", "fx", "dtype", "item_shape", "item_size", "cat_index")

    def __init__(self, name: str, kind: str, fx: Any, dtype: Any,
                 item_shape: Tuple[int, ...], cat_index: int = -1) -> None:
        self.name = name
        self.kind = kind
        self.fx = fx
        self.dtype = dtype
        self.item_shape = item_shape
        self.item_size = int(np.prod(item_shape, dtype=np.int64)) if item_shape else 1
        self.cat_index = cat_index

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"LeafSpec({self.name!r}, {self.kind}, fx={self.fx!r}, "
                f"dtype={self.dtype}, item={self.item_shape})")


class SyncPlan:
    """The fused schedule for one schema: which leaves ride which collective.

    ``n_collectives(world)`` is the payload-collective budget (header not
    included): one per reduce bucket, one per non-empty cat bucket, plus the
    per-leaf cost of unplannable fallbacks.
    """

    __slots__ = ("leaves", "cat_leaves", "reduce_buckets", "cat_buckets", "fallback", "schema_key")

    def __init__(self, leaves: Dict[str, LeafSpec], cat_leaves: List[LeafSpec],
                 reduce_buckets: Dict[Tuple[str, str], List[LeafSpec]],
                 cat_buckets: Dict[str, List[LeafSpec]],
                 fallback: List[LeafSpec], schema_key: str) -> None:
        self.leaves = leaves
        self.cat_leaves = cat_leaves
        self.reduce_buckets = reduce_buckets
        self.cat_buckets = cat_buckets
        self.fallback = fallback
        self.schema_key = schema_key

    @property
    def n_buckets(self) -> int:
        return len(self.reduce_buckets) + len(self.cat_buckets)


# The schema-keyed cache that used to live here moved into the unified plan
# store (``core/plan.py``): one ``ExecutionPlan`` per schema owns the
# ``SyncPlan`` layout this module builds, alongside the compiled-program and
# compute-group bookkeeping the other planners used to cache separately.
# These two names are the long-standing public API — kept as views.


def clear_sync_plan_cache() -> None:
    from metrics_tpu.core.plan import clear_plans

    clear_plans()


def sync_plan_cache_info() -> Dict[str, int]:
    from metrics_tpu.core.plan import plan_cache_info

    info = plan_cache_info()
    return {"size": info["size"], "hits": info["hits"], "misses": info["misses"]}


def _classify(state: Dict[str, Any], reductions: Dict[str, Any], schema_key: str) -> SyncPlan:
    from metrics_tpu.core.cat_buffer import CatBuffer

    cat_order = {n: j for j, n in enumerate(cat_family_names(state, reductions))}
    leaves: Dict[str, LeafSpec] = {}
    cat_leaves: List[LeafSpec] = []
    reduce_buckets: Dict[Tuple[str, str], List[LeafSpec]] = {}
    cat_buckets: Dict[str, List[LeafSpec]] = {}
    fallback: List[LeafSpec] = []
    for name in sorted(state):
        v = state[name]
        fx = reductions.get(name)
        if isinstance(v, CatBuffer):
            item = None if v.buffer is None else tuple(v.buffer.shape[1:])
            dtype = None if v.buffer is None else v.buffer.dtype
            spec = LeafSpec(name, "catbuf", fx, dtype, item or (), cat_order[name])
        elif isinstance(v, (list, tuple)):
            if len(v):
                first = jnp.asarray(v[0])
                item = tuple(first.shape[1:]) if first.ndim else ()
                dtype = first.dtype
            else:
                item, dtype = (), None
            spec = LeafSpec(name, "list", fx, dtype, item, cat_order[name])
        else:
            arr = jnp.asarray(v)
            if fx in ("cat", None):
                item = tuple(arr.shape[1:]) if arr.ndim else ()
                spec = LeafSpec(name, "cat", fx, arr.dtype, item, cat_order[name])
            elif fx in _REDUCERS:
                spec = LeafSpec(name, "reduce", fx, arr.dtype, tuple(arr.shape))
            else:
                # callable fx: opaque reduction over the [world, ...] stack —
                # cannot ride a shared buffer, so it keeps the per-leaf path
                spec = LeafSpec(name, "fallback", fx, arr.dtype, tuple(arr.shape))
        leaves[name] = spec
        if spec.kind == "reduce":
            reduce_buckets.setdefault((str(spec.dtype), spec.fx), []).append(spec)
        elif spec.kind == "fallback":
            fallback.append(spec)
        else:
            cat_leaves.append(spec)
            if spec.dtype is not None:
                cat_buckets.setdefault(str(spec.dtype), []).append(spec)
            else:
                # item spec unknown (empty list / unmaterialized CatBuffer):
                # unreachable after a passed health check (count column == 0
                # raises first); routed to the per-leaf path defensively
                fallback.append(spec)
    return SyncPlan(leaves, cat_leaves, reduce_buckets, cat_buckets, fallback, schema_key)


def build_sync_plan(state: Dict[str, Any], reductions: Dict[str, Any]) -> SyncPlan:
    """The (cached) fused schedule for this state schema — a view into the
    unified :class:`~metrics_tpu.core.plan.ExecutionPlan` store, which keys
    on the exact schema string the health word hashes, so any change a rank
    could legally make between syncs (a CatBuffer materializing its item
    spec, a dtype cast) keys a fresh plan, while repeated syncs of the same
    schema — every ``compute()`` of a long eval — hit the cache.
    """
    from metrics_tpu.core.plan import plan_for

    return plan_for(state, reductions).sync_layout


def _local_flat_rows(value: Any, spec: LeafSpec):
    """(rows, flat 1-D payload) of this rank's contribution to a cat leaf."""
    from metrics_tpu.core.cat_buffer import CatBuffer

    if isinstance(value, CatBuffer):
        rows = int(np.asarray(value.count))
        return rows, value.values().reshape(-1)
    if isinstance(value, (list, tuple)):
        cat = jnp.concatenate([jnp.asarray(x)[None] if jnp.asarray(x).ndim == 0 else jnp.asarray(x) for x in value], axis=0)
        return int(cat.shape[0]), cat.reshape(-1)
    arr = jnp.asarray(value)
    if arr.ndim == 0:
        arr = arr[None]
    return int(arr.shape[0]), arr.reshape(-1)


def _assemble_cat(spec: LeafSpec, pieces: List[Any], local_value: Any, world: int) -> Any:
    """Reconstruct one cat-family leaf from its per-rank row blocks —
    byte-identical to what ``host_sync_leaf`` builds from its own gather."""
    from metrics_tpu.core.cat_buffer import CatBuffer

    if spec.kind == "catbuf":
        merged = CatBuffer(world * local_value.capacity)
        for p in pieces:
            merged.append(p)
        return merged
    if spec.kind == "list":
        return list(pieces)
    return jnp.concatenate(pieces, axis=0)


def host_sync_state_bucketed(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    words: Optional[np.ndarray] = None,
    timeout: Optional[float] = None,
    plan: Optional[SyncPlan] = None,
) -> Dict[str, Any]:
    """Fused payload sync of a whole (possibly collection-combined) state.

    Caller contract: the gathered health ``words`` have been *verified*
    (``host_sync_state`` does this) — the plan assumes schema equality,
    non-empty cat states and clean CatBuffers across ranks. Issues exactly
    one ``process_allgather`` per reduce bucket and per cat bucket (plus the
    per-leaf cost of callable-``fx`` fallbacks, and one length-vector gather
    only when the schema outgrows the header's ``CAT_LENGTH_SLOTS``).
    """
    from metrics_tpu.parallel.resilience import effective_world
    from metrics_tpu.parallel.sync import _process_allgather, host_sync_leaf

    world = effective_world()
    if plan is None:
        plan = build_sync_plan(state, reductions)
    out: Dict[str, Any] = {}

    # ---- dynamic input: per-rank row counts for every cat-family leaf ----
    n_cat = len(plan.cat_leaves)
    lengths: Optional[np.ndarray] = None
    if n_cat:
        if words is not None:
            lengths = header_cat_lengths(words, n_cat)
        if lengths is None:
            kinds = {"catbuf": "catbuf", "list": "list"}
            local = np.asarray(
                [cat_row_count(state[s.name], kinds.get(s.kind, "leaf")) for s in plan.cat_leaves],
                np.int32,
            )
            lengths = np.asarray(_process_allgather(jnp.asarray(local), timeout=timeout))
        lengths = np.asarray(lengths, dtype=np.int64)

    # ---- reduce buckets: one collective per (dtype, fx) ------------------
    for (_dtype, fx), specs in plan.reduce_buckets.items():
        flat = jnp.concatenate([jnp.asarray(state[s.name]).reshape(-1) for s in specs])
        if flat.size == 0:
            for s in specs:
                out[s.name] = jnp.asarray(state[s.name])
            continue
        gathered = _process_allgather(flat, timeout=timeout)  # [world, total]
        reduced = _REDUCERS[fx](gathered)
        off = 0
        for s in specs:
            out[s.name] = reduced[off : off + s.item_size].reshape(s.item_shape)
            off += s.item_size

    # ---- cat buckets: one padded ragged collective per dtype -------------
    for _dtype, specs in plan.cat_buckets.items():
        rows = lengths[:, [s.cat_index for s in specs]]  # [world, k]
        elems = rows * np.asarray([s.item_size for s in specs], np.int64)
        totals = elems.sum(axis=1)
        max_total = int(totals.max()) if totals.size else 0
        parts = []
        for s in specs:
            _n_rows, flat = _local_flat_rows(state[s.name], s)
            # plan dtype = the schema hash's dtype rule (first element for
            # lists). A heterogeneous list whose local concat promoted past
            # it is cast back: the cross-rank collective must be well-formed
            # and rank-symmetric, and the schema check only pins the
            # first-element dtype (the per-leaf path has the same blind spot
            # — it would feed dtype-divergent payloads straight into the
            # gather). Homogeneous lists — the supported contract — no-op.
            parts.append(flat if flat.dtype == s.dtype else flat.astype(s.dtype))
        local_flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if max_total == 0:
            # nothing to move anywhere (every rank's rows are empty): skip the
            # collective symmetrically (max_total is identical on all ranks)
            gathered = jnp.zeros((world, 0), local_flat.dtype)
        else:
            padded = jnp.pad(local_flat, (0, max_total - int(local_flat.size)))
            gathered = _process_allgather(padded, timeout=timeout)  # [world, max_total]
        for j, s in enumerate(specs):
            pieces = []
            for r in range(world):
                start = int(elems[r, :j].sum())
                n = int(elems[r, j])
                pieces.append(gathered[r, start : start + n].reshape((int(rows[r, j]),) + s.item_shape))
            out[s.name] = _assemble_cat(s, pieces, state[s.name], world)

    # ---- unplannable leaves: per-leaf path (prechecks already done) ------
    for s in plan.fallback:
        out[s.name] = host_sync_leaf(state[s.name], s.fx, precheck=False, timeout=timeout)

    return out
