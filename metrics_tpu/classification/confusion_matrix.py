"""ConfusionMatrix module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/confusion_matrix.py`` (145 LoC): one [C, C]
(or [C, 2, 2] multilabel) sum state, psum across the mesh.
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.confusion_matrix import (
    _confusion_matrix_compute,
    _confusion_matrix_update,
)


class ConfusionMatrix(Metric):
    """The ``[C, C]`` count matrix — rows are true classes, columns
    predicted classes (reference ``confusion_matrix.py``); with
    ``multilabel=True`` a per-label ``[C, 2, 2]`` stack instead.

    The running state is the matrix itself (a "sum" leaf — one ``psum``
    across the mesh), filled per batch with a one-hot scatter-add, so
    memory is constant in the number of samples.

    Args:
        num_classes: number of classes ``C`` (mandatory — sets the static
            state shape).
        normalize: divide counts at compute: ``"true"`` by row sums (each
            row shows where that class's samples went), ``"pred"`` by
            column sums, ``"all"`` by the grand total; ``None`` keeps raw
            counts.
        threshold: binarization cut for probabilistic binary/multilabel
            input.
        multilabel: treat input as independent per-label binary decisions
            and return one 2×2 matrix per label.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Raises:
        ValueError: unknown ``normalize`` option.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ConfusionMatrix
        >>> preds = jnp.asarray([1, 0, 1, 1])
        >>> target = jnp.asarray([1, 0, 0, 1])
        >>> confmat = ConfusionMatrix(num_classes=2)
        >>> print(confmat(preds, target).tolist())
        [[1, 1], [0, 2]]
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: int,
        normalize: Optional[str] = None,
        threshold: float = 0.5,
        multilabel: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.normalize = normalize
        self.threshold = threshold
        self.multilabel = multilabel

        allowed_normalize = ("true", "pred", "all", "none", None)
        if normalize not in allowed_normalize:
            raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")

        default = (
            jnp.zeros((num_classes, 2, 2), dtype=jnp.int32)
            if multilabel
            else jnp.zeros((num_classes, num_classes), dtype=jnp.int32)
        )
        self.add_state("confmat", default=default, dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        confmat = _confusion_matrix_update(
            preds, target, self.num_classes, self.threshold, self.multilabel
        )
        self.confmat = self.confmat + confmat

    def compute(self) -> Array:
        return _confusion_matrix_compute(self.confmat, self.normalize)
