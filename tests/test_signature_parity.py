"""Constructor-signature parity gate vs the reference.

The export AST diff guarantees NAME parity; this gate guarantees every
constructor parameter of a same-named reference class exists on ours too
(the round-3 sweep caught three: ROUGEScore newline_sep/decimal_places,
WER concatenate_texts, BERTScore baseline_url). Statically parses the
reference tree — it cannot be imported here (needs pkg_resources) — and
skips when the reference checkout is absent so the repo stands alone.
"""
import ast
import inspect
import pathlib

import pytest

REF = pathlib.Path("/root/reference/torchmetrics")

# our-side params that intentionally replace (not miss) reference params
_EQUIVALENT = {
    # reference FID(feature=int) — ours additionally accepts a callable and
    # splits the declaration; keep any such mappings here with a reason
}


@pytest.mark.skipif(not REF.exists(), reason="reference checkout not present")
def test_every_reference_constructor_param_exists():
    import metrics_tpu as ours

    ref_sigs = {}
    for p in REF.rglob("*.py"):
        try:
            tree = ast.parse(p.read_text())
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) and item.name == "__init__":
                        params = [a.arg for a in item.args.args if a.arg != "self"]
                        params += [a.arg for a in item.args.kwonlyargs]
                        ref_sigs.setdefault(node.name, set()).update(params)

    problems = []
    checked = 0
    for name in dir(ours):
        cls = getattr(ours, name)
        if not inspect.isclass(cls) or name not in ref_sigs:
            continue
        checked += 1
        mine = set(inspect.signature(cls.__init__).parameters) - {"self", "kwargs", "args"}
        missing = ref_sigs[name] - mine - {"kwargs", "args"} - _EQUIVALENT.get(name, set())
        if missing:
            problems.append(f"{name} lacks reference params {sorted(missing)}")
    assert checked >= 50, f"sweep degenerated: only {checked} classes compared"
    assert not problems, "\n".join(problems)


@pytest.mark.skipif(not REF.exists(), reason="reference checkout not present")
def test_every_reference_functional_param_exists():
    import metrics_tpu.functional as ours

    ref_sigs = {}
    for p in (REF / "functional").rglob("*.py"):
        try:
            tree = ast.parse(p.read_text())
        except SyntaxError:
            continue
        for node in tree.body:  # public top-level functions only
            if isinstance(node, ast.FunctionDef) and not node.name.startswith("_"):
                params = [a.arg for a in node.args.args]
                params += [a.arg for a in node.args.kwonlyargs]
                ref_sigs.setdefault(node.name, set()).update(params)

    problems = []
    checked = 0
    for name in dir(ours):
        fn = getattr(ours, name)
        if not callable(fn) or inspect.isclass(fn) or name not in ref_sigs:
            continue
        try:
            mine = set(inspect.signature(fn).parameters)
        except (ValueError, TypeError):
            continue
        checked += 1
        missing = ref_sigs[name] - mine - {"kwargs", "args"}
        if missing:
            problems.append(f"{name} lacks reference params {sorted(missing)}")
    assert checked >= 50, f"sweep degenerated: only {checked} functions compared"
    assert not problems, "\n".join(problems)


@pytest.mark.skipif(not REF.exists(), reason="reference checkout not present")
def test_every_reference_public_method_exists():
    import metrics_tpu as ours

    ref_methods = {}
    for p in REF.rglob("*.py"):
        try:
            tree = ast.parse(p.read_text())
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) and not item.name.startswith("_"):
                        ref_methods.setdefault(node.name, set()).add(item.name)

    problems = []
    checked = 0
    for name in dir(ours):
        cls = getattr(ours, name)
        if not inspect.isclass(cls) or name not in ref_methods:
            continue
        checked += 1
        missing = ref_methods[name] - set(dir(cls))
        if missing:
            problems.append(f"{name} lacks reference methods {sorted(missing)}")
    assert checked >= 50, f"sweep degenerated: only {checked} classes compared"
    assert not problems, "\n".join(problems)
