"""Specificity — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/specificity.py:23-215``.
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _reduce_stat_scores,
    _stat_scores_update,
)
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod


def _specificity_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, average: str, mdmc_average: Optional[str]
) -> Array:
    numerator = tn
    denominator = tn + fp
    if average in (AverageMethod.NONE, None) and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        absent = (tp | fn | fp) == 0
        numerator = jnp.where(absent, -1, numerator)
        denominator = jnp.where(absent, -1, denominator)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else denominator,
        average=average,
        mdmc_average=mdmc_average,
    )


def specificity(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    r"""Specificity :math:`\frac{TN}{TN + FP}` in one stateless call — the
    true-negative rate (reference ``specificity.py:70-215``). Functional
    twin of :class:`~metrics_tpu.Specificity`.

    Args:
        preds: predictions — labels, probabilities, or logits in any
            supported classification shape (``[N]``, ``[N, C]``,
            ``[N, C, X]``).
        target: ground-truth labels of the matching shape.
        average: ``"micro"`` pools all decisions; ``"macro"`` /
            ``"weighted"`` / ``"samples"`` / ``"none"``/``None`` as
            documented on :class:`~metrics_tpu.Precision`.
        mdmc_average: multidim policy (``"global"``/``"samplewise"``/
            ``None``).
        ignore_index: class label excluded from every counter.
        num_classes: class count; required for per-class averages.
        threshold: binarization cut for probabilistic input.
        top_k: count top-k multiclass hits instead of argmax only.
        multiclass: force/forbid multiclass interpretation.

    Returns:
        A scalar, or ``[C]`` for per-class averages / ``[N]`` for
        samplewise reduction.

    Raises:
        ValueError: invalid ``average``/``mdmc_average`` combination,
            per-class average without ``num_classes``, or out-of-range
            ``ignore_index``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import specificity
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> print(round(float(specificity(preds, target, average="micro")), 4))
        0.75
    """
    allowed_average = ["micro", "macro", "weighted", "samples", "none", None]
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = [None, "samplewise", "global"]
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ["macro", "weighted", "none", None] and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    reduce = "macro" if average in ["weighted", "none", None] else average
    tp, fp, tn, fn = _stat_scores_update(
        preds, target, reduce=reduce, mdmc_reduce=mdmc_average, threshold=threshold,
        num_classes=num_classes, top_k=top_k, multiclass=multiclass, ignore_index=ignore_index,
    )
    return _specificity_compute(tp, fp, tn, fn, average, mdmc_average)
