"""AverageMeter / BootStrapper / MetricTracker tests."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, AverageMeter, BootStrapper, MeanSquaredError, MetricTracker
from tests.helpers.testers import DummyMetricSum


def test_average_meter_simple():
    avg = AverageMeter()
    avg.update(3)
    avg.update(1)
    np.testing.assert_allclose(np.asarray(avg.compute()), 2.0)


def test_average_meter_weighted():
    avg = AverageMeter()
    v = avg(jnp.asarray([1.0, 2.0]), jnp.asarray([3.0, 1.0]))
    np.testing.assert_allclose(np.asarray(v), 1.25)


def test_average_meter_vector():
    avg = AverageMeter()
    v = avg(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(v), 2.0)


def test_bootstrapper_accuracy():
    rng = np.random.RandomState(123)
    boot = BootStrapper(Accuracy(num_classes=5), num_bootstraps=20, seed=1)
    boot.update(jnp.asarray(rng.randint(0, 5, (200,))), jnp.asarray(rng.randint(0, 5, (200,))))
    out = boot.compute()
    assert set(out.keys()) == {"mean", "std"}
    # random preds vs random targets -> accuracy ~ 0.2
    assert abs(float(out["mean"]) - 0.2) < 0.1
    assert float(out["std"]) > 0


def test_bootstrapper_quantile_raw():
    rng = np.random.RandomState(5)
    boot = BootStrapper(
        Accuracy(num_classes=5), num_bootstraps=10, quantile=0.5, raw=True, sampling_strategy="multinomial"
    )
    boot.update(jnp.asarray(rng.randint(0, 5, (100,))), jnp.asarray(rng.randint(0, 5, (100,))))
    out = boot.compute()
    assert "quantile" in out and "raw" in out
    assert out["raw"].shape == (10,)


def test_bootstrapper_invalid():
    with pytest.raises(ValueError, match="Expected base metric"):
        BootStrapper("not-a-metric")
    with pytest.raises(ValueError, match="sampling_strategy"):
        BootStrapper(Accuracy(), sampling_strategy="bogus")


def test_tracker_lifecycle():
    tracker = MetricTracker(DummyMetricSum(), maximize=True)
    with pytest.raises(ValueError, match="cannot be called before"):
        tracker.update(jnp.asarray(1.0))
    vals = [1.0, 5.0, 3.0]
    for v in vals:
        tracker.increment()
        tracker.update(jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(tracker.compute_all()), vals)
    best, step = tracker.best_metric(return_step=True)
    assert best == 5.0 and step == 1
    assert tracker.n_steps == 3


def test_tracker_minimize():
    tracker = MetricTracker(DummyMetricSum(), maximize=False)
    for v in [3.0, 1.0, 2.0]:
        tracker.increment()
        tracker.update(jnp.asarray(v))
    assert tracker.best_metric() == 1.0


@pytest.mark.parametrize("sampling_strategy", ["poisson", "multinomial"])
def test_bootstrap_sampler_resamples_with_replacement(sampling_strategy):
    """Analogue of reference ``test_bootstrapping.py::test_bootstrap_sampler``:
    sampled indices draw only from the original rows, some row repeats, and
    some row is left out (sampling WITH replacement)."""
    import jax

    from metrics_tpu.wrappers.bootstrapping import _bootstrap_sampler

    key = jax.random.PRNGKey(7)
    idx = np.asarray(_bootstrap_sampler(key, 20, sampling_strategy=sampling_strategy))
    assert idx.min() >= 0 and idx.max() < 20
    counts = np.bincount(idx, minlength=20)
    assert counts.max() >= 2, "no row sampled twice — not with-replacement"
    assert (counts == 0).any(), "every row sampled — not a bootstrap draw"


@pytest.mark.parametrize(
    "metric_ctor, data",
    [
        # classification row compiles 20 bootstrap copies of a 4-class
        # metric (~80 s on the CI host) — nightly; the regression row keeps
        # the mean-tracking property in CI
        pytest.param(lambda: Accuracy(num_classes=4), "cls", marks=pytest.mark.nightly),
        (lambda: MeanSquaredError(), "reg"),
    ],
)
def test_bootstrap_mean_tracks_full_data_value(metric_ctor, data):
    """Reference ``test_bootstrap``: the bootstrapped mean sits near the
    full-data metric value, and std is small but nonzero."""
    rng = np.random.RandomState(42)
    if data == "cls":
        a = jnp.asarray(rng.randint(0, 4, (400,)))
        b = jnp.asarray(rng.randint(0, 4, (400,)))
    else:
        a = jnp.asarray(rng.randn(400).astype(np.float32))
        b = jnp.asarray(rng.randn(400).astype(np.float32))
    base = metric_ctor()
    base.update(a, b)
    full = float(base.compute())

    boot = BootStrapper(metric_ctor(), num_bootstraps=50, seed=3)
    boot.update(a, b)
    out = boot.compute()
    assert abs(float(out["mean"]) - full) < 0.15 * max(abs(full), 0.1)
    assert 0 < float(out["std"]) < max(abs(full), 0.5)


def test_tracker_wrong_input_raises():
    with pytest.raises(TypeError, match="instance of a metrics_tpu metric"):
        MetricTracker([1, 2, 3])


@pytest.mark.parametrize(
    "method, args",
    [("update", (jnp.asarray(1.0),)), ("forward", (jnp.asarray(1.0),)), ("compute", ())],
)
def test_tracker_all_methods_require_increment(method, args):
    tracker = MetricTracker(DummyMetricSum())
    with pytest.raises(ValueError, match=f"`{method}` cannot be called before"):
        getattr(tracker, method)(*args)


def test_tracker_update_and_forward_interleaved():
    """Reference ``test_tracker``: both update() and forward() accumulate into
    the current step's clone."""
    tracker = MetricTracker(MeanSquaredError(), maximize=False)
    rng = np.random.RandomState(1)
    for i in range(3):
        tracker.increment()
        for _ in range(2):
            tracker.update(jnp.asarray(rng.randn(20)), jnp.asarray(rng.randn(20)))
        for _ in range(2):
            tracker(jnp.asarray(rng.randn(20)), jnp.asarray(rng.randn(20)))
        assert float(tracker.compute()) > 0
        assert tracker.n_steps == i + 1
    assert np.asarray(tracker.compute_all()).shape[0] == 3
    best, idx = tracker.best_metric(return_step=True)
    assert best > 0 and idx in range(3)
