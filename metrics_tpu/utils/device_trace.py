"""Device-side execution timing from ``jax.profiler`` traces.

Wall clocks over a remote-TPU tunnel measure dispatch + network jitter as
much as compute (BENCH.md's drift taxonomy); the profiler's chrome trace,
by contrast, records every XLA program execution **on the device timeline**
with sub-microsecond resolution. :class:`DeviceTrace` captures a trace and
returns per-program device durations, so a per-step number excludes host
dispatch and tunnel drift *entirely* — the round-5 methodology of record
for BENCH configs 1/2/3/7.

The parser reads the ``*.trace.json.gz`` chrome trace jax writes (pure
gzip+json — no tensorflow/tensorboard dependency): complete events
(``ph=="X"``) on pids whose ``process_name`` metadata starts with
``/device:`` are device-side; a compiled program appears there as one
top-level event named ``jit_<fn_name>(<fingerprint>)`` per execution, with
``dur`` in microseconds (its fusions appear as separate nested events and
are NOT double-counted — matching is by program name).

The reference has no analogue (its only telemetry is a usage-logging call,
reference ``metric.py:84``); this is part of the TPU build's
tracing/profiling subsystem (SURVEY §5).
"""
import glob
import gzip
import json
import os
import shutil
import tempfile
from typing import Callable, Dict, List, Mapping, Optional, Tuple

__all__ = ["DeviceTrace", "parse_device_events", "measure_device_time_us"]


def parse_device_events(trace_dir: str) -> Dict[str, List[float]]:
    """Parse every ``*.trace.json.gz`` under ``trace_dir``.

    Returns ``{event_name: [duration_us, ...]}`` for complete events on
    device pids only (process name ``/device:*``), durations sorted
    chronologically by the events' ``ts`` timestamps (ADVICE round 5: raw
    ``traceEvents`` order is a serialization artifact, not execution order,
    so positional pairing of two programs' k-th executions was unsound).
    """
    paths = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True)
    )
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {trace_dir}")
    # (ts, dur) pairs per event name, sorted by ts once all files are read
    acc: Dict[str, List[Tuple[float, float]]] = {}
    for path in paths:
        with gzip.open(path, "rt") as fh:
            data = json.load(fh)
        events = data.get("traceEvents", [])
        device_pids = {
            e["pid"]
            for e in events
            if e.get("ph") == "M"
            and e.get("name") == "process_name"
            and str(e.get("args", {}).get("name", "")).startswith("/device:")
        }
        for e in events:
            if e.get("ph") == "X" and e.get("pid") in device_pids:
                acc.setdefault(e["name"], []).append(
                    (float(e.get("ts", 0.0)), float(e.get("dur", 0.0)))
                )
    return {name: [dur for _, dur in sorted(pairs)] for name, pairs in acc.items()}


def _program_durations(events: Dict[str, List[float]], program: str) -> List[float]:
    """Durations of the top-level device event for jitted fn ``program``.

    Matches ``jit_<program>`` exactly or with a ``(<fingerprint>)`` suffix —
    never the program's nested fusion events.
    """
    exact = f"jit_{program}"
    hits: List[float] = []
    for name, durs in events.items():
        if name == exact or name.startswith(exact + "("):
            hits.extend(durs)
    return hits


class DeviceTrace:
    """Context manager capturing a jax.profiler trace into a temp dir.

    Usage::

        with DeviceTrace() as dt:
            run_base(state)   # jitted fns, already warmed
            run_full(state)
        base_us = dt.program_times_us("run_base")   # one entry per execution

    ``keep_dir=True`` preserves the raw trace directory (``dt.trace_dir``)
    for offline inspection; otherwise it is deleted on exit after parsing.
    """

    def __init__(self, keep_dir: bool = False):
        self._keep = keep_dir
        self.trace_dir: Optional[str] = None
        self._events: Optional[Dict[str, List[float]]] = None

    def __enter__(self) -> "DeviceTrace":
        import jax

        self.trace_dir = tempfile.mkdtemp(prefix="metrics_tpu_trace_")
        jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        import jax

        try:
            jax.profiler.stop_trace()
            if exc_type is None:
                self._events = parse_device_events(self.trace_dir)
        finally:
            if not self._keep and self.trace_dir:
                shutil.rmtree(self.trace_dir, ignore_errors=True)

    @property
    def events(self) -> Dict[str, List[float]]:
        if self._events is None:
            raise RuntimeError("trace not finished — use within/after the `with` block")
        return self._events

    def program_times_us(self, program: str) -> List[float]:
        """Per-execution device durations (µs) for jitted fn ``program``."""
        return _program_durations(self.events, program)


def measure_device_time_us(
    programs: Mapping[str, Callable[[], object]],
    execs: int = 4,
) -> Dict[str, Tuple[float, List[float]]]:
    """Run each (warmed, jitted) thunk ``execs`` times under ONE trace.

    Thunks rotate round-robin so chip-state drift within the window hits
    every program alike (the pairing idea from the wall-clock methodology,
    BENCH.md r4). The key of ``programs`` must be the jitted function's
    ``__name__`` — that is how its device events are found. Returns
    ``{name: (median_us, all_durations_us)}`` per device execution.

    Raises RuntimeError when a program produced no device events (e.g. a
    CPU backend, which has no device timeline), or when the event count
    disagrees with ``execs`` — one top-level device event per execution is
    the matching contract, and a mismatch means the name matched extra
    events (a colliding program name, multi-device duplication) or the
    trace dropped executions; truncating to ``min(...)`` would silently
    pair the wrong executions (ADVICE round 5). Callers fall back to
    wall-clock slope timing.
    """
    import jax
    import numpy as np

    with DeviceTrace() as dt:
        for _ in range(execs):
            for thunk in programs.values():
                jax.block_until_ready(thunk())
    out: Dict[str, Tuple[float, List[float]]] = {}
    for name in programs:
        durs = dt.program_times_us(name)
        if not durs:
            raise RuntimeError(
                f"no device-timeline events for program {name!r} "
                f"(device events seen: {sorted(dt.events)[:12]})"
            )
        if len(durs) != execs:
            raise RuntimeError(
                f"program {name!r} recorded {len(durs)} device executions, "
                f"expected {execs}: the per-execution pairing is unsound "
                "(name collision, multi-device duplication, or dropped trace "
                "events) — refusing to truncate"
            )
        out[name] = (float(np.median(durs)), durs)
    return out
