"""BLEU parity against nltk's corpus_bleu — the reference's own oracle.

Mirror of `tests/text/test_blue.py`: the nltk documentation corpora through
n_gram ∈ {1..4} × smoothing, functional and class (accumulation + ddp-style
merge), checked against ``nltk.translate.bleu_score.corpus_bleu``.
"""
from functools import partial

import numpy as np
import pytest

nltk = pytest.importorskip("nltk", reason="nltk provides the BLEU oracle (reference test_blue.py does the same)")
from nltk.translate.bleu_score import SmoothingFunction, corpus_bleu  # noqa: E402

from metrics_tpu import BLEUScore
from metrics_tpu.functional import bleu_score

HYPOTHESIS_A = tuple(
    "It is a guide to action which ensures that the military always obeys the commands of the party".split()
)
REFERENCE_1A = tuple("It is a guide to action that ensures that the military will forever heed Party commands".split())
REFERENCE_2A = tuple(
    "It is a guiding principle which makes the military forces always being under the command of the Party".split()
)
REFERENCE_3A = tuple("It is the practical guide for the army always to heed the directions of the party".split())

HYPOTHESIS_B = tuple("he read the book because he was interested in world history".split())
REFERENCE_1B = tuple("he was interested in world history because he read the book".split())

HYPOTHESIS_C = tuple("the cat the cat on the mat".split())
REFERENCE_1C = tuple("the cat is on the mat".split())
REFERENCE_2C = tuple("there is a cat on the mat".split())

# two "batches" of (references, hypotheses) like the reference's BATCHES dict
_TARGETS = [
    [[REFERENCE_1A, REFERENCE_2A, REFERENCE_3A], [REFERENCE_1B]],
    [[REFERENCE_1B], [REFERENCE_1C, REFERENCE_2C]],
]
_PREDS = [
    [HYPOTHESIS_A, HYPOTHESIS_B],
    [HYPOTHESIS_B, HYPOTHESIS_C],
]

_smooth2 = SmoothingFunction().method2  # add-one for orders > 1 == our smooth=True


@pytest.mark.parametrize(
    "weights, n_gram, smooth_func, smooth",
    [
        ([1.0], 1, None, False),
        ([0.5, 0.5], 2, _smooth2, True),
        ([1 / 3] * 3, 3, None, False),
        ([0.25] * 4, 4, _smooth2, True),
    ],
    ids=["1gram", "2gram_smooth", "3gram", "4gram_smooth"],
)
class TestBLEUvsNLTK:
    def test_functional_corpus(self, weights, n_gram, smooth_func, smooth):
        """Whole corpus in one call vs corpus_bleu."""
        all_refs = [r for batch in _TARGETS for r in batch]
        all_hyps = [h for batch in _PREDS for h in batch]
        expected = corpus_bleu(all_refs, all_hyps, weights=weights, smoothing_function=smooth_func)
        ours = float(bleu_score(all_refs, all_hyps, n_gram=n_gram, smooth=smooth))
        np.testing.assert_allclose(ours, expected, atol=1e-6)

    @pytest.mark.parametrize("world", [1, 2])
    def test_class_accumulation_matches_corpus(self, weights, n_gram, smooth_func, smooth, world):
        """Batch-wise update (one metric per simulated rank, states merged)
        equals corpus_bleu over everything at once."""
        metrics = [BLEUScore(n_gram=n_gram, smooth=smooth) for _ in range(world)]
        for i, (refs, hyps) in enumerate(zip(_TARGETS, _PREDS)):
            metrics[i % world].update(refs, hyps)
        merged = metrics[0]
        for other in metrics[1:]:
            merged.merge_state(other)
        all_refs = [r for batch in _TARGETS for r in batch]
        all_hyps = [h for batch in _PREDS for h in batch]
        expected = corpus_bleu(all_refs, all_hyps, weights=weights, smoothing_function=smooth_func)
        np.testing.assert_allclose(float(merged.compute()), expected, atol=1e-6)


def test_nltk_example_sentence_level_zero_overlap():
    """Degenerate candidate with no 4-gram overlap: both nltk (unsmoothed)
    and ours go to 0."""
    refs = [[REFERENCE_1C, REFERENCE_2C]]
    hyps = [tuple("completely unrelated words here now".split())]
    expected = corpus_bleu(refs, hyps, weights=[0.25] * 4)
    ours = float(bleu_score(refs, hyps, n_gram=4, smooth=False))
    np.testing.assert_allclose(ours, expected, atol=1e-6)
