"""Chrome-trace exporter: structural validity, the background sync lane,
and cross-rank sync_epoch correlation."""
import json

import jax.numpy as jnp
import numpy as np

from metrics_tpu.observability import journal
from metrics_tpu.observability.trace_export import (
    STEP_LANE,
    SYNC_LANE,
    chrome_trace,
    export_chrome_trace,
)


def _ev(ts, rank, kind, label="m", step=-1, **fields):
    return journal.Event(ts, rank, step, kind, label, fields)


def test_empty_journal_exports_valid_trace():
    trace = chrome_trace([])
    assert trace == {"traceEvents": [], "displayTimeUnit": "ms"}
    json.dumps(trace)


def test_compiled_dispatches_become_duration_events():
    evs = [
        _ev(10.000, 0, "compiled.dispatch", "Sum", step=1, op="update", dur_s=0.002),
        _ev(10.010, 0, "compiled.trace", "Sum", step=1, op="update", traces=1),
    ]
    trace = chrome_trace(evs)
    xs = [t for t in trace["traceEvents"] if t["ph"] == "X"]
    assert len(xs) == 1
    assert xs[0]["tid"] == STEP_LANE and xs[0]["pid"] == 0
    assert abs(xs[0]["dur"] - 2000.0) < 1e-6  # 2 ms in µs
    assert any(t["ph"] == "i" and "compiled.trace" in t["name"]
               for t in trace["traceEvents"])


def test_overlapped_round_renders_background_lane_with_epoch():
    """The acceptance look: the background gather is its OWN track (tid 1),
    overlapping the step lane, correlated across ranks by sync_epoch."""
    evs = []
    for rank in (0, 1):
        evs.append(_ev(100.0, rank, "sync.launch", "Sum", sync_epoch=3,
                       update_count=5))
        # step keeps running 100.0..100.1 while the gather rides behind it
        evs.append(_ev(100.002 + rank * 1e-4, rank, "compiled.dispatch", "Sum",
                       op="update", dur_s=0.001))
        evs.append(_ev(100.100, rank, "sync.resolve", "Sum", sync_epoch=3,
                       stale=False, policy="snapshot", verdict="fresh",
                       wait_s=0.0001, gather_s=0.05, gather_start=100.001))
    trace = chrome_trace(evs)
    gathers = [t for t in trace["traceEvents"]
               if t["ph"] == "X" and t["tid"] == SYNC_LANE]
    assert len(gathers) == 2  # one background span per rank
    assert {t["pid"] for t in gathers} == {0, 1}
    for g in gathers:
        assert g["args"]["sync_epoch"] == 3
        assert abs(g["dur"] - 50_000.0) < 1e-3  # 50 ms gather in µs
    # the background span OVERLAPS the step lane's dispatch span in time
    steps = [t for t in trace["traceEvents"]
             if t["ph"] == "X" and t["tid"] == STEP_LANE and t["pid"] == 0]
    g0 = next(t for t in gathers if t["pid"] == 0)
    s0 = steps[0]
    assert g0["ts"] < s0["ts"] + s0["dur"] and s0["ts"] < g0["ts"] + g0["dur"]
    # cross-rank correlation: identical epoch args on both ranks' rounds
    resolves = [t for t in trace["traceEvents"]
                if t["ph"] == "X" and "resolve" in t["name"]]
    assert {t["args"]["sync_epoch"] for t in resolves} == {3}
    # flow events tie launch -> resolve per epoch
    assert any(t["ph"] == "s" and t["id"] == 3 for t in trace["traceEvents"])
    assert any(t["ph"] == "f" and t["id"] == 3 for t in trace["traceEvents"])


def test_lane_metadata_present_per_rank():
    trace = chrome_trace([_ev(1.0, 2, "health.watchdog", "")])
    names = {(t["pid"], t.get("args", {}).get("name"))
             for t in trace["traceEvents"] if t["ph"] == "M"}
    assert (2, "rank 2") in names
    assert (2, "step") in names and (2, "sync-background") in names


def test_export_writes_loadable_json(tmp_path):
    journal.enable()
    journal.record("sync.launch", label="m", sync_epoch=1)
    journal.record("health.watchdog", label="process_allgather", timeout_s=5)
    path = tmp_path / "trace.json"
    trace = export_chrome_trace(str(path))
    loaded = json.loads(path.read_text())
    assert loaded["traceEvents"] == json.loads(json.dumps(trace))["traceEvents"]
    assert len(loaded["traceEvents"]) >= 2
    for t in loaded["traceEvents"]:
        assert "ph" in t and "pid" in t and "ts" in t or t["ph"] == "M"


def test_real_compiled_loop_exports(tmp_path):
    from metrics_tpu.core.metric import Metric

    class _Sum(Metric):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

        def update(self, x):
            self.total = self.total + jnp.sum(x)

        def compute(self):
            return self.total

    journal.enable()
    m = _Sum(compiled_update=True)
    for _ in range(3):
        m.update(jnp.asarray(np.ones((4,), np.float32)))
    trace = export_chrome_trace(str(tmp_path / "t.json"))
    dispatches = [t for t in trace["traceEvents"]
                  if t["ph"] == "X" and t["name"] == "dispatch _Sum"]
    assert len(dispatches) == 3
    assert all(t["ts"] >= 0 for t in trace["traceEvents"] if t["ph"] != "M")
