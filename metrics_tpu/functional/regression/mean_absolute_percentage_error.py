"""MAPE — analogue of reference
``torchmetrics/functional/regression/mean_absolute_percentage_error.py``."""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target), epsilon, None)
    return jnp.sum(abs_per_error), target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_absolute_percentage_error
        >>> print(round(float(mean_absolute_percentage_error(jnp.asarray([9.0, 19.0]), jnp.asarray([10.0, 20.0]))), 4))
        0.075
    """
    sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
