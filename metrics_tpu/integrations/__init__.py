"""Framework integrations.

The reference integrates with PyTorch Lightning implicitly — ``Metric`` is an
``nn.Module`` so Lightning's module system picks metrics up, logs metric
objects lazily, and resets them at epoch end
(reference ``integrations/test_lightning.py``, ``docs/source/pages/lightning.rst``).

The TPU-native analogue is explicit and functional: metric state is a pytree
carried inside the flax ``TrainState``, updated inside the jitted train step
(one fused XLA program with the model forward/backward), with Lightning-style
deferred logging + epoch-end auto-reset provided by :class:`MetricLogger`.
"""
from metrics_tpu.integrations.flax import MetricLogger, MetricTrainState

__all__ = ["MetricLogger", "MetricTrainState"]
