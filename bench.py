"""Benchmark: fused metric-step throughput on the available accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config 1 of BASELINE.md: Accuracy (10-class) + StatScores in a MetricCollection.
The baseline proxy is a faithful torch-CPU implementation of the same
accumulation (the reference publishes no numbers — BASELINE.md), timed in-process.
"""
import json
import time

import numpy as np

BATCH = 2048
NUM_CLASSES = 10
STEPS = 50


def bench_ours() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MetricCollection, StatScores

    mc = MetricCollection(
        {"acc": Accuracy(num_classes=NUM_CLASSES), "stats": StatScores(reduce="macro", num_classes=NUM_CLASSES)}
    )
    rng = np.random.RandomState(0)
    preds = jnp.asarray(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = jnp.asarray(rng.randint(0, NUM_CLASSES, (BATCH,)))

    # donate the state pytree: accumulators update in place in HBM
    step = jax.jit(mc.pure_update, donate_argnums=(0,))

    state = mc.init_state()
    state = step(state, preds, target)  # compile
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state = step(state, preds, target)
    jax.block_until_ready(state)
    dt = (time.perf_counter() - t0) / STEPS
    # sanity: value must be finite
    vals = mc.pure_compute(state)
    assert np.isfinite(float(np.asarray(vals["acc"]))), "bench produced non-finite metric"
    return dt


def bench_torch_baseline() -> float:
    """Reference-style accumulation in torch (CPU), same math, same shapes."""
    import torch

    rng = np.random.RandomState(0)
    preds = torch.from_numpy(rng.rand(BATCH, NUM_CLASSES).astype(np.float32))
    target = torch.from_numpy(rng.randint(0, NUM_CLASSES, (BATCH,)))

    def step(tp, fp, tn, fn, correct, total):
        p1 = preds.argmax(1)
        oh_p = torch.nn.functional.one_hot(p1, NUM_CLASSES)
        oh_t = torch.nn.functional.one_hot(target, NUM_CLASSES)
        true_pred = oh_t == oh_p
        pos_pred = oh_p == 1
        tp = tp + (true_pred & pos_pred).sum(0)
        fp = fp + (~true_pred & pos_pred).sum(0)
        tn = tn + (true_pred & ~pos_pred).sum(0)
        fn = fn + (~true_pred & ~pos_pred).sum(0)
        correct = correct + (p1 == target).sum()
        total = total + target.numel()
        return tp, fp, tn, fn, correct, total

    z = torch.zeros(NUM_CLASSES, dtype=torch.long)
    st = (z, z.clone(), z.clone(), z.clone(), torch.zeros((), dtype=torch.long), 0)
    st = step(*st)  # warm
    t0 = time.perf_counter()
    for _ in range(STEPS):
        st = step(*st)
    return (time.perf_counter() - t0) / STEPS


def main() -> None:
    ours = bench_ours()
    try:
        base = bench_torch_baseline()
        vs = base / ours
    except Exception:
        vs = None
    print(
        json.dumps(
            {
                "metric": "fused_metric_step_time",
                "value": round(ours * 1e6, 2),
                "unit": "us/step",
                "vs_baseline": round(vs, 3) if vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
