"""metricslint fixture: every undeclared-state mutation variant.

Never imported by tests — the checker is pure AST — but kept import-safe.
The CI gate asserts the CLI exits NONZERO on this file.
"""
import jax.numpy as jnp


class PlainAssignLatch:
    """update assigns an attribute no add_state declares."""

    def __init__(self):
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def add_state(self, *a, **k):  # stand-in so the file imports standalone
        pass

    def update(self, x):
        self.seen = True  # finding: undeclared-state
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class InPlaceContainerLatch:
    """update mutates an undeclared container in place (append / [k]=)."""

    def __init__(self):
        self.add_state("rows", [], dist_reduce_fx="cat")
        self.shapes = []
        self.by_kind = {}

    def add_state(self, *a, **k):
        pass

    def update(self, x):
        self.shapes.append(x.shape)  # finding: undeclared-state (in place)
        self.by_kind["n"] = 1  # finding: undeclared-state (in place)
        self.rows.append(x)  # clean: declared cat state

    def compute(self):
        return self.rows


class AugAssignLatch:
    """augmented assignment on an undeclared attribute."""

    def __init__(self):
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.calls = 0

    def add_state(self, *a, **k):
        pass

    def update(self, x):
        self.calls += 1  # finding: undeclared-state
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class HelperWriterLatch:
    """the write hides one self-method call away from update."""

    def __init__(self):
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def add_state(self, *a, **k):
        pass

    def _note(self, x):
        self.last_batch = x  # finding: undeclared-state (via helper)

    def update(self, x):
        self._note(x)
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


class ComputeWriterLatch:
    """compute() caches into an undeclared attribute."""

    def __init__(self):
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def add_state(self, *a, **k):
        pass

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        self.cached = self.total  # finding: undeclared-state
        return self.cached
