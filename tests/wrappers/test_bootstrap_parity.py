"""Per-bootstrap exact parity vs sklearn on captured resamples.

Mirror of the reference's `tests/wrappers/test_bootstrapping.py:86-123`:
subclass BootStrapper to capture the exact resampled inputs each copy
receives, accumulate over batches, then assert each copy's compute equals
sklearn on its own resampled stream, and that mean/std/quantile/raw are the
matching numpy reductions over the per-copy scores.
"""
from functools import partial

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import mean_squared_error, precision_score, recall_score

from metrics_tpu import MeanSquaredError, Precision, Recall
from metrics_tpu.wrappers.bootstrapping import BootStrapper, _bootstrap_sampler
from metrics_tpu.utils.data import apply_to_collection

NUM_BATCHES, BATCH = 10, 32
rng = np.random.RandomState(42)
_preds_cls = rng.randint(0, 10, (NUM_BATCHES, BATCH))
_target_cls = rng.randint(0, 10, (NUM_BATCHES, BATCH))
_preds_reg = rng.rand(NUM_BATCHES, BATCH).astype(np.float32)
_target_reg = rng.rand(NUM_BATCHES, BATCH).astype(np.float32)


class _CapturingBootStrapper(BootStrapper):
    """Capture the resampled args each bootstrap copy receives (reference
    TestBootStrapper, test_bootstrapping.py:35-46)."""

    def update(self, *args):
        import jax

        self.out = []
        size = len(args[0])
        for idx in range(self.num_bootstraps):
            self._key, subkey = jax.random.split(self._key)
            sample_idx = _bootstrap_sampler(subkey, size, sampling_strategy=self.sampling_strategy)
            new_args = apply_to_collection(args, jnp.ndarray, lambda x: jnp.take(x, sample_idx, axis=0))
            self.metrics[idx].update(*new_args)
            self.out.append(new_args)


@pytest.mark.parametrize("sampling_strategy", ["poisson", "multinomial"])
@pytest.mark.parametrize(
    "metric_ctor, sk_metric, preds, target",
    [
        (partial(Precision, num_classes=10, average="micro"),
         partial(precision_score, average="micro"), _preds_cls, _target_cls),
        # recall mirrors precision through the identical wrapper path —
        # nightly keeps it, CI runs precision + mse
        pytest.param(partial(Recall, num_classes=10, average="micro"),
                     partial(recall_score, average="micro"), _preds_cls, _target_cls,
                     marks=pytest.mark.nightly),
        (MeanSquaredError, mean_squared_error, _preds_reg, _target_reg),
    ],
    ids=["precision_micro", "recall_micro", "mse"],
)
def test_bootstrap_per_copy_parity(sampling_strategy, metric_ctor, sk_metric, preds, target):
    boot = _CapturingBootStrapper(
        metric_ctor(), num_bootstraps=5, mean=True, std=True, raw=True,
        quantile=jnp.asarray([0.05, 0.95]), sampling_strategy=sampling_strategy, seed=7,
    )

    collected_p = [[] for _ in range(boot.num_bootstraps)]
    collected_t = [[] for _ in range(boot.num_bootstraps)]
    for p, t in zip(preds, target):
        boot.update(jnp.asarray(p), jnp.asarray(t))
        for i, (rp, rt) in enumerate(boot.out):
            collected_p[i].append(np.asarray(rp))
            collected_t[i].append(np.asarray(rt))

    sk_scores = [
        sk_metric(np.concatenate(ct), np.concatenate(cp))
        for cp, ct in zip(collected_p, collected_t)
    ]

    out = boot.compute()
    np.testing.assert_allclose(np.asarray(out["raw"]), sk_scores, atol=1e-5)
    np.testing.assert_allclose(float(out["mean"]), np.mean(sk_scores), atol=1e-5)
    np.testing.assert_allclose(float(out["std"]), np.std(sk_scores, ddof=1), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(out["quantile"]),
        [np.quantile(sk_scores, 0.05), np.quantile(sk_scores, 0.95)],
        atol=1e-5,
    )
