"""ROUGEScore module — analogue of reference ``torchmetrics/text/rouge.py`` (170 LoC)."""
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.text.rouge import (
    ALLOWED_ROUGE_KEYS,
    _get_stemmer,
    _rouge_score_compute,
    _rouge_score_update,
)


class ROUGEScore(Metric):
    """ROUGE-N / ROUGE-L / ROUGE-Lsum, averaged over accumulated sentences.

    Per-sentence P/R/F scores are cat-states (all-gathered across ranks at
    compute), so the distributed mean matches single-process evaluation.

    Args:
        use_stemmer: Porter-stem tokens >3 chars before matching (built-in
            stemmer — no nltk needed; nltk used when importable).
        rouge_keys: ``rouge1``..``rouge9``, ``rougeL``, ``rougeLsum``.

    Example:
        >>> targets = ["Is your name John"]
        >>> preds = ["My name is John"]
        >>> rouge = ROUGEScore(rouge_keys="rouge1")
        >>> scores = rouge(preds, targets)
        >>> float(scores["rouge1_fmeasure"])
        0.75
    """

    def __init__(
        self,
        newline_sep: Optional[bool] = None,  # deprecated (reference v0.6); remove in v0.7
        use_stemmer: bool = False,
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        decimal_places: Optional[bool] = None,  # deprecated (reference v0.6); remove in v0.7
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        # accepted-but-inert deprecation kwargs, mirroring the reference
        # (`text/rouge.py:84-102`): warn exactly as v0.6 does
        import warnings

        if newline_sep is not None:
            warnings.warn("Argument `newline_sep` is deprecated in v0.6 and will be removed in v0.7")
        if decimal_places is not None:
            warnings.warn("Argument `decimal_places` is deprecated in v0.6 and will be removed in v0.7")
        if not isinstance(rouge_keys, tuple):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(
                    f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}"
                )
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]
        self.stemmer = _get_stemmer() if use_stemmer else None
        for rouge_key in self.rouge_keys:
            for score in ("fmeasure", "precision", "recall"):
                self.add_state(f"{rouge_key}_{score}", [], dist_reduce_fx="cat")

    def update(  # type: ignore[override]
        self, preds: Union[str, List[str]], targets: Union[str, List[str]]
    ) -> None:
        if isinstance(preds, str):
            preds = [preds]
        if isinstance(targets, str):
            targets = [targets]
        output = _rouge_score_update(preds, targets, self.rouge_keys_values, stemmer=self.stemmer)
        for rouge_key, metrics in output.items():
            for metric in metrics:
                for kind, value in metric.items():
                    getattr(self, f"rouge{rouge_key}_{kind}").append(jnp.atleast_1d(value))

    def compute(self) -> Dict[str, Array]:
        update_output: Dict[str, List[Array]] = {}
        for rouge_key in self.rouge_keys_values:
            for kind in ("fmeasure", "precision", "recall"):
                update_output[f"rouge{rouge_key}_{kind}"] = getattr(self, f"rouge{rouge_key}_{kind}")
        return _rouge_score_compute(update_output)
