"""Repo-root pytest config: pin the virtual CPU platform for EVERY pytest
invocation, including ``--doctest-modules metrics_tpu`` where the tests/
conftest is not on the collection path. Without the pin, the preloaded jax
tries the ambient axon TPU plugin (PYTHONPATH site preload), which can hang
collection when the tunnel is unreachable. See tests/conftest.py."""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
