from metrics_tpu.utils.data import (
    METRIC_EPS,
    apply_to_collection,
    dim_zero_cat,
    dim_zero_max,
    dim_zero_mean,
    dim_zero_min,
    dim_zero_sum,
    get_group_indexes,
    select_topk,
    to_categorical,
    to_onehot,
)
from metrics_tpu.utils import compile_cache
from metrics_tpu.utils.enums import AverageMethod, DataType, EnumStr, MDMCAverageMethod
from metrics_tpu.utils.exceptions import MetricsTPUUserError, TorchMetricsUserError
from metrics_tpu.utils.prints import (
    rank_zero_debug,
    rank_zero_info,
    rank_zero_only,
    rank_zero_warn,
)
