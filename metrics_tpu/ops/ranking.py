"""Static-shape, padding-aware ranking statistics.

The reference computes AUROC by building an explicit ROC curve over unique
thresholds (``functional/classification/precision_recall_curve.py:23-61`` →
``roc.py`` → trapezoid), whose intermediate sizes depend on the data — fine
eagerly, impossible under XLA's static shapes.

:func:`masked_binary_auroc` instead uses the Mann–Whitney U statistic with
tie-averaged ranks:

    AUROC = (Σ ranks(positives) − P(P+1)/2) / (P·N)

which is *exactly* the trapezoidal ROC area including tie handling, and every
intermediate has the input's static shape. With the ``mask`` argument, padded
rows (e.g. the unfilled tail of a
:class:`~metrics_tpu.core.cat_buffer.CatBuffer`) are excluded without any
dynamic slicing — so a CatBuffer-mode AUROC's full
``update → all_gather sync → compute`` pipeline traces into ONE jitted XLA
program (the fused-collection design goal, `BASELINE.md` config 2).
"""
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

__all__ = [
    "masked_binary_auroc",
    "masked_binary_average_precision",
    "masked_multiclass_auroc",
    "masked_multiclass_average_precision",
    "masked_multilabel_auroc",
    "tie_averaged_ranks",
]


def _tie_group_ids(v_sorted: Array, valid_sorted: Array) -> Array:
    """Segment ids of tied-value groups along a sorted order.

    A validity change always starts a new group, so equal values never tie
    across the valid/invalid boundary.
    """
    first = jnp.concatenate(
        [
            jnp.ones((1,), bool),
            (v_sorted[1:] != v_sorted[:-1]) | (valid_sorted[1:] != valid_sorted[:-1]),
        ]
    )
    return jnp.cumsum(first) - 1


def tie_averaged_ranks(values: Array, valid: Array) -> Array:
    """1-based tie-averaged ranks of ``values`` among rows where ``valid``.

    Invalid rows receive arbitrary (unused) rank values; callers must weight
    them out. All shapes static; one sort + two segment sums.
    """
    n = values.shape[0]
    # lexicographic sort (valid, value): invalid rows first, then ascending
    # values — no sentinel value, so valid -inf / finfo.min scores stay exact
    order = jnp.lexsort((values, valid.astype(jnp.int32)))
    v_sorted = values[order]
    valid_sorted = valid[order]
    n_invalid = jnp.sum(~valid)
    # position among VALID rows only (invalid occupy the first slots)
    pos = jnp.arange(1, n + 1) - n_invalid
    pos = pos.astype(values.dtype)
    w = valid_sorted.astype(values.dtype)
    gid = _tie_group_ids(v_sorted, valid_sorted)
    sum_pos = jax.ops.segment_sum(pos * w, gid, num_segments=n)
    cnt = jax.ops.segment_sum(w, gid, num_segments=n)
    rank_sorted = (sum_pos / jnp.maximum(cnt, 1.0))[gid]
    # scatter back to original row order
    ranks = jnp.zeros_like(rank_sorted).at[order].set(rank_sorted)
    return ranks


def masked_binary_average_precision(
    preds: Array, target: Array, mask: Optional[Array] = None
) -> Array:
    """Exact binary average precision over the rows where ``mask`` — jittable.

    Step-integral definition ``AP = Σ_k (R_k − R_{k−1})·P_k`` over *unique*
    descending thresholds (sklearn/reference semantics: each tied score group
    contributes once, evaluated at the group's cumulative counts). Tie groups
    are handled with segment sums at static shape: every row computes its
    group's recall increment, but only the last row of each group (where the
    cumulative precision is the group's) contributes to the sum.

    Returns NaN when no valid positives exist, matching ``0/0`` curve
    semantics.
    """
    preds = jnp.asarray(preds, jnp.float32).reshape(-1)
    target = jnp.asarray(target).reshape(-1).astype(jnp.float32)
    valid = jnp.ones(preds.shape, bool) if mask is None else jnp.asarray(mask, bool).reshape(-1)
    n = preds.shape[0]

    # valid rows first, descending score
    order = jnp.lexsort((-preds, ~valid))
    t_sorted = jnp.where(valid[order], target[order], 0.0)
    v_sorted = preds[order]
    valid_sorted = valid[order]
    w = valid_sorted.astype(jnp.float32)

    tps = jnp.cumsum(t_sorted * w)
    fps = jnp.cumsum((1.0 - t_sorted) * w)
    n_pos = tps[-1] if n > 0 else jnp.asarray(0.0)

    precision = tps / jnp.maximum(tps + fps, 1.0)
    # last row of each tie group among valid rows: next value differs, next row
    # is invalid, or end of array
    next_differs = jnp.concatenate(
        [
            (v_sorted[1:] != v_sorted[:-1]) | (~valid_sorted[1:]),
            jnp.ones((1,), bool),
        ]
    )
    is_group_end = next_differs & valid_sorted

    # recall increment of the whole group, available at its end row:
    # R_end − R_prev_end = (tps_end − tps_prev_end) / n_pos. tps_prev_end is
    # the cumsum at the previous group's end — reconstruct via segment sums.
    gid = _tie_group_ids(v_sorted, valid_sorted)
    group_pos = jax.ops.segment_sum(t_sorted * w, gid, num_segments=n)[gid]

    contrib = jnp.where(is_group_end, group_pos * precision, 0.0)
    ap = jnp.sum(contrib) / n_pos  # NaN when n_pos == 0, matching 0/0 curves
    return ap


def masked_binary_auroc(preds: Array, target: Array, mask: Optional[Array] = None) -> Array:
    """Exact binary AUROC over the rows where ``mask`` — fully jittable.

    Args:
        preds: ``[N]`` scores.
        target: ``[N]`` binary labels (0/1).
        mask: ``[N]`` bool validity; ``None`` = all valid.

    Returns 0.5 when either class is absent among valid rows (degenerate
    curve), matching the convention of an uninformative classifier.
    """
    preds = jnp.asarray(preds, jnp.float32).reshape(-1)
    target = jnp.asarray(target).reshape(-1).astype(jnp.float32)
    valid = jnp.ones(preds.shape, bool) if mask is None else jnp.asarray(mask, bool).reshape(-1)

    ranks = tie_averaged_ranks(preds, valid)
    w = valid.astype(jnp.float32)
    pos = target * w
    num_pos = jnp.sum(pos)
    num_neg = jnp.sum(w) - num_pos
    sum_ranks_pos = jnp.sum(ranks * pos)
    u = sum_ranks_pos - num_pos * (num_pos + 1.0) / 2.0
    denom = num_pos * num_neg
    return jnp.where(denom > 0, u / jnp.maximum(denom, 1.0), jnp.asarray(0.5, jnp.float32))


def _average_per_class(
    per_class: Array, support: Array, average: Optional[str], nan_ignoring: bool = False
) -> Array:
    """Reduce ``[C]`` per-class scores like the eager curve paths do.

    ``weighted`` weights by class support, so unobserved classes (support 0)
    drop out exactly as the reference's explicit column-drop does
    (``functional/classification/auroc.py:257`` analogue). With
    ``nan_ignoring`` (AP semantics), NaN classes are excluded from macro /
    weighted means, mirroring
    ``_average_precision_compute_with_precision_recall``.
    """
    if average in (None, "none"):
        return per_class
    if nan_ignoring:
        ok = ~jnp.isnan(per_class)
        safe = jnp.where(ok, per_class, 0.0)
    else:
        ok = jnp.ones(per_class.shape, bool)
        safe = per_class
    okf = ok.astype(per_class.dtype)
    if average == "macro":
        return jnp.sum(safe * okf) / jnp.maximum(jnp.sum(okf), 1.0)
    if average == "weighted":
        w = support.astype(per_class.dtype) * okf
        return jnp.sum(safe * w) / jnp.maximum(jnp.sum(w), 1.0)
    raise ValueError(f"Unsupported average {average!r} for the masked ranking path")


def _per_class_ovr(kernel, preds: Array, labels: Array, mask: Optional[Array]):
    """vmap a masked binary ``kernel(scores, labels, valid)`` over the class
    axis of ``[N, C]`` inputs; returns per-class scores + valid supports."""
    n, _ = preds.shape
    valid = jnp.ones((n,), bool) if mask is None else jnp.asarray(mask, bool).reshape(-1)
    per_class = jax.vmap(lambda p, t: kernel(p, t, valid), in_axes=(1, 1))(preds, labels)
    support = jnp.sum(labels * valid[:, None].astype(jnp.float32), axis=0)
    return per_class, support, valid


def _onehot_f32(target: Array, num_classes: int) -> Array:
    target = jnp.asarray(target).reshape(-1)
    return (target[:, None] == jnp.arange(num_classes)[None, :]).astype(jnp.float32)


def masked_multiclass_auroc(
    preds: Array, target: Array, mask: Optional[Array] = None, average: Optional[str] = "macro"
) -> Array:
    """One-vs-rest AUROC over ``[N, C]`` scores — vectorized, fully jittable.

    TPU-native extension of the reference's multiclass AUROC
    (``functional/classification/auroc.py:120-257``): instead of per-class
    python-loop ROC curves, every class runs the Mann–Whitney masked path of
    :func:`masked_binary_auroc` under one ``vmap`` — a single XLA program with
    static shapes, so CatBuffer-mode multiclass AUROC fuses
    update → all_gather → compute end to end.

    Degenerate classes (absent among valid rows) score 0.5; under
    ``weighted`` their zero support drops them, matching the reference's
    column-drop behavior without dynamic shapes.
    """
    preds = jnp.asarray(preds, jnp.float32)
    onehot = _onehot_f32(target, preds.shape[1])
    per_class, support, _ = _per_class_ovr(masked_binary_auroc, preds, onehot, mask)
    return _average_per_class(per_class, support, average)


def masked_multilabel_auroc(
    preds: Array, target: Array, mask: Optional[Array] = None, average: Optional[str] = "macro"
) -> Array:
    """Per-label AUROC over ``[N, C]`` scores and ``[N, C]`` binary targets.

    ``micro`` flattens labels into one binary problem (reference
    ``functional/classification/auroc.py:84-86``); other averages reduce the
    per-column scores like :func:`masked_multiclass_auroc`.
    """
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target).astype(jnp.float32)
    n, num_classes = preds.shape
    if average == "micro":
        valid = jnp.ones((n,), bool) if mask is None else jnp.asarray(mask, bool).reshape(-1)
        flat_mask = jnp.broadcast_to(valid[:, None], (n, num_classes)).reshape(-1)
        return masked_binary_auroc(preds.reshape(-1), target.reshape(-1), flat_mask)
    per_class, support, _ = _per_class_ovr(masked_binary_auroc, preds, target, mask)
    return _average_per_class(per_class, support, average)


def masked_multiclass_average_precision(
    preds: Array, target: Array, mask: Optional[Array] = None, average: Optional[str] = "macro"
) -> Array:
    """One-vs-rest average precision over ``[N, C]`` scores — jittable.

    Vectorized CatBuffer analogue of the reference's multiclass AP
    (``functional/classification/average_precision.py:37-86``): per-class
    :func:`masked_binary_average_precision` under ``vmap``; classes with no
    valid positives are NaN and are excluded from ``macro``/``weighted``
    averages exactly like the eager path's nan-filter.
    """
    preds = jnp.asarray(preds, jnp.float32)
    onehot = _onehot_f32(target, preds.shape[1])
    per_class, support, _ = _per_class_ovr(
        masked_binary_average_precision, preds, onehot, mask
    )
    # reference weighted-AP normalizes weights over ALL classes (including
    # nan-dropped ones) — keep that quirk for value parity
    if average == "weighted":
        w = support / jnp.maximum(jnp.sum(support), 1.0)
        ok = ~jnp.isnan(per_class)
        return jnp.sum(jnp.where(ok, per_class * w, 0.0))
    return _average_per_class(per_class, support, average, nan_ignoring=True)
