"""BERT encoder as a pure-JAX XLA graph.

TPU-native replacement for the reference's ``transformers.AutoModel`` load in
BERTScore (``torchmetrics/functional/text/bert.py:575-577``): embeddings +
N post-layernorm transformer layers as one jittable function over a params
pytree, returning **all hidden states** (the reference selects
``hidden_states[num_layers]``, ``bert.py:315-317``).

Attention/FFN matmuls are large batched einsums — MXU-shaped, bfloat16-safe.
Weights convert from a HuggingFace ``bert-base``-style torch state dict via
:func:`load_torch_bert_weights` (checkpoint supplied by the user — no network
access). Without weights the encoder runs with deterministic random init: the
BERTScore *mechanism* is exact and tested; scores are then not comparable to
published numbers.
"""
import math
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array


class BertConfig:
    """Minimal config mirroring HF ``BertConfig`` fields BERTScore needs."""

    def __init__(
        self,
        vocab_size: int = 30522,
        hidden_size: int = 128,
        num_hidden_layers: int = 4,
        num_attention_heads: int = 4,
        intermediate_size: int = 512,
        max_position_embeddings: int = 512,
        type_vocab_size: int = 2,
        layer_norm_eps: float = 1e-12,
    ) -> None:
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.layer_norm_eps = layer_norm_eps


def _dense_init(key: Array, din: int, dout: int) -> Dict[str, Array]:
    std = 0.02
    return {
        "kernel": jax.random.normal(key, (din, dout), dtype=jnp.float32) * std,
        "bias": jnp.zeros((dout,)),
    }


def _ln_init(dim: int) -> Dict[str, Array]:
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def bert_init(config: Optional[BertConfig] = None, key: Optional[Array] = None) -> Dict[str, Any]:
    """Initialize a params pytree for :func:`bert_apply`."""
    config = config or BertConfig()
    if key is None:
        key = jax.random.PRNGKey(0)
    d = config.hidden_size
    keys = jax.random.split(key, 3 + 6 * config.num_hidden_layers)
    params: Dict[str, Any] = {
        "word_embeddings": jax.random.normal(keys[0], (config.vocab_size, d)) * 0.02,
        "position_embeddings": jax.random.normal(keys[1], (config.max_position_embeddings, d)) * 0.02,
        "token_type_embeddings": jax.random.normal(keys[2], (config.type_vocab_size, d)) * 0.02,
        "embeddings_ln": _ln_init(d),
        "layers": [],
    }
    for i in range(config.num_hidden_layers):
        k = keys[3 + 6 * i : 9 + 6 * i]
        params["layers"].append(
            {
                "q": _dense_init(k[0], d, d),
                "k": _dense_init(k[1], d, d),
                "v": _dense_init(k[2], d, d),
                "attn_out": _dense_init(k[3], d, d),
                "attn_ln": _ln_init(d),
                "ffn_in": _dense_init(k[4], d, config.intermediate_size),
                "ffn_out": _dense_init(k[5], config.intermediate_size, d),
                "ffn_ln": _ln_init(d),
            }
        )
    return params


def _layer_norm(p: Dict[str, Array], x: Array, eps: float) -> Array:
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"]


def _dense(p: Dict[str, Array], x: Array) -> Array:
    return x @ p["kernel"] + p["bias"]


def bert_apply(
    params: Dict[str, Any],
    input_ids: Array,
    attention_mask: Array,
    config: Optional[BertConfig] = None,
    token_type_ids: Optional[Array] = None,
) -> List[Array]:
    """Forward pass; returns hidden states for every layer (len = n_layers+1).

    Args:
        input_ids: [batch, seq] int token ids.
        attention_mask: [batch, seq] 1 for real tokens, 0 for padding.

    All shapes are static — padded to the tokenizer's max_length — so the
    whole stack jits once and reruns for every eval batch.
    """
    config = config or BertConfig()
    seq_len = input_ids.shape[1]
    d = config.hidden_size
    n_heads = config.num_attention_heads
    head_dim = d // n_heads

    x = (
        jnp.take(params["word_embeddings"], input_ids, axis=0)
        + params["position_embeddings"][None, :seq_len]
        + jnp.take(
            params["token_type_embeddings"],
            token_type_ids if token_type_ids is not None else jnp.zeros_like(input_ids),
            axis=0,
        )
    )
    x = _layer_norm(params["embeddings_ln"], x, config.layer_norm_eps)

    # additive mask: 0 for real tokens, -inf for padding
    neg = jnp.asarray(jnp.finfo(x.dtype).min, dtype=x.dtype)
    attn_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)

    hidden_states = [x]
    for layer in params["layers"]:
        def heads(t: Array) -> Array:  # [B, S, D] -> [B, H, S, hd]
            return t.reshape(t.shape[0], seq_len, n_heads, head_dim).transpose(0, 2, 1, 3)

        q, k, v = heads(_dense(layer["q"], x)), heads(_dense(layer["k"], x)), heads(_dense(layer["v"], x))
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(head_dim) + attn_bias
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(x.shape[0], seq_len, d)
        x = _layer_norm(layer["attn_ln"], x + _dense(layer["attn_out"], ctx), config.layer_norm_eps)
        ffn = _dense(layer["ffn_out"], jax.nn.gelu(_dense(layer["ffn_in"], x), approximate=False))
        x = _layer_norm(layer["ffn_ln"], x + ffn, config.layer_norm_eps)
        hidden_states.append(x)
    return hidden_states


def load_torch_bert_weights(source: Any) -> Dict[str, Any]:
    """Convert a HF BERT torch state dict (or .pt path) to the params pytree.

    Accepts the standard ``bert.*``-prefixed or unprefixed key layout of
    ``BertModel`` checkpoints; the pooler head is ignored (BERTScore uses
    hidden states only).
    """
    if isinstance(source, str):
        import torch

        source = torch.load(source, map_location="cpu")
    sd = {k[5:] if k.startswith("bert.") else k: np.asarray(v) for k, v in source.items()}

    def dense(prefix: str) -> Dict[str, Array]:
        return {
            "kernel": jnp.asarray(sd[f"{prefix}.weight"].T),
            "bias": jnp.asarray(sd[f"{prefix}.bias"]),
        }

    def ln(prefix: str) -> Dict[str, Array]:
        return {
            "scale": jnp.asarray(sd[f"{prefix}.weight"]),
            "bias": jnp.asarray(sd[f"{prefix}.bias"]),
        }

    params: Dict[str, Any] = {
        "word_embeddings": jnp.asarray(sd["embeddings.word_embeddings.weight"]),
        "position_embeddings": jnp.asarray(sd["embeddings.position_embeddings.weight"]),
        "token_type_embeddings": jnp.asarray(sd["embeddings.token_type_embeddings.weight"]),
        "embeddings_ln": ln("embeddings.LayerNorm"),
        "layers": [],
    }
    i = 0
    while f"encoder.layer.{i}.attention.self.query.weight" in sd:
        base = f"encoder.layer.{i}"
        params["layers"].append(
            {
                "q": dense(f"{base}.attention.self.query"),
                "k": dense(f"{base}.attention.self.key"),
                "v": dense(f"{base}.attention.self.value"),
                "attn_out": dense(f"{base}.attention.output.dense"),
                "attn_ln": ln(f"{base}.attention.output.LayerNorm"),
                "ffn_in": dense(f"{base}.intermediate.dense"),
                "ffn_out": dense(f"{base}.output.dense"),
                "ffn_ln": ln(f"{base}.output.LayerNorm"),
            }
        )
        i += 1
    return params


def config_from_params(params: Dict[str, Any]) -> BertConfig:
    """Infer a :class:`BertConfig` from a params pytree (after weight load)."""
    vocab, d = params["word_embeddings"].shape
    n_layers = len(params["layers"])
    inter = params["layers"][0]["ffn_in"]["kernel"].shape[1] if n_layers else 4 * d
    # HF bert heads: hidden 768->12, 1024->16, small models d/64
    n_heads = max(1, d // 64)
    return BertConfig(
        vocab_size=vocab,
        hidden_size=d,
        num_hidden_layers=n_layers,
        num_attention_heads=n_heads,
        intermediate_size=inter,
        max_position_embeddings=params["position_embeddings"].shape[0],
        type_vocab_size=params["token_type_embeddings"].shape[0],
    )
