"""Pretrained-weight conversion parity vs real torch forwards.

The reference gets pretrained Inception/BERT/AlexNet features from
torch-fidelity / transformers / lpips (reference ``image/fid.py:26-27``,
``functional/text/bert.py:27-28``, ``image/lpip_similarity.py:22-33``).
Our converters (``models/{inception,bert,lpips_net}.py``) map torch state
dicts onto JAX pytrees; these tests prove the mapping is numerically exact
by comparing against *actual torch forwards* on randomly-initialized
architectures — a transposed conv kernel, swapped BN stat, or wrong
layer-norm epsilon fails here.

torchvision is not in the image, so the Inception/AlexNet towers are
re-built from plain ``torch.nn`` with the exact torchvision topology; BERT
uses the real ``transformers.BertModel``.
"""
import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from metrics_tpu.models.bert import (  # noqa: E402
    BertConfig,
    bert_apply,
    load_torch_bert_weights,
)
from metrics_tpu.models.inception import (  # noqa: E402
    _basic_conv,
    inception_v3_init,
    load_torch_inception_weights,
)
from metrics_tpu.models.lpips_net import (  # noqa: E402
    _ALEX_TAPS,
    _SCALE,
    _SHIFT,
    load_torch_lpips_weights,
    lpips_apply,
)

SEED = 1234


def _rand_conv_bn(gen, cin, cout, kh, kw):
    """A torch conv+bn pair with non-trivial random eval-mode stats."""
    conv = nn.Conv2d(cin, cout, (kh, kw), bias=False)
    bn = nn.BatchNorm2d(cout, eps=1e-3)
    with torch.no_grad():
        conv.weight.copy_(torch.randn(conv.weight.shape, generator=gen) * 0.1)
        bn.weight.copy_(torch.rand(cout, generator=gen) + 0.5)
        bn.bias.copy_(torch.randn(cout, generator=gen) * 0.3)
        bn.running_mean.copy_(torch.randn(cout, generator=gen) * 0.5)
        bn.running_var.copy_(torch.rand(cout, generator=gen) + 0.25)
    conv.eval()
    bn.eval()
    return conv, bn


class TestInceptionConversion:
    """conv→BN→relu block + full-state-dict mapping parity."""

    # asymmetric kernels/strides/pads catch H/W transposition mistakes
    @pytest.mark.parametrize(
        "cin,cout,kh,kw,stride,pad",
        [
            (3, 8, 3, 3, (2, 2), ((0, 0), (0, 0))),
            (8, 12, 1, 7, (1, 1), ((0, 0), (3, 3))),
            (8, 12, 7, 1, (1, 1), ((3, 3), (0, 0))),
            (4, 6, 1, 1, (1, 1), ((0, 0), (0, 0))),
            (5, 9, 5, 5, (1, 1), ((2, 2), (2, 2))),
        ],
    )
    def test_conv_bn_block_matches_torch(self, cin, cout, kh, kw, stride, pad):
        gen = torch.Generator().manual_seed(SEED)
        conv, bn = _rand_conv_bn(gen, cin, cout, kh, kw)
        # asymmetric spatial input catches NHWC/NCHW mixups
        x = torch.randn(2, cin, 13, 17, generator=gen)
        with torch.no_grad():
            ref = torch.relu(
                bn(nn.functional.conv2d(x, conv.weight, stride=stride,
                                        padding=(pad[0][0], pad[1][0])))
            ).numpy()

        # the exact transform load_torch_inception_weights applies per conv
        p = {
            "kernel": jnp.asarray(conv.weight.detach().numpy().transpose(2, 3, 1, 0)),
            "bn_scale": jnp.asarray(bn.weight.detach().numpy()),
            "bn_bias": jnp.asarray(bn.bias.detach().numpy()),
            "bn_mean": jnp.asarray(bn.running_mean.numpy()),
            "bn_var": jnp.asarray(bn.running_var.numpy()),
        }
        ours = _basic_conv(p, jnp.asarray(x.numpy().transpose(0, 2, 3, 1)),
                           stride=stride, padding=pad)
        np.testing.assert_allclose(
            np.asarray(ours).transpose(0, 3, 1, 2), ref, rtol=1e-4, atol=1e-4
        )

    def _synth_state_dict(self, num_classes=1008):
        """Full torch-layout inception_v3 state dict with distinct random
        values per tensor (shapes derived from our init tree)."""
        gen = torch.Generator().manual_seed(SEED)
        tree = inception_v3_init(num_classes=num_classes)
        sd = {}

        def fill(shape):
            return torch.randn(tuple(shape), generator=gen) * 0.1

        def conv_entries(prefix, sub):
            kh, kw, cin, cout = sub["kernel"].shape
            sd[f"{prefix}.conv.weight"] = fill((cout, cin, kh, kw))
            sd[f"{prefix}.bn.weight"] = fill((cout,)) + 1.0
            sd[f"{prefix}.bn.bias"] = fill((cout,))
            sd[f"{prefix}.bn.running_mean"] = fill((cout,))
            sd[f"{prefix}.bn.running_var"] = torch.rand(cout, generator=gen) + 0.5

        for name, sub in tree.items():
            if name == "fc":
                sd["fc.weight"] = fill((num_classes, 2048))
                sd["fc.bias"] = fill((num_classes,))
            elif "kernel" in sub:
                conv_entries(name, sub)
            else:
                for b in sub:
                    conv_entries(f"{name}.{b}", sub[b])
        return sd

    def test_full_state_dict_round_trip(self):
        sd = self._synth_state_dict()
        params = load_torch_inception_weights(sd)

        # every leaf landed in the right slot with the right transform
        def check_conv(prefix, sub):
            np.testing.assert_array_equal(
                np.asarray(sub["kernel"]),
                sd[f"{prefix}.conv.weight"].numpy().transpose(2, 3, 1, 0),
            )
            np.testing.assert_array_equal(
                np.asarray(sub["bn_mean"]), sd[f"{prefix}.bn.running_mean"].numpy()
            )
            np.testing.assert_array_equal(
                np.asarray(sub["bn_var"]), sd[f"{prefix}.bn.running_var"].numpy()
            )
            np.testing.assert_array_equal(
                np.asarray(sub["bn_scale"]), sd[f"{prefix}.bn.weight"].numpy()
            )
            np.testing.assert_array_equal(
                np.asarray(sub["bn_bias"]), sd[f"{prefix}.bn.bias"].numpy()
            )

        for name, sub in params.items():
            if name == "fc":
                np.testing.assert_array_equal(
                    np.asarray(sub["weight"]), sd["fc.weight"].numpy().T
                )
                np.testing.assert_array_equal(
                    np.asarray(sub["bias"]), sd["fc.bias"].numpy()
                )
            elif "kernel" in sub:
                check_conv(name, sub)
            else:
                for b in sub:
                    check_conv(f"{name}.{b}", sub[b])

    def test_fc_head_matches_torch_linear(self):
        sd = self._synth_state_dict(num_classes=10)
        params = load_torch_inception_weights(sd)
        gen = torch.Generator().manual_seed(SEED + 1)
        pooled = torch.randn(4, 2048, generator=gen)
        ref = nn.functional.linear(pooled, sd["fc.weight"], sd["fc.bias"]).numpy()
        ours = np.asarray(
            jnp.asarray(pooled.numpy()) @ params["fc"]["weight"] + params["fc"]["bias"]
        )
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4)


class TestBertConversion:
    """End-to-end parity against the real transformers.BertModel."""

    def test_hidden_states_match_transformers(self):
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.BertConfig(
            vocab_size=99,
            hidden_size=32,
            num_hidden_layers=3,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=48,
            type_vocab_size=2,
            hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0,
        )
        torch.manual_seed(SEED)
        model = transformers.BertModel(hf_cfg).eval()

        batch, seq = 3, 11
        gen = torch.Generator().manual_seed(SEED)
        ids = torch.randint(0, 99, (batch, seq), generator=gen)
        mask = torch.ones(batch, seq, dtype=torch.long)
        mask[1, 7:] = 0  # padded row exercises the attention mask path
        mask[2, 4:] = 0
        with torch.no_grad():
            out = model(input_ids=ids, attention_mask=mask, output_hidden_states=True)
        ref_hidden = [h.numpy() for h in out.hidden_states]

        params = load_torch_bert_weights(
            {k: v.numpy() for k, v in model.state_dict().items()}
        )
        cfg = BertConfig(
            vocab_size=99,
            hidden_size=32,
            num_hidden_layers=3,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=48,
        )
        ours = bert_apply(
            params, jnp.asarray(ids.numpy()), jnp.asarray(mask.numpy()), config=cfg
        )

        assert len(ours) == len(ref_hidden)
        for layer_idx, (o, r) in enumerate(zip(ours, ref_hidden)):
            np.testing.assert_allclose(
                np.asarray(o), r, rtol=1e-4, atol=2e-4,
                err_msg=f"hidden state {layer_idx} diverged",
            )


class _AlexFeatures(nn.Module):
    """torchvision AlexNet ``features`` topology from plain torch.nn —
    state-dict keys ``features.<i>.{weight,bias}`` like the real one."""

    def __init__(self):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 64, 11, 4, 2), nn.ReLU(inplace=False),
            nn.MaxPool2d(3, 2),
            nn.Conv2d(64, 192, 5, 1, 2), nn.ReLU(inplace=False),
            nn.MaxPool2d(3, 2),
            nn.Conv2d(192, 384, 3, 1, 1), nn.ReLU(inplace=False),
            nn.Conv2d(384, 256, 3, 1, 1), nn.ReLU(inplace=False),
            nn.Conv2d(256, 256, 3, 1, 1), nn.ReLU(inplace=False),
        )

    def taps(self, x):
        """Relu output after each conv — LPIPS's five AlexNet taps."""
        out = []
        for layer in self.features:
            x = layer(x)
            if isinstance(layer, nn.ReLU):
                out.append(x)
        return out


class TestLpipsConversion:
    def _tower(self):
        torch.manual_seed(SEED)
        m = _AlexFeatures().eval()
        # non-trivial biases so a dropped bias fails loudly
        with torch.no_grad():
            for layer in m.features:
                if isinstance(layer, nn.Conv2d):
                    layer.bias.copy_(torch.randn_like(layer.bias) * 0.2)
        return m

    def test_tower_taps_match_torch(self):
        m = self._tower()
        params = load_torch_lpips_weights("alex", m.state_dict())

        gen = torch.Generator().manual_seed(SEED)
        x = torch.randn(2, 3, 64, 64, generator=gen)
        with torch.no_grad():
            ref_taps = [t.numpy() for t in m.taps(x)]

        from metrics_tpu.models.lpips_net import _tower_features

        ours = _tower_features(
            params, jnp.asarray(x.numpy().transpose(0, 2, 3, 1)), "alex"
        )
        assert len(ours) == len(_ALEX_TAPS) == len(ref_taps)
        for i, (o, r) in enumerate(zip(ours, ref_taps)):
            np.testing.assert_allclose(
                np.asarray(o).transpose(0, 3, 1, 2), r, rtol=1e-4, atol=1e-4,
                err_msg=f"tap {i} diverged",
            )

    def test_lpips_distance_matches_manual_torch(self):
        """Full lpips_apply vs an independent torch implementation of the
        LPIPS formula (unit-normalize taps, squared diff, 1x1 head,
        spatial mean) — lin heads in lpips-package key layout."""
        m = self._tower()
        gen = torch.Generator().manual_seed(SEED + 7)
        tap_dims = [64, 192, 384, 256, 256]
        lin_sd = {
            f"lin{i}.model.1.weight": torch.rand(1, d, 1, 1, generator=gen) * 0.1
            for i, d in enumerate(tap_dims)
        }
        params = load_torch_lpips_weights("alex", m.state_dict(), lin_sd)

        img0 = torch.rand(2, 3, 64, 64, generator=gen) * 2 - 1
        img1 = torch.rand(2, 3, 64, 64, generator=gen) * 2 - 1

        shift = torch.tensor(_SHIFT).view(1, 3, 1, 1)
        scale = torch.tensor(_SCALE).view(1, 3, 1, 1)
        with torch.no_grad():
            t0 = m.taps((img0 - shift) / scale)
            t1 = m.taps((img1 - shift) / scale)
            ref = torch.zeros(2)
            for a, b, (i, d) in zip(t0, t1, enumerate(tap_dims)):
                a = a / torch.sqrt((a * a).sum(1, keepdim=True) + 1e-10)
                b = b / torch.sqrt((b * b).sum(1, keepdim=True) + 1e-10)
                w = lin_sd[f"lin{i}.model.1.weight"].view(1, d, 1, 1)
                ref += ((a - b) ** 2 * w).sum(1).mean(dim=(1, 2))

        ours = lpips_apply(
            params, jnp.asarray(img0.numpy()), jnp.asarray(img1.numpy()), net="alex"
        )
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(), rtol=1e-4, atol=1e-5)
