"""MeanAbsoluteError module — analogue of reference
``torchmetrics/regression/mean_absolute_error.py`` (89 LoC)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.regression.mean_absolute_error import (
    _mean_absolute_error_compute,
    _mean_absolute_error_update,
)


class MeanAbsoluteError(Metric):
    r"""MAE accumulated over batches.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanAbsoluteError
        >>> preds = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0.0, 1.0, 2.0, 2.0])
        >>> mae = MeanAbsoluteError()
        >>> print(round(float(mae(preds, target)), 4))
        0.25
    """

    is_differentiable = True

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.add_state("sum_abs_error", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
        self.sum_abs_error = self.sum_abs_error + sum_abs_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        return _mean_absolute_error_compute(self.sum_abs_error, self.total)
