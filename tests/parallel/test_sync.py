"""Distributed sync primitives over the virtual mesh — analogue of reference
`tests/bases/test_ddp.py` (sum/cat reductions, uneven shapes, state machine)."""
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu.parallel.sync import (
    class_reduce,
    host_sync_state,
    reduce,
    sync_in_jit,
    sync_leaf_in_jit,
)
from tests.helpers.testers import DummyListMetric, DummyMetricSum


def _mesh(n=2):
    return Mesh(np.array(jax.devices()[:n]), axis_names=("dp",))


def test_reduce():
    x = jnp.asarray([1.0, 2.0, 3.0])
    np.testing.assert_allclose(np.asarray(reduce(x, "elementwise_mean")), 2.0)
    np.testing.assert_allclose(np.asarray(reduce(x, "sum")), 6.0)
    np.testing.assert_allclose(np.asarray(reduce(x, "none")), [1, 2, 3])
    with pytest.raises(ValueError):
        reduce(x, "bogus")


def test_class_reduce():
    num = jnp.asarray([1.0, 2.0])
    denom = jnp.asarray([2.0, 4.0])
    w = jnp.asarray([1.0, 3.0])
    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, w, "micro")), 0.5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, w, "macro")), 0.5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, w, "weighted")), 0.5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(class_reduce(num, denom, w, "none")), [0.5, 0.5], atol=1e-5)


@pytest.mark.parametrize("fx, expected", [("sum", 3.0), ("mean", 1.5), ("max", 2.0), ("min", 1.0)])
def test_sync_leaf_reductions(fx, expected):
    mesh = _mesh(2)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P())
    def f(x):
        return sync_leaf_in_jit(x[0], fx, "dp")

    out = f(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(out), expected)


def test_sync_leaf_cat():
    mesh = _mesh(2)

    @partial(jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(), check_vma=False)
    def f(x):
        return sync_leaf_in_jit(x[0], "cat", "dp")

    out = f(jnp.asarray([[1.0, 2.0], [3.0, 4.0]]))
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0, 3.0, 4.0])


def test_sync_in_jit_state_dict():
    mesh = _mesh(4)
    reductions = {"s": "sum", "c": "cat"}

    @partial(jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=(P(), P()), check_vma=False)
    def f(x):
        state = {"s": jnp.sum(x[0]), "c": [x[0]]}
        synced = sync_in_jit(state, reductions, "dp")
        return synced["s"], synced["c"][0]

    data = jnp.arange(8.0).reshape(4, 2)
    s, c = f(data)
    np.testing.assert_allclose(np.asarray(s), 28.0)
    np.testing.assert_allclose(np.asarray(c), np.arange(8.0))


def test_host_sync_single_process_noop():
    state = {"s": jnp.asarray(5.0), "c": [jnp.asarray([1.0])]}
    out = host_sync_state(state, {"s": "sum", "c": None})
    np.testing.assert_allclose(np.asarray(out["s"]), 5.0)


def test_metric_pure_sync_mixed_collection_one_program():
    """A metric's full pure_forward with sync compiles to ONE program."""
    mesh = _mesh(2)
    m = DummyMetricSum()

    @partial(jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=(P(), P()))
    def step(x):
        state = m.pure_update(m.init_state(), x[0])
        synced = m.pure_sync(state, "dp")
        return synced["x"], m.pure_compute(synced)

    synced, val = jax.jit(step)(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(val), 3.0)


def test_uneven_cat_state_sync_in_jit():
    """Cat-states with different per-device batch *contents* but equal shapes
    gather correctly (XLA collectives need static shapes; uneven counts are a
    host-path concern, tested via gather_all_arrays protocol)."""
    mesh = _mesh(2)
    m = DummyListMetric()

    @partial(jax.shard_map, mesh=mesh, in_specs=P("dp"), out_specs=P(), check_vma=False)
    def step(x):
        state = m.init_state()
        state["x"] = [x[0]]
        synced = m.pure_sync(state, "dp")
        return synced["x"][0]

    out = step(jnp.asarray([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]))
    np.testing.assert_allclose(np.asarray(out), [1, 2, 3, 4, 5, 6])
