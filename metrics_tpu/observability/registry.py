"""Unified per-instance stats registry — one schema'd ``telemetry()`` surface.

Before this module, diagnostics were hand-maintained in four disjoint
places: ``compile_stats()`` (dispatcher attribute counters),
``sync_stats()`` (two copies of the same dict bookkeeping, on ``Metric``
AND ``MetricCollection``), checkpoint paths (uncounted), and the health
layer (uncounted process-global latches). :class:`StatsRegistry` is the one
storage those surfaces now share:

- the ``sync`` domain IS the dict ``Metric._sync_stats_dict()`` /
  ``MetricCollection._sync_stats_dict()`` mutate — ``sync_stats()`` is a
  view over it;
- the ``compile`` domain IS the dict ``core.compiled.CompiledDispatcher``
  counts into — ``compile_stats()`` is a view over it;
- the ``checkpoint`` and ``health`` domains are new counters bumped by
  ``core/checkpoint.py`` and the sync failure/degradation ladder;
- process-wide facts (watchdog fires, the channel-suspect latch) live in
  the module-level :data:`PROCESS` counters, snapshotted into every
  ``telemetry()`` call under the ``process`` key.

``telemetry()`` (on ``Metric`` and ``MetricCollection``) returns the full
schema'd snapshot; ``telemetry(delta=True)`` returns the numeric change
since the previous delta call (the poll-loop form). :func:`telemetry_jsonl`
and :func:`telemetry_prometheus` are the export encoders (JSON-lines for
log shippers, Prometheus text exposition for scrapers).
"""
import json
import threading
from typing import Any, Dict, List, Optional

__all__ = [
    "TELEMETRY_SCHEMA",
    "PROCESS",
    "StatsRegistry",
    "add_process",
    "bump_process",
    "process_snapshot",
    "registry_of",
    "set_process",
    "telemetry_jsonl",
    "telemetry_prometheus",
]

#: Schema identifier stamped into every snapshot (bump on layout changes).
TELEMETRY_SCHEMA = "metrics_tpu.telemetry.v1"

#: Storage-backed domains and their counter defaults. ``compile`` is listed
#: for schema completeness but its storage lives with the instance's
#: ``CompiledDispatcher`` (created on first dispatch); ``Metric.telemetry``
#: splices it in from ``compile_stats()``.
DOMAIN_DEFAULTS: Dict[str, Dict[str, Any]] = {
    "compile": {
        "traces": 0,
        "dispatches": 0,
        "cache_hits": 0,
        "steps_seen": 0,
        "fallback": None,
    },
    "sync": {
        "launched": 0,
        "resolved": 0,
        "stale_resolves": 0,
        "degraded": 0,
        "cancelled": 0,
        "served_local": 0,
        "gather_s": 0.0,
        "resolve_wait_s": 0.0,
        "overlap_saved_s": 0.0,
        # tiered (two-level) schedule per-hop byte ledger: what this rank put
        # on the fast (intra-tier) vs slow (inter-tier) wire, and how many
        # slow-hop bytes the schedule avoided vs the flat world gather
        "intra_tier_bytes": 0,
        "inter_tier_bytes": 0,
        "inter_tier_bytes_saved": 0,
    },
    "checkpoint": {
        "saves": 0,
        "loads": 0,
        "pruned_steps": 0,
        "refused": 0,
        "auto_snapshots": 0,
    },
    "health": {
        "sync_failures": 0,
        "degraded": 0,
        "errors": {},  # typed SyncError class name -> count
    },
    "plan": {
        "builds": 0,
        "cache_hits": 0,
        "invalidations": 0,
        "invalidate_reasons": {},  # invalidation reason -> count
        "fused_steps": 0,
    },
}

#: Process-wide counters and gauges (no instance owns a watchdog): bumped
#: by ``parallel/health.py`` / ``parallel/resilience.py``, snapshotted under
#: the ``process`` key of every ``telemetry()`` call. The ``*_s`` entries
#: are seconds: ``suspect_episode_s`` accumulates how long the channel spent
#: in probation across episodes, ``watchdog_margin_s`` is the LAST observed
#: headroom (timeout minus gather time — the adaptive controller's signal),
#: ``adaptive_timeout_s`` the controller's current watchdog bound (0 = not
#: tuning).
PROCESS: Dict[str, Any] = {
    "watchdog_fired": 0,
    "channel_suspect_latched": 0,
    "channel_resets": 0,
    "channel_readmits": 0,
    "membership_transitions": 0,
    "quorum_shrinks": 0,
    "quorum_readmits": 0,
    "suspect_episode_s": 0.0,
    "watchdog_margin_s": 0.0,
    "adaptive_timeout_s": 0.0,
}
_PROCESS_LOCK = threading.Lock()


def bump_process(key: str, by: int = 1) -> None:
    with _PROCESS_LOCK:
        PROCESS[key] = PROCESS.get(key, 0) + by


def add_process(key: str, by: float) -> None:
    """Accumulate a float process gauge (e.g. probation episode seconds)."""
    with _PROCESS_LOCK:
        PROCESS[key] = PROCESS.get(key, 0.0) + float(by)


def set_process(key: str, value: float) -> None:
    """Set a last-observed process gauge (e.g. the watchdog margin)."""
    with _PROCESS_LOCK:
        PROCESS[key] = value


def process_snapshot() -> Dict[str, Any]:
    """Current process-wide health facts (the live suspect flag included)."""
    from metrics_tpu.parallel.health import channel_is_suspect

    with _PROCESS_LOCK:
        snap: Dict[str, Any] = dict(PROCESS)
    snap["channel_suspect"] = bool(channel_is_suspect())
    return snap


def _deep_copy_counters(d: Dict[str, Any]) -> Dict[str, Any]:
    return {k: (dict(v) if isinstance(v, dict) else v) for k, v in d.items()}


def _numeric_delta(now: Any, before: Any) -> Any:
    """Recursive numeric difference (non-numeric leaves pass through as
    their current value)."""
    if isinstance(now, dict):
        before = before if isinstance(before, dict) else {}
        return {k: _numeric_delta(v, before.get(k)) for k, v in now.items()}
    if isinstance(now, bool) or not isinstance(now, (int, float)):
        return now
    prev = before if isinstance(before, (int, float)) and not isinstance(before, bool) else 0
    return now - prev


class StatsRegistry:
    """Counter storage for one ``Metric`` / ``MetricCollection`` instance.

    Domains are plain dicts (picklable, deepcopy-able with their owner);
    callers mutate them in place through :meth:`domain` — the same live-dict
    convention the historical ``_sync_stats`` bookkeeping used, so the
    counting sites read identically while the storage is unified.
    """

    __slots__ = ("label", "_domains", "_last")

    def __init__(self, label: str) -> None:
        self.label = label
        self._domains: Dict[str, Dict[str, Any]] = {}
        self._last: Optional[Dict[str, Any]] = None

    def domain(self, name: str) -> Dict[str, Any]:
        """The live counter dict for ``name`` (created from the schema
        defaults on first use). Mutations through the returned reference ARE
        the registry's state."""
        d = self._domains.get(name)
        if d is None:
            d = _deep_copy_counters(DOMAIN_DEFAULTS.get(name, {}))
            self._domains[name] = d
        return d

    def inc(self, name: str, key: str, by: float = 1) -> None:
        d = self.domain(name)
        d[key] = d.get(key, 0) + by

    def count_error(self, err: BaseException, degraded: bool) -> None:
        """The health-domain bump shared by every sync-failure path."""
        h = self.domain("health")
        h["sync_failures"] += 1
        errors = h.setdefault("errors", {})
        cls = type(err).__name__
        errors[cls] = errors.get(cls, 0) + 1
        if degraded:
            h["degraded"] += 1

    def snapshot(self, extra: Optional[Dict[str, Dict[str, Any]]] = None) -> Dict[str, Any]:
        """The full schema'd telemetry snapshot for this instance. ``extra``
        splices in provider-backed domains (``compile`` from the dispatcher)
        so the registry itself stays closure-free and picklable."""
        snap: Dict[str, Any] = {"schema": TELEMETRY_SCHEMA, "label": self.label}
        domains = dict(extra or {})
        for name in DOMAIN_DEFAULTS:
            if name not in domains:
                domains[name] = self.domain(name)
        for name, counters in domains.items():
            snap[name] = _deep_copy_counters(counters)
        snap["process"] = process_snapshot()
        return snap

    def delta(self, extra: Optional[Dict[str, Dict[str, Any]]] = None) -> Dict[str, Any]:
        """Numeric change since the previous ``delta()`` call (first call
        deltas against zero). Non-numeric entries (labels, fallback reasons,
        the live suspect flag) carry their current value."""
        now = self.snapshot(extra)
        before = self._last or {}
        self._last = now
        out = {k: _numeric_delta(v, before.get(k)) for k, v in now.items()}
        out["schema"] = TELEMETRY_SCHEMA
        out["label"] = self.label
        return out

    def __deepcopy__(self, memo: dict) -> "StatsRegistry":
        new = StatsRegistry(self.label)
        new._domains = {k: _deep_copy_counters(v) for k, v in self._domains.items()}
        return new


def registry_of(obj: Any) -> StatsRegistry:
    """The instance's registry (created on first use). Works for ``Metric``
    (custom ``__setattr__`` routed around via ``object.__setattr__``) and
    ``MetricCollection`` alike."""
    reg = obj.__dict__.get("_telemetry")
    if reg is None:
        reg = StatsRegistry(type(obj).__name__)
        object.__setattr__(obj, "_telemetry", reg)
    return reg


# ---------------------------------------------------------------------------
# export encoders
# ---------------------------------------------------------------------------


def _flatten(prefix: str, value: Any, out: Dict[str, Any]) -> None:
    if isinstance(value, dict):
        for k, v in value.items():
            _flatten(f"{prefix}_{k}" if prefix else str(k), v, out)
    else:
        out[prefix] = value


def telemetry_jsonl(snapshot: Dict[str, Any]) -> str:
    """Encode one telemetry snapshot as JSON-lines: one line per domain
    (collection snapshots recurse into members, each member its own block
    of lines with a ``member`` field)."""
    lines: List[str] = []

    def emit(snap: Dict[str, Any], member: Optional[str] = None) -> None:
        label = snap.get("label", "")
        for domain, counters in snap.items():
            if domain in ("schema", "label") or not isinstance(counters, dict):
                continue
            row: Dict[str, Any] = {
                "schema": snap.get("schema", TELEMETRY_SCHEMA),
                "label": label,
                "domain": domain,
            }
            if member is not None:
                row["member"] = member
            row.update(counters)
            lines.append(json.dumps(row, sort_keys=True, default=str))

    if "collection" in snapshot and "members" in snapshot:
        emit(snapshot["collection"])
        for key, member_snap in snapshot["members"].items():
            emit(member_snap, member=key)
    else:
        emit(snapshot)
    return "\n".join(lines)


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def telemetry_prometheus(snapshot: Dict[str, Any]) -> str:
    """Encode one telemetry snapshot as Prometheus text exposition.

    Numeric counters become ``metrics_tpu_<domain>_<counter>`` samples with
    ``label=""`` (and ``member=""`` for collection members); booleans encode
    as 0/1 gauges; strings are skipped (they ride the JSON-lines form).
    """
    samples: List[str] = []
    typed: set = set()

    def emit(snap: Dict[str, Any], member: Optional[str] = None) -> None:
        label = _prom_escape(str(snap.get("label", "")))
        for domain, counters in snap.items():
            if domain in ("schema", "label") or not isinstance(counters, dict):
                continue
            flat: Dict[str, Any] = {}
            _flatten("", counters, flat)
            for key, value in sorted(flat.items()):
                if isinstance(value, bool):
                    value = int(value)
                if not isinstance(value, (int, float)):
                    continue
                name = f"metrics_tpu_{domain}_{key}".replace("-", "_").replace(".", "_")
                if name not in typed:
                    typed.add(name)
                    kind = "gauge" if domain == "process" else "counter"
                    samples.append(f"# TYPE {name} {kind}")
                tags = f'label="{label}"'
                if member is not None:
                    tags += f',member="{_prom_escape(member)}"'
                samples.append(f"{name}{{{tags}}} {value}")

    if "collection" in snapshot and "members" in snapshot:
        emit(snapshot["collection"])
        for key, member_snap in snapshot["members"].items():
            emit(member_snap, member=key)
    else:
        emit(snapshot)
    return "\n".join(samples) + "\n"
