"""PrecisionRecallCurve module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/precision_recall_curve.py`` (152 LoC): "cat"
list-states gathered across devices with one all_gather each.
"""
from typing import Any, Callable, List, Optional, Tuple, Union

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utils.data import dim_zero_cat


class PrecisionRecallCurve(Metric):
    """Exact precision–recall pairs at every distinct score threshold.

    Scores/targets accumulate as "cat" states; :meth:`compute` sorts once
    and cumulative-sums. Memory grows with the stream — for large or
    unbounded streams prefer
    :class:`~metrics_tpu.BinnedPrecisionRecallCurve`, whose fixed
    thresholds keep state at ``[C, T]`` sums (and dispatch to the pallas
    kernel on TPU).

    Args:
        num_classes: class count for multiclass scores ``[N, C]``;
            ``None`` for binary ``[N]``.
        pos_label: the label treated as positive in binary input.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    :meth:`compute` returns ``(precision, recall, thresholds)`` — arrays
    for binary, per-class lists for multiclass. The final (1, 0) point is
    appended so the curve always spans recall 1 → 0.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PrecisionRecallCurve
        >>> preds = jnp.asarray([0.1, 0.4, 0.35, 0.8])
        >>> target = jnp.asarray([0, 0, 1, 1])
        >>> pr_curve = PrecisionRecallCurve(pos_label=1)
        >>> precision, recall, thresholds = pr_curve(preds, target)
        >>> print([round(p, 4) for p in precision.tolist()])
        [0.6667, 0.5, 1.0, 1.0]
        >>> print(recall.tolist())
        [1.0, 0.5, 0.5, 0.0]
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    #: the shared clf-curve preprocessing infers num_classes/pos_label; a
    #: grouped dispatch copies the inference to every sibling
    _group_shared_attrs = ("num_classes", "pos_label")

    def update_identity(self):
        """Compute-group key of the clf-curve family (see ``ROC``): this
        update is the defining ``_precision_recall_curve_update`` call, so
        equal ``(num_classes, pos_label)`` instances — including ROC and
        non-micro AveragePrecision — share one preds/target accumulation."""
        return ("clf_curve", self.num_classes, self.pos_label)

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        preds, target, num_classes, pos_label = _precision_recall_curve_update(
            preds, target, self.num_classes, self.pos_label
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(
        self,
    ) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _precision_recall_curve_compute(preds, target, self.num_classes, self.pos_label)
