"""AUC module metric (generic x/y curve area).

Behavioral analogue of the reference's ``torchmetrics/classification/auc.py``
(96 LoC).
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.auc import _auc_compute, _auc_update
from metrics_tpu.utils.data import dim_zero_cat


class AUC(Metric):
    """Area under any accumulated (x, y) curve via the trapezoidal rule.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUC
        >>> x = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> y = jnp.asarray([0.0, 1.0, 2.0, 2.0])
        >>> auc = AUC()
        >>> print(round(float(auc(x, y)), 4))
        4.0
    """

    is_differentiable = False

    def __init__(
        self,
        reorder: bool = False,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.reorder = reorder
        self.add_state("x", default=[], dist_reduce_fx="cat")
        self.add_state("y", default=[], dist_reduce_fx="cat")

    def update(self, x: Array, y: Array) -> None:  # type: ignore[override]
        x, y = _auc_update(x, y)
        self.x.append(x)
        self.y.append(y)

    def compute(self) -> Array:
        x = dim_zero_cat(self.x)
        y = dim_zero_cat(self.y)
        return _auc_compute(x, y, reorder=self.reorder)
