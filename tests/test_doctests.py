"""Run docstring examples as tests — the analogue of the reference's doctest
suite (``Makefile:17-21`` runs pytest over the package with doctests on).

Every ``Example:`` block in a metric docstring must execute and reproduce its
printed output on the virtual CPU mesh.
"""
import doctest
import importlib
import pkgutil

import pytest

import metrics_tpu


def _package_modules():
    out = []
    for info in pkgutil.walk_packages(metrics_tpu.__path__, prefix="metrics_tpu."):
        if ".models" in info.name:  # heavy model defs hold no doctests
            continue
        out.append(info.name)
    return sorted(out)


@pytest.mark.parametrize("module_name", _package_modules())
def test_module_doctests(module_name):
    module = importlib.import_module(module_name)
    results = doctest.testmod(
        module,
        verbose=False,
        optionflags=doctest.NORMALIZE_WHITESPACE | doctest.ELLIPSIS,
    )
    assert results.failed == 0, f"{results.failed} doctest failure(s) in {module_name}"
