"""SMAPE — analogue of reference
``torchmetrics/functional/regression/symmetric_mean_absolute_percentage_error.py``."""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    abs_per_error = 2 * jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), epsilon, None)
    return jnp.sum(abs_per_error), target.size


def _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs) -> Array:
    return sum_abs_per_error / num_obs


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Symmetric mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import symmetric_mean_absolute_percentage_error
        >>> print(round(float(symmetric_mean_absolute_percentage_error(jnp.asarray([9.0, 19.0]), jnp.asarray([10.0, 20.0]))), 4))
        0.0783
    """
    sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
