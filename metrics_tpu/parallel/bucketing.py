"""Bucketed host-path sync: one collective per dtype/fx class, not per leaf.

The health-word protocol (``parallel/health.py``) collapsed the *precheck*
collectives into a single gather, but the *payload* path still issued one
``process_allgather`` per state leaf — plus a shape pre-gather per uneven
leaf — and a ``MetricCollection`` multiplied that by the number of metrics.
Collective fusion is exactly the lever the related work pulls (EQuARX,
arxiv 2506.17615: fused quantized AllReduce; portable collective
redistribution, arxiv 2112.01075: many small transfers batched into few
large ones): latency hides in per-collective launch overhead, so the fix is
to move the same bytes in O(#dtypes × #fx-classes) collectives.

This module is the **bucketed sync planner**. Given the state dict of one
metric — or the combined, key-prefixed states of an entire
``MetricCollection`` (``MetricCollection.sync``) — it classifies every leaf
and builds a :class:`SyncPlan`. Compute groups (``core/collections.py``)
compose with the planner upstream: the collection combines ONE state per
group (not one per member), so a grouped collection's plan carries fewer
leaves — fewer header count/length columns consumed and strictly smaller
bucket payloads — while staying rank-symmetric (grouping is deterministic
from construction, so every rank plans the identical combined schema):

- **reduce leaves** (``fx`` in ``sum``/``mean``/``max``/``min``) group by
  ``(dtype, fx)``: each bucket flattens and concatenates into one flat
  buffer, gathers once to ``[world, total]``, applies the reduction over the
  world axis, and splits back — elementwise over the same ``world`` values
  as the per-leaf path, so results are bit-identical;
- **cat-family leaves** (CatBuffer, list states, arrays with ``fx`` in
  ``("cat", None)``) group by dtype into one padded ragged buffer: each rank
  flattens its rows leaf-by-leaf, pads to the max total across ranks (known
  from the header's length columns — no shape pre-gathers), gathers once,
  and every rank slices each leaf's per-rank pieces back out;
- **callable-``fx`` leaves** cannot be planned (opaque reduction) and fall
  back to :func:`~metrics_tpu.parallel.sync.host_sync_leaf`.

The static plan (leaf order, bucket membership, item shapes/sizes) is
cached in the unified :class:`~metrics_tpu.core.plan.ExecutionPlan` store
(``core/plan.py``), keyed on the exact schema string behind the health
word's CRC (:func:`~metrics_tpu.parallel.health.state_schema_parts` — the
full string, so a CRC collision can never alias two schemas onto one
plan), so repeated ``compute()`` calls pay zero re-planning. Per-rank row
counts — the only dynamic input — ride the header gather's length columns.
The store is lock-protected and plans are immutable after construction, so
the async overlap layer (``parallel/async_sync.py``) reuses them from its
background thread across overlapped rounds — a round's snapshot has the
same schema the blocking path would sync, so rounds hit the cached plan
without re-planning. This module keeps the *classifier* (the pure layout
builder) and the execution engine; the cache itself lives with the plan.

Execution requires the caller to have *already verified* the gathered
health words: the plan trusts cross-rank schema equality (verified via the
schema hash), non-empty cat states (count columns), and un-overflowed
CatBuffers (overflow column). ``host_sync_state`` wires this up and is the
supported entry point; the ``METRICS_TPU_FUSED_SYNC=0`` env knob is the
escape hatch back to the per-leaf path.
"""
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.observability import journal
from metrics_tpu.parallel.health import (
    cat_family_names,
    cat_row_count,
    header_cat_lengths,
)

__all__ = [
    "LeafSpec",
    "SyncPlan",
    "build_sync_plan",
    "clear_sync_plan_cache",
    "fused_sync_enabled",
    "host_sync_state_bucketed",
    "sync_plan_cache_info",
]

#: Env escape hatch: set to 0/false/off to restore the per-leaf payload path.
FUSED_SYNC_ENV = "METRICS_TPU_FUSED_SYNC"

_REDUCERS = {
    "sum": lambda g: jnp.sum(g, axis=0),
    "mean": lambda g: jnp.mean(g, axis=0),
    "max": lambda g: jnp.max(g, axis=0),
    "min": lambda g: jnp.min(g, axis=0),
}


def fused_sync_enabled() -> bool:
    """Default payload strategy: bucketed (fused) unless the env knob opts out."""
    return os.environ.get(FUSED_SYNC_ENV, "1").strip().lower() not in ("0", "false", "off", "no")


class LeafSpec:
    """Static per-leaf plan entry.

    ``kind`` ∈ ``reduce`` | ``cat`` | ``list`` | ``catbuf`` | ``fallback``.
    ``item_shape``/``item_size`` describe one *row* for cat-family leaves and
    the full (rank-invariant) array for reduce leaves. ``cat_index`` is the
    leaf's column in the header's length table (-1 for non-cat kinds).
    """

    __slots__ = ("name", "kind", "fx", "dtype", "item_shape", "item_size", "cat_index")

    def __init__(self, name: str, kind: str, fx: Any, dtype: Any,
                 item_shape: Tuple[int, ...], cat_index: int = -1) -> None:
        self.name = name
        self.kind = kind
        self.fx = fx
        self.dtype = dtype
        self.item_shape = item_shape
        self.item_size = int(np.prod(item_shape, dtype=np.int64)) if item_shape else 1
        self.cat_index = cat_index

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"LeafSpec({self.name!r}, {self.kind}, fx={self.fx!r}, "
                f"dtype={self.dtype}, item={self.item_shape})")


class SyncPlan:
    """The fused schedule for one schema: which leaves ride which collective.

    ``n_collectives(world)`` is the payload-collective budget (header not
    included): one per reduce bucket, one per non-empty cat bucket, plus the
    per-leaf cost of unplannable fallbacks.
    """

    __slots__ = ("leaves", "cat_leaves", "reduce_buckets", "cat_buckets", "fallback", "schema_key")

    def __init__(self, leaves: Dict[str, LeafSpec], cat_leaves: List[LeafSpec],
                 reduce_buckets: Dict[Tuple[str, str], List[LeafSpec]],
                 cat_buckets: Dict[str, List[LeafSpec]],
                 fallback: List[LeafSpec], schema_key: str) -> None:
        self.leaves = leaves
        self.cat_leaves = cat_leaves
        self.reduce_buckets = reduce_buckets
        self.cat_buckets = cat_buckets
        self.fallback = fallback
        self.schema_key = schema_key

    @property
    def n_buckets(self) -> int:
        return len(self.reduce_buckets) + len(self.cat_buckets)


# The schema-keyed cache that used to live here moved into the unified plan
# store (``core/plan.py``): one ``ExecutionPlan`` per schema owns the
# ``SyncPlan`` layout this module builds, alongside the compiled-program and
# compute-group bookkeeping the other planners used to cache separately.
# These two names are the long-standing public API — kept as views.


def clear_sync_plan_cache() -> None:
    from metrics_tpu.core.plan import clear_plans

    clear_plans()


def sync_plan_cache_info() -> Dict[str, int]:
    from metrics_tpu.core.plan import plan_cache_info

    info = plan_cache_info()
    return {"size": info["size"], "hits": info["hits"], "misses": info["misses"]}


def _classify(state: Dict[str, Any], reductions: Dict[str, Any], schema_key: str) -> SyncPlan:
    from metrics_tpu.core.cat_buffer import CatBuffer

    cat_order = {n: j for j, n in enumerate(cat_family_names(state, reductions))}
    leaves: Dict[str, LeafSpec] = {}
    cat_leaves: List[LeafSpec] = []
    reduce_buckets: Dict[Tuple[str, str], List[LeafSpec]] = {}
    cat_buckets: Dict[str, List[LeafSpec]] = {}
    fallback: List[LeafSpec] = []
    for name in sorted(state):
        v = state[name]
        fx = reductions.get(name)
        if isinstance(v, CatBuffer):
            item = None if v.buffer is None else tuple(v.buffer.shape[1:])
            dtype = None if v.buffer is None else v.buffer.dtype
            spec = LeafSpec(name, "catbuf", fx, dtype, item or (), cat_order[name])
        elif isinstance(v, (list, tuple)):
            if len(v):
                first = jnp.asarray(v[0])
                item = tuple(first.shape[1:]) if first.ndim else ()
                dtype = first.dtype
            else:
                item, dtype = (), None
            spec = LeafSpec(name, "list", fx, dtype, item, cat_order[name])
        else:
            arr = jnp.asarray(v)
            if fx in ("cat", None):
                item = tuple(arr.shape[1:]) if arr.ndim else ()
                spec = LeafSpec(name, "cat", fx, arr.dtype, item, cat_order[name])
            elif fx in _REDUCERS:
                spec = LeafSpec(name, "reduce", fx, arr.dtype, tuple(arr.shape))
            else:
                # callable fx: opaque reduction over the [world, ...] stack —
                # cannot ride a shared buffer, so it keeps the per-leaf path
                spec = LeafSpec(name, "fallback", fx, arr.dtype, tuple(arr.shape))
        leaves[name] = spec
        if spec.kind == "reduce":
            reduce_buckets.setdefault((str(spec.dtype), spec.fx), []).append(spec)
        elif spec.kind == "fallback":
            fallback.append(spec)
        else:
            cat_leaves.append(spec)
            if spec.dtype is not None:
                cat_buckets.setdefault(str(spec.dtype), []).append(spec)
            else:
                # item spec unknown (empty list / unmaterialized CatBuffer):
                # unreachable after a passed health check (count column == 0
                # raises first); routed to the per-leaf path defensively
                fallback.append(spec)
    return SyncPlan(leaves, cat_leaves, reduce_buckets, cat_buckets, fallback, schema_key)


def build_sync_plan(state: Dict[str, Any], reductions: Dict[str, Any]) -> SyncPlan:
    """The (cached) fused schedule for this state schema — a view into the
    unified :class:`~metrics_tpu.core.plan.ExecutionPlan` store, which keys
    on the exact schema string the health word hashes, so any change a rank
    could legally make between syncs (a CatBuffer materializing its item
    spec, a dtype cast) keys a fresh plan, while repeated syncs of the same
    schema — every ``compute()`` of a long eval — hit the cache.
    """
    from metrics_tpu.core.plan import plan_for

    return plan_for(state, reductions).sync_layout


def _local_flat_rows(value: Any, spec: LeafSpec):
    """(rows, flat 1-D payload) of this rank's contribution to a cat leaf."""
    from metrics_tpu.core.cat_buffer import CatBuffer

    if isinstance(value, CatBuffer):
        rows = int(np.asarray(value.count))
        return rows, value.values().reshape(-1)
    if isinstance(value, (list, tuple)):
        cat = jnp.concatenate([jnp.asarray(x)[None] if jnp.asarray(x).ndim == 0 else jnp.asarray(x) for x in value], axis=0)
        return int(cat.shape[0]), cat.reshape(-1)
    arr = jnp.asarray(value)
    if arr.ndim == 0:
        arr = arr[None]
    return int(arr.shape[0]), arr.reshape(-1)


def _assemble_cat(spec: LeafSpec, pieces: List[Any], local_value: Any, world: int) -> Any:
    """Reconstruct one cat-family leaf from its per-rank row blocks —
    byte-identical to what ``host_sync_leaf`` builds from its own gather."""
    from metrics_tpu.core.cat_buffer import CatBuffer

    if spec.kind == "catbuf":
        merged = CatBuffer(world * local_value.capacity)
        for p in pieces:
            merged.append(p)
        return merged
    if spec.kind == "list":
        return list(pieces)
    return jnp.concatenate(pieces, axis=0)


# ---------------------------------------------------------------------------
# Two-level (tiered) collective schedule: reduce/concatenate within the tier
# over the fast hop, ONE inter-tier exchange per bucket over the slow hop,
# then an intra-tier broadcast. The topology is negotiated via the health
# word's tier column (``parallel/tiering.py``), so by the time these helpers
# run, every live rank has verified it derives the identical schedule.
# ---------------------------------------------------------------------------


def _bump_stats(stats: Optional[Dict[str, Any]], key: str, by: float) -> None:
    if stats is not None:
        stats[key] = stats.get(key, 0) + by


def _tier_collective(transport: Any, x: Any, ranks: Any, timeout: Optional[float], what: str):
    """One watchdog-guarded subset collective over ``ranks`` — the tiered
    schedule's only primitive (same ``subset_allgather`` interface as the
    quorum transport)."""
    from metrics_tpu.parallel.health import call_with_sync_watchdog

    arr = jnp.asarray(x)
    return call_with_sync_watchdog(
        lambda: jnp.asarray(transport.subset_allgather(arr, frozenset(ranks))),
        timeout=timeout,
        what=what,
    )


def _account_hop(
    stats: Optional[Dict[str, Any]],
    topo: Any,
    hop: str,
    nbytes: int,
    participants: int,
    dur_s: float,
) -> None:
    """Per-hop byte counters + ``sync.hop`` journal event. ``nbytes`` is
    what THIS rank put on the wire for the hop (payload × (participants−1));
    summed across ranks it is the fleet's traffic on that hop class."""
    _bump_stats(stats, "intra_tier_bytes" if hop == "intra" else "inter_tier_bytes", int(nbytes))
    if journal.ACTIVE:
        journal.record(
            "sync.hop",
            label=hop,
            tier=-1 if topo.my_tier is None else int(topo.my_tier),
            participants=int(participants),
            nbytes=int(nbytes),
            dur_s=float(dur_s),
        )


def _tiered_allgather(
    flat: Any,
    topo: Any,
    transport: Any,
    timeout: Optional[float],
    stats: Optional[Dict[str, Any]],
    precision: Optional[str] = None,
):
    """Tiered replacement for one flat ``_process_allgather(flat)``.

    Three hops — (1) intra-tier gather of every rank's payload, (2) leaders
    exchange the concatenated (padded) tier blocks in ONE inter-tier
    collective, (3) intra-tier broadcast of the exchanged blocks — then
    every rank reassembles the ``[world, n]`` matrix in global rank order
    via ``topo.assembly``. With ``precision=None`` the blocks move raw, so
    the result is **bit-identical** to the flat gather (same rows, no
    arithmetic); the slow hop simply carries ``n_tiers`` participants
    instead of ``world``. ``precision`` (bf16/int8, float payloads only)
    encodes ONLY the inter-tier wire — intra-tier hops always move full
    precision.
    """
    from metrics_tpu.parallel import quantize

    flat = jnp.asarray(flat)
    n = int(flat.size)
    item = np.dtype(flat.dtype).itemsize
    members = topo.my_tier_ranks
    k = len(members)

    t0 = time.monotonic()
    block = _tier_collective(transport, flat, members, timeout, "tier intra-gather")
    _account_hop(stats, topo, "intra", n * item * (k - 1), k, time.monotonic() - t0)

    width = topo.max_tier * n
    if precision is not None and not jnp.issubdtype(np.dtype(flat.dtype), np.floating):
        precision = None  # schema-static pass-through: identical on every rank
    enc_n = quantize.encoded_size(width, flat.dtype, precision)
    wire_dtype = (
        flat.dtype
        if precision is None
        else (jnp.bfloat16 if precision == "bf16" else jnp.int8)
    )
    wire_item = np.dtype(wire_dtype).itemsize
    if topo.is_leader:
        payload = jnp.pad(jnp.asarray(block).reshape(-1), (0, width - k * n))
        wire = quantize.encode(payload, precision)
        t0 = time.monotonic()
        inter = _tier_collective(transport, wire, topo.leaders, timeout, "tier inter-exchange")
        _account_hop(
            stats, topo, "inter",
            enc_n * wire_item * (topo.n_tiers - 1), topo.n_tiers,
            time.monotonic() - t0,
        )
        bc_payload = jnp.asarray(inter).reshape(-1)
        actual_inter = enc_n * wire_item * (topo.n_tiers - 1)
    else:
        bc_payload = jnp.zeros((topo.n_tiers * enc_n,), wire_dtype)
        actual_inter = 0
    # what the flat world gather would have moved across tiers from this
    # rank (payload to every rank outside its tier) minus what the tiered
    # schedule actually moved — the headline "saved" counter
    _bump_stats(
        stats, "inter_tier_bytes_saved",
        n * item * (len(topo.live) - k) - actual_inter,
    )

    t0 = time.monotonic()
    bc = _tier_collective(transport, bc_payload, members, timeout, "tier broadcast")
    _account_hop(
        stats, topo, "intra",
        int(bc_payload.size) * wire_item * (k - 1), k, time.monotonic() - t0,
    )
    rows = jnp.asarray(bc)[0].reshape(topo.n_tiers, enc_n)  # leader = min rank = row 0
    decoded = quantize.decode(rows, width, flat.dtype, precision)  # [n_tiers, width]
    return jnp.asarray(decoded).reshape(topo.n_tiers * topo.max_tier, n)[topo.assembly]


def _tiered_quantized_reduce(
    flat: Any,
    fx: str,
    topo: Any,
    transport: Any,
    timeout: Optional[float],
    stats: Optional[Dict[str, Any]],
    precision: str,
):
    """Quantized slow-hop reduce: full-precision reduce *within* the tier
    first (so the fast hop loses nothing), encode the per-tier partial,
    ONE inter-tier exchange of the encoded partials, decode, and combine
    across tiers with error-compensated (Kahan) summation. Deterministic
    end to end, so the result is bit-stable run-to-run."""
    from metrics_tpu.parallel import quantize

    flat = jnp.asarray(flat)
    n = int(flat.size)
    item = np.dtype(flat.dtype).itemsize
    members = topo.my_tier_ranks
    k = len(members)

    t0 = time.monotonic()
    block = jnp.asarray(
        _tier_collective(transport, flat, members, timeout, "tier intra-gather")
    )
    _account_hop(stats, topo, "intra", n * item * (k - 1), k, time.monotonic() - t0)

    if fx in ("sum", "mean"):
        partial = jnp.sum(block.astype(jnp.float32), axis=0)
    elif fx == "max":
        partial = jnp.max(block, axis=0).astype(jnp.float32)
    else:
        partial = jnp.min(block, axis=0).astype(jnp.float32)
    wire = quantize.encode(partial, precision)
    enc_n = int(wire.size)
    wire_item = np.dtype(wire.dtype).itemsize
    if topo.is_leader:
        t0 = time.monotonic()
        inter = _tier_collective(transport, wire, topo.leaders, timeout, "tier inter-exchange")
        _account_hop(
            stats, topo, "inter",
            enc_n * wire_item * (topo.n_tiers - 1), topo.n_tiers,
            time.monotonic() - t0,
        )
        bc_payload = jnp.asarray(inter).reshape(-1)
        actual_inter = enc_n * wire_item * (topo.n_tiers - 1)
    else:
        bc_payload = jnp.zeros((topo.n_tiers * enc_n,), wire.dtype)
        actual_inter = 0
    _bump_stats(
        stats, "inter_tier_bytes_saved",
        n * item * (len(topo.live) - k) - actual_inter,
    )
    t0 = time.monotonic()
    bc = _tier_collective(transport, bc_payload, members, timeout, "tier broadcast")
    _account_hop(
        stats, topo, "intra",
        int(bc_payload.size) * wire_item * (k - 1), k, time.monotonic() - t0,
    )
    rows = jnp.asarray(bc)[0].reshape(topo.n_tiers, enc_n)
    partials = jnp.asarray(quantize.decode(rows, n, jnp.float32, precision))
    if fx == "sum":
        combined = quantize.kahan_sum(partials)
    elif fx == "mean":
        combined = quantize.kahan_sum(partials) / len(topo.live)
    elif fx == "max":
        combined = jnp.max(partials, axis=0)
    else:
        combined = jnp.min(partials, axis=0)
    return combined.astype(flat.dtype)


def host_sync_state_bucketed(
    state: Dict[str, Any],
    reductions: Dict[str, Any],
    words: Optional[np.ndarray] = None,
    timeout: Optional[float] = None,
    plan: Optional[SyncPlan] = None,
    sync_precision: Optional[str] = None,
    stats: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Fused payload sync of a whole (possibly collection-combined) state.

    Caller contract: the gathered health ``words`` have been *verified*
    (``host_sync_state`` does this) — the plan assumes schema equality,
    non-empty cat states, clean CatBuffers, AND an agreed tier topology /
    payload precision across ranks (the v5 header columns). Issues exactly
    one ``process_allgather`` per reduce bucket and per cat bucket (plus the
    per-leaf cost of callable-``fx`` fallbacks, and one length-vector gather
    only when the schema outgrows the header's ``CAT_LENGTH_SLOTS``).

    When a tier map is configured (``parallel/tiering.py``) and a subset
    transport is available, each bucket's flat world gather is replaced by
    the two-level schedule (``core/plan.py``'s tier dimension): intra-tier
    gather → ONE inter-tier exchange between tier leaders → intra-tier
    broadcast. Full precision moves the raw blocks, so results stay
    bit-identical to the flat gather; ``sync_precision`` ("bf16"/"int8",
    explicit opt-in threaded from the Metric) encodes only the inter-tier
    wire, with reduce buckets reduced within the tier first and recombined
    across tiers via Kahan summation. ``stats`` (a ``sync``-domain counter
    dict) receives the per-hop byte counters.
    """
    from metrics_tpu.core import plan as plan_mod
    from metrics_tpu.parallel.quantize import validate_sync_precision
    from metrics_tpu.parallel.resilience import effective_world
    from metrics_tpu.parallel.sync import _process_allgather, host_sync_leaf

    world = effective_world()
    if plan is None:
        plan = build_sync_plan(state, reductions)
    precision = validate_sync_precision(sync_precision)
    sched = plan_mod.tier_schedule_for(plan)
    topo = sched.topology if sched is not None else None
    transport = sched.transport if sched is not None else None
    out: Dict[str, Any] = {}

    # ---- dynamic input: per-rank row counts for every cat-family leaf ----
    n_cat = len(plan.cat_leaves)
    lengths: Optional[np.ndarray] = None
    if n_cat:
        if words is not None:
            lengths = header_cat_lengths(words, n_cat)
        if lengths is None:
            kinds = {"catbuf": "catbuf", "list": "list"}
            local = np.asarray(
                [cat_row_count(state[s.name], kinds.get(s.kind, "leaf")) for s in plan.cat_leaves],
                np.int32,
            )
            lengths = np.asarray(_process_allgather(jnp.asarray(local), timeout=timeout))
        lengths = np.asarray(lengths, dtype=np.int64)

    # ---- reduce buckets: one collective per (dtype, fx) ------------------
    for (_dtype, fx), specs in plan.reduce_buckets.items():
        flat = jnp.concatenate([jnp.asarray(state[s.name]).reshape(-1) for s in specs])
        if flat.size == 0:
            for s in specs:
                out[s.name] = jnp.asarray(state[s.name])
            continue
        if topo is None:
            gathered = _process_allgather(flat, timeout=timeout)  # [world, total]
            reduced = _REDUCERS[fx](gathered)
        elif precision is not None and jnp.issubdtype(np.dtype(flat.dtype), np.floating):
            reduced = _tiered_quantized_reduce(
                flat, fx, topo, transport, timeout, stats, precision
            )
        else:
            gathered = _tiered_allgather(flat, topo, transport, timeout, stats)
            reduced = _REDUCERS[fx](gathered)
        off = 0
        for s in specs:
            out[s.name] = reduced[off : off + s.item_size].reshape(s.item_shape)
            off += s.item_size

    # ---- cat buckets: one padded ragged collective per dtype -------------
    for _dtype, specs in plan.cat_buckets.items():
        rows = lengths[:, [s.cat_index for s in specs]]  # [world, k]
        elems = rows * np.asarray([s.item_size for s in specs], np.int64)
        totals = elems.sum(axis=1)
        max_total = int(totals.max()) if totals.size else 0
        parts = []
        for s in specs:
            _n_rows, flat = _local_flat_rows(state[s.name], s)
            # plan dtype = the schema hash's dtype rule (first element for
            # lists). A heterogeneous list whose local concat promoted past
            # it is cast back: the cross-rank collective must be well-formed
            # and rank-symmetric, and the schema check only pins the
            # first-element dtype (the per-leaf path has the same blind spot
            # — it would feed dtype-divergent payloads straight into the
            # gather). Homogeneous lists — the supported contract — no-op.
            parts.append(flat if flat.dtype == s.dtype else flat.astype(s.dtype))
        local_flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if max_total == 0:
            # nothing to move anywhere (every rank's rows are empty): skip the
            # collective symmetrically (max_total is identical on all ranks)
            gathered = jnp.zeros((world, 0), local_flat.dtype)
        elif topo is None:
            padded = jnp.pad(local_flat, (0, max_total - int(local_flat.size)))
            gathered = _process_allgather(padded, timeout=timeout)  # [world, max_total]
        else:
            padded = jnp.pad(local_flat, (0, max_total - int(local_flat.size)))
            gathered = _tiered_allgather(
                padded, topo, transport, timeout, stats, precision
            )  # [world, max_total]; slow hop encoded iff precision + float dtype
        for j, s in enumerate(specs):
            pieces = []
            for r in range(world):
                start = int(elems[r, :j].sum())
                n = int(elems[r, j])
                pieces.append(gathered[r, start : start + n].reshape((int(rows[r, j]),) + s.item_shape))
            out[s.name] = _assemble_cat(s, pieces, state[s.name], world)

    # ---- unplannable leaves: per-leaf path (prechecks already done) ------
    for s in plan.fallback:
        out[s.name] = host_sync_leaf(state[s.name], s.fx, precheck=False, timeout=timeout)

    return out
