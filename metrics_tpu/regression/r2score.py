"""Deprecated import-path alias for :class:`R2Score`.

Parity shim for the reference's ``torchmetrics/regression/r2score.py``
(deprecated in its v0.5: ``r2score`` renamed ``r2_score``): importing from
this module warns once and hands back the real class.
"""
from typing import Any

from metrics_tpu.regression.r2 import R2Score as _R2Score
from metrics_tpu.utils.prints import rank_zero_deprecation


class R2Score(_R2Score):
    """Deprecated alias of :class:`metrics_tpu.regression.r2.R2Score`.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.regression.r2score import R2Score
        >>> r2 = R2Score()
        >>> r2.update(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))
        >>> print(round(float(r2.compute()), 4))
        0.9486
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_deprecation(
            "`metrics_tpu.regression.r2score.R2Score` is a deprecated alias;"
            " import `R2Score` from `metrics_tpu` instead."
        )
        super().__init__(*args, **kwargs)
