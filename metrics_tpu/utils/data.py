"""Array utilities shared by every metric.

TPU-native analogue of the reference's ``torchmetrics/utilities/data.py:21-227``.
All ops are pure jnp and jit-safe unless noted; ``get_group_indexes`` is the one
host-side helper (ragged output) — :mod:`metrics_tpu.ops.segment` holds the
jittable segment-op alternative used by retrieval metrics.
"""
from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

METRIC_EPS = 1e-6


def is_traced(x: Any) -> bool:
    """True when ``x`` is an abstract tracer (inside jit/scan/vmap tracing).

    The single place the package touches ``jax.core.Tracer`` (an accessor
    path newer JAX releases may move/deprecate) — every other site goes
    through this helper so one edit absorbs a future API move (ADVICE r4).
    """
    return isinstance(x, jax.core.Tracer)


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (possibly list- or CatBuffer-valued) state along dim 0."""
    from metrics_tpu.core.cat_buffer import CatBuffer

    if isinstance(x, CatBuffer):
        if x.buffer is None or (not is_traced(x.count) and len(x) == 0):
            raise ValueError("No samples to concatenate")
        return x.values()
    x = list(x) if isinstance(x, (list, tuple)) else [x]
    if not x:
        raise ValueError("No samples to concatenate")
    x = [y[None] if y.ndim == 0 else y for y in map(jnp.asarray, x)]
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    return [item for sublist in x for item in sublist]


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert integer labels ``[N, d1, ...]`` to one-hot ``[N, C, d1, ...]``.

    Mirrors the reference's ``utilities/data.py:44-75`` but as a broadcast
    compare (XLA fuses it; no scatter needed).
    """
    label_tensor = jnp.asarray(label_tensor)
    if num_classes is None:
        num_classes = int(jnp.max(label_tensor)) + 1  # data-dependent: eager only
    classes = jnp.arange(num_classes).reshape((num_classes,) + (1,) * label_tensor.ndim)
    onehot = (label_tensor[None] == classes).astype(jnp.int32)
    return jnp.moveaxis(onehot, 0, 1)  # [C, N, ...] -> [N, C, ...]


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binarize a score tensor: 1 where a value is among the top-k along ``dim``.

    Analogue of ``utilities/data.py:78-101``. The hot k=1 case (every
    Accuracy/StatScores step) is an argmax one-hot — a sort-based ``top_k``
    here cost ~124 µs/step on a [2048, 10] batch vs ~0 for the comparison
    formulation (sorts are the slow path on both TPU and CPU backends).
    """
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    if topk == 1:
        # argmax matches lax.top_k's total order exactly (first NaN position
        # if any NaN, else first max on ties) without the sort that made this
        # the hot path's dominant cost
        mask = jax.nn.one_hot(jnp.argmax(moved, axis=-1), moved.shape[-1], dtype=jnp.int32)
    else:
        _, idx = jax.lax.top_k(moved, topk)
        mask = jnp.zeros(moved.shape, dtype=jnp.int32)
        mask = jnp.put_along_axis(mask, idx, 1, axis=-1, inplace=False)
    return jnp.moveaxis(mask, -1, dim)


def to_categorical(tensor: Array, argmax_dim: int = 1) -> Array:
    """Probabilities/one-hot ``[N, C, ...]`` -> integer labels ``[N, ...]``."""
    return jnp.argmax(tensor, axis=argmax_dim)


def get_num_classes(preds: Array, target: Array, num_classes: Optional[int] = None) -> int:
    """Infer the number of classes from data (eager-only: reads values).

    Analogue of reference ``utilities/data.py:122-151``.
    """
    from metrics_tpu.utils.prints import rank_zero_warn

    num_target_classes = int(jnp.max(target)) + 1
    num_pred_classes = int(jnp.max(preds)) + 1
    num_all_classes = max(num_target_classes, num_pred_classes)
    if num_classes is None:
        num_classes = num_all_classes
    elif num_classes != num_all_classes:
        rank_zero_warn(
            f"You have set {num_classes} number of classes which is different from predicted "
            f"({num_pred_classes}) and target ({num_target_classes}) number of classes",
            RuntimeWarning,
        )
    return num_classes


def get_group_indexes(indexes: Array) -> List[Array]:
    """Group row positions by query id (host-side, ragged output).

    Analogue of ``utilities/data.py:203-227``. Eager-only: retrieval metrics'
    jitted path uses sorted segment ops instead (``metrics_tpu/ops/segment.py``).
    """
    indexes = np.asarray(indexes)
    res: dict = {}
    for i, idx in enumerate(indexes.tolist()):
        res.setdefault(idx, []).append(i)
    return [jnp.asarray(x, dtype=jnp.int32) for x in res.values()]


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    wrong_dtype: Optional[Union[type, tuple]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all ``dtype`` leaves of a collection.

    Analogue of ``utilities/data.py:153-200``.
    """
    from metrics_tpu.core.cat_buffer import CatBuffer

    if isinstance(data, dtype) and (wrong_dtype is None or not isinstance(data, wrong_dtype)):
        return function(data, *args, **kwargs)
    if isinstance(data, CatBuffer):
        return CatBuffer(
            data.capacity,
            None if data.buffer is None else apply_to_collection(data.buffer, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs),
            apply_to_collection(data.count, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs),
            apply_to_collection(data.overflowed, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs),
        )
    if isinstance(data, Mapping):
        return type(data)(
            {k: apply_to_collection(v, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for k, v in data.items()}
        )
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return type(data)(*(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data))
    if isinstance(data, (list, tuple)):
        return type(data)(apply_to_collection(d, dtype, function, *args, wrong_dtype=wrong_dtype, **kwargs) for d in data)
    return data


def _bincount(x: Array, minlength: int) -> Array:
    """Static-shape bincount: counts of each value in ``[0, minlength)``.

    jit-safe replacement for ``torch.bincount`` used by confusion-matrix style
    scatter accumulation; lowers to one-hot matmul-free segment sum on TPU.
    """
    return jnp.zeros(minlength, dtype=jnp.int32).at[x.astype(jnp.int32)].add(1)


def allclose(a: Array, b: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    return bool(jnp.allclose(a, b, rtol=rtol, atol=atol))
