"""Cross-rank journal symmetry over LockstepWorld: both ranks record the
SAME event sequence for a blocking and an overlapped sync round (epochs
aligned), and degradation events land symmetrically. This is the journal's
core contract — the trace exporter's cross-rank correlation (and the
``guarded-telemetry-emit`` lint rule backing it) only mean something if the
per-rank event streams actually line up."""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.parallel.async_sync as async_mod
import metrics_tpu.parallel.sync as sync_mod
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability import journal
from metrics_tpu.parallel.bucketing import clear_sync_plan_cache
from metrics_tpu.parallel.health import reset_channel_health
from tests.helpers.fake_world import LockstepWorld

WORLD = 2


@pytest.fixture(autouse=True)
def _fresh_channel_and_plans():
    clear_sync_plan_cache()
    reset_channel_health()
    yield
    clear_sync_plan_cache()
    reset_channel_health()


@pytest.fixture
def lockstep(monkeypatch):
    world = LockstepWorld(WORLD)
    monkeypatch.setattr(jax, "process_count", lambda: world.world)
    monkeypatch.setattr(sync_mod, "_raw_process_allgather", world.allgather)
    monkeypatch.setattr(async_mod, "_get_executor", world.executor_for_current_rank)
    monkeypatch.setattr(async_mod, "_current_domain", world.rank_domain)
    # journal rank seam: events attribute to the fake rank's thread-local
    # identity (the background lanes adopt it via the executor initializer)
    prev = journal.set_rank_provider(lambda: world.rank_domain() or 0)
    yield world
    journal.set_rank_provider(prev)
    world.shutdown_executors()


class _Sum(Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total


def rank_kinds(rank, exclude=("sync.plan", "plan.build", "plan.hit")):
    """This rank's (kind, epoch) sequence. ``sync.plan`` and the execution
    plan store's ``plan.build``/``plan.hit`` are excluded: the plan cache is
    per PROCESS in production, but LockstepWorld's fake ranks share one
    module-level cache, so which fake rank records the one build (and which
    records a hit) is a harness artifact, not a protocol fact."""
    return [
        (e.kind, e.fields.get("sync_epoch"))
        for e in journal.events(rank=rank)
        if e.kind not in exclude
    ]


def test_blocking_sync_journals_identically_on_both_ranks(lockstep):
    journal.enable()

    def body(rank):
        m = _Sum(sync_timeout=0)
        m.distributed_available_fn = lambda: True
        m.update(jnp.asarray(float(rank + 1)))
        m.sync()
        m.unsync()
        return float(np.asarray(m.total))

    lockstep.run(body)
    seq0, seq1 = rank_kinds(0), rank_kinds(1)
    assert seq0 == seq1
    assert ("sync.gather", 0) in seq0  # blocking = epoch 0


def test_overlapped_round_journals_identically_with_aligned_epochs(lockstep):
    journal.enable()

    def body(rank):
        m = _Sum(sync_timeout=0)
        m.distributed_available_fn = lambda: True
        m.update(jnp.asarray(float(rank + 1)))
        m.sync(blocking=False)          # launch
        m.update(jnp.asarray(10.0))     # post-snapshot delta (stale resolve)
        # compute() resolves the round (snapshot policy) inside its own
        # sync_context, which also restores the local accumulation on exit
        return float(np.asarray(m.compute()))

    values = lockstep.run(body)
    assert values[0] == values[1] == 3.0  # the consistent world cut
    seq0, seq1 = rank_kinds(0), rank_kinds(1)
    assert seq0 == seq1, (seq0, seq1)
    kinds = [k for k, _ in seq0]
    assert "sync.launch" in kinds and "sync.resolve" in kinds
    assert kinds.index("sync.launch") < kinds.index("sync.resolve")
    # epochs aligned: the launch and resolve of round 1 agree on both ranks
    launch_epochs = [e for k, e in seq0 if k == "sync.launch"]
    resolve_epochs = [e for k, e in seq0 if k == "sync.resolve"]
    assert launch_epochs == resolve_epochs == [1]
    # the resolve observed the post-snapshot update and said so
    resolve = [e for e in journal.events(rank=0, kinds=("sync.resolve",))][0]
    assert resolve.fields["stale"] is True
    assert resolve.fields["verdict"] == "stale:snapshot"
    assert resolve.fields["gather_s"] >= 0.0


def test_degradation_events_are_symmetric(lockstep):
    """A symmetric typed failure (strict update-count skew) degrades under
    on_error='local' with the SAME health.failure + degrade.local events on
    both ranks."""
    journal.enable()

    def body(rank):
        m = _Sum(sync_timeout=0, sync_on_error="local")
        m.sync_strict_update_count = True
        m.distributed_available_fn = lambda: True
        for _ in range(rank + 1):  # rank 1 updates twice: update-count skew
            m.update(jnp.asarray(1.0))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m.sync()
        assert m._sync_degraded
        return m.telemetry()["health"]

    healths = lockstep.run(body)
    for rank in (0, 1):
        kinds = [k for k, _ in rank_kinds(rank)]
        assert kinds == ["sync.gather", "health.failure", "degrade.local"], kinds
    assert rank_kinds(0) == rank_kinds(1)
    for h in healths:
        assert h["sync_failures"] == 1 and h["degraded"] == 1
        assert h["errors"] == {"StateDivergenceError": 1}


def test_exported_trace_shows_background_lane_overlapping_step(lockstep):
    """End-to-end acceptance: export a sync_mode='overlap' run and find the
    background gather on its own track with sync_epoch-correlated events
    identical across ranks."""
    import json

    from metrics_tpu.observability.trace_export import SYNC_LANE, chrome_trace

    journal.enable()

    def body(rank):
        m = _Sum(sync_timeout=0, sync_mode="overlap")
        m.distributed_available_fn = lambda: True
        for interval in range(3):
            for _ in range(2):
                m.update(jnp.asarray(float(rank + 1)))
            m.compute()  # resolve previous round, relaunch
        m.unsync()  # drain the tail round
        return m.sync_stats()["resolved"]

    resolved = lockstep.run(body)
    assert min(resolved) >= 1
    trace = chrome_trace()
    json.dumps(trace)  # valid chrome-trace JSON
    gathers = [t for t in trace["traceEvents"]
               if t["ph"] == "X" and t["tid"] == SYNC_LANE]
    assert {t["pid"] for t in gathers} == {0, 1}
    by_rank = {
        r: sorted(t["args"]["sync_epoch"] for t in gathers if t["pid"] == r)
        for r in (0, 1)
    }
    assert by_rank[0] == by_rank[1] and by_rank[0]  # correlated epochs
