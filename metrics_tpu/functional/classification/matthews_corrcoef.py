"""Matthews correlation coefficient — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/matthews_corrcoef.py:22-78``.
"""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_update

_matthews_corrcoef_update = _confusion_matrix_update


def _matthews_corrcoef_compute(confmat: Array) -> Array:
    tk = jnp.sum(confmat, axis=1).astype(jnp.float32)
    pk = jnp.sum(confmat, axis=0).astype(jnp.float32)
    c = jnp.trace(confmat).astype(jnp.float32)
    s = jnp.sum(confmat).astype(jnp.float32)
    return (c * s - jnp.sum(tk * pk)) / (
        jnp.sqrt(s ** 2 - jnp.sum(pk * pk)) * jnp.sqrt(s ** 2 - jnp.sum(tk * tk))
    )


def matthews_corrcoef(
    preds: Array,
    target: Array,
    num_classes: int,
    threshold: float = 0.5,
) -> Array:
    r"""Matthews correlation coefficient in one stateless call — the
    correlation between predicted and true labels off a full confusion
    matrix, robust under class imbalance (+1 perfect, 0 chance, −1 total
    disagreement; NaN on degenerate single-class marginals, matching
    sklearn). Functional twin of :class:`~metrics_tpu.MatthewsCorrcoef`.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import matthews_corrcoef
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> print(round(float(matthews_corrcoef(preds, target, num_classes=2)), 4))
        0.5774
    """
    confmat = _matthews_corrcoef_update(preds, target, num_classes, threshold)
    return _matthews_corrcoef_compute(confmat)
