"""SI_SNR module — analogue of reference ``torchmetrics/audio/si_snr.py`` (103 LoC)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.audio.si_snr import si_snr


class SI_SNR(Metric):
    r"""Scale-invariant signal-to-noise ratio, averaged over signals.

    Forward accepts ``preds``/``target`` of shape ``[..., time]``.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> float(SI_SNR()(preds, target))  # doctest: +ELLIPSIS
        15.09...
    """

    def __init__(
        self,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        self.add_state("sum_si_snr", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("total", jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        batch_vals = si_snr(preds=preds, target=target)
        self.sum_si_snr = self.sum_si_snr + jnp.sum(batch_vals)
        self.total = self.total + batch_vals.size

    def compute(self) -> Array:
        return self.sum_si_snr / self.total

    is_differentiable = True
