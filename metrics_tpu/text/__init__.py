from metrics_tpu.text.bert import BERTScore
from metrics_tpu.text.bleu import BLEUScore
from metrics_tpu.text.rouge import ROUGEScore
from metrics_tpu.text.wer import WER

__all__ = ["BERTScore", "BLEUScore", "ROUGEScore", "WER"]
