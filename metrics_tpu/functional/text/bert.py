"""BERTScore — analogue of reference
``torchmetrics/functional/text/bert.py:134-651``, restructured for XLA:

- **Static shapes, no DataLoader.** The reference length-sorts sentences and
  dynamically trims every batch to its longest sequence to save wall-time on
  GPU (``bert.py:103-126,625-626``); under XLA that forces a recompile per
  batch shape. Here every sentence pads to ``max_length`` once, the encoder
  jits once, and chunks of ``batch_size`` reuse the compiled program (the
  last chunk pads to a full batch, so there are exactly one or two program
  shapes).
- **The whole scoring path is one jitted function**: hidden-state selection,
  L2 normalization, special-token masking, the ``blpd,blrd->blpr`` cosine
  similarity, greedy max-matching and IDF weighting (reference
  ``bert.py:302-375``) fuse into a single XLA program.
- **Models are params pytrees + pure apply fns** (:mod:`metrics_tpu.models.bert`),
  not ``nn.Module``s; a HF torch checkpoint converts via
  :func:`metrics_tpu.models.bert.load_torch_bert_weights`. A custom model
  plugs in through ``user_forward_fn`` exactly like the reference's
  own-model example (``tm_examples/bert_score-own_model.py``).
- **Offline-first baselines.** Baseline rescaling reads a local csv/tsv
  (``baseline_path``) or an explicit array; ``baseline_url`` keeps the
  reference's URL fetch (``bert.py:411-449``) for connected machines, with
  failures degrading to a warning instead of killing the scoring run.
"""
import csv
import math
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.models.bert import BertConfig, bert_apply, bert_init, config_from_params
from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_warn

_CLS_ID, _SEP_ID, _PAD_ID = 101, 102, 0

# (tokenizer, jitted forward) per (model key, num_layers, all_layers)
_FORWARD_CACHE: Dict[Tuple, Tuple[Any, Callable]] = {}


class SimpleTokenizer:
    """Deterministic hash tokenizer used when no tokenizer is supplied.

    Lowercased word-ish tokens hashed into the vocab range, [CLS]/[SEP]
    framing and zero padding — shape-compatible with a BERT tokenizer so the
    whole pipeline (and tests) run without the ``transformers`` package.
    """

    def __init__(self, vocab_size: int = 30522, max_length: int = 512) -> None:
        self.vocab_size = vocab_size
        self.max_length = max_length

    def __call__(self, text: List[str], max_length: Optional[int] = None) -> Dict[str, np.ndarray]:
        import re
        import zlib

        max_length = max_length or self.max_length
        ids = np.full((len(text), max_length), _PAD_ID, dtype=np.int32)
        mask = np.zeros((len(text), max_length), dtype=np.int32)
        for row, sentence in enumerate(text):
            tokens = re.findall(r"[a-z0-9]+|[^\sa-z0-9]", sentence.lower())
            tokens = tokens[: max_length - 2]
            ids[row, 0] = _CLS_ID
            for col, tok in enumerate(tokens, start=1):
                # crc32: stable across processes/ranks (builtin hash() is
                # salted per process, which would desync distributed ranks)
                ids[row, col] = 1000 + zlib.crc32(tok.encode()) % (self.vocab_size - 1000)
            ids[row, len(tokens) + 1] = _SEP_ID
            mask[row, : len(tokens) + 2] = 1
        return {"input_ids": ids, "attention_mask": mask}


def _preprocess_text(
    text: List[str], tokenizer: Any, max_length: int = 512, own_tokenizer: bool = False
) -> Dict[str, np.ndarray]:
    """Tokenize to fixed [N, max_length] arrays (reference ``bert.py:34-82``,
    minus length sorting — static shapes make it pointless under XLA)."""
    if not own_tokenizer:
        out = tokenizer(
            text, padding="max_length", max_length=max_length, truncation=True, return_tensors="np"
        )
    else:
        try:
            out = tokenizer(text, max_length)
        except BaseException as e:  # noqa: B036 - mirror reference contract
            raise BaseException(f"Tokenization was not successful: {e}")
    return {
        "input_ids": np.asarray(out["input_ids"]),
        "attention_mask": np.asarray(out["attention_mask"]),
    }


def _special_token_mask(attention_mask: Array) -> Array:
    """Zero out [CLS] (position 0) and [SEP] (last attended position)."""
    processed = attention_mask.at[:, 0].set(0)
    sep_pos = jnp.argmax(jnp.cumsum(attention_mask, axis=-1) - 0.1, axis=-1)
    return processed.at[jnp.arange(attention_mask.shape[0]), sep_pos].set(0)


def _tokens_idf(input_ids: np.ndarray, attention_mask: np.ndarray) -> Dict[int, float]:
    """Inverse document frequencies over the reference corpus
    (reference ``bert.py:183-206``)."""
    num_sentences = input_ids.shape[0]
    counter: Counter = Counter()
    for row in range(num_sentences):
        counter.update(set(input_ids[row][attention_mask[row] > 0].tolist()))
    default = math.log((num_sentences + 1) / 1)
    idf = {idx: math.log((num_sentences + 1) / (occ + 1)) for idx, occ in counter.items()}

    class _IdfTable(dict):
        def __missing__(self, key: int) -> float:
            return default

    return _IdfTable(idf)


def _idf_matrix(input_ids: np.ndarray, idf_table: Dict[int, float]) -> np.ndarray:
    lookup = np.vectorize(lambda t: idf_table[int(t)])
    return lookup(input_ids).astype(np.float32)


def _embed_corpus(
    forward: Callable[[Array, Array], Array],
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    batch_size: int,
) -> Array:
    """Run the (jitted) forward in fixed-size chunks; returns [N, L, S, D]."""
    n = input_ids.shape[0]
    outs = []
    for start in range(0, n, batch_size):
        ids = input_ids[start : start + batch_size]
        mask = attention_mask[start : start + batch_size]
        pad = batch_size - ids.shape[0]
        if pad and n > batch_size:  # keep one compiled shape across chunks
            ids = np.concatenate([ids, np.zeros((pad,) + ids.shape[1:], ids.dtype)])
            mask = np.concatenate([mask, np.zeros((pad,) + mask.shape[1:], mask.dtype)])
            outs.append(forward(jnp.asarray(ids), jnp.asarray(mask))[: batch_size - pad])
        else:
            outs.append(forward(jnp.asarray(ids), jnp.asarray(mask)))
    return jnp.concatenate(outs, axis=0)


def _score_from_embeddings(
    pred_emb: Array,
    ref_emb: Array,
    pred_idf_scale: Array,
    ref_idf_scale: Array,
) -> Tuple[Array, Array, Array]:
    """Greedy-match P/R/F1 (reference ``bert.py:342-375``); jit-friendly."""
    cos_sim = jnp.einsum("blpd,blrd->blpr", pred_emb, ref_emb)
    precision = jnp.einsum("bls,bs->bl", jnp.max(cos_sim, axis=3), pred_idf_scale)
    recall = jnp.einsum("bls,bs->bl", jnp.max(cos_sim, axis=2), ref_idf_scale)
    denom = precision + recall
    f1 = jnp.where(denom > 0, 2 * precision * recall / jnp.where(denom == 0, 1.0, denom), 0.0)

    def to_layer_major(t: Array) -> Array:
        # [B, L] -> [L, B]; drop only the layer axis when single-layer so a
        # one-sentence batch still yields a per-sentence list
        t = t.swapaxes(0, 1)
        return t[0] if t.shape[0] == 1 else t

    return to_layer_major(precision), to_layer_major(recall), to_layer_major(f1)


def bundled_baseline_path(name: str = "example_en") -> str:
    """Path to a baseline csv shipped with the package.

    Only ``example_en`` ships today — a synthetic five-representation baseline
    matching the in-repo default BERT config, for tests and as a format
    template. Real baselines come from the official bert-score repo
    (``rescale_baseline/<lang>/<model>.tsv``; the reference downloads them
    over HTTP, ``functional/text/bert.py:411-449``) — fetch once on a
    connected machine, drop the file next to your run, and point
    ``baseline_path`` at it. See ``docs/api.md`` ("BERTScore baselines").
    """
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
                        "text", "baselines", f"{name}.csv")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no bundled baseline named {name!r} (looked at {path})")
    return path


def _read_baseline_csv(path: str) -> Array:
    with open(path) as handle:
        delimiter = "\t" if path.endswith(".tsv") else ","
        rows = [
            [float(item) for item in row]
            for idx, row in enumerate(csv.reader(handle, delimiter=delimiter))
            if idx > 0
        ]
    return jnp.asarray(rows)[:, 1:]


# official bert-score baseline tree (reference `functional/text/bert.py:407-425`)
_BASELINE_URL_BASE = "https://raw.githubusercontent.com/Tiiiger/bert_score/master/bert_score/rescale_baseline"


def _read_baseline_url(url: str, timeout: float = 30.0) -> Array:
    """Fetch a baseline csv/tsv over HTTP (reference `_read_csv_from_url`,
    `functional/text/bert.py:396-403`). Requires network access — offline
    runs should pass ``baseline_path`` (see ``bundled_baseline_path``)."""
    import io
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as response:  # noqa: S310 — user-supplied source, parity with reference
        text = response.read().decode("utf-8")
    delimiter = "\t" if url.endswith(".tsv") else ","
    rows = [
        [float(item) for item in row]
        for idx, row in enumerate(csv.reader(io.StringIO(text), delimiter=delimiter))
        if idx > 0
    ]
    return jnp.asarray(rows)[:, 1:]


def _rescale_with_baseline(
    precision: Array,
    recall: Array,
    f1: Array,
    baseline: Array,
    num_layers: Optional[int],
    all_layers: bool,
) -> Tuple[Array, Array, Array]:
    if num_layers is None and not all_layers:
        num_layers = -1
    metrics = jnp.stack([precision, recall, f1], axis=-1)
    scale = baseline[:, None, :] if all_layers else baseline[num_layers]
    metrics = (metrics - scale) / (1 - scale)
    return metrics[..., 0], metrics[..., 1], metrics[..., 2]


def _get_hash(model_name_or_path: Optional[str], num_layers: Optional[int], idf: bool) -> str:
    return f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"


def _default_model_forward(
    params: Dict[str, Any], config: BertConfig, num_layers: Optional[int], all_layers: bool
) -> Callable[[Array, Array], Array]:
    """Jitted in-framework BERT forward returning [B, L, S, D] unit vectors."""

    @jax.jit
    def fwd(input_ids: Array, attention_mask: Array) -> Array:
        hidden = bert_apply(params, input_ids, attention_mask, config=config)
        if all_layers:
            out = jnp.stack(hidden, axis=1)
        else:
            out = hidden[num_layers if num_layers is not None else -1][:, None]
        norm = jnp.linalg.norm(out, axis=-1, keepdims=True)
        out = out / jnp.where(norm > 0, norm, 1.0)  # zero vectors stay zero, not NaN
        return jnp.einsum("blsd,bs->blsd", out, _special_token_mask(attention_mask))

    return fwd


def _user_model_forward(
    model: Any, user_forward_fn: Optional[Callable]
) -> Callable[[Array, Array], Array]:
    """Wrap a user model/callable into the [B, L, S, D] unit-vector contract."""

    def fwd(input_ids: Array, attention_mask: Array) -> Array:
        batch = {"input_ids": input_ids, "attention_mask": attention_mask}
        out = user_forward_fn(model, batch) if user_forward_fn else model(**batch)
        out = jnp.asarray(out)
        if out.ndim != 3 or out.shape[0] != input_ids.shape[0] or out.shape[1] != input_ids.shape[1]:
            raise ValueError(
                "The model output must be a tensor of shape [batch_size, seq_len, model_dim] "
                f"i.e. [{input_ids.shape[0]}, {input_ids.shape[1]}, model_dim], but got {out.shape}."
            )
        out = out[:, None]
        norm = jnp.linalg.norm(out, axis=-1, keepdims=True)
        out = out / jnp.where(norm > 0, norm, 1.0)  # zero vectors stay zero, not NaN
        return jnp.einsum("blsd,bs->blsd", out, _special_token_mask(attention_mask))

    return fwd


def bert_score(
    predictions: Union[List[str], Dict[str, Any]],
    references: Union[List[str], Dict[str, Any]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    all_layers: bool = False,
    model: Optional[Any] = None,
    user_tokenizer: Any = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 4,  # reference default; inert here (no host DataLoader pool)
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
    baseline: Optional[Array] = None,
) -> Dict[str, Union[List[float], str]]:
    """BERTScore: greedy cosine matching of contextual embeddings.

    Args:
        predictions: candidate sentences, or a dict of ``input_ids`` /
            ``attention_mask`` arrays (already tokenized).
        references: reference sentences or tokenized dict.
        model_name_or_path: HF model name loaded via ``transformers`` (needs
            the package and a locally cached checkpoint).
        num_layers: hidden-state index to use (default: last).
        all_layers: score with every layer's representation.
        model: user model — a callable or pytree+``user_forward_fn`` pair.
        user_tokenizer: callable ``(List[str], max_length) -> dict`` of arrays.
        user_forward_fn: ``(model, batch_dict) -> [B, S, D]`` embeddings.
        idf: weight tokens by inverse document frequency over the references.
        max_length: pad/truncate length (static shape for jit).
        batch_size: chunk size for the embedding forward.
        rescale_with_baseline: linearly rescale with a per-layer baseline.
        baseline_path: local csv/tsv with baseline values.
        baseline_url: fetch the baseline csv/tsv over HTTP (reference
            `text/bert.py:142`); when neither path nor url is given and a
            ``model_name_or_path`` is set, the official bert-score tree is
            tried (``<base>/<lang>/<model>.tsv``). Offline runs should use
            ``baseline_path``.
        baseline: explicit baseline array ``[n_layers(+1), 3]``.

    Returns:
        dict with per-sentence ``precision``/``recall``/``f1`` lists
        (+ ``hash`` when ``return_hash``).

    Example:
        >>> predictions = ["hello there", "general kenobi"]
        >>> references = ["hello there", "master kenobi"]
        >>> score = bert_score(predictions=predictions, references=references)
        >>> sorted(score.keys())
        ['f1', 'precision', 'recall']
    """
    if len(predictions) != len(references):
        raise ValueError("Number of predicted and reference sentences must be the same!")

    _are_empty_lists = all(
        isinstance(text, list) and len(text) == 0 for text in (predictions, references)
    )
    if _are_empty_lists:
        rank_zero_warn("Predictions and references are empty.")
        output_dict: Dict[str, Union[List[float], str]] = {
            "precision": [0.0],
            "recall": [0.0],
            "f1": [0.0],
        }
        if return_hash:
            output_dict["hash"] = _get_hash(model_name_or_path, num_layers, idf)
        return output_dict

    # ---- resolve tokenizer + forward ------------------------------------
    # named/default models cache their (tokenizer, jitted forward) so repeated
    # bert_score calls — e.g. BERTScore.compute every step — reuse one
    # compiled program instead of reloading/reconverting/recompiling
    if model is not None:
        tokenizer = user_tokenizer or SimpleTokenizer(max_length=max_length)
        forward = _user_model_forward(model, user_forward_fn)
        own_tokenizer = True
    elif model_name_or_path is not None:
        if not _TRANSFORMERS_AVAILABLE:
            raise ValueError(
                "`bert_score` with a named pretrained model requires the `transformers` "
                "package. Pass `model`/`user_forward_fn` for a self-contained model instead."
            )
        cache_key = (model_name_or_path, num_layers, all_layers)
        cached = _FORWARD_CACHE.get(cache_key)
        if cached is None:
            from transformers import AutoModel, AutoTokenizer

            from metrics_tpu.models.bert import load_torch_bert_weights

            tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
            hf_model = AutoModel.from_pretrained(model_name_or_path)
            params = load_torch_bert_weights(hf_model.state_dict())
            config = config_from_params(params)
            if getattr(hf_model.config, "num_attention_heads", None):
                config.num_attention_heads = hf_model.config.num_attention_heads
            if num_layers is not None and num_layers > config.num_hidden_layers:
                raise ValueError(
                    f"num_layers={num_layers} is forbidden for {model_name_or_path}. "
                    f"Please use num_layers <= {config.num_hidden_layers}"
                )
            forward = _default_model_forward(params, config, num_layers, all_layers)
            _FORWARD_CACHE[cache_key] = (tokenizer, forward)
        else:
            tokenizer, forward = cached
        own_tokenizer = False
    else:
        rank_zero_warn(
            "No model specified — using the in-framework BERT encoder with deterministic "
            "random weights. The BERTScore mechanism is exact but scores are not comparable "
            "with pretrained-model numbers; pass `model_name_or_path` or `model`."
        )
        config = BertConfig()
        cache_key = ("__default__", num_layers, all_layers)
        cached = _FORWARD_CACHE.get(cache_key)
        if cached is None:
            forward = _default_model_forward(bert_init(config), config, num_layers, all_layers)
            _FORWARD_CACHE[cache_key] = (None, forward)
        else:
            forward = cached[1]
        tokenizer = user_tokenizer or SimpleTokenizer(config.vocab_size, max_length)
        own_tokenizer = True

    # ---- tokenize (host) -------------------------------------------------
    _are_valid_tensors = all(
        isinstance(text, dict) and "input_ids" in text for text in (predictions, references)
    )
    if _are_valid_tensors:
        pred_tok = {k: np.asarray(v) for k, v in predictions.items()}
        ref_tok = {k: np.asarray(v) for k, v in references.items()}
    else:
        pred_tok = _preprocess_text(list(predictions), tokenizer, max_length, own_tokenizer)
        ref_tok = _preprocess_text(list(references), tokenizer, max_length, own_tokenizer)

    # ---- IDF weighting (host table, device matrix) ----------------------
    host_special = lambda mask: np.asarray(_special_token_mask(jnp.asarray(mask)))  # noqa: E731
    pred_special = host_special(pred_tok["attention_mask"]).astype(np.float32)
    ref_special = host_special(ref_tok["attention_mask"]).astype(np.float32)
    if idf:
        idf_table = _tokens_idf(ref_tok["input_ids"], ref_tok["attention_mask"])
        pred_scale = _idf_matrix(pred_tok["input_ids"], idf_table) * pred_special
        ref_scale = _idf_matrix(ref_tok["input_ids"], idf_table) * ref_special
    else:
        pred_scale, ref_scale = pred_special, ref_special
    pred_scale = pred_scale / np.clip(pred_scale.sum(-1, keepdims=True), 1e-12, None)
    ref_scale = ref_scale / np.clip(ref_scale.sum(-1, keepdims=True), 1e-12, None)

    # ---- embed + score (device) -----------------------------------------
    pred_emb = _embed_corpus(forward, pred_tok["input_ids"], pred_tok["attention_mask"], batch_size)
    ref_emb = _embed_corpus(forward, ref_tok["input_ids"], ref_tok["attention_mask"], batch_size)
    precision, recall, f1 = _score_from_embeddings(
        pred_emb, ref_emb, jnp.asarray(pred_scale), jnp.asarray(ref_scale)
    )

    if rescale_with_baseline:
        if baseline is None and baseline_path is not None:
            baseline = _read_baseline_csv(baseline_path)
        if baseline is None and (baseline_url or (lang and model_name_or_path)):
            # explicit url, or the official bert-score tree for (lang, model)
            # — mirrors the reference's resolution chain
            # (`functional/text/bert.py:415-425`); fetch failures degrade to
            # the no-baseline warning instead of raising
            url = baseline_url or f"{_BASELINE_URL_BASE}/{lang}/{model_name_or_path}.tsv"
            try:
                baseline = _read_baseline_url(url)
            except Exception as err:  # noqa: BLE001 — offline/404 must not kill scoring
                rank_zero_warn(f"Baseline fetch from {url!r} failed ({err}).")
        if baseline is None:
            rank_zero_warn("Baseline was not successfully loaded. No baseline is going to be used.")
        else:
            precision, recall, f1 = _rescale_with_baseline(
                precision, recall, f1, baseline, num_layers, all_layers
            )

    output_dict = {
        "precision": np.asarray(precision).tolist(),
        "recall": np.asarray(recall).tolist(),
        "f1": np.asarray(f1).tolist(),
    }
    if return_hash:
        output_dict["hash"] = _get_hash(model_name_or_path, num_layers, idf)
    return output_dict
