"""metricslint collective-schedule pass: rule coverage over the schedule
fixture plus the invariant that the shipped parallel/ modules verify."""
import ast
import os

from metrics_tpu.analysis import analyze_paths, analyze_source
from metrics_tpu.analysis.schedule_pass import run_schedule_pass

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def findings_for(name: str):
    findings, errors = analyze_paths([os.path.join(FIXTURES, name)])
    assert not errors
    return findings


def by_function(findings):
    out = {}
    for f in findings:
        out.setdefault(f.owner, set()).add(f.rule)
    return out


def test_schedule_fixture_covers_every_rule():
    owners = by_function(findings_for("violating_schedule.py"))
    assert owners["rank_zero_extra_gather"] == {"rank-dependent-collective"}
    assert owners["data_dependent_gather"] == {"data-dependent-collective"}
    assert owners["early_exit_desync"] == {"data-dependent-collective"}
    assert owners["collective_in_handler"] == {"collective-in-handler"}
    assert "nondeterministic-collective-order" in owners["set_iteration_order"]
    assert owners["transitive_rank_dependence"] == {"rank-dependent-collective"}
    # symmetric branching (gathered results, world size, schema) is clean
    assert "clean_symmetric_paths" not in owners


def test_collective_result_is_symmetric():
    src = '''
import jax.numpy as jnp

def _process_allgather(x, timeout=None):
    return x

def uneven_gather(result):
    shapes = _process_allgather(jnp.asarray(result.shape))
    if (shapes == shapes[0]).all():
        return _process_allgather(result)       # clean: gathered guard
    return _process_allgather(jnp.pad(result, (0, 3)))
'''
    assert run_schedule_pass(ast.parse(src), "<s>") == []


def test_dict_iteration_order_is_schema_but_elements_are_data():
    src = '''
def _process_allgather(x, timeout=None):
    return x

def per_leaf(state):
    out = {}
    for name, value in state.items():
        if len(value) == 0:        # local-data guard over a collective
            continue
        out[name] = _process_allgather(value)
    return out
'''
    findings = run_schedule_pass(ast.parse(src), "<s>")
    # the items() loop itself is fine; the empty-skip is the finding
    assert {f.rule for f in findings} == {"data-dependent-collective"}


def test_finally_block_counts_as_handler():
    src = '''
def _process_allgather(x, timeout=None):
    return x

def f(x):
    try:
        return _process_allgather(x)
    finally:
        _process_allgather(x)
'''
    findings = run_schedule_pass(ast.parse(src), "<s>")
    assert any(f.rule == "collective-in-handler" for f in findings)


def test_in_jit_collectives_are_tracked():
    src = '''
import jax

def f(value, axis_name, fx):
    if len(value) == 0:
        return value
    return jax.lax.psum(value, axis_name)
'''
    findings = run_schedule_pass(ast.parse(src), "<s>")
    assert {f.rule for f in findings} == {"data-dependent-collective"}


def test_async_round_api_is_known_emitting():
    """launch/resolve/drain of an overlapped round schedule or consume
    collectives, so their call sites are checked exactly like a direct
    gather — a per-rank-data guard over any of them is a finding, and a
    resolved round's result washes taint like any collective result."""
    src = '''
def maybe_launch(state, reductions):
    if len(state) > 0:
        return launch_round(state, reductions, update_count=1, epoch=1)
    return None

def maybe_resolve(round_, value):
    if value.sum() > 0:
        return resolve_round(round_)
    return None

def rank_zero_drain(round_):
    import jax
    if jax.process_index() == 0:
        drain_round(round_)

def clean_resolve(round_):
    synced, wait_s = resolve_round(round_)
    if synced.sum() > 0:      # collective result: symmetric guard
        return host_sync_state(synced, {})
    return synced
'''
    findings = run_schedule_pass(ast.parse(src), "<s>")
    owners = by_function(findings)
    assert owners["maybe_launch"] == {"data-dependent-collective"}
    assert owners["maybe_resolve"] == {"data-dependent-collective"}
    assert owners["rank_zero_drain"] == {"rank-dependent-collective"}
    assert "clean_resolve" not in owners


def test_shipped_parallel_modules_verify():
    """The tentpole invariant: every reachable path in parallel/{sync,health,
    bucketing,async_sync}.py emits collectives in rank/data-independent
    order — the overlapped-sync module's launch/resolve/drain sites
    included (KNOWN_EMITTING_CALLS). The deliberate exceptions (trace-time
    SPMD branches in sync_in_jit, the channel-suspect refusal in
    host_sync_state) carry explicit, commented suppressions and anything
    NEW must fail this test."""
    import metrics_tpu

    parallel = os.path.join(os.path.dirname(metrics_tpu.__file__), "parallel")
    findings, errors = analyze_paths([parallel])
    assert not errors
    assert findings == [], "\n".join(f.format() for f in findings)
    # and the suppressions are real: stripping them resurfaces the findings
    sync_path = os.path.join(parallel, "sync.py")
    src = open(sync_path).read().replace("# metricslint: disable", "# stripped")
    resurfaced = analyze_source(src, sync_path)
    assert any(f.rule == "data-dependent-collective" for f in resurfaced)
