"""MetricTracker — track a metric across steps/epochs.

Behavioral analogue of the reference's
``torchmetrics/wrappers/tracker.py:23-127``.
"""
from copy import deepcopy
from typing import Any, Tuple, Union

import jax.numpy as jnp

from metrics_tpu.core.metric import Metric


class MetricTracker(list):
    """Keeps one metric clone per ``increment()``; exposes best/all values.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricTracker
        >>> tracker = MetricTracker(Accuracy(num_classes=3))
        >>> for preds in ([0, 2, 1], [0, 1, 1]):
        ...     tracker.increment()
        ...     _ = tracker(jnp.asarray(preds), jnp.asarray([0, 1, 1]))
        >>> best, step = tracker.best_metric(return_step=True)
        >>> print(round(float(best), 4), int(step))
        1.0 1
    """

    def __init__(self, metric: Metric, maximize: bool = True) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise TypeError(f"metric arg need to be an instance of a metrics_tpu metric but got {metric}")
        self._base_metric = metric
        self.maximize = maximize
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        return len(self)

    def increment(self) -> None:
        """Start tracking a fresh clone of the base metric."""
        self._increment_called = True
        self.append(deepcopy(self._base_metric))

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        return self[-1](*args, **kwargs)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        self._check_for_increment("compute")
        return self[-1].compute()

    def compute_all(self) -> jnp.ndarray:
        self._check_for_increment("compute_all")
        return jnp.stack([metric.compute() for metric in self], axis=0)

    def reset(self) -> None:
        self[-1].reset()

    def reset_all(self) -> None:
        for metric in self:
            metric.reset()

    def best_metric(self, return_step: bool = False) -> Union[float, Tuple[int, float]]:
        """Best tracked value (and optionally which step produced it)."""
        vals = self.compute_all()
        idx = int(jnp.argmax(vals) if self.maximize else jnp.argmin(vals))
        best = float(vals[idx])
        if return_step:
            return best, idx
        return best

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called")
