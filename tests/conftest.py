"""Global test configuration.

Forces an 8-device virtual CPU platform BEFORE any backend initializes, so
every test can exercise multi-device meshes (`jax.sharding.Mesh` + shard_map
collectives) without TPU hardware — the analogue of the reference's 2-process
gloo simulation (`tests/helpers/testers.py:33-57`).

jax may already be *imported* (preloaded interpreter-wide), so env vars alone
are too late for `jax_platforms`; `jax.config.update` works until the first
backend is actually created. XLA_FLAGS is read at CPU-client creation, which
also hasn't happened yet at conftest load time.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, "virtual CPU mesh failed to initialize"

# The suite's wall-time is dominated by XLA compiles; cache them on disk so
# reruns (driver, CI, judge) skip recompilation. Repo-local dir, gitignored.
# Tests that assert cache behavior use their own dirs in subprocesses and
# are unaffected. Opt out with METRICS_TPU_TEST_NO_COMPILE_CACHE=1.
if not os.environ.get("METRICS_TPU_TEST_NO_COMPILE_CACHE"):
    from metrics_tpu.utils import compile_cache  # noqa: E402

    compile_cache.enable(
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"),
        min_compile_seconds=1.0,
    )
