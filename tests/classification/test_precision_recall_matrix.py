"""Precision / Recall full input-type × average × mdmc × ignore_index matrix.

Mirror of the reference's `tests/classification/test_precision_recall.py`:
13-row input grid × average ∈ {micro, macro, none, weighted, samples} ×
ignore_index ∈ {None, 0}, against sklearn's precision_score / recall_score
composed after the shared input formatting, plus the wrong-params,
zero-division, and no-support edge cases.
"""
from functools import partial
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import precision_score, recall_score

from metrics_tpu import Precision, Recall
from metrics_tpu.functional import precision, precision_recall, recall
from metrics_tpu.utils.checks import _input_format_classification
from tests.classification.inputs import (
    _input_binary,
    _input_binary_logits,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_logits as _input_mcls_logits,
    _input_multiclass_prob as _input_mcls_prob,
    _input_multidim_multiclass as _input_mdmc,
    _input_multidim_multiclass_prob as _input_mdmc_prob,
    _input_multilabel as _input_mlb,
    _input_multilabel_logits as _input_mlb_logits,
    _input_multilabel_prob as _input_mlb_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_prec_recall(preds, target, sk_fn, num_classes, average, multiclass, ignore_index, mdmc_average=None):
    """Reference `test_precision_recall.py:43-67`, with the repo formatter."""
    if average == "none":
        average = None
    if num_classes == 1:
        average = "binary"

    labels = list(range(num_classes))
    try:
        labels.remove(ignore_index)
    except ValueError:
        pass

    sk_preds, sk_target, _ = _input_format_classification(
        preds, target, THRESHOLD, num_classes=num_classes, multiclass=multiclass
    )
    sk_preds, sk_target = np.asarray(sk_preds), np.asarray(sk_target)

    sk_scores = sk_fn(sk_target, sk_preds, average=average, zero_division=0, labels=labels)

    if len(labels) != num_classes and not average:
        sk_scores = np.insert(sk_scores, ignore_index, np.nan)

    return sk_scores


def _sk_prec_recall_multidim_multiclass(
    preds, target, sk_fn, num_classes, average, multiclass, ignore_index, mdmc_average
):
    """Reference `test_precision_recall.py:70-92`."""
    preds, target, _ = _input_format_classification(
        preds, target, threshold=THRESHOLD, num_classes=num_classes, multiclass=multiclass
    )
    preds, target = np.asarray(preds), np.asarray(target)

    if mdmc_average == "global":
        preds = np.moveaxis(preds, 1, 2).reshape(-1, preds.shape[1])
        target = np.moveaxis(target, 1, 2).reshape(-1, target.shape[1])
        return _sk_prec_recall(preds, target, sk_fn, num_classes, average, False, ignore_index)
    if mdmc_average == "samplewise":
        scores = []
        for i in range(preds.shape[0]):
            scores_i = _sk_prec_recall(preds[i].T, target[i].T, sk_fn, num_classes, average, False, ignore_index)
            scores.append(np.expand_dims(scores_i, 0))
        return np.concatenate(scores).mean(axis=0)
    raise ValueError(mdmc_average)


@pytest.mark.parametrize("metric, fn_metric", [(Precision, precision), (Recall, recall)])
@pytest.mark.parametrize(
    "average, mdmc_average, num_classes, ignore_index, match_str",
    [
        ("wrong", None, None, None, "`average`"),
        ("micro", "wrong", None, None, "`mdmc"),
        ("macro", None, None, None, "number of classes"),
        ("macro", None, 1, 0, "ignore_index"),
    ],
)
def test_wrong_params(metric, fn_metric, average, mdmc_average, num_classes, ignore_index, match_str):
    """Invalid average/mdmc_average/num_classes/ignore_index raise with the
    reference's messages (`test_precision_recall.py:96-131`)."""
    with pytest.raises(ValueError, match=match_str):
        metric(average=average, mdmc_average=mdmc_average, num_classes=num_classes, ignore_index=ignore_index)
    with pytest.raises(ValueError, match=match_str):
        fn_metric(
            jnp.asarray(_input_binary.preds[0]),
            jnp.asarray(_input_binary.target[0]),
            average=average,
            mdmc_average=mdmc_average,
            num_classes=num_classes,
            ignore_index=ignore_index,
        )
    with pytest.raises(ValueError, match=match_str):
        precision_recall(
            jnp.asarray(_input_binary.preds[0]),
            jnp.asarray(_input_binary.target[0]),
            average=average,
            mdmc_average=mdmc_average,
            num_classes=num_classes,
            ignore_index=ignore_index,
        )


@pytest.mark.parametrize("metric_class, metric_fn", [(Recall, recall), (Precision, precision)])
def test_zero_division(metric_class, metric_fn):
    """0/0 class scores come back as 0 (`test_precision_recall.py:134-147`)."""
    preds = jnp.asarray([0, 2, 1, 1])
    target = jnp.asarray([2, 1, 2, 1])
    cl_metric = metric_class(average="none", num_classes=3)
    cl_metric(preds, target)
    assert float(cl_metric.compute()[0]) == float(metric_fn(preds, target, average="none", num_classes=3)[0]) == 0


@pytest.mark.parametrize("metric_class, metric_fn", [(Recall, recall), (Precision, precision)])
def test_no_support(metric_class, metric_fn):
    """weighted average with all support ignored returns zero_division, not NaN
    (`test_precision_recall.py:150-172`)."""
    preds = jnp.asarray([1, 1, 0, 0])
    target = jnp.asarray([0, 0, 0, 0])
    cl_metric = metric_class(average="weighted", num_classes=2, ignore_index=0)
    cl_metric(preds, target)
    assert float(cl_metric.compute()) == float(
        metric_fn(preds, target, average="weighted", num_classes=2, ignore_index=0)
    ) == 0


@pytest.mark.parametrize(
    "metric_class, metric_fn, sk_fn", [(Recall, recall, recall_score), (Precision, precision, precision_score)]
)
@pytest.mark.parametrize("average", ["micro", "macro", None, "weighted", "samples"])
@pytest.mark.parametrize("ignore_index", [None, 0])
@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass, mdmc_average, sk_wrapper",
    [
        (_input_binary_logits.preds, _input_binary_logits.target, 1, None, None, _sk_prec_recall),
        (_input_binary_prob.preds, _input_binary_prob.target, 1, None, None, _sk_prec_recall),
        (_input_binary.preds, _input_binary.target, 1, False, None, _sk_prec_recall),
        (_input_mlb_logits.preds, _input_mlb_logits.target, NUM_CLASSES, None, None, _sk_prec_recall),
        (_input_mlb_prob.preds, _input_mlb_prob.target, NUM_CLASSES, None, None, _sk_prec_recall),
        (_input_mlb.preds, _input_mlb.target, NUM_CLASSES, False, None, _sk_prec_recall),
        (_input_mcls_logits.preds, _input_mcls_logits.target, NUM_CLASSES, None, None, _sk_prec_recall),
        (_input_mcls_prob.preds, _input_mcls_prob.target, NUM_CLASSES, None, None, _sk_prec_recall),
        (_input_multiclass.preds, _input_multiclass.target, NUM_CLASSES, None, None, _sk_prec_recall),
        (_input_mdmc.preds, _input_mdmc.target, NUM_CLASSES, None, "global", _sk_prec_recall_multidim_multiclass),
        (
            _input_mdmc_prob.preds,
            _input_mdmc_prob.target,
            NUM_CLASSES,
            None,
            "global",
            _sk_prec_recall_multidim_multiclass,
        ),
        (_input_mdmc.preds, _input_mdmc.target, NUM_CLASSES, None, "samplewise", _sk_prec_recall_multidim_multiclass),
        (
            _input_mdmc_prob.preds,
            _input_mdmc_prob.target,
            NUM_CLASSES,
            None,
            "samplewise",
            _sk_prec_recall_multidim_multiclass,
        ),
    ],
)
class TestPrecisionRecallMatrix(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_precision_recall_class(
        self,
        ddp: bool,
        preds: np.ndarray,
        target: np.ndarray,
        sk_wrapper: Callable,
        metric_class,
        metric_fn: Callable,
        sk_fn: Callable,
        multiclass: Optional[bool],
        num_classes: Optional[int],
        average: str,
        mdmc_average: Optional[str],
        ignore_index: Optional[int],
    ):
        if num_classes == 1 and average == "samples":
            pytest.skip("'samples' average needs per-sample label sets; binary rows have none")
        # binary macro/weighted/none collapse to the single class's score, so
        # sklearn's 'binary' average IS the oracle (the wrapper maps it) —
        # r4: converted from reference-mirrored skips into live assertions
        if ignore_index is not None and num_classes == 1:
            pytest.skip("ignore_index is undefined for binary inputs (constructor raises)")
        if average == "weighted" and ignore_index is not None and mdmc_average is not None:
            pytest.skip("ignoring an entire sample under 'weighted' is a degenerate case")

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=metric_class,
            sk_metric=partial(
                sk_wrapper,
                sk_fn=sk_fn,
                average=average,
                num_classes=num_classes,
                multiclass=multiclass,
                ignore_index=ignore_index,
                mdmc_average=mdmc_average,
            ),
            metric_args={
                "num_classes": num_classes,
                "average": average,
                "threshold": THRESHOLD,
                "multiclass": multiclass,
                "ignore_index": ignore_index,
                "mdmc_average": mdmc_average,
            },
            check_jit=False,  # jit gates for every input type run in test_input_variants
        )

    def test_precision_recall_fn(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        sk_wrapper: Callable,
        metric_class,
        metric_fn: Callable,
        sk_fn: Callable,
        multiclass: Optional[bool],
        num_classes: Optional[int],
        average: str,
        mdmc_average: Optional[str],
        ignore_index: Optional[int],
    ):
        if num_classes == 1 and average == "samples":
            pytest.skip("'samples' average needs per-sample label sets; binary rows have none")
        # binary macro/weighted/none collapse to the single class's score, so
        # sklearn's 'binary' average IS the oracle (the wrapper maps it) —
        # r4: converted from reference-mirrored skips into live assertions
        if ignore_index is not None and num_classes == 1:
            pytest.skip("ignore_index is undefined for binary inputs (constructor raises)")

        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=metric_fn,
            sk_metric=partial(
                sk_wrapper,
                sk_fn=sk_fn,
                average=average,
                num_classes=num_classes,
                multiclass=multiclass,
                ignore_index=ignore_index,
                mdmc_average=mdmc_average,
            ),
            metric_args={
                "num_classes": num_classes,
                "average": average,
                "threshold": THRESHOLD,
                "multiclass": multiclass,
                "ignore_index": ignore_index,
                "mdmc_average": mdmc_average,
            },
        )


def test_precision_recall_joint():
    """`precision_recall` returns the same pair as the two single functionals
    (reference `test_precision_recall.py:292-305`)."""
    preds = jnp.asarray(_input_mcls_prob.preds[0])
    target = jnp.asarray(_input_mcls_prob.target[0])
    prec, rec = precision_recall(preds, target, average="macro", num_classes=NUM_CLASSES)
    np.testing.assert_allclose(
        np.asarray(prec), np.asarray(precision(preds, target, average="macro", num_classes=NUM_CLASSES))
    )
    np.testing.assert_allclose(
        np.asarray(rec), np.asarray(recall(preds, target, average="macro", num_classes=NUM_CLASSES))
    )
