"""Tweedie deviance score — analogue of reference
``torchmetrics/functional/regression/tweedie_deviance.py:22-139``. The
power-dependent branch is static (python float); value-domain checks run only
on concrete arrays (eager), so the arithmetic path jits.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape, _is_concrete


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    _check_same_shape(preds, targets)
    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")

    concrete = _is_concrete(preds, targets)
    if power == 0:
        deviance_score = (targets - preds) ** 2
    elif power == 1:
        if concrete and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0))):
            raise ValueError(
                f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative."
            )
        deviance_score = 2 * (targets * jnp.log(targets / preds) + preds - targets)
    elif power == 2:
        if concrete and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0))):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
        deviance_score = 2 * (jnp.log(preds / targets) + targets / preds - 1)
    else:
        if concrete:
            if power < 0 and bool(jnp.any(preds <= 0)):
                raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
            if 1 < power < 2 and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0))):
                raise ValueError(
                    f"For power={power}, 'targets' has to be strictly positive and 'preds' cannot be negative."
                )
            if power > 2 and (bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0))):
                raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
        term_1 = jnp.maximum(targets, 0.0) ** (2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * preds ** (1 - power) / (1 - power)
        term_3 = preds ** (2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    return jnp.sum(deviance_score), jnp.asarray(deviance_score.size)


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    r"""Tweedie deviance: Gaussian (0), Poisson (1), Gamma (2) or compound.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import tweedie_deviance_score
        >>> print(round(float(tweedie_deviance_score(jnp.asarray([2.0, 0.5]), jnp.asarray([1.0, 1.0]), power=0.0)), 4))
        0.625
    """
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, power)
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)
