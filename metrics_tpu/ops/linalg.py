"""On-device linear algebra for metrics.

Replaces the reference's device→host escape to ``scipy.linalg.sqrtm``
(``torchmetrics/image/fid.py:58-93`` detaches to CPU numpy inside an
autograd.Function). Everything here is pure jnp — jittable, differentiable,
and it stays on the TPU.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array, lax


def kahan_merge(
    a_total: Array, a_comp: Array, b_total: Array, b_comp: Array
) -> Tuple[Array, Array]:
    """Merge two Kahan accumulator pairs into one, preserving the rescue.

    Two-sum captures the roundoff ``e`` of ``a_total + b_total`` exactly, so
    the merged pair satisfies ``total - comp == (a_total - a_comp) +
    (b_total - b_comp)`` to full compensated precision. Used by state merges
    (forward accumulation, checkpoint resume, map-reduce eval).
    """
    t = a_total + b_total
    bv = t - a_total
    av = t - bv
    e = (a_total - av) + (b_total - bv)  # exact: a+b == t + e
    return t, a_comp + b_comp - e


def kahan_add(total: Array, comp: Array, value: Array) -> Tuple[Array, Array]:
    """One Kahan-compensated accumulation step: returns ``(total', comp')``.

    Precision rescue for float32 streaming sums (SURVEY §7): the compensation
    term carries the roundoff lost by ``total + value``, so a long stream of
    batch statistics keeps ~2x the mantissa. Works under jit — XLA does not
    reassociate float arithmetic. Both terms are plain "sum" states, so
    cross-device ``psum`` composes: per-device compensations add.
    """
    y = value - comp
    t = total + y
    comp_new = (t - total) - y
    return t, comp_new


def sqrtm_newton_schulz(mat: Array, num_iters: int = 25) -> Array:
    """Matrix square root of a symmetric PSD matrix via Newton–Schulz.

    The iteration converges for ``||I - A/||A||_F|| < 1``, so the input is
    pre-scaled by its Frobenius norm and the result rescaled by its sqrt.
    Runs in the input dtype (float32 on TPU; float64 if x64 is enabled) —
    the jittable analogue of the reference's CPU-scipy ``sqrtm``.
    """
    dim = mat.shape[-1]
    norm = jnp.linalg.norm(mat)
    y0 = mat / norm
    z0 = jnp.eye(dim, dtype=mat.dtype)

    def body(_, yz: Tuple[Array, Array]) -> Tuple[Array, Array]:
        y, z = yz
        t = 0.5 * (3.0 * jnp.eye(dim, dtype=mat.dtype) - z @ y)
        return y @ t, t @ z

    y, _ = lax.fori_loop(0, num_iters, body, (y0, z0))
    return y * jnp.sqrt(norm)


def trace_sqrtm_product_eigh(sigma1: Array, sigma2: Array) -> Array:
    """``trace(sqrtm(sigma1 @ sigma2))`` for symmetric PSD inputs, via eigh.

    ``sigma1 @ sigma2`` is similar to the PSD matrix ``A1 @ sigma2 @ A1``
    with ``A1 = sqrtm(sigma1)``, so the trace of its square root is the sum
    of the square roots of that PSD matrix's eigenvalues — two ``eigh`` calls,
    no iteration, numerically stabler than Newton–Schulz in float32.
    """
    vals1, vecs1 = jnp.linalg.eigh(sigma1)
    sqrt1 = (vecs1 * jnp.sqrt(jnp.clip(vals1, 0.0))) @ vecs1.T
    inner = sqrt1 @ sigma2 @ sqrt1
    eigs = jnp.linalg.eigvalsh(inner)
    return jnp.sum(jnp.sqrt(jnp.clip(eigs, 0.0)))


def trace_sqrtm_product_ns(sigma1: Array, sigma2: Array, max_iters: int = 40) -> Array:
    """``trace(sqrtm(sigma1 @ sigma2))`` via monitored Newton–Schulz.

    Pure matmuls — the MXU-native path: XLA's ``eigh`` costs ~100 s of
    compile time per instance on TPU, while this compiles in seconds and
    runs a handful of 2048³ matmuls. Newton–Schulz in float32 converges and
    then *diverges* from roundoff on ill-conditioned inputs, and the usual
    residual ``||I - Z@Y||`` cannot flag convergence for *rank-deficient*
    inputs (sample covariances with N < D — the common FID case — where
    Z@Y approaches a projection, not I). The trace itself plateaus at the
    true value before divergence, so the iterate with the smallest
    ``|Δtrace|`` between consecutive steps is returned (validated ≤1e-3
    relative error vs scipy float64 up to condition 1e8 and on N<D sample
    covariances — the reference's FID parity bar,
    ``/root/reference/tests/image/test_fid.py:28-40``).
    """
    a = sigma1 @ sigma2
    dim = a.shape[-1]
    dtype = a.dtype
    norm = jnp.linalg.norm(a)
    eye = jnp.eye(dim, dtype=dtype)
    big = jnp.asarray(jnp.finfo(dtype).max, dtype)

    def body(_, carry):
        y, z, prev_tr, best_tr, best_dt = carry
        t = 0.5 * (3.0 * eye - z @ y)
        y, z = y @ t, t @ z
        tr = jnp.trace(y)
        dt = jnp.abs(tr - prev_tr)
        # strict < is NaN-safe: once roundoff divergence NaNs the iterates,
        # every later comparison is False and the plateau iterate sticks
        better = dt < best_dt
        best_tr = jnp.where(better, tr, best_tr)
        best_dt = jnp.where(better, dt, best_dt)
        return y, z, tr, best_tr, best_dt

    # zero (or fully underflowed) product: sqrtm is the zero matrix; guard
    # the normalization so the iteration cannot manufacture NaNs
    safe_norm = jnp.where(norm > 0, norm, 1.0)
    tr0 = jnp.trace(a / safe_norm)
    init = (a / safe_norm, eye, tr0, tr0, big)
    _, _, _, best_tr, _ = lax.fori_loop(0, max_iters, body, init)
    return jnp.where(norm > 0, best_tr * jnp.sqrt(safe_norm), jnp.zeros((), dtype))


def trace_sqrtm_product(sigma1: Array, sigma2: Array, method: str = "auto") -> Array:
    """``trace(sqrtm(sigma1 @ sigma2))`` with backend-aware dispatch.

    ``auto`` picks Newton–Schulz on TPU (eigh's XLA compile there is ~100 s
    per instance; NS is matmul-only and compiles in seconds) and eigh
    elsewhere. Pass ``'eigh'``/``'ns'`` to force a path.
    """
    if method == "auto":
        try:
            import jax

            method = "ns" if jax.default_backend() == "tpu" else "eigh"
        except RuntimeError:
            method = "eigh"
    if method == "ns":
        return trace_sqrtm_product_ns(sigma1, sigma2)
    if method == "eigh":
        return trace_sqrtm_product_eigh(sigma1, sigma2)
    raise ValueError(f"unknown sqrtm method {method!r}; use 'auto', 'eigh' or 'ns'")
