"""RetrievalMRR — analogue of reference
``torchmetrics/retrieval/mean_reciprocal_rank.py``."""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.segment import GroupedByQuery, segment_min
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric

_BIG = jnp.iinfo(jnp.int32).max


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank of the first relevant document per query.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> mrr = RetrievalMRR()
        >>> print(round(float(mrr(preds, target, indexes=indexes)), 4))
        1.0
    """

    def _segment_metric(self, g: GroupedByQuery) -> Array:
        first_rel_rank = segment_min(jnp.where(g.target > 0, g.rank, _BIG), g)
        return jnp.where(first_rel_rank == _BIG, 0.0, 1.0 / first_rel_rank)
