"""CalibrationError / Hinge / KLDivergence parity tests."""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.stats import entropy as scipy_entropy
from sklearn.metrics import hinge_loss as sk_hinge_loss

from metrics_tpu import CalibrationError, Hinge, KLDivergence
from metrics_tpu.functional import calibration_error, hinge, kl_divergence
from tests.classification.inputs import (
    _input_binary_logits,
    _input_binary_prob,
    _input_multiclass_logits,
    _input_multiclass_prob,
)
from tests.helpers.testers import NUM_CLASSES, MetricTester


def _np_calibration_error(confidences, accuracies, n_bins=15, norm="l1"):
    """Direct numpy replica of the reference's per-bin loop."""
    bins = np.linspace(0, 1, n_bins + 1)
    conf_b, acc_b, prop_b = np.zeros(n_bins), np.zeros(n_bins), np.zeros(n_bins)
    for i in range(n_bins):
        in_bin = (confidences > bins[i]) & (confidences <= bins[i + 1])
        if in_bin.mean() > 0:
            acc_b[i] = accuracies[in_bin].mean()
            conf_b[i] = confidences[in_bin].mean()
            prop_b[i] = in_bin.mean()
    if norm == "l1":
        return np.sum(np.abs(acc_b - conf_b) * prop_b)
    if norm == "max":
        return np.max(np.abs(acc_b - conf_b))
    ce = np.sum((acc_b - conf_b) ** 2 * prop_b)
    return np.sqrt(ce) if ce > 0 else 0.0


@pytest.mark.parametrize("norm", ["l1", "l2", "max"])
def test_calibration_error_multiclass(norm):
    preds = np.concatenate(list(_input_multiclass_prob.preds))
    target = np.concatenate(list(_input_multiclass_prob.target))
    result = calibration_error(jnp.asarray(preds), jnp.asarray(target), norm=norm)
    conf, acc = preds.max(1), (preds.argmax(1) == target).astype(float)
    expected = _np_calibration_error(conf, acc, norm=norm)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)


@pytest.mark.parametrize("norm", ["l1", "max"])
def test_calibration_error_binary(norm):
    preds = np.concatenate(list(_input_binary_prob.preds))
    target = np.concatenate(list(_input_binary_prob.target))
    result = calibration_error(jnp.asarray(preds), jnp.asarray(target), norm=norm)
    expected = _np_calibration_error(preds, target.astype(float), norm=norm)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)


def test_calibration_error_module_matches_fn():
    m = CalibrationError(n_bins=15, norm="l1")
    for i in range(3):
        m.update(
            jnp.asarray(_input_multiclass_prob.preds[i]), jnp.asarray(_input_multiclass_prob.target[i])
        )
    preds = np.concatenate([_input_multiclass_prob.preds[i] for i in range(3)])
    target = np.concatenate([_input_multiclass_prob.target[i] for i in range(3)])
    expected = calibration_error(jnp.asarray(preds), jnp.asarray(target), norm="l1")
    np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(expected), atol=1e-6)


def test_hinge_binary_vs_sklearn():
    preds = np.concatenate(list(_input_binary_logits.preds))
    target = np.concatenate(list(_input_binary_logits.target))
    result = hinge(jnp.asarray(preds), jnp.asarray(target))
    expected = sk_hinge_loss(target, preds)
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)


def test_hinge_multiclass_crammer_singer_vs_sklearn():
    preds = np.concatenate(list(_input_multiclass_logits.preds))
    target = np.concatenate(list(_input_multiclass_logits.target))
    result = hinge(jnp.asarray(preds), jnp.asarray(target))
    expected = sk_hinge_loss(target, preds, labels=list(range(NUM_CLASSES)))
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)


def test_hinge_one_vs_all_reference_values():
    # reference doctest (functional/classification/hinge.py:141-147)
    target = jnp.asarray([0, 1, 2])
    preds = jnp.asarray([[-1.0, 0.9, 0.2], [0.5, -1.1, 0.8], [2.2, -0.5, 0.3]])
    result = hinge(preds, target, multiclass_mode="one-vs-all")
    np.testing.assert_allclose(np.asarray(result), [2.2333, 1.5, 1.2333], atol=1e-3)


def test_hinge_class_ddp():
    tester = MetricTester()
    tester.atol = 1e-5
    tester.run_class_metric_test(
        ddp=True,
        preds=_input_multiclass_logits.preds,
        target=_input_multiclass_logits.target,
        metric_class=Hinge,
        sk_metric=lambda p, t: sk_hinge_loss(t, p, labels=list(range(NUM_CLASSES))),
        metric_args={},
    )


def test_kl_divergence_vs_scipy():
    p = np.abs(np.random.RandomState(7).randn(32, 8)) + 0.1
    q = np.abs(np.random.RandomState(8).randn(32, 8)) + 0.1
    result = kl_divergence(jnp.asarray(p), jnp.asarray(q))
    pn = p / p.sum(1, keepdims=True)
    qn = q / q.sum(1, keepdims=True)
    expected = np.mean([scipy_entropy(pn[i], qn[i]) for i in range(32)])
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)


@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_kl_divergence_module(reduction):
    rng = np.random.RandomState(3)
    m = KLDivergence(reduction=reduction)
    ps, qs = [], []
    for _ in range(3):
        p = jnp.asarray(np.abs(rng.randn(16, 5)) + 0.1)
        q = jnp.asarray(np.abs(rng.randn(16, 5)) + 0.1)
        m.update(p, q)
        ps.append(p)
        qs.append(q)
    expected = kl_divergence(jnp.concatenate(ps), jnp.concatenate(qs), reduction=reduction)
    np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(expected), atol=1e-5)


def test_kl_divergence_log_prob():
    rng = np.random.RandomState(4)
    logits = rng.randn(16, 5)
    p_log = jnp.asarray(logits - np.log(np.exp(logits).sum(1, keepdims=True)))
    q_log = jnp.asarray(np.zeros((16, 5)) - np.log(5.0))
    result = kl_divergence(p_log, q_log, log_prob=True)
    p = np.exp(np.asarray(p_log))
    expected = np.mean(np.sum(p * (np.asarray(p_log) - np.asarray(q_log)), axis=1))
    np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)
