"""Curve family (PR-curve, ROC, AUROC, AP, AUC, Binned*) parity vs sklearn."""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import (
    average_precision_score as sk_average_precision,
    precision_recall_curve as sk_precision_recall_curve,
    roc_auc_score as sk_roc_auc,
    roc_curve as sk_roc_curve,
)

from metrics_tpu import (
    AUC,
    AUROC,
    AveragePrecision,
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    PrecisionRecallCurve,
    ROC,
)
from metrics_tpu.functional import auc, auroc, average_precision, precision_recall_curve, roc
from tests.classification.inputs import (
    _input_binary_prob,
    _input_multiclass_prob,
    _input_multilabel_prob,
)
from tests.helpers.testers import NUM_CLASSES, MetricTester


class TestBinaryCurves(MetricTester):
    atol = 1e-6

    def test_roc_binary_fn(self):
        preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
        fpr, tpr, thr = roc(jnp.asarray(preds), jnp.asarray(target), pos_label=1)
        sk_fpr, sk_tpr, sk_thr = sk_roc_curve(target, preds, drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sk_fpr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), sk_tpr, atol=1e-6)

    def test_prc_binary_fn(self):
        preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
        p, r, t = precision_recall_curve(jnp.asarray(preds), jnp.asarray(target), pos_label=1)
        sk_p, sk_r, sk_t = sk_precision_recall_curve(target, preds)
        # the reference truncates the full-recall plateau to its last point
        # (torchmetrics precision_recall_curve.py:146-149); sklearn >=1.0 keeps
        # the whole plateau, so our curve equals sklearn's tail
        off = len(sk_p) - len(np.asarray(p))
        np.testing.assert_allclose(np.asarray(p), sk_p[off:], atol=1e-6)
        np.testing.assert_allclose(np.asarray(r), sk_r[off:], atol=1e-6)
        np.testing.assert_allclose(np.asarray(t), sk_t[off:], atol=1e-6)

    def test_auroc_binary_fn(self):
        preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
        result = auroc(jnp.asarray(preds), jnp.asarray(target), pos_label=1)
        np.testing.assert_allclose(np.asarray(result), sk_roc_auc(target, preds), atol=1e-6)

    def test_auroc_binary_max_fpr(self):
        preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
        result = auroc(jnp.asarray(preds), jnp.asarray(target), pos_label=1, max_fpr=0.5)
        np.testing.assert_allclose(np.asarray(result), sk_roc_auc(target, preds, max_fpr=0.5), atol=1e-6)

    def test_ap_binary_fn(self):
        preds, target = _input_binary_prob.preds[0], _input_binary_prob.target[0]
        result = average_precision(jnp.asarray(preds), jnp.asarray(target), pos_label=1)
        np.testing.assert_allclose(np.asarray(result), sk_average_precision(target, preds), atol=1e-6)

    def test_auc_fn(self):
        x = jnp.asarray([0, 1, 2, 3])
        y = jnp.asarray([0, 1, 2, 2])
        np.testing.assert_allclose(np.asarray(auc(x, y)), 4.0, atol=1e-6)
        # decreasing x
        np.testing.assert_allclose(np.asarray(auc(x[::-1], y[::-1])), -4.0 * -1, atol=1e-6)

    @pytest.mark.parametrize("metric_class, sk_fn", [
        (AUROC, sk_roc_auc),
        (AveragePrecision, sk_average_precision),
    ])
    @pytest.mark.parametrize("ddp", [False, True])
    def test_binary_class(self, metric_class, sk_fn, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=_input_binary_prob.preds,
            target=_input_binary_prob.target,
            metric_class=metric_class,
            sk_metric=lambda p, t: sk_fn(t, p),
            metric_args={"pos_label": 1},
            check_jit=False,  # cat-state curves are eager-only by design
        )

    def test_auroc_sharded(self):
        self.run_sharded_metric_test(
            preds=_input_binary_prob.preds,
            target=_input_binary_prob.target,
            metric_class=AUROC,
            sk_metric=lambda p, t: sk_roc_auc(t, p),
            metric_args={"pos_label": 1},
        )


class TestMulticlassCurves(MetricTester):
    atol = 1e-6

    def test_auroc_multiclass(self):
        preds = np.concatenate(list(_input_multiclass_prob.preds))
        target = np.concatenate(list(_input_multiclass_prob.target))
        result = auroc(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES)
        expected = sk_roc_auc(target, preds, multi_class="ovr", average="macro")
        np.testing.assert_allclose(np.asarray(result), expected, atol=1e-6)

    def test_auroc_multilabel(self):
        preds = np.concatenate(list(_input_multilabel_prob.preds))
        target = np.concatenate(list(_input_multilabel_prob.target))
        result = auroc(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES)
        expected = sk_roc_auc(target, preds, average="macro")
        np.testing.assert_allclose(np.asarray(result), expected, atol=1e-5)

    def test_ap_multiclass(self):
        preds = np.concatenate(list(_input_multiclass_prob.preds))
        target = np.concatenate(list(_input_multiclass_prob.target))
        result = average_precision(
            jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES, average=None
        )
        onehot = np.eye(NUM_CLASSES)[target]
        for c in range(NUM_CLASSES):
            np.testing.assert_allclose(
                np.asarray(result[c]), sk_average_precision(onehot[:, c], preds[:, c]), atol=1e-6
            )

    def test_roc_multiclass(self):
        preds = _input_multiclass_prob.preds[0]
        target = _input_multiclass_prob.target[0]
        fprs, tprs, _ = roc(jnp.asarray(preds), jnp.asarray(target), num_classes=NUM_CLASSES)
        for c in range(NUM_CLASSES):
            sk_fpr, sk_tpr, _ = sk_roc_curve((target == c).astype(int), preds[:, c], drop_intermediate=False)
            np.testing.assert_allclose(np.asarray(fprs[c]), sk_fpr, atol=1e-6)
            np.testing.assert_allclose(np.asarray(tprs[c]), sk_tpr, atol=1e-6)


class TestBinned(MetricTester):
    def test_binned_pr_curve_approaches_exact(self):
        """With fine bins, binned AP ~= exact AP."""
        preds = np.concatenate(list(_input_binary_prob.preds))
        target = np.concatenate(list(_input_binary_prob.target))
        m = BinnedAveragePrecision(num_classes=1, thresholds=1001)
        m.update(jnp.asarray(preds), jnp.asarray(target))
        result = m.compute()
        expected = sk_average_precision(target, preds)
        np.testing.assert_allclose(np.asarray(result), expected, atol=2e-2)

    def test_binned_pr_curve_reference_values(self):
        """Reference doctest values (binned_precision_recall.py:65-75)."""
        pred = jnp.asarray([0, 0.1, 0.8, 0.4])
        target = jnp.asarray([0, 1, 1, 0])
        m = BinnedPrecisionRecallCurve(num_classes=1, thresholds=5)
        precision, recall, thresholds = m(pred, target)
        np.testing.assert_allclose(np.asarray(precision), [0.5, 0.5, 1.0, 1.0, 1.0, 1.0], atol=1e-4)
        np.testing.assert_allclose(np.asarray(recall), [1.0, 0.5, 0.5, 0.5, 0.0, 0.0], atol=1e-4)
        np.testing.assert_allclose(np.asarray(thresholds), [0.0, 0.25, 0.5, 0.75, 1.0], atol=1e-6)

    def test_binned_recall_at_precision(self):
        pred = jnp.asarray([0, 0.2, 0.5, 0.8])
        target = jnp.asarray([0, 1, 1, 0])
        m = BinnedRecallAtFixedPrecision(num_classes=1, thresholds=10, min_precision=0.5)
        recall, threshold = m(pred, target)
        np.testing.assert_allclose(np.asarray(recall), 1.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(threshold), 1 / 9, atol=1e-4)

    def test_binned_is_jittable(self):
        """The binned family's whole update+compute must jit (the TPU path)."""
        import jax

        m = BinnedPrecisionRecallCurve(num_classes=NUM_CLASSES, thresholds=50)
        state = m.init_state()
        step = jax.jit(m.pure_update)
        for i in range(3):
            state = step(
                state,
                jnp.asarray(_input_multiclass_prob.preds[i]),
                jnp.asarray(_input_multiclass_prob.target[i]),
            )
        p, r, t = jax.jit(lambda s: m.pure_compute(s))(state)
        assert len(p) == NUM_CLASSES

    def test_binned_ap_multiclass_parity(self):
        preds = np.concatenate(list(_input_multiclass_prob.preds))
        target = np.concatenate(list(_input_multiclass_prob.target))
        m = BinnedAveragePrecision(num_classes=NUM_CLASSES, thresholds=1001)
        m.update(jnp.asarray(preds), jnp.asarray(target))
        result = m.compute()
        onehot = np.eye(NUM_CLASSES)[target]
        for c in range(NUM_CLASSES):
            np.testing.assert_allclose(
                np.asarray(result[c]), sk_average_precision(onehot[:, c], preds[:, c]), atol=5e-2
            )
