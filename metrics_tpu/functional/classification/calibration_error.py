"""Top-label calibration error (ECE / RMSCE / MCE) — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/calibration_error.py:23-156``. The
reference bins with a python loop over bin boundaries (``_ce_compute``); here
binning is one vectorized bucketize + masked segment-mean — jit-safe and fused
by XLA.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _input_format_classification
from metrics_tpu.utils.enums import DataType


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Array,
    norm: str = "l1",
    debias: bool = False,
) -> Array:
    """Calibration error over (lower, upper] confidence bins."""
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")

    n_bins = bin_boundaries.shape[0] - 1
    # bin i is (b_i, b_{i+1}]; confidences exactly 0 fall in no bin
    # (reference semantics: `gt(lower) * le(upper)`, calibration_error.py:54)
    idx = jnp.searchsorted(bin_boundaries, confidences, side="left") - 1
    onehot = (idx[:, None] == jnp.arange(n_bins)[None, :]) & (idx >= 0)[:, None]  # [N, B]
    count_bin = jnp.sum(onehot, axis=0).astype(jnp.float32)
    safe_count = jnp.where(count_bin == 0, 1.0, count_bin)
    conf_bin = jnp.sum(onehot * confidences[:, None], axis=0) / safe_count
    acc_bin = jnp.sum(onehot * accuracies[:, None], axis=0) / safe_count
    prop_bin = count_bin / confidences.shape[0]
    conf_bin = jnp.where(count_bin == 0, 0.0, conf_bin)
    acc_bin = jnp.where(count_bin == 0, 0.0, acc_bin)

    if norm == "l1":
        return jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    if norm == "max":
        return jnp.max(jnp.abs(acc_bin - conf_bin))
    # l2
    ce = jnp.sum((acc_bin - conf_bin) ** 2 * prop_bin)
    if debias:
        debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (
            prop_bin * accuracies.shape[0] - 1
        )
        ce = ce + jnp.sum(jnp.nan_to_num(debias_bins))
    return jnp.where(ce > 0, jnp.sqrt(jnp.maximum(ce, 0.0)), 0.0)


def _ce_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Top-1 confidences and their correctness, per input mode."""
    _, _, mode = _input_format_classification(preds, target)

    if mode == DataType.BINARY:
        confidences, accuracies = preds, target
    elif mode == DataType.MULTICLASS:
        confidences = jnp.max(preds, axis=1)
        predictions = jnp.argmax(preds, axis=1)
        accuracies = predictions == target
    elif mode == DataType.MULTIDIM_MULTICLASS:
        flat = jnp.swapaxes(preds, 1, -1).reshape(-1, preds.shape[1])
        confidences = jnp.max(flat, axis=1)
        predictions = jnp.argmax(flat, axis=1)
        accuracies = predictions == target.ravel()
    else:
        raise ValueError(
            f"Calibration error is not well-defined for data with size {preds.shape} and targets {target.shape}."
        )
    return confidences.astype(jnp.float32), accuracies.astype(jnp.float32)


def calibration_error(preds: Array, target: Array, n_bins: int = 15, norm: str = "l1") -> Array:
    r"""Top-label calibration error (L1 = ECE, L2 = RMSCE, max = MCE).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import calibration_error
        >>> preds = jnp.asarray([0.25, 0.25, 0.55, 0.75, 0.75])
        >>> target = jnp.asarray([0, 0, 1, 1, 1])
        >>> print(round(float(calibration_error(preds, target, n_bins=2, norm="l1")), 4))
        0.29
    """
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")
    if not isinstance(n_bins, int) or n_bins <= 0:
        raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")
    confidences, accuracies = _ce_update(preds, target)
    bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    return _ce_compute(confidences, accuracies, bin_boundaries, norm=norm)
