"""Elastic fleet resilience: quorum membership, probation healing, adaptive control.

A single dead or preempted rank historically collapsed the whole fleet to
local-only metrics: the sync watchdog fired, the channel-suspect latch
poisoned every later sync, and recovery required a manual
``reset_channel_health()`` call no production loop ever makes. This module
replaces that blanket degradation with three cooperating mechanisms:

- **Quorum membership.** A per-process :class:`Membership` (epoch + sorted
  live-rank tuple) describes which ranks currently participate in host
  collectives. Under ``on_missing="quorum"`` (``Metric.sync`` /
  ``MetricCollection.sync``), a sync round that loses ranks negotiates a
  shrunken membership *symmetrically* — every survivor probes the same
  world state, proposes ``local_epoch + 1``, and agrees on the max over the
  survivor set — then the caller re-runs the health-checked gather over the
  survivor set only. The health word carries the membership epoch and live
  count (protocol v4, ``parallel/health.py``), so a rank that missed a
  transition raises a typed ``StateDivergenceError`` on every rank instead
  of pairing collectives across disagreeing memberships. When every rank is
  live, none of this code runs: the non-degraded fast path is the
  pre-quorum sync, bit for bit.
- **Probation (self-healing channel).** The permanent channel-suspect latch
  becomes a state machine: ``healthy → suspect`` (a watchdog fired) →
  cooldown with exponential backoff → ``probe`` (one sync round is allowed
  through) → readmitted on success, re-suspected with doubled backoff on
  failure. ``parallel/health.py``'s public latch API delegates here, so
  existing callers (and the fault-injection suite) keep their semantics:
  a freshly suspected channel still refuses syncs, but it now heals itself
  once the cooldown elapses and a probe round succeeds — zero manual
  ``reset_channel_health()`` calls.
- **Adaptive control.** :class:`AdaptiveController` subscribes to the
  telemetry journal (``observability.on_event``) and tunes the watchdog
  timeout from an EWMA of observed gather times (with a floor), replacing
  the static 600 s default as the only line of defense. The watchdog bound
  is a *rank-local liveness guard* — it never changes which collectives are
  issued, only how long a rank waits before declaring a peer dead — so
  tuning it from rank-local timings is safe by construction. Decisions that
  WOULD change the collective schedule (sync cadence, staleness policy) must
  flow through :func:`commit_schedule_decision`, whose inputs
  ``metricslint``'s schedule pass verifies are symmetric (membership epoch,
  health-word columns); every decision is journaled and revertible.

Every membership transition and controller decision is a typed, journaled
event (``resilience.membership``, ``resilience.quorum``,
``controller.timeout``, ``controller.schedule``, ``controller.revert``).

**Transport.** Shrinking a JAX process group in place is not expressible
with ``multihost_utils.process_allgather`` (the collective is defined over
the full world), so subset gathers and membership negotiation ride a
pluggable :func:`set_quorum_transport` seam. Simulated fleets
(``tests/helpers/fake_world.py``) install one; production deployments can
back it with a side channel (e.g. the coordinator KV store). Without a
transport, quorum mode degrades gracefully: a ``warn_once`` diagnostic
fires and the error falls through to the ``on_error`` ladder unchanged.
"""
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from metrics_tpu.observability import journal
from metrics_tpu.observability.registry import add_process, bump_process, set_process
from metrics_tpu.utils.exceptions import (
    StateDivergenceError,
    SyncError,
    SyncTimeoutError,
)

__all__ = [
    "Membership",
    "AdaptiveController",
    "advance_membership",
    "active_subset_transport",
    "adaptive_sync_timeout",
    "channel_gate",
    "channel_is_suspect",
    "channel_probe_succeeded",
    "commit_schedule_decision",
    "configure_probation",
    "current_membership",
    "effective_world",
    "is_missing_rank_error",
    "last_schedule_decisions",
    "live_ranks",
    "mark_channel_suspect",
    "maybe_rejoin",
    "membership_epoch",
    "negotiate_quorum",
    "note_sync_round",
    "reset_channel_health",
    "reset_resilience",
    "set_quorum_transport",
]

#: patchable clock seam (probation tests freeze it instead of sleeping)
_now = time.monotonic


def _current_domain() -> Any:
    """Identity of the owning "process". In production every rank IS its own
    process, so one constant domain suffices and all per-domain state below
    is effectively process-global. Simulated multi-rank worlds (thread-per-
    rank harnesses, ``tests/helpers/fake_world.py``) share this module
    across fake ranks and monkeypatch this to the current thread's rank
    identity — mirroring ``async_sync._current_domain`` — so each fake rank
    gets its own membership, probation state, and flap window."""
    return None


_STATE_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# Membership: who participates in host collectives right now
# ---------------------------------------------------------------------------


class Membership:
    """One negotiated membership: ``epoch`` (monotonic per domain), the
    sorted ``live`` rank tuple, and the full ``world`` size the fleet
    started with. ``degraded`` is the one bit the sync path branches on —
    a non-degraded membership takes the exact pre-quorum code path."""

    __slots__ = ("epoch", "live", "world")

    def __init__(self, epoch: int, live: Any, world: int) -> None:
        self.epoch = int(epoch)
        self.live: Tuple[int, ...] = tuple(sorted(int(r) for r in live))
        self.world = int(world)

    @property
    def degraded(self) -> bool:
        return len(self.live) < self.world

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Membership(epoch={self.epoch}, live={self.live}, world={self.world})"


_MEMBERSHIPS: Dict[Any, Membership] = {}


def _full_world() -> int:
    import jax

    return jax.process_count()


def current_membership() -> Membership:
    """This domain's membership (lazily the full-world epoch-0 one)."""
    key = _current_domain()
    with _STATE_LOCK:
        m = _MEMBERSHIPS.get(key)
        if m is None:
            world = _full_world()
            m = Membership(0, range(world), world)
            _MEMBERSHIPS[key] = m
        return m


def membership_epoch() -> int:
    """The current membership epoch (0 until a quorum transition happens) —
    a symmetric input: every live rank agreed on it by negotiation."""
    m = _MEMBERSHIPS.get(_current_domain())
    return 0 if m is None else m.epoch


def live_ranks() -> Tuple[int, ...]:
    """The negotiated live-rank tuple (all ranks until a transition)."""
    m = _MEMBERSHIPS.get(_current_domain())
    return tuple(range(_full_world())) if m is None else m.live


def live_count() -> int:
    """``len(live_ranks())`` without materializing the tuple twice."""
    m = _MEMBERSHIPS.get(_current_domain())
    return _full_world() if m is None else len(m.live)


def effective_world() -> int:
    """World size the payload gathers run over: the full process count on
    the non-degraded fast path (bit-identical to pre-quorum sync), the
    survivor count once a quorum transition shrank the membership."""
    m = _MEMBERSHIPS.get(_current_domain())
    if m is None or not m.degraded:
        return _full_world()
    return len(m.live)


def advance_membership(live: Any, epoch: int, reason: str = "shrink") -> Membership:
    """Install the negotiated ``(epoch, live)`` membership for this domain.

    Epoch-guarded and idempotent: a proposal at or below the current epoch
    is a no-op returning the installed membership (two code paths racing to
    install the same agreed transition commit it once). Every transition is
    a typed, journaled event; probation state resets to healthy — the
    transition IS the recovery action (the channel is re-negotiated over
    the new live set), which is what makes degradation converge with zero
    manual ``reset_channel_health()`` calls.
    """
    key = _current_domain()
    with _STATE_LOCK:
        cur = _MEMBERSHIPS.get(key)
        world = cur.world if cur is not None else _full_world()
        cur_epoch = cur.epoch if cur is not None else 0
        if int(epoch) <= cur_epoch:
            return cur if cur is not None else Membership(0, range(world), world)
        prev_live = cur.live if cur is not None else tuple(range(world))
        m = Membership(epoch, live, world)
        _MEMBERSHIPS[key] = m
        shrank = len(m.live) < len(prev_live)
    bump_process("membership_transitions")
    if journal.ACTIVE:
        journal.record(
            "resilience.membership",
            label=reason,
            epoch=m.epoch,
            live_count=len(m.live),
            world=m.world,
            prev_live_count=len(prev_live),
        )
    _channel_force_healthy(key)
    if shrank:
        _note_shrink(key)
    return m


def reset_membership() -> None:
    """Drop this domain's membership back to the full-world epoch-0 state
    (tests; a production fleet restart re-imports the module anyway)."""
    with _STATE_LOCK:
        _MEMBERSHIPS.pop(_current_domain(), None)


# ---------------------------------------------------------------------------
# Flap detection: repeated shrinks in a short round window
# ---------------------------------------------------------------------------

#: A membership that shrinks more than once inside this many sync rounds is
#: "flapping" — a rank oscillating between dead and alive, usually a
#: probation cooldown tuned too short for the failure it keeps readmitting.
FLAP_WINDOW_ROUNDS = 32

_ROUND_COUNTS: Dict[Any, int] = {}
_SHRINK_ROUNDS: Dict[Any, List[int]] = {}


def note_sync_round() -> None:
    """Advance this domain's quorum-mode round counter (called once per
    ``host_sync_state`` entered with ``on_missing="quorum"``) — the clock
    the flap window is measured in."""
    key = _current_domain()
    with _STATE_LOCK:
        _ROUND_COUNTS[key] = _ROUND_COUNTS.get(key, 0) + 1


def _note_shrink(key: Any) -> None:
    with _STATE_LOCK:
        round_ = _ROUND_COUNTS.get(key, 0)
        rounds = _SHRINK_ROUNDS.setdefault(key, [])
        rounds.append(round_)
        flapping = (
            len(rounds) >= 2 and rounds[-1] - rounds[-2] <= FLAP_WINDOW_ROUNDS
        )
    if flapping:
        from metrics_tpu.observability.diagnostics import warn_once

        warn_once(
            "quorum-flapping",
            "quorum mode shrank the sync membership more than once within "
            f"{FLAP_WINDOW_ROUNDS} rounds — a rank is flapping (repeatedly "
            "readmitted and lost). Lengthen the probation cooldown "
            "(METRICS_TPU_PROBATION_COOLDOWN_S or "
            "resilience.configure_probation(base_cooldown_s=...)) so an "
            "unstable rank stays out longer before it is probed back in.",
        )


# ---------------------------------------------------------------------------
# Probation: suspect -> cooldown -> probe -> readmit
# ---------------------------------------------------------------------------

_HEALTHY, _SUSPECT, _PROBE = "healthy", "suspect", "probe"

#: Default cooldown before the first probe round is allowed through; env
#: knob ``METRICS_TPU_PROBATION_COOLDOWN_S``. Doubled per consecutive
#: failed probe (exponential backoff), capped at ``max_cooldown_s``.
DEFAULT_PROBATION_COOLDOWN_S = 60.0

_PROBATION = {
    "base_cooldown_s": None,  # None -> env knob -> default
    "max_cooldown_s": 3600.0,
    "backoff": 2.0,
}


def configure_probation(
    base_cooldown_s: Optional[float] = None,
    max_cooldown_s: Optional[float] = None,
    backoff: Optional[float] = None,
) -> None:
    """Override the probation knobs process-wide (tests, tuning loops)."""
    if base_cooldown_s is not None:
        _PROBATION["base_cooldown_s"] = float(base_cooldown_s)
    if max_cooldown_s is not None:
        _PROBATION["max_cooldown_s"] = float(max_cooldown_s)
    if backoff is not None:
        _PROBATION["backoff"] = float(backoff)


def _base_cooldown_s() -> float:
    base = _PROBATION["base_cooldown_s"]
    if base is not None:
        return float(base)
    return float(
        os.environ.get("METRICS_TPU_PROBATION_COOLDOWN_S", DEFAULT_PROBATION_COOLDOWN_S)
    )


class _ChannelState:
    __slots__ = ("phase", "failures", "cooldown_until", "episode_started")

    def __init__(self) -> None:
        self.phase = _HEALTHY
        self.failures = 0
        self.cooldown_until = 0.0
        self.episode_started = 0.0


_CHANNELS: Dict[Any, _ChannelState] = {}


def _channel(key: Any) -> _ChannelState:
    st = _CHANNELS.get(key)
    if st is None:
        st = _ChannelState()
        _CHANNELS[key] = st
    return st


def channel_is_suspect() -> bool:
    """True while the channel is anywhere in probation (suspect OR probing):
    collective ordering is not yet re-established. The latch-era name is
    kept — ``parallel/health.py`` re-exports this for existing callers."""
    st = _CHANNELS.get(_current_domain())
    return st is not None and st.phase != _HEALTHY


def mark_channel_suspect() -> None:
    """Enter (or re-enter) probation. From healthy this starts a suspect
    episode with the base cooldown; from a probe round it means the probe
    FAILED, so the cooldown doubles (exponential backoff, capped). Journals
    the transition exactly once per episode entry, like the old latch."""
    key = _current_domain()
    with _STATE_LOCK:
        st = _channel(key)
        if st.phase == _SUSPECT:
            return
        failed_probe = st.phase == _PROBE
        if failed_probe:
            st.failures += 1
        else:
            st.failures = 0
            st.episode_started = _now()
        cooldown = min(
            _base_cooldown_s() * (_PROBATION["backoff"] ** st.failures),
            _PROBATION["max_cooldown_s"],
        )
        st.phase = _SUSPECT
        st.cooldown_until = _now() + cooldown
    bump_process("channel_suspect_latched")
    if journal.ACTIVE:
        journal.record(
            "health.channel_suspect",
            label="probe_failed" if failed_probe else "suspect",
            cooldown_s=cooldown,
            failures=st.failures,
        )


def channel_gate() -> str:
    """The sync path's admission decision: ``"open"`` (healthy — issue
    collectives normally), ``"refuse"`` (suspect, cooling down — raise the
    refusal error without touching the channel), or ``"probe"`` (cooldown
    elapsed — let exactly this sync through as the probe round; its success
    readmits the channel, its failure re-suspects with doubled backoff)."""
    key = _current_domain()
    with _STATE_LOCK:
        st = _CHANNELS.get(key)
        if st is None or st.phase == _HEALTHY:
            return "open"
        if st.phase == _PROBE:
            return "probe"
        if _now() < st.cooldown_until:
            return "refuse"
        st.phase = _PROBE
    if journal.ACTIVE:
        journal.record("health.channel_probe", failures=st.failures)
    return "probe"


def channel_probe_succeeded() -> None:
    """A probe round's collectives completed: readmit the channel. Records
    the episode duration into the ``suspect_episode_s`` telemetry gauge and
    journals the readmission."""
    key = _current_domain()
    with _STATE_LOCK:
        st = _CHANNELS.get(key)
        if st is None or st.phase != _PROBE:
            return
        episode_s = max(0.0, _now() - st.episode_started)
        failures = st.failures
        st.phase = _HEALTHY
        st.failures = 0
    add_process("suspect_episode_s", episode_s)
    bump_process("channel_readmits")
    if journal.ACTIVE:
        journal.record("health.channel_readmit", episode_s=episode_s, failures=failures)


def _channel_force_healthy(key: Any) -> None:
    """Silently drop probation state (membership transitions re-establish
    the channel over the new live set, which IS the recovery)."""
    with _STATE_LOCK:
        st = _CHANNELS.get(key)
        if st is not None:
            st.phase = _HEALTHY
            st.failures = 0


def reset_channel_health() -> None:
    """Force the channel healthy — the latch-era manual recovery hook, kept
    for operators that re-established the process group out of band (and
    for test fixtures). Probation makes calling it optional, not wrong."""
    key = _current_domain()
    with _STATE_LOCK:
        st = _CHANNELS.get(key)
        if st is None or st.phase == _HEALTHY:
            return
        st.phase = _HEALTHY
        st.failures = 0
    bump_process("channel_resets")
    if journal.ACTIVE:
        journal.record("health.channel_reset")


# ---------------------------------------------------------------------------
# Quorum transport + negotiation
# ---------------------------------------------------------------------------

#: Installed transport (None in production until a deployment provides one).
#: Duck-typed: ``probe() -> iterable[int]`` (ranks currently reachable,
#: self included), ``negotiate_allgather(vec, live) -> [len(live), k]``
#: int array, ``subset_allgather(x, live) -> [len(live), ...]`` array.
_TRANSPORT: Optional[Any] = None


def set_quorum_transport(transport: Optional[Any]) -> None:
    """Install (or clear, with ``None``) the subset-collective transport
    quorum negotiation rides on. Simulated fleets install theirs in tests;
    production backends can wrap a coordinator side channel."""
    global _TRANSPORT
    _TRANSPORT = transport


def active_subset_transport() -> Optional[Callable[[Any], Any]]:
    """The payload-gather routing hook: ``None`` on the non-degraded fast
    path (callers use the full-world collective, bit-identical to the
    pre-quorum sync), else a closure gathering over the survivor set."""
    m = _MEMBERSHIPS.get(_current_domain())
    if m is None or not m.degraded or _TRANSPORT is None:
        return None
    live = m.live
    transport = _TRANSPORT
    return lambda x: transport.subset_allgather(x, frozenset(live))


def is_missing_rank_error(err: BaseException) -> bool:
    """Is this sync failure in the missing-rank class quorum mode handles?

    Watchdog timeouts and dead transports always are; a divergent header
    (``StateDivergenceError``) is *possibly* one — a dead rank cannot
    contribute a word, but so does a software-skew divergence between live
    ranks — which is why :func:`negotiate_quorum` probes before shrinking
    and falls through when nobody is actually missing.
    """
    return isinstance(err, (SyncTimeoutError, StateDivergenceError))


def _no_transport_warning() -> None:
    from metrics_tpu.observability.diagnostics import warn_once

    warn_once(
        "quorum-no-transport",
        "on_missing='quorum' requested but no quorum transport is installed "
        "(resilience.set_quorum_transport) — the full-world collective "
        "cannot shrink, so the failure falls through to the on_error "
        "policy unchanged.",
    )


# Negotiation is symmetric by construction: every live rank probes the same
# fleet state, proposes local_epoch+1 over the SAME survivor set, and takes
# max() of the gathered proposals — deterministic over identical input, the
# same contract verify_health_words relies on.
def negotiate_quorum(
    err: BaseException, *, metric_name: str = "metric"
) -> Optional[Membership]:
    """Shrink the membership after a missing-rank sync failure.

    Returns the newly agreed membership when ranks are actually missing, or
    ``None`` when quorum cannot help (no transport, nobody missing, or the
    probe shows the full current membership alive — e.g. a genuine schema
    divergence between live ranks) — the caller then falls through to the
    ``on_error`` ladder exactly as before quorum mode existed.
    """
    if _TRANSPORT is None:
        _no_transport_warning()
        return None
    cur = current_membership()
    try:
        reachable = set(int(r) for r in _TRANSPORT.probe())
    except Exception:
        return None
    live = sorted(reachable & set(cur.live))
    if not live or set(live) == set(cur.live):
        return None
    proposal = np.asarray([cur.epoch + 1, len(live)], dtype=np.int32)
    try:
        agreed = np.asarray(
            _TRANSPORT.negotiate_allgather(proposal, frozenset(live))
        )
    except SyncError:
        return None
    if agreed.shape[0] != len(live) or not (agreed[:, 1] == len(live)).all():
        raise StateDivergenceError(
            f"quorum negotiation for {metric_name} diverged: survivors "
            f"disagree on the live set (counts {agreed[:, 1].tolist()} vs "
            f"local {len(live)}). All probing ranks raised together."
        )
    epoch = int(agreed[:, 0].max())
    m = advance_membership(live, epoch, reason="shrink")
    bump_process("quorum_shrinks")
    if journal.ACTIVE:
        journal.record(
            "resilience.quorum",
            label=metric_name,
            epoch=m.epoch,
            live_count=len(m.live),
            error=type(err).__name__,
        )
    return m


def maybe_rejoin(*, metric_name: str = "metric") -> Optional[Membership]:
    """Grow a degraded membership back when lost ranks are reachable again.

    Called at the top of every quorum-mode sync: survivors and a recovered
    rank each probe, see the same reachable superset, and negotiate the
    next epoch over it (max of proposals — a readmitted rank whose local
    epoch lags still lands on the agreed value). The readmitted rank's
    accumulated local state simply participates in the next gather, so it
    catches up through the same ``merge_states`` fold every sync applies.
    Returns the grown membership, or ``None`` when nothing changed (the
    overwhelmingly common case — one dict lookup and no collectives on the
    non-degraded fast path).
    """
    m = _MEMBERSHIPS.get(_current_domain())
    if m is None or not m.degraded or _TRANSPORT is None:
        return None
    try:
        reachable = set(int(r) for r in _TRANSPORT.probe())
    except Exception:
        return None
    grown = sorted(reachable | set(m.live)) if reachable > set(m.live) else None
    if grown is None:
        return None
    proposal = np.asarray([m.epoch + 1, len(grown)], dtype=np.int32)
    try:
        agreed = np.asarray(
            _TRANSPORT.negotiate_allgather(proposal, frozenset(grown))
        )
    except SyncError:
        # a candidate fell away mid-negotiation: stay degraded, next sync
        # probes again — rejoin is opportunistic, never load-bearing
        return None
    if agreed.shape[0] != len(grown) or not (agreed[:, 1] == len(grown)).all():
        return None
    epoch = int(agreed[:, 0].max())
    new = advance_membership(grown, epoch, reason="readmit")
    bump_process("quorum_readmits")
    if journal.ACTIVE:
        journal.record(
            "resilience.quorum",
            label=metric_name,
            epoch=new.epoch,
            live_count=len(new.live),
            error="",
        )
    return new


# ---------------------------------------------------------------------------
# Adaptive controller: telemetry-driven watchdog + schedule tuning
# ---------------------------------------------------------------------------

#: Controller-installed watchdog timeout; consulted by
#: ``health.get_sync_timeout`` between the explicit override and the env
#: knob. None until a controller commits one.
_ADAPTIVE_TIMEOUT_S: Optional[float] = None

#: Last committed schedule decisions, keyed by decision kind — inspection
#: surface for tests and dashboards ("what is the controller doing?").
_SCHEDULE_DECISIONS: Dict[str, Dict[str, Any]] = {}


def adaptive_sync_timeout() -> Optional[float]:
    """The controller's current watchdog bound (None = not tuning)."""
    return _ADAPTIVE_TIMEOUT_S


def _set_adaptive_timeout(value: Optional[float]) -> None:
    global _ADAPTIVE_TIMEOUT_S
    _ADAPTIVE_TIMEOUT_S = value


def commit_schedule_decision(
    kind: str, value: Any, *, epoch: int, reason: str = ""
) -> Any:
    """THE choke point for controller decisions that change the collective
    schedule (sync cadence, staleness policy). ``metricslint``'s schedule
    pass verifies every value flowing in here derives only from symmetric
    inputs (membership epoch, health-word columns) — a rank-local tuning
    decision that changed the schedule would be exactly the divergence
    class the health word exists to catch. Journals the decision and
    records it for :func:`last_schedule_decisions`; returns ``value``.
    """
    with _STATE_LOCK:
        _SCHEDULE_DECISIONS[kind] = {"value": value, "epoch": int(epoch), "reason": reason}
    if journal.ACTIVE:
        journal.record(
            "controller.schedule", label=kind, value=value, epoch=int(epoch),
            reason=reason,
        )
    return value


def last_schedule_decisions() -> Dict[str, Dict[str, Any]]:
    with _STATE_LOCK:
        return {k: dict(v) for k, v in _SCHEDULE_DECISIONS.items()}


class AdaptiveController:
    """Telemetry-subscribed tuner for the sync liveness/schedule knobs.

    Subscribes to the ``sync``, ``health`` and ``resilience`` journal
    classes (:func:`observability.on_event`) and maintains an EWMA of observed
    gather wall-clock (``sync.resolve``'s ``gather_s`` field, plus the
    ``health.margin`` events the watchdog emits on successful guarded
    collectives). The watchdog timeout recommendation is
    ``max(floor_s, multiplier * ewma)`` — committed through
    :func:`adaptive_sync_timeout` (journaled as ``controller.timeout``)
    whenever it moves by more than ``hysteresis`` relative. Watchdog
    *pressure* (a fired watchdog, or margins below 25% of the bound) raises
    the recommendation immediately.

    Schedule-affecting recommendations (cadence back-off while the
    membership is degraded, pinning ``staleness_policy="snapshot"`` while
    overlapped rounds resolve stale under pressure) flow through
    :func:`commit_schedule_decision` with the membership epoch as input —
    the symmetric-input contract the lint pass enforces.

    Every decision is revertible: :meth:`revert` clears the adaptive
    timeout and committed decisions, journaling ``controller.revert``.
    """

    def __init__(
        self,
        *,
        floor_s: float = 5.0,
        multiplier: float = 8.0,
        alpha: float = 0.2,
        hysteresis: float = 0.25,
    ) -> None:
        self.floor_s = float(floor_s)
        self.multiplier = float(multiplier)
        self.alpha = float(alpha)
        self.hysteresis = float(hysteresis)
        self.ewma_gather_s: Optional[float] = None
        self._subscription: Optional[Any] = None
        self._lock = threading.Lock()

    def start(self) -> "AdaptiveController":
        if self._subscription is None:
            self._subscription = journal.on_event(
                self._on_event, classes=("sync", "health", "resilience")
            )
        return self

    def stop(self) -> None:
        sub = self._subscription
        self._subscription = None
        if sub is not None:
            sub.close()

    # -- event plumbing ----------------------------------------------------

    def _on_event(self, event: Any) -> None:
        kind = event.kind
        if kind in ("sync.resolve", "health.margin"):
            gather_s = event.fields.get("gather_s")
            if gather_s is None and kind == "health.margin":
                gather_s = event.fields.get("elapsed_s")
            if gather_s is not None and float(gather_s) > 0:
                self._observe_gather(float(gather_s))
        elif kind == "health.watchdog":
            self._on_watchdog_fired(float(event.fields.get("timeout_s", 0.0)))
        elif kind == "resilience.membership":
            self._on_membership(event.fields)

    def _observe_gather(self, gather_s: float) -> None:
        with self._lock:
            if self.ewma_gather_s is None:
                self.ewma_gather_s = gather_s
            else:
                self.ewma_gather_s += self.alpha * (gather_s - self.ewma_gather_s)
            recommended = max(self.floor_s, self.multiplier * self.ewma_gather_s)
            current = adaptive_sync_timeout()
            move = (
                abs(recommended - current) / current if current else float("inf")
            )
        if move > self.hysteresis:
            self._commit_timeout(recommended, reason="ewma")

    def _on_watchdog_fired(self, fired_timeout_s: float) -> None:
        # pressure: the bound was too tight (or a peer is dead — either way
        # a tighter bound cannot help); back off immediately
        current = adaptive_sync_timeout()
        if current is not None and fired_timeout_s and current <= fired_timeout_s:
            self._commit_timeout(current * 2.0, reason="watchdog_pressure")

    def _on_membership(self, event: Dict[str, Any]) -> None:
        # schedule decision from symmetric inputs only: the negotiated
        # membership epoch (identical on every live rank by construction)
        epoch = int(event.get("epoch", 0))
        degraded = int(event.get("live_count", 0)) < int(event.get("world", 0))
        commit_schedule_decision(
            "sync_cadence_multiplier",
            2 if degraded else 1,
            epoch=epoch,
            reason="degraded membership" if degraded else "membership restored",
        )
        commit_schedule_decision(
            "staleness_policy",
            "snapshot",
            epoch=epoch,
            reason="pin consistent snapshot serving across a membership change",
        )

    def _commit_timeout(self, value: float, reason: str) -> None:
        _set_adaptive_timeout(float(value))
        set_process("adaptive_timeout_s", float(value))
        if journal.ACTIVE:
            journal.record(
                "controller.timeout", label=reason, timeout_s=float(value),
                ewma_gather_s=self.ewma_gather_s or 0.0,
            )

    def revert(self) -> None:
        """Undo every committed decision (journaled): adaptive timeout off,
        schedule decisions cleared — the escape hatch the issue requires."""
        _set_adaptive_timeout(None)
        with _STATE_LOCK:
            _SCHEDULE_DECISIONS.clear()
        if journal.ACTIVE:
            journal.record("controller.revert")


# ---------------------------------------------------------------------------
# test/fixture hygiene
# ---------------------------------------------------------------------------


def reset_resilience() -> None:
    """Drop ALL per-domain resilience state (memberships, probation,
    flap windows, adaptive decisions, transport) — fixture teardown for
    simulated fleets; production code never calls this."""
    global _TRANSPORT
    with _STATE_LOCK:
        _MEMBERSHIPS.clear()
        _CHANNELS.clear()
        _ROUND_COUNTS.clear()
        _SHRINK_ROUNDS.clear()
        _SCHEDULE_DECISIONS.clear()
    _TRANSPORT = None
    _set_adaptive_timeout(None)
