"""ROUGE parity against the rouge_score package — the reference's oracle.

Mirror of `tests/text/test_rouge.py`: every key × {precision, recall,
fmeasure} × use_stemmer over the reference's example corpora, functional and
class (accumulation + merge), against ``rouge_score.rouge_scorer`` with the
reference's BootstrapAggregator mid value (the mid of per-sentence scores is
the plain mean, matching our accumulation).
"""
import numpy as np
import pytest

rouge_score_pkg = pytest.importorskip(
    "rouge_score", reason="rouge_score provides the ROUGE oracle (reference test_rouge.py does the same)"
)
from rouge_score.rouge_scorer import RougeScorer  # noqa: E402
from rouge_score.scoring import BootstrapAggregator  # noqa: E402

from metrics_tpu import ROUGEScore  # noqa: E402
from metrics_tpu.functional import rouge_score  # noqa: E402

ROUGE_KEYS = ("rouge1", "rouge2", "rougeL", "rougeLsum")

BATCHES_1 = {
    "preds": [["the cat was under the bed"], ["the cat was found under the bed"]],
    "targets": [["the cat was found under the bed"], ["the tiny little cat was found under the big funny bed "]],
}
BATCHES_2 = {
    "preds": [["The quick brown fox jumps over the lazy dog"], ["My name is John"]],
    "targets": [["The quick brown dog jumps on the log."], ["Is your name John"]],
}


def _oracle(preds, targets, use_stemmer, rouge_level, metric):
    scorer = RougeScorer(ROUGE_KEYS, use_stemmer=use_stemmer)
    aggregator = BootstrapAggregator()
    for pred, target in zip(preds, targets):
        aggregator.add_scores(scorer.score(target, pred))
    return getattr(aggregator.aggregate()[rouge_level].mid, metric)


@pytest.mark.parametrize(
    "key, use_stemmer",
    [
        ("rouge1_precision", True),
        ("rouge1_recall", True),
        ("rouge1_fmeasure", False),
        ("rouge2_precision", False),
        ("rouge2_recall", True),
        ("rouge2_fmeasure", True),
        ("rougeL_precision", False),
        ("rougeL_recall", False),
        ("rougeL_fmeasure", True),
        ("rougeLsum_precision", True),
        ("rougeLsum_recall", False),
        ("rougeLsum_fmeasure", False),
    ],
)
@pytest.mark.parametrize(
    "preds_batches, target_batches",
    [
        (BATCHES_1["preds"], BATCHES_1["targets"]),
        (BATCHES_2["preds"], BATCHES_2["targets"]),
    ],
    ids=["batches1", "batches2"],
)
class TestROUGEOracle:
    def test_functional(self, preds_batches, target_batches, key, use_stemmer):
        all_preds = [p for b in preds_batches for p in b]
        all_targets = [t for b in target_batches for t in b]
        rouge_level, metric = key.split("_")
        expected = _oracle(all_preds, all_targets, use_stemmer, rouge_level, metric)
        ours = rouge_score(all_preds, all_targets, use_stemmer=use_stemmer)
        np.testing.assert_allclose(float(np.asarray(ours[key])), expected, atol=1e-6)

    @pytest.mark.parametrize("world", [1, 2])
    def test_class_accumulation(self, preds_batches, target_batches, key, use_stemmer, world):
        metrics = [ROUGEScore(use_stemmer=use_stemmer) for _ in range(world)]
        for i, (p, t) in enumerate(zip(preds_batches, target_batches)):
            metrics[i % world].update(p, t)
        merged = metrics[0]
        for other in metrics[1:]:
            merged.merge_state(other)
        out = merged.compute()
        all_preds = [p for b in preds_batches for p in b]
        all_targets = [t for b in target_batches for t in b]
        rouge_level, metric = key.split("_")
        expected = _oracle(all_preds, all_targets, use_stemmer, rouge_level, metric)
        np.testing.assert_allclose(float(np.asarray(out[key])), expected, atol=1e-6)
