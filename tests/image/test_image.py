"""Image-domain metric tests.

References are hand-rolled numpy/scipy (the reference repo does the same for
metrics sklearn lacks, ``tests/helpers/non_sklearn_metrics.py``); FID's matrix
sqrt is validated against ``scipy.linalg.sqrtm`` exactly as the reference does
(``tests/image/test_fid.py:28-40``).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.linalg
from scipy.ndimage import uniform_filter

from metrics_tpu import FID, IS, KID, LPIPS, PSNR, SSIM
from metrics_tpu.functional import image_gradients, psnr, ssim
from metrics_tpu.ops.linalg import sqrtm_newton_schulz, trace_sqrtm_product

from tests.helpers.testers import _assert_allclose

SEED = 42


# ---------------------------------------------------------------------------
# numpy references
# ---------------------------------------------------------------------------


def _np_psnr(preds, target, data_range=None, base=10.0):
    if data_range is None:
        data_range = target.max() - target.min()
    mse = np.mean((preds - target) ** 2)
    return (2 * np.log(data_range) - np.log(mse)) * 10 / np.log(base)


def _np_gaussian_kernel(kernel_size, sigma):
    dist = np.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2)
    gauss = np.exp(-((dist / sigma) ** 2) / 2)
    g = gauss / gauss.sum()
    return np.outer(g, g)


def _np_ssim(preds, target, data_range, kernel_size=11, sigma=1.5, k1=0.01, k2=0.03):
    """Direct per-image SSIM over valid windows (independent numpy path)."""
    from scipy.signal import convolve2d

    kern = _np_gaussian_kernel(kernel_size, sigma)
    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2
    vals = []
    for b in range(preds.shape[0]):
        for c in range(preds.shape[1]):
            x = preds[b, c]
            y = target[b, c]
            mu_x = convolve2d(x, kern, mode="valid")
            mu_y = convolve2d(y, kern, mode="valid")
            sq_x = convolve2d(x * x, kern, mode="valid")
            sq_y = convolve2d(y * y, kern, mode="valid")
            xy = convolve2d(x * y, kern, mode="valid")
            sig_x = sq_x - mu_x**2
            sig_y = sq_y - mu_y**2
            sig_xy = xy - mu_x * mu_y
            s = ((2 * mu_x * mu_y + c1) * (2 * sig_xy + c2)) / (
                (mu_x**2 + mu_y**2 + c1) * (sig_x + sig_y + c2)
            )
            vals.append(s)
    return np.mean(vals)


def _np_fid(real, fake):
    mu1, mu2 = real.mean(0), fake.mean(0)
    cov1 = np.cov(real, rowvar=False)
    cov2 = np.cov(fake, rowvar=False)
    covmean = scipy.linalg.sqrtm(cov1 @ cov2).real
    diff = mu1 - mu2
    return diff @ diff + np.trace(cov1) + np.trace(cov2) - 2 * np.trace(covmean)


def _np_poly_mmd(f_real, f_fake, degree=3, coef=1.0):
    gamma = 1.0 / f_real.shape[1]
    k11 = (f_real @ f_real.T * gamma + coef) ** degree
    k22 = (f_fake @ f_fake.T * gamma + coef) ** degree
    k12 = (f_real @ f_fake.T * gamma + coef) ** degree
    m = k11.shape[0]
    val = ((k11.sum() - np.trace(k11)) + (k22.sum() - np.trace(k22))) / (m * (m - 1))
    return val - 2 * k12.sum() / m**2


# ---------------------------------------------------------------------------
# PSNR
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("data_range", [None, 3.0])
def test_psnr_functional(data_range):
    rng = np.random.RandomState(SEED)
    preds = rng.rand(2, 3, 16, 16).astype(np.float32)
    target = rng.rand(2, 3, 16, 16).astype(np.float32)
    res = psnr(jnp.asarray(preds), jnp.asarray(target), data_range=data_range)
    _assert_allclose(res, _np_psnr(preds, target, data_range), atol=1e-4)


def test_psnr_module_accumulates():
    rng = np.random.RandomState(SEED)
    preds = rng.rand(4, 8, 8).astype(np.float32)
    target = rng.rand(4, 8, 8).astype(np.float32)
    m = PSNR()
    for i in range(4):
        m.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    # the module's range trackers start at 0 (reference ``psnr.py:106-108``)
    tracked_range = max(target.max(), 0.0) - min(target.min(), 0.0)
    _assert_allclose(m.compute(), _np_psnr(preds, target, data_range=tracked_range), atol=1e-4)


def test_psnr_module_dim():
    rng = np.random.RandomState(SEED)
    preds = rng.rand(4, 8, 8).astype(np.float32)
    target = rng.rand(4, 8, 8).astype(np.float32)
    m = PSNR(data_range=1.0, dim=(1, 2), reduction="elementwise_mean")
    for i in range(2):
        m.update(jnp.asarray(preds[2 * i : 2 * i + 2]), jnp.asarray(target[2 * i : 2 * i + 2]))
    per_img = [
        (2 * np.log(1.0) - np.log(np.mean((preds[i] - target[i]) ** 2))) * 10 / np.log(10)
        for i in range(4)
    ]
    _assert_allclose(m.compute(), np.mean(per_img), atol=1e-4)


def test_psnr_dim_requires_data_range():
    with pytest.raises(ValueError, match="data_range"):
        PSNR(dim=1)
    with pytest.raises(ValueError, match="data_range"):
        psnr(jnp.zeros((2, 2)), jnp.ones((2, 2)), dim=1)


# ---------------------------------------------------------------------------
# SSIM
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("data_range", [1.0, None])
def test_ssim_functional(data_range):
    rng = np.random.RandomState(SEED)
    preds = rng.rand(2, 2, 24, 24).astype(np.float64)
    target = (preds * 0.75 + 0.125 * rng.rand(2, 2, 24, 24)).astype(np.float64)
    effective_range = data_range or max(preds.max() - preds.min(), target.max() - target.min())
    res = ssim(jnp.asarray(preds, dtype=jnp.float32), jnp.asarray(target, dtype=jnp.float32),
               data_range=data_range)
    _assert_allclose(res, _np_ssim(preds, target, effective_range), atol=1e-4)


@pytest.mark.parametrize("streaming", [True, False])
def test_ssim_module(streaming):
    rng = np.random.RandomState(SEED)
    preds = rng.rand(4, 1, 24, 24).astype(np.float32)
    target = (preds * 0.75).astype(np.float32)
    m = SSIM(data_range=1.0) if streaming else SSIM()
    assert m._streaming == streaming
    for i in range(2):
        m.update(jnp.asarray(preds[2 * i : 2 * i + 2]), jnp.asarray(target[2 * i : 2 * i + 2]))
    expected = _np_ssim(
        preds.astype(np.float64), target.astype(np.float64),
        1.0 if streaming else max(preds.max() - preds.min(), target.max() - target.min()),
    )
    _assert_allclose(m.compute(), expected, atol=1e-4)


def test_ssim_validation():
    with pytest.raises(ValueError, match="BxCxHxW"):
        ssim(jnp.zeros((16, 16)), jnp.zeros((16, 16)))
    with pytest.raises(TypeError, match="same data type"):
        ssim(jnp.zeros((1, 1, 16, 16), dtype=jnp.float32), jnp.zeros((1, 1, 16, 16), dtype=jnp.float16))
    with pytest.raises(ValueError, match="odd positive"):
        ssim(jnp.zeros((1, 1, 16, 16)), jnp.zeros((1, 1, 16, 16)), kernel_size=(4, 4))


def test_ssim_jit():
    rng = np.random.RandomState(SEED)
    preds = jnp.asarray(rng.rand(2, 1, 16, 16).astype(np.float32))
    target = jnp.asarray(rng.rand(2, 1, 16, 16).astype(np.float32))
    jitted = jax.jit(lambda p, t: ssim(p, t, data_range=1.0))
    _assert_allclose(jitted(preds, target), ssim(preds, target, data_range=1.0), atol=1e-6)


# ---------------------------------------------------------------------------
# image_gradients
# ---------------------------------------------------------------------------


def test_image_gradients():
    img = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
    dy, dx = image_gradients(img)
    assert dy.shape == img.shape and dx.shape == img.shape
    np.testing.assert_allclose(np.asarray(dy[0, 0, :3]), 4.0 * np.ones((3, 4)))
    np.testing.assert_allclose(np.asarray(dy[0, 0, 3]), np.zeros(4))
    np.testing.assert_allclose(np.asarray(dx[0, 0, :, :3]), np.ones((4, 3)))
    np.testing.assert_allclose(np.asarray(dx[0, 0, :, 3]), np.zeros(4))
    with pytest.raises(RuntimeError, match="BxCxHxW"):
        image_gradients(jnp.zeros((4, 4)))


# ---------------------------------------------------------------------------
# matrix sqrt (the FID host-escape replacement) vs scipy
# ---------------------------------------------------------------------------


def test_sqrtm_vs_scipy():
    rng = np.random.RandomState(SEED)
    a = rng.rand(16, 16)
    psd = (a @ a.T).astype(np.float32) + 1e-3 * np.eye(16, dtype=np.float32)
    ours = np.asarray(sqrtm_newton_schulz(jnp.asarray(psd)))
    ref = scipy.linalg.sqrtm(psd.astype(np.float64)).real
    np.testing.assert_allclose(ours, ref, atol=1e-2)


def test_trace_sqrtm_product_vs_scipy():
    rng = np.random.RandomState(SEED)
    a, b = rng.rand(12, 12), rng.rand(12, 12)
    s1 = (a @ a.T).astype(np.float32)
    s2 = (b @ b.T).astype(np.float32)
    ours = float(trace_sqrtm_product(jnp.asarray(s1), jnp.asarray(s2)))
    ref = float(np.trace(scipy.linalg.sqrtm(s1.astype(np.float64) @ s2.astype(np.float64)).real))
    np.testing.assert_allclose(ours, ref, rtol=1e-3)


# ---------------------------------------------------------------------------
# FID / KID / IS — mechanics with an injected feature extractor
# ---------------------------------------------------------------------------


def _identity_features(imgs):
    """Stand-in extractor: flatten images to feature rows."""
    return imgs.reshape(imgs.shape[0], -1)


@pytest.mark.parametrize("streaming", [False, True])
def test_fid_vs_scipy(streaming):
    rng = np.random.RandomState(SEED)
    real = rng.rand(64, 8).astype(np.float32)
    fake = (rng.rand(64, 8) + 0.3).astype(np.float32)
    if streaming:
        fid = FID(feature=_identity_features, streaming=True, feature_dim=8)
    else:
        fid = FID(feature=_identity_features)
    for i in range(4):
        fid.update(jnp.asarray(real[16 * i : 16 * (i + 1)]), real=True)
        fid.update(jnp.asarray(fake[16 * i : 16 * (i + 1)]), real=False)
    _assert_allclose(fid.compute(), _np_fid(real.astype(np.float64), fake.astype(np.float64)), atol=1e-2)


def test_fid_same_distribution_is_zero():
    rng = np.random.RandomState(SEED)
    x = rng.rand(32, 8).astype(np.float32)
    fid = FID(feature=_identity_features)
    fid.update(jnp.asarray(x), real=True)
    fid.update(jnp.asarray(x), real=False)
    assert abs(float(fid.compute())) < 1e-2


def test_fid_invalid_feature():
    with pytest.raises(ValueError, match="feature"):
        FID(feature=123)


def test_kid_mechanics():
    rng = np.random.RandomState(SEED)
    real = rng.rand(40, 8).astype(np.float32)
    fake = (rng.rand(40, 8) + 0.5).astype(np.float32)
    kid = KID(feature=_identity_features, subsets=4, subset_size=40)
    kid.update(jnp.asarray(real), real=True)
    kid.update(jnp.asarray(fake), real=False)
    mean, std = kid.compute()
    # subset_size == n so every subset sees all data -> exact poly-MMD, std 0
    _assert_allclose(mean, _np_poly_mmd(real.astype(np.float64), fake.astype(np.float64)), atol=1e-4)
    assert float(std) < 1e-5


def test_kid_subset_size_check():
    kid = KID(feature=_identity_features, subsets=2, subset_size=100)
    kid.update(jnp.ones((10, 4)), real=True)
    kid.update(jnp.ones((10, 4)), real=False)
    with pytest.raises(ValueError, match="subset_size"):
        kid.compute()


def test_kid_arg_validation():
    for kwargs in [
        dict(subsets=0), dict(subset_size=0), dict(degree=0), dict(gamma=-1.0), dict(coef=-1.0),
    ]:
        with pytest.raises(ValueError):
            KID(feature=_identity_features, **kwargs)


def test_inception_score_mechanics():
    rng = np.random.RandomState(SEED)
    logits = rng.rand(60, 10).astype(np.float32) * 5
    m = IS(feature=lambda x: x, splits=3)
    for i in range(3):
        m.update(jnp.asarray(logits[20 * i : 20 * (i + 1)]))
    mean, std = m.compute()
    assert float(mean) >= 1.0  # IS is exp(KL) >= 1
    assert np.isfinite(float(std))
    # uniform logits -> p(y|x) == p(y) -> IS == 1
    m2 = IS(feature=lambda x: x, splits=2)
    m2.update(jnp.zeros((20, 10)))
    _assert_allclose(m2.compute()[0], 1.0, atol=1e-6)


# ---------------------------------------------------------------------------
# LPIPS — mechanics with the in-framework tower (random weights)
# ---------------------------------------------------------------------------


def test_lpips_identical_images_zero():
    rng = np.random.RandomState(SEED)
    img = jnp.asarray(rng.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1)
    m = LPIPS(net_type="alex")
    m.update(img, img)
    assert abs(float(m.compute())) < 1e-6


def test_lpips_distinct_images_positive():
    rng = np.random.RandomState(SEED)
    a = jnp.asarray(rng.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1)
    b = jnp.asarray(rng.rand(2, 3, 64, 64).astype(np.float32) * 2 - 1)
    m = LPIPS(net_type="alex", reduction="mean")
    m.update(a, b)
    assert float(m.compute()) > 0


def test_lpips_validation():
    m = LPIPS(net_type="alex")
    with pytest.raises(ValueError, match="normalized"):
        m.update(jnp.ones((2, 3, 32, 32)) * 2.0, jnp.ones((2, 3, 32, 32)))
    with pytest.raises(ValueError, match="net_type"):
        LPIPS(net_type="squeeze")
    with pytest.raises(ValueError, match="reduction"):
        LPIPS(net_type="alex", reduction="max")


# ---------------------------------------------------------------------------
# Inception-v3 backbone: shape/tap smoke test (tiny batch; full 299x299 graph)
# ---------------------------------------------------------------------------


def test_inception_v3_taps():
    from metrics_tpu.models.inception import inception_v3_apply, inception_v3_init

    params = inception_v3_init(jax.random.PRNGKey(0))
    imgs = jnp.asarray(np.random.RandomState(SEED).rand(1, 3, 32, 32).astype(np.float32))
    out = inception_v3_apply(params, imgs, ("64", "192", "768", "2048", "logits_unbiased", "logits"))
    assert out["64"].shape == (1, 64)
    assert out["192"].shape == (1, 192)
    assert out["768"].shape == (1, 768)
    assert out["2048"].shape == (1, 2048)
    assert out["logits_unbiased"].shape == (1, 1008)
    assert np.isfinite(np.asarray(out["2048"])).all()


def test_bfloat16_extractor_runs_and_tracks_float32():
    """The dtype knob must actually run (preprocessing is float32, so the
    CNN input needs a cast to the params dtype — a conv dtype mismatch here
    went uncaught until r4) and stay close to the f32 features. Pure-JAX:
    deliberately NOT in the torch-gated parity module so a torch-less
    image still runs it."""
    import numpy as np
    import jax.numpy as jnp

    from metrics_tpu.models.inception import InceptionFeatureExtractor

    rng = np.random.RandomState(77)
    imgs = jnp.asarray(rng.randint(0, 256, (2, 3, 64, 64)).astype(np.uint8))
    f32 = InceptionFeatureExtractor(feature=64)(imgs)
    bf16 = InceptionFeatureExtractor(feature=64, dtype=jnp.bfloat16)(imgs)
    assert bf16.dtype == jnp.float32  # features are returned re-promoted
    assert np.isfinite(np.asarray(bf16)).all()
    np.testing.assert_allclose(np.asarray(bf16), np.asarray(f32), atol=0.15, rtol=0.15)
