"""Shared axon-tunnel probe for the hardware-evidence scripts.

jax.devices() against a dead axon tunnel blocks forever in-process
(probe_log.txt is a museum of such hangs), so the probe runs in a killable
subprocess with an external timeout.
"""
from __future__ import annotations

import subprocess
import sys

PROBE_TIMEOUT_S = 75


def probe_tunnel(timeout_s: int = PROBE_TIMEOUT_S) -> bool:
    """True when the TPU backend answers within ``timeout_s``; never hangs."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; print('OK', jax.devices()[0])"],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"backend probe hung ({timeout_s}s) — tunnel dead", file=sys.stderr)
        return False
    if r.returncode != 0 or "OK" not in r.stdout:
        print(f"backend probe failed: {(r.stdout + r.stderr)[-300:]}", file=sys.stderr)
        return False
    return True
