"""Precision-recall curve — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/precision_recall_curve.py:23-334``.
Curve outputs are inherently dynamic-shape (one point per distinct threshold),
so these run eagerly at compute() time; the jit/constant-memory alternative is
the Binned* family (``metrics_tpu/classification/binned_precision_recall.py``),
which the TPU build treats as the preferred hot-path design.
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn


def _binary_clf_curve(
    preds: Array,
    target: Array,
    sample_weights: Optional[Sequence] = None,
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Cumulative fps/tps per distinct score threshold (descending).

    Same contract as the reference's ``_binary_clf_curve``
    (``precision_recall_curve.py:23-61``, itself following sklearn's
    ``_ranking.py``): argsort + cumsum, deduplicated at distinct values.
    """
    if sample_weights is not None and not isinstance(sample_weights, jnp.ndarray):
        sample_weights = jnp.asarray(sample_weights, dtype=jnp.float32)

    if preds.ndim > target.ndim:
        preds = preds[:, 0]
    desc = jnp.argsort(-preds)
    preds = preds[desc]
    target = target[desc]
    weight = sample_weights[desc] if sample_weights is not None else 1.0

    distinct_idx = jnp.nonzero(preds[1:] - preds[:-1])[0]
    threshold_idxs = jnp.concatenate(
        [distinct_idx, jnp.asarray([target.shape[0] - 1], dtype=distinct_idx.dtype)]
    )
    target = (target == pos_label).astype(jnp.int32)
    tps = jnp.cumsum(target * weight, axis=0)[threshold_idxs]
    if sample_weights is not None:
        fps = jnp.cumsum((1 - target) * weight, axis=0)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps
    return fps, tps, preds[threshold_idxs]


def _precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
) -> Tuple[Array, Array, int, Optional[int]]:
    """Normalize inputs to (flat) binary / [N', C] layout."""
    if preds.ndim == target.ndim:
        if pos_label is None:
            rank_zero_warn("`pos_label` automatically set 1.")
            pos_label = 1
        if num_classes is not None and num_classes != 1:
            if num_classes != preds.shape[1]:
                raise ValueError(
                    f"Argument `num_classes` was set to {num_classes} in metric"
                    f" `precision_recall_curve` but detected {preds.shape[1]} number of classes from predictions"
                )
            preds = jnp.swapaxes(preds, 0, 1).reshape(num_classes, -1).swapaxes(0, 1)
            target = jnp.swapaxes(target, 0, 1).reshape(num_classes, -1).swapaxes(0, 1)
        else:
            preds = preds.ravel()
            target = target.ravel()
            num_classes = 1
    elif preds.ndim == target.ndim + 1:
        if pos_label is not None:
            rank_zero_warn(
                f"Argument `pos_label` should be `None` when running multiclass precision recall curve. Got {pos_label}"
            )
        if num_classes != preds.shape[1]:
            raise ValueError(
                f"Argument `num_classes` was set to {num_classes} in metric"
                f" `precision_recall_curve` but detected {preds.shape[1]} number of classes from predictions"
            )
        preds = jnp.swapaxes(preds, 0, 1).reshape(num_classes, -1).swapaxes(0, 1)
        target = target.ravel()
    else:
        raise ValueError("preds and target must have same number of dimensions, or one additional dimension for preds")
    return preds, target, num_classes, pos_label


def _precision_recall_curve_compute_single_class(
    preds: Array,
    target: Array,
    pos_label: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[Array, Array, Array]:
    fps, tps, thresholds = _binary_clf_curve(preds, target, sample_weights, pos_label)
    precision = tps / (tps + fps)
    recall = tps / tps[-1]

    # stop when full recall attained, reverse so recall is decreasing
    last_ind = int(jnp.nonzero(tps == tps[-1])[0][0])
    sl = slice(0, last_ind + 1)
    precision = jnp.concatenate([precision[sl][::-1], jnp.ones(1, dtype=precision.dtype)])
    recall = jnp.concatenate([recall[sl][::-1], jnp.zeros(1, dtype=recall.dtype)])
    thresholds = thresholds[sl][::-1]
    return precision, recall, thresholds


def _precision_recall_curve_compute_multi_class(
    preds: Array,
    target: Array,
    num_classes: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[List[Array], List[Array], List[Array]]:
    precision, recall, thresholds = [], [], []
    for cls in range(num_classes):
        preds_cls = preds[:, cls]
        prc_args = dict(preds=preds_cls, target=target, num_classes=1, pos_label=cls, sample_weights=sample_weights)
        if target.ndim > 1:
            prc_args.update(dict(target=target[:, cls], pos_label=1))
        res = precision_recall_curve(**prc_args)
        precision.append(res[0])
        recall.append(res[1])
        thresholds.append(res[2])
    return precision, recall, thresholds


def _precision_recall_curve_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    if num_classes == 1:
        if pos_label is None:
            pos_label = 1
        return _precision_recall_curve_compute_single_class(preds, target, pos_label, sample_weights)
    return _precision_recall_curve_compute_multi_class(preds, target, num_classes, sample_weights)


def precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Exact precision–recall pairs at every distinct score, in one
    stateless call — functional twin of
    :class:`~metrics_tpu.PrecisionRecallCurve` (argsort + cumulative sums;
    O(N log N), no threshold loop).

    Args:
        preds: binary scores ``[N]`` or per-class scores ``[N, C]``.
        target: labels of the matching shape.
        num_classes: class count for multiclass scores.
        pos_label: the label treated as positive in binary input.
        sample_weights: optional per-sample weights for the counts.

    Returns:
        ``(precision, recall, thresholds)`` — arrays for binary input,
        per-class lists for multiclass; precision/recall carry the
        appended (1, 0) endpoint so they are one longer than thresholds.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import precision_recall_curve
        >>> pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> precisions, recalls, thresholds = precision_recall_curve(pred, target, pos_label=1)
        >>> print(precisions)
        [0.6666667 0.5       0.        1.       ]
    """
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    return _precision_recall_curve_compute(preds, target, num_classes, pos_label, sample_weights)
