"""Binned PR-family matrices vs sklearn curve oracles.

Mirror of the reference's `tests/classification/test_binned_precision_recall.py`:
BinnedRecallAtFixedPrecision over binary / plausible / multilabel fixtures ×
min_precision sweep (inputs rounded to 2 decimals so 101 bins capture the
curve exactly), and BinnedAveragePrecision vs sklearn's continuous AP, all
through class accumulation (single + 2-rank merge).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_average_precision
from sklearn.metrics import precision_recall_curve as sk_precision_recall_curve

from metrics_tpu import BinnedAveragePrecision, BinnedRecallAtFixedPrecision
from tests.classification.inputs import (
    _input_binary_prob,
    _input_binary_prob_plausible as _input_binary_prob_ok,
    _input_multilabel_prob as _input_mlb_prob,
    _input_multilabel_prob_plausible as _input_mlb_prob_ok,
)
from tests.helpers.testers import NUM_CLASSES, accumulate_and_merge


def _recall_at_precision(predictions, targets, min_precision):
    """Reference `test_binned_precision_recall.py:37-47`."""
    precision, recall, thresholds = sk_precision_recall_curve(targets, predictions)
    tuple_all = [(r, p, t) for p, r, t in zip(precision, recall, thresholds) if p >= min_precision]
    if not tuple_all:
        return 0.0, 1e6
    max_recall, _, best_threshold = max(tuple_all)
    return float(max_recall), float(best_threshold)


_GRID = [
    (_input_binary_prob, 1, "binary"),
    (_input_binary_prob_ok, 1, "binary_plausible"),
    (_input_mlb_prob_ok, NUM_CLASSES, "multilabel_plausible"),
    (_input_mlb_prob, NUM_CLASSES, "multilabel"),
]
_IDS = [g[2] for g in _GRID]


@pytest.mark.parametrize("inputs, num_classes, _name", _GRID, ids=_IDS)
@pytest.mark.parametrize("min_precision", [0.05, 0.1, 0.3, 0.5, 0.8, 0.95])
@pytest.mark.parametrize("world", [1, 2], ids=["single", "ddp_merge"])
def test_binned_recall_at_fixed_precision(inputs, num_classes, _name, min_precision, world):
    # rounding to 2 decimals makes the 101-threshold binning exact for both
    preds = np.round(np.asarray(inputs.preds), 2) + 1e-6
    target = np.asarray(inputs.target)

    recalls, thresholds = accumulate_and_merge(
        lambda: BinnedRecallAtFixedPrecision(
            num_classes=num_classes, min_precision=min_precision, thresholds=101
        ),
        preds, target, world,
    )
    p_all = preds.reshape(-1, num_classes) if num_classes > 1 else preds.reshape(-1)
    t_all = target.reshape(-1, num_classes) if num_classes > 1 else target.reshape(-1)
    def check(ours_r, ours_t, exp_r, exp_t, msg):
        np.testing.assert_allclose(ours_r, exp_r, atol=0.02, err_msg=msg)
        # thresholds agree within one bin width (or both hit the no-bin
        # sentinel, 1e6)
        if exp_t >= 1e6 or ours_t >= 1e6:
            assert exp_t >= 1e6 and ours_t >= 1e6, f"{msg}: sentinel mismatch ({ours_t} vs {exp_t})"
        else:
            np.testing.assert_allclose(ours_t, exp_t, atol=0.02, err_msg=msg)

    if num_classes == 1:
        exp_r, exp_t = _recall_at_precision(p_all, t_all, min_precision)
        check(float(jnp.ravel(jnp.asarray(recalls))[0]), float(jnp.ravel(jnp.asarray(thresholds))[0]),
              exp_r, exp_t, "binary")
    else:
        for c in range(num_classes):
            exp_r, exp_t = _recall_at_precision(p_all[:, c], t_all[:, c], min_precision)
            check(float(np.asarray(recalls)[c]), float(np.asarray(thresholds)[c]), exp_r, exp_t, f"class {c}")


@pytest.mark.parametrize("inputs, num_classes, _name", _GRID, ids=_IDS)
@pytest.mark.parametrize("world", [1, 2], ids=["single", "ddp_merge"])
def test_binned_average_precision(inputs, num_classes, _name, world):
    preds = np.round(np.asarray(inputs.preds), 2) + 1e-6
    target = np.asarray(inputs.target)

    result = accumulate_and_merge(
        lambda: BinnedAveragePrecision(num_classes=num_classes, thresholds=101),
        preds, target, world,
    )
    p_all = preds.reshape(-1, num_classes) if num_classes > 1 else preds.reshape(-1)
    t_all = target.reshape(-1, num_classes) if num_classes > 1 else target.reshape(-1)
    expected = np.nan_to_num(sk_average_precision(t_all, p_all, average=None))
    np.testing.assert_allclose(
        np.ravel(np.asarray(jnp.asarray(result))), np.ravel(np.atleast_1d(expected)), atol=0.02
    )
