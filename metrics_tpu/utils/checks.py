"""Classification / retrieval input normalization.

Behavioral analogue of the reference's ``torchmetrics/utilities/checks.py:23-583``,
re-designed for XLA:

- **Case dispatch is static.** Which of binary / multi-label / multi-class /
  multi-dim multi-class a ``(preds, target)`` pair falls into depends only on
  shapes and dtypes, both static under jit — so :func:`_input_format_classification`
  traces cleanly when ``num_classes`` is provided.
- **Value-dependent validation is eager-only.** Checks like ``target.min() < 0``
  (reference ``checks.py:32-48``) force a device sync; they run only on concrete
  (non-traced) arrays and are skipped inside jit, mirroring the reference's
  guidance that validation move out of the hot path.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import DataType


try:  # 161 ns trace-context check; private, so fall back to a probe op
    from jax._src.core import EvalTrace as _EvalTrace, trace_ctx as _trace_ctx

    def _tracing_active() -> bool:
        return not isinstance(_trace_ctx.trace, _EvalTrace)

except ImportError:  # pragma: no cover - older/newer jax layout

    def _tracing_active() -> bool:
        from metrics_tpu.utils.data import is_traced

        return is_traced(jnp.zeros(()) + 0)


def _is_concrete(*arrays: Array) -> bool:
    """True when running eagerly: no argument is a tracer AND no trace is
    ambient. The second condition matters for jit/scan over closure-constant
    inputs — the arguments look concrete, but any op on them binds to the
    ambient trace, so value-dependent validation would blow up on `int()`."""
    from metrics_tpu.utils.data import is_traced

    if any(is_traced(a) for a in arrays):
        return False
    return not _tracing_active()


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _check_same_shape(preds: Array, target: Array) -> None:
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, "
            f"got {preds.shape} and {target.shape}"
        )


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Drop all size-1 dims except the leading batch dim (static reshape)."""

    def squeeze_keep_batch(x: Array) -> Array:
        if x.ndim <= 1:
            return x
        kept = [x.shape[0]] + [d for d in x.shape[1:] if d != 1]
        return x.reshape(kept)

    return squeeze_keep_batch(jnp.asarray(preds)), squeeze_keep_batch(jnp.asarray(target))


def _classify_case(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Static shape/dtype-based case detection.

    Returns ``(case, implied_classes)``; raises on inconsistent shapes. This is
    the dispatch half of the reference's ``_check_shape_and_type_consistency``
    (``checks.py:51-106``) with every decision jit-static.
    """
    preds_float = _is_floating(preds)
    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                f"The `preds` and `target` should have the same shape, got "
                f"{preds.shape} and {target.shape}."
            )
        if preds.ndim == 1:
            case = DataType.BINARY if preds_float else DataType.MULTICLASS
        else:
            case = DataType.MULTILABEL if preds_float else DataType.MULTIDIM_MULTICLASS
        implied_classes = 1
        for d in preds.shape[1:]:
            implied_classes *= d
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError(
                "If `preds` have one dimension more than `target`, `preds` should be a float tensor."
            )
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be "
                "(N, C, ...) and the shape of `target` (N, ...)."
            )
        implied_classes = preds.shape[1]
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` "
            "should be (N, ...) and `preds` should be (N, C, ...)."
        )
    return case, implied_classes


def _validate_values(
    preds: Array,
    target: Array,
    case: DataType,
    implied_classes: int,
    num_classes: Optional[int],
    multiclass: Optional[bool],
) -> None:
    """Value-dependent validation; eager-only (skipped under jit tracing)."""
    if not _is_concrete(preds, target):
        return
    if _is_floating(target):
        raise ValueError("The `target` has to be an integer tensor.")
    if int(jnp.min(target)) < 0:
        raise ValueError("The `target` has to be a non-negative tensor.")
    preds_float = _is_floating(preds)
    if not preds_float and int(jnp.min(preds)) < 0:
        raise ValueError("If `preds` are integers, they have to be non-negative.")
    if preds.shape[0] != target.shape[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")
    if multiclass is False and int(jnp.max(target)) > 1:
        raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
    if multiclass is False and not preds_float and int(jnp.max(preds)) > 1:
        raise ValueError(
            "If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1."
        )
    if preds.ndim == target.ndim and preds_float and int(jnp.max(target)) > 1:
        raise ValueError(
            "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
        )
    if preds.shape != target.shape:
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if int(jnp.max(target)) >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )
    if num_classes:
        if case == DataType.BINARY:
            if num_classes > 2:
                raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
            if num_classes == 2 and not multiclass:
                raise ValueError(
                    "Your data is binary and `num_classes=2`, but `multiclass` is not True."
                )
            if num_classes == 1 and multiclass:
                raise ValueError(
                    "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
                )
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            if num_classes == 1 and multiclass is not False:
                raise ValueError(
                    "You have set `num_classes=1`, but predictions are integers."
                    " If you want to convert (multi-dimensional) multi-class data with 2 classes"
                    " to binary/multi-label, set `multiclass=False`."
                )
            if num_classes > 1:
                if multiclass is False and implied_classes != num_classes:
                    raise ValueError(
                        "You have set `multiclass=False`, but the implied number of classes"
                        " does not match `num_classes`."
                    )
                if num_classes <= int(jnp.max(target)):
                    raise ValueError(
                        "The highest label in `target` should be smaller than `num_classes`."
                    )
                if preds.shape != target.shape and num_classes != implied_classes:
                    raise ValueError(
                        "The size of C dimension of `preds` does not match `num_classes`."
                    )
        elif case == DataType.MULTILABEL:
            if multiclass and num_classes != 2:
                raise ValueError(
                    "You have set `multiclass=True`, but `num_classes` is not equal to 2."
                )
            if not multiclass and num_classes != implied_classes:
                raise ValueError(
                    "The implied number of classes (from shape of inputs) does not match num_classes."
                )


def _check_top_k(
    top_k: int, case: DataType, implied_classes: int, multiclass: Optional[bool], preds_float: bool
) -> None:
    if case == DataType.BINARY:
        raise ValueError("You can not use `top_k` parameter with binary data.")
    if not isinstance(top_k, int) or top_k <= 0:
        raise ValueError("The `top_k` has to be an integer larger than 0.")
    if not preds_float:
        raise ValueError("You have set `top_k`, but you do not have probability predictions.")
    if multiclass is False:
        raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
    if case == DataType.MULTILABEL and multiclass:
        raise ValueError(
            "If you want to transform multi-label data to 2 class multi-dimensional"
            " multi-class data using `multiclass=True`, you can not use `top_k`."
        )
    if top_k >= implied_classes:
        raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
) -> DataType:
    """Full input validation; returns the detected case.

    Analogue of the reference's ``checks.py:190-281``. Static checks always run;
    value checks only when arrays are concrete.
    """
    case, implied_classes = _classify_case(preds, target)
    _validate_values(preds, target, case, implied_classes, num_classes, multiclass)
    # threshold sanity, probability- and usage-aware — EAGER-ONLY, like every
    # value-dependent check here: under jit the values are tracers, and probs
    # cannot be told apart from logits without values, so a jitted call with a
    # mistyped threshold computes straight through (run one eager batch first
    # if you want this net — the Metric classes do exactly that on their first
    # update). Beyond that boundary: thresholds
    # live in the input's own space — raw logits may legitimately cut at 0.0
    # (or any real) — and only binary/multi-label cases threshold at all
    # (multi-class probs go through top-k). For probability-valued preds on a
    # thresholded case, a threshold outside (0,1) silently maps every
    # prediction to one class; the reference documents this contract (e.g.
    # ``classification/hamming_distance.py:59``) without enforcing it
    # anywhere — enforcing it here covers every threshold consumer at once.
    if (
        case in (DataType.BINARY, DataType.MULTILABEL)
        and not top_k
        and _is_floating(preds)
        and _is_concrete(preds)
        and not 0 < threshold < 1
        and bool(jnp.all((preds >= 0) & (preds <= 1)))
    ):
        raise ValueError(
            f"The `threshold` {threshold} is outside (0,1) but `preds` are probabilities;"
            " probability thresholds must lie strictly between 0 and 1"
            " (raw logit inputs may use any threshold)."
        )
    if top_k is not None:
        _check_top_k(top_k, case, implied_classes, multiclass, _is_floating(preds))
    return case


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    validate: bool = True,
) -> Tuple[Array, Array, DataType]:
    """Normalize any accepted (preds, target) pair to binary int arrays.

    Output shapes are ``(N, C)`` or ``(N, C, X)``; semantics mirror the
    reference's ``_input_format_classification`` (``checks.py:296-432``):

    - binary / multi-label float preds are thresholded (or top-k'd for
      multi-label with ``top_k``);
    - (multi-dim) multi-class preds/targets are one-hot encoded, float preds by
      top-k selection over the C dim;
    - ``multiclass=True`` lifts binary/multi-label to 2-class one-hot form;
      ``multiclass=False`` projects 2-class data down to the positive column.

    jit-compatible when ``num_classes`` is given (or implied by a C dim) and
    ``validate=False`` or inputs are traced.
    """
    preds, target = _input_squeeze(preds, target)
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype == jnp.float16 or preds.dtype == jnp.bfloat16:
        preds = preds.astype(jnp.float32)

    if validate:
        case = _check_classification_inputs(
            preds, target, threshold=threshold, num_classes=num_classes,
            multiclass=multiclass, top_k=top_k,
        )
    else:
        case, _ = _classify_case(preds, target)

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32)
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if _is_floating(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if not num_classes:
                # data-dependent inference: eager only
                num_classes = int(max(int(jnp.max(preds)), int(jnp.max(target)))) + 1
            preds = to_onehot(preds, max(2, num_classes))
        target = to_onehot(target, max(2, num_classes))
        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
        target = target.reshape(target.shape[0], target.shape[1], -1)
        preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
    else:
        target = target.reshape(target.shape[0], -1)
        preds = preds.reshape(preds.shape[0], -1)

    # squeeze the trailing X dim the reshapes above introduce for plain MC/binary
    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = preds.squeeze(-1), target.squeeze(-1)

    return preds.astype(jnp.int32), target.astype(jnp.int32), case


def _check_retrieval_target_and_prediction_types(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    if not (
        jnp.issubdtype(target.dtype, jnp.integer)
        or target.dtype == jnp.bool_
        or _is_floating(target)
    ):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target and _is_concrete(target):
        if int(jnp.max(target)) > 1 or int(jnp.min(target)) < 0:
            raise ValueError("`target` must contain `binary` values")
    target = (
        target.astype(jnp.float32).ravel()
        if _is_floating(target)
        else target.astype(jnp.int32).ravel()
    )
    return preds.astype(jnp.float32).ravel(), target


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.size == 0 or preds.ndim == 0:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    return _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)


def _check_retrieval_inputs(
    indexes: Array, preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array, Array]:
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if indexes.size == 0 or indexes.ndim == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    preds, target = _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)
    return indexes.astype(jnp.int32).ravel(), preds, target


def _check_retrieval_k(k) -> None:
    """Shared top-k validation for retrieval metrics (module + functional
    layers); rejects bools, which python counts as ints."""
    if k is not None and (isinstance(k, bool) or not isinstance(k, int) or k <= 0):
        raise ValueError("`k` has to be a positive integer or None")
