"""Deprecated module alias for :func:`r2_score`.

Parity shim mirroring the reference's
``torchmetrics/functional/regression/r2score.py:1-48`` (deprecated in its
v0.5: ``r2score`` renamed ``r2_score``): the shim warns and hands off to the
real implementation. As in the reference, the package re-export rebinds the
``regression.r2score`` attribute to this *function*, so reach the shim via
``from metrics_tpu.functional import r2score`` (dotted module access resolves
to the function, not this module).
"""
from warnings import warn

from metrics_tpu.functional.regression.r2 import r2_score
from metrics_tpu.utils.data import Array


def r2score(
    preds: Array,
    target: Array,
    adjusted: int = 0,
    multioutput: str = "uniform_average",
) -> Array:
    """Deprecated alias of :func:`r2_score` (reference
    ``torchmetrics/functional/regression/r2score.py:22-60``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import r2score
        >>> print(round(float(r2score(jnp.asarray([2.5, 0.0, 2.0, 8.0]), jnp.asarray([3.0, -0.5, 2.0, 7.0]))), 4))
        0.9486
    """
    warn(
        "`functional.r2score` was renamed to `functional.r2_score` and will be removed.",
        DeprecationWarning,
    )
    return r2_score(preds, target, adjusted, multioutput)
