"""Two-level (tiered) collective schedule suite (ISSUE 20 tentpole).

The contract under test: with a tier map configured
(``tiering.set_tier_map`` / ``METRICS_TPU_TIER_SIZE``) and a subset
transport installed, every bucketed sync runs reduce-within-tier first,
ONE inter-tier exchange per bucket, then an intra-tier broadcast — and at
full precision the result is **bit-identical** to today's flat world
gather for reduce AND cat states, over real :class:`LockstepWorld`
rendezvous collectives. Quantization (``sync_precision="bf16"/"int8"``)
engages ONLY the slow hop, only on explicit opt-in, stays within the
documented tolerance, and is exactly bit-stable run-to-run. Asymmetric
tier maps and mixed-precision ranks fail loudly and symmetrically through
the health word's v5 columns (typed :class:`StateDivergenceError` on
every rank, before any payload moves). FleetWorld rows: a dead rank
inside a tier shrinks the quorum and renegotiates the topology in the
same membership epoch; a whole dead tier collapses the layout to the
degenerate (flat) schedule.
"""
import contextlib
import threading
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.parallel.async_sync as async_mod
import metrics_tpu.parallel.resilience as resilience
import metrics_tpu.parallel.sync as sync_mod
from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu.core.plan import clear_plans, tier_schedule_for
from metrics_tpu.observability import journal
from metrics_tpu.observability.trace_export import chrome_trace
from metrics_tpu.parallel import tiering
from metrics_tpu.parallel.bucketing import clear_sync_plan_cache
from metrics_tpu.parallel.health import reset_channel_health
from metrics_tpu.parallel.quantize import validate_sync_precision
from metrics_tpu.parallel.sync import host_sync_state
from metrics_tpu.utils.exceptions import MetricsTPUUserError, StateDivergenceError
from tests.helpers.fake_world import FaultProfile, FleetWorld, LockstepWorld

pytestmark = pytest.mark.filterwarnings("ignore::UserWarning")

WORLD = 8


@pytest.fixture(autouse=True)
def _fresh_tiering():
    clear_sync_plan_cache()
    clear_plans()
    tiering.reset_tiering()
    reset_channel_health()
    journal.clear()
    yield
    clear_sync_plan_cache()
    clear_plans()
    tiering.reset_tiering()
    reset_channel_health()
    journal.disable()
    journal.clear()


@contextlib.contextmanager
def _lockstep(world=WORLD, tier_size=None):
    """A LockstepWorld wired over every seam the tiered stack reaches
    through: the flat gather, the per-rank identity (so each fake rank
    derives ITS OWN topology view), the async executor lanes, and — when
    ``tier_size`` is given — the explicit tier map + subset transport."""
    w = LockstepWorld(world)
    saved = (
        jax.process_count,
        sync_mod._raw_process_allgather,
        tiering._current_rank,
        async_mod._get_executor,
        async_mod._current_domain,
    )
    jax.process_count = lambda: world
    sync_mod._raw_process_allgather = w.allgather
    tiering._current_rank = lambda: w._rank.value
    async_mod._get_executor = w.executor_for_current_rank
    async_mod._current_domain = w.rank_domain
    if tier_size is not None:
        tiering.set_tier_map(tier_size)
        tiering.set_tier_transport(w)
    try:
        yield w
    finally:
        (
            jax.process_count,
            sync_mod._raw_process_allgather,
            tiering._current_rank,
            async_mod._get_executor,
            async_mod._current_domain,
        ) = saved
        tiering.reset_tiering()
        clear_plans()
        w.shutdown_executors()


def _mixed_state(rank: int):
    """Mixed dtypes/reductions, uneven cat rows and a CatBuffer — every
    payload class the bucketed engine routes."""
    buf = CatBuffer(16)
    buf.append(jnp.arange(2 + rank, dtype=jnp.float32) + 10.0 * rank)
    state = {
        "sum_f32": jnp.asarray([[1.5, 2.5]]) * (rank + 1),
        "sum_i32": jnp.asarray([2, 3], jnp.int32) + rank,
        "mean_f32": jnp.asarray([0.25, 0.75]) + rank,
        "max_f32": jnp.asarray(1.0 + 3 * rank),
        "cat_f32": jnp.arange(3 + rank, dtype=jnp.float32) + 10.0 * rank,
        "buf": buf,
    }
    reductions = {
        "sum_f32": "sum", "sum_i32": "sum", "mean_f32": "mean",
        "max_f32": "max", "cat_f32": "cat", "buf": "cat",
    }
    return state, reductions


def _state_bytes(state):
    out = {}
    for name in sorted(state):
        v = state[name]
        if isinstance(v, CatBuffer):
            out[name] = (
                v.capacity,
                int(np.asarray(v.count)),
                np.asarray(v.buffer).tobytes(),
            )
        elif isinstance(v, list):
            out[name] = tuple(np.asarray(x).tobytes() for x in v)
        else:
            arr = np.asarray(v)
            out[name] = (arr.dtype.str, arr.shape, arr.tobytes())
    return out


def _run_sync(tier_size=None, sync_precision=None, world=WORLD):
    """Drive one host_sync_state round on every rank; returns the per-rank
    (state bytes, stats dict, rendezvous call count)."""
    with _lockstep(world, tier_size) as w:

        def body(rank):
            state, reds = _mixed_state(rank)
            stats = {}
            synced = host_sync_state(
                state, reds, update_count=1, timeout=0, metric_name="tiered",
                sync_precision=sync_precision, stats=stats,
            )
            return _state_bytes(synced), stats, w.calls

        return w.run(body)


# ---------------------------------------------------------------------------
# bit-identity: tiered full precision ≡ flat, reduce + cat, real collectives
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("tier_size", [2, 4])
def test_tiered_full_precision_bit_identical_to_flat(tier_size):
    flat = _run_sync(tier_size=None)
    tiered = _run_sync(tier_size=tier_size)
    for rank in range(WORLD):
        assert tiered[rank][0] == flat[rank][0], rank
    # SPMD symmetric: every rank holds the identical synced view
    assert all(tiered[r][0] == tiered[0][0] for r in range(WORLD))
    # the slow hop really did shrink: per-rank byte counters populated
    stats = tiered[0][1]
    assert stats["intra_tier_bytes"] > 0
    assert stats["inter_tier_bytes"] > 0


def test_tiered_mean_matches_flat_bitwise():
    """mean routes through sum-of-partials / live-count on both paths —
    the tiered combine must land on the identical float."""
    flat = _run_sync(tier_size=None)
    tiered = _run_sync(tier_size=2)
    for rank in range(WORLD):
        assert tiered[rank][0]["mean_f32"] == flat[rank][0]["mean_f32"]


def test_flat_world_pays_zero_extra_collectives():
    """No tier map -> the flat path, same rendezvous count as HEAD; a
    degenerate map (single tier) must also collapse to exactly that."""
    flat = _run_sync(tier_size=None)
    single_tier = _run_sync(tier_size=WORLD)  # one tier == flat world
    per_rank = _run_sync(tier_size=1)  # one rank per tier == flat world
    for rank in range(WORLD):
        assert single_tier[rank][0] == flat[rank][0]
        assert per_rank[rank][0] == flat[rank][0]
    assert single_tier[0][2] == flat[0][2]  # identical collective budget
    assert per_rank[0][2] == flat[0][2]
    assert "inter_tier_bytes" not in single_tier[0][1]


# ---------------------------------------------------------------------------
# overlapped + grouped paths launch the same tiered schedule
# ---------------------------------------------------------------------------


class _Sum(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)
        self.count = self.count + jnp.asarray(jnp.size(x), jnp.int32)

    def compute(self):
        return self.total / self.count


def _metric_bytes(m):
    return tuple(np.asarray(m._state[name]).tobytes() for name in sorted(m._defaults))


def _run_overlapped(tier_size):
    with _lockstep(WORLD, tier_size) as w:

        def body(rank):
            feed = jnp.asarray([1.0 + rank, 2.0 * (rank + 1)])
            over = _Sum(sync_timeout=0)
            block = _Sum(sync_timeout=0)
            over.update(feed)
            block.update(feed)
            block.sync()
            over.sync(blocking=False)  # overlapped launch rides the same schedule
            over.sync()
            bits = (_metric_bytes(over), _metric_bytes(block))
            over.unsync()
            block.unsync()
            return bits

        return w.run(body)


@pytest.mark.parametrize("tier_size", [2, 4])
def test_overlapped_round_bit_identical_tiered_vs_blocking(tier_size):
    flat = _run_overlapped(None)
    tiered = _run_overlapped(tier_size)
    for rank in range(WORLD):
        over_bits, block_bits = tiered[rank]
        assert over_bits == block_bits  # overlapped ≡ blocking, tiered
        assert over_bits == flat[rank][0]  # tiered ≡ flat, bitwise


def _run_grouped(tier_size):
    with _lockstep(WORLD, tier_size) as w:

        def body(rank):
            mc = MetricCollection({"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)})
            mc.update(jnp.asarray([1.0 + rank, 0.5 * rank]))
            mc.sync()  # ONE fused round for the whole collection
            bits = tuple(_metric_bytes(m) for m in mc.values())
            mc.unsync()
            return bits

        return w.run(body)


@pytest.mark.parametrize("tier_size", [2, 4])
def test_grouped_fused_collection_bit_identical_tiered_vs_flat(tier_size):
    flat = _run_grouped(None)
    tiered = _run_grouped(tier_size)
    for rank in range(WORLD):
        assert tiered[rank] == flat[rank]


# ---------------------------------------------------------------------------
# quantized slow hop: opt-in only, documented tolerance, bit-stable
# ---------------------------------------------------------------------------

_FLOAT_KEYS = ("sum_f32", "mean_f32", "max_f32", "cat_f32", "buf")


def _as_arrays(bytes_state):
    """Decode the _state_bytes tuples back to float arrays for allclose."""
    out = {}
    for name in _FLOAT_KEYS:
        entry = bytes_state[name]
        if name == "buf":
            out[name] = np.frombuffer(entry[2], np.float32)
        elif isinstance(entry, tuple) and isinstance(entry[0], bytes):
            out[name] = np.concatenate([np.frombuffer(b, np.float32) for b in entry])
        else:
            out[name] = np.frombuffer(entry[2], np.dtype(entry[0]))
    return out


def test_bf16_slow_hop_within_tolerance_and_bit_stable():
    flat = _run_sync(tier_size=None)
    q1 = _run_sync(tier_size=4, sync_precision="bf16")
    q2 = _run_sync(tier_size=4, sync_precision="bf16")
    for rank in range(WORLD):
        # exactly bit-stable run-to-run: deterministic encode/combine order
        assert q1[rank][0] == q2[rank][0], rank
        got = _as_arrays(q1[rank][0])
        want = _as_arrays(flat[rank][0])
        for name in _FLOAT_KEYS:
            # documented tolerance: bf16 mantissa (8 bits) -> rtol 2^-7
            np.testing.assert_allclose(
                got[name], want[name], rtol=2e-2, atol=1e-6, err_msg=name
            )
        # int32 payloads pass through raw even under the precision knob
        assert q1[rank][0]["sum_i32"] == flat[rank][0]["sum_i32"]
    assert all(q1[r][0] == q1[0][0] for r in range(WORLD))  # SPMD symmetric


def test_int8_slow_hop_within_tolerance_and_bit_stable():
    flat = _run_sync(tier_size=None)
    q1 = _run_sync(tier_size=4, sync_precision="int8")
    q2 = _run_sync(tier_size=4, sync_precision="int8")
    for rank in range(WORLD):
        assert q1[rank][0] == q2[rank][0], rank
        got = _as_arrays(q1[rank][0])
        want = _as_arrays(flat[rank][0])
        for name in _FLOAT_KEYS:
            # documented tolerance: block-scaled int8, 1/127 of block maxabs
            np.testing.assert_allclose(
                got[name], want[name], rtol=0.05, atol=0.1, err_msg=name
            )
        assert q1[rank][0]["sum_i32"] == flat[rank][0]["sum_i32"]


def test_quantization_needs_explicit_opt_in():
    """No ``sync_precision=`` -> full precision even with tiers configured
    (bit-identical, covered above); an unknown precision is a loud typed
    error at construction, not a silent fallback mid-sync."""
    with pytest.raises(MetricsTPUUserError, match="sync_precision"):
        validate_sync_precision("fp4")
    with pytest.raises(MetricsTPUUserError, match="sync_precision"):
        _Sum(sync_precision="fp4")
    with pytest.raises(MetricsTPUUserError, match="sync_precision"):
        MetricCollection({"a": _Sum()}, sync_precision="int4")
    # "full" is the explicit spelling of the default
    m = _Sum(sync_precision="full")
    assert m.sync_precision is None


def test_precision_without_tier_map_stays_flat_and_exact():
    """The knob quantizes ONLY the slow hop; with no tiers there is no
    slow hop, so results stay bit-identical to the flat gather."""
    flat = _run_sync(tier_size=None)
    q = _run_sync(tier_size=None, sync_precision="int8")
    for rank in range(WORLD):
        assert q[rank][0] == flat[rank][0]


# ---------------------------------------------------------------------------
# negotiation: asymmetric maps / mixed precision fail loudly + symmetrically
# ---------------------------------------------------------------------------


def test_mixed_precision_ranks_raise_on_every_rank():
    with _lockstep(4, tier_size=2) as w:

        def body(rank):
            state = {"s": jnp.asarray(1.0 + rank)}
            with pytest.raises(StateDivergenceError, match="precision"):
                host_sync_state(
                    state, {"s": "sum"}, update_count=1, timeout=0,
                    sync_precision="bf16" if rank % 2 == 0 else None,
                )
            return True

        assert w.run(body) == [True] * 4


def test_asymmetric_tier_map_raises_on_every_rank():
    with _lockstep(WORLD) as w:
        # ranks < 4 believe tier_size=2, ranks >= 4 believe tier_size=4 —
        # the health word's tier column catches the split before any
        # payload collective, on EVERY rank
        tiering.set_tier_map(lambda r: r // (2 if w._rank.value < 4 else 4))
        tiering.set_tier_transport(w)

        def body(rank):
            state = {"s": jnp.asarray(1.0 + rank)}
            with pytest.raises(StateDivergenceError, match="tier"):
                host_sync_state(state, {"s": "sum"}, update_count=1, timeout=0)
            return True

        assert w.run(body) == [True] * WORLD


def test_unconfigured_peer_raises_on_every_rank():
    """One rank with NO tier map against configured peers is the classic
    deploy skew — must fail typed and symmetric, not deadlock."""
    with _lockstep(4) as w:
        tiering.set_tier_map(lambda r: -1 if w._rank.value == 3 else r // 2)
        tiering.set_tier_transport(w)

        def body(rank):
            with pytest.raises(StateDivergenceError, match="tier"):
                host_sync_state(
                    {"s": jnp.asarray(1.0)}, {"s": "sum"}, update_count=1, timeout=0
                )
            return True

        assert w.run(body) == [True] * 4


# ---------------------------------------------------------------------------
# plan layer: one cached schedule per (schema, topology)
# ---------------------------------------------------------------------------


def test_tier_schedule_cached_per_schema_and_topology(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(tiering, "_current_rank", lambda: 0)
    transport = types.SimpleNamespace(subset_allgather=lambda x, ranks: x)
    tiering.set_tier_map(2)
    tiering.set_tier_transport(transport)
    plan = types.SimpleNamespace(schema_key="k1")

    sched = tier_schedule_for(plan)
    assert sched is not None
    assert sched.inter_participants == 2  # tiers, not ranks
    assert sched.flat_participants == 4
    assert sched.hops_per_bucket == 3
    assert tier_schedule_for(plan) is sched  # cached
    assert tier_schedule_for(types.SimpleNamespace(schema_key="k2")) is not sched

    clear_plans()
    assert tier_schedule_for(plan) is not sched  # invalidated with the plans
    assert tier_schedule_for(None) is None
    tiering.set_tier_map(None)
    assert tier_schedule_for(plan) is None  # flat world -> no schedule


# ---------------------------------------------------------------------------
# observability: per-hop counters, journal events, trace spans
# ---------------------------------------------------------------------------


def test_telemetry_counters_and_hop_events():
    journal.enable()
    tiered = _run_sync(tier_size=4)
    # fleet-wide: the tiered schedule must be a net win over flat gather
    saved = sum(t[1].get("inter_tier_bytes_saved", 0) for t in tiered)
    inter = sum(t[1].get("inter_tier_bytes", 0) for t in tiered)
    intra = sum(t[1].get("intra_tier_bytes", 0) for t in tiered)
    assert saved > 0 and inter > 0 and intra > 0

    hops = journal.events(kinds=["sync.hop"])
    assert hops, "tiered sync must journal its hops"
    assert {e.label for e in hops} == {"intra", "inter"}
    assert all(e.fields["tier"] >= 0 for e in hops)
    assert all(e.fields["participants"] >= 1 for e in hops)
    plans = journal.events(kinds=["plan.tier"])
    assert plans and plans[0].fields["inter_participants"] == 2  # 8 ranks / tier 4
    assert plans[0].fields["flat_participants"] == WORLD

    # Chrome-trace export: the two hop classes land on distinguishable spans
    cats = {ev.get("cat") for ev in chrome_trace()["traceEvents"]}
    assert "sync-intra-tier" in cats and "sync-inter-tier" in cats


def test_metric_surfaces_tier_counters_via_telemetry():
    with _lockstep(4, tier_size=2) as w:

        def body(rank):
            m = _Sum(sync_timeout=0)
            m.update(jnp.asarray([1.0 + rank]))
            m.sync()
            stats = m.sync_stats()
            m.unsync()
            return stats

        stats = w.run(body)
    assert sum(s.get("inter_tier_bytes", 0) for s in stats) > 0
    assert sum(s.get("intra_tier_bytes", 0) for s in stats) > 0
    assert sum(s.get("inter_tier_bytes_saved", 0) for s in stats) > 0


# ---------------------------------------------------------------------------
# FleetWorld: dead rank inside vs across a tier
# ---------------------------------------------------------------------------


@pytest.fixture
def fleet(monkeypatch):
    holder = {"world": None}

    def make(world=4, profile=None, tier_size=None, **kwargs):
        if holder["world"] is not None:
            holder["world"].uninstall()
        clear_sync_plan_cache()
        clear_plans()
        w = FleetWorld(world, profile, **kwargs)
        w.install(monkeypatch)
        if tier_size is not None:
            tiering.set_tier_map(tier_size)  # transport: the quorum fallback
        holder["world"] = w
        return w

    yield make
    if holder["world"] is not None:
        holder["world"].uninstall()
    clear_plans()


def _drive_fleet(world, steps):
    def body(rank):
        outs = []
        for step in range(steps):
            world.begin_round(rank, step)
            state = {
                "s": jnp.asarray(float(10 * rank + step)),
                "c": jnp.arange(1 + rank % 2, dtype=jnp.float32) + rank + step,
            }
            synced = host_sync_state(
                state, {"s": "sum", "c": "cat"}, update_count=1, timeout=0,
                on_missing="quorum", metric_name="fleet",
            )
            outs.append(_state_bytes(synced))
        topo = tiering.active_topology()
        layout = None if topo is None else (topo.n_tiers, topo.leaders, topo.live)
        return outs, resilience.membership_epoch(), resilience.live_ranks(), layout

    return world.run(body)


def test_fleet_dead_rank_inside_tier_renegotiates_same_epoch(fleet):
    """Rank 3 (tier 1) dies: survivors shrink to (0,1,2) in ONE membership
    transition and the tier map renegotiates in that same epoch — tier 1
    lives on with its single survivor, leaders recomputed from the
    survivor set, values bit-equal to a survivors-only reference fleet."""
    world = fleet(world=4, profile=FaultProfile(preempt_at={3: 1}, tier_size=2),
                  tier_size=2)
    results = _drive_fleet(world, 3)
    assert world.preempted == {3}
    assert results[3] is None

    for rank in (0, 1, 2):
        outs, epoch, live, layout = results[rank]
        assert epoch == 1  # exactly ONE transition: renegotiated in-epoch
        assert live == (0, 1, 2)
        assert layout == (2, (0, 2), (0, 1, 2))  # tier 1 = {2}, led by 2

    ref_world = fleet(world=3, tier_size=2)
    ref = _drive_fleet(ref_world, 3)
    for rank in (0, 1, 2):
        for step in (1, 2):  # post-death rounds gather over survivors
            assert results[rank][0][step] == ref[rank][0][step], (rank, step)
    assert results[0][0] == results[1][0] == results[2][0]


def test_fleet_dead_tier_collapses_to_degenerate_schedule(fleet):
    """Both ranks of tier 1 die: the surviving layout is a single tier, so
    the schedule must collapse to the flat (degenerate) path instead of
    scheduling an inter-tier hop with one participant."""
    world = fleet(
        world=4,
        profile=FaultProfile(preempt_at={2: 1, 3: 1}, tier_size=2),
        tier_size=2,
    )
    results = _drive_fleet(world, 3)
    assert world.preempted == {2, 3}

    for rank in (0, 1):
        outs, epoch, live, layout = results[rank]
        assert live == (0, 1)
        assert layout is None  # single surviving tier -> degenerate -> flat

    ref_world = fleet(world=2, tier_size=2)
    ref = _drive_fleet(ref_world, 3)
    for rank in (0, 1):
        for step in (1, 2):
            assert results[rank][0][step] == ref[rank][0][step], (rank, step)


def test_fleet_all_live_tiered_bit_identical_to_flat_quorum(fleet):
    """With everyone alive, the tiered quorum fleet and the flat quorum
    fleet agree bit-for-bit (the FleetWorld equivalence row)."""
    tiered_world = fleet(world=4, profile=FaultProfile(tier_size=2), tier_size=2)
    tiered = _drive_fleet(tiered_world, 2)
    flat_world = fleet(world=4)
    flat = _drive_fleet(flat_world, 2)
    for rank in range(4):
        assert tiered[rank][0] == flat[rank][0]
        assert tiered[rank][3] == (2, (0, 2), (0, 1, 2, 3))
