"""PSNR module — analogue of reference ``torchmetrics/image/psnr.py`` (147 LoC).

State pattern mirrors the reference: scalar sum states when ``dim`` is None
(psum-able, constant memory); cat-list states of per-slice statistics when
``dim`` is set; min/max-reduced range trackers when ``data_range`` must be
inferred (reference ``psnr.py:92-112``).
"""
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.image.psnr import _psnr_compute, _psnr_update
from metrics_tpu.utils.prints import rank_zero_warn


class PSNR(Metric):
    r"""Peak signal-to-noise ratio, accumulated over batches.

    Args:
        data_range: value range of the input; tracked from data when ``None``
            (disallowed when ``dim`` is set).
        base: logarithm base.
        reduction: 'elementwise_mean' | 'sum' | 'none' over per-``dim`` scores.
        dim: dimensions to reduce over; ``None`` = all (scalar states).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PSNR
        >>> preds = jnp.asarray([[[[0.0, 1.0], [2.0, 3.0]]]])
        >>> target = jnp.asarray([[[[3.0, 2.0], [1.0, 0.0]]]])
        >>> psnr = PSNR()
        >>> print(round(float(psnr(preds, target)), 4))
        2.5527
    """

    def __init__(
        self,
        data_range: Optional[float] = None,
        base: float = 10.0,
        reduction: str = "elementwise_mean",
        dim: Optional[Union[int, Tuple[int, ...]]] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(compute_on_step, dist_sync_on_step, process_group, dist_sync_fn)
        if dim is None and reduction != "elementwise_mean":
            rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")

        if dim is None:
            self.add_state("sum_squared_error", jnp.zeros(()), dist_reduce_fx="sum")
            # pixel counts overflow int32 on large datasets; float32 accumulates safely
            self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        else:
            self.add_state("sum_squared_error", [], dist_reduce_fx="cat")
            self.add_state("total", [], dist_reduce_fx="cat")

        if data_range is None:
            if dim is not None:
                raise ValueError("The `data_range` must be given when `dim` is not None.")
            self.data_range = None
            self.add_state("min_target", jnp.zeros(()), dist_reduce_fx="min")
            self.add_state("max_target", jnp.zeros(()), dist_reduce_fx="max")
        else:
            # constant across ranks, so 'max' ≡ the reference's 'mean' under
            # sync (`psnr.py:103`) — and unlike mean it has an exact algebraic
            # merge, so the merge-based forward/merge_state paths work too
            self.add_state("data_range", jnp.asarray(float(data_range)), dist_reduce_fx="max")
        self.base = base
        self.reduction = reduction
        self.dim = tuple(dim) if isinstance(dim, Sequence) else dim

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        sum_squared_error, n_obs = _psnr_update(preds, target, dim=self.dim)
        if self.dim is None:
            if self.data_range is None:
                self.min_target = jnp.minimum(target.min(), self.min_target)
                self.max_target = jnp.maximum(target.max(), self.max_target)
            self.sum_squared_error = self.sum_squared_error + sum_squared_error
            self.total = self.total + n_obs
        else:
            self.sum_squared_error.append(sum_squared_error)
            self.total.append(n_obs)

    def compute(self) -> Array:
        data_range = (
            self.data_range if self.data_range is not None else self.max_target - self.min_target
        )
        if self.dim is None:
            sum_squared_error = self.sum_squared_error
            total = self.total
        else:
            sum_squared_error = jnp.concatenate([jnp.ravel(v) for v in self.sum_squared_error])
            total = jnp.concatenate([jnp.ravel(v) for v in self.total])
        return _psnr_compute(sum_squared_error, total, data_range, base=self.base, reduction=self.reduction)
