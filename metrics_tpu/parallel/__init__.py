from metrics_tpu.parallel.sync import (
    class_reduce,
    gather_all_arrays,
    host_sync_state,
    jit_distributed_available,
    reduce,
    sync_in_jit,
    sync_leaf_in_jit,
)
