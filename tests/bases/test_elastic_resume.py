"""Elastic checkpoint resume: W-rank snapshots restored at W' != W ranks.

Shards are assigned rank-strided (shard ``i`` -> new rank ``i % W'``) and
folded with ``merge_states``, so the union of all resumed ranks' states
equals the union of all saved shards — after the next sync (simulated here
by merging every rank's state, the documented host-sync algebra) the result
is identical to an uninterrupted run. Covers scale-down (4->2), scale-up
(2->4, surplus ranks restore defaults), grouped collections, and CatBuffer
curve states.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    AUROC,
    F1,
    Accuracy,
    MetricCollection,
    Precision,
    Recall,
    Specificity,
    load_checkpoint,
    save_checkpoint,
)

rng = np.random.RandomState(7)
N_BATCH = 12
PREDS = rng.rand(N_BATCH, 16, 5).astype(np.float32)
TARGET = rng.randint(0, 5, (N_BATCH, 16))
BPREDS = rng.rand(N_BATCH, 24).astype(np.float32)
BTARGET = rng.randint(0, 2, (N_BATCH, 24))


def _stat_collection(grouped=True):
    return MetricCollection(
        {
            "prec": Precision(num_classes=5, average="macro"),
            "rec": Recall(num_classes=5, average="macro"),
            "f1": F1(num_classes=5, average="macro"),
            "spec": Specificity(num_classes=5, average="macro"),
        },
        compute_groups=grouped,
    )


def _feed(metric, idxs, preds=PREDS, target=TARGET):
    for i in idxs:
        metric.update(jnp.asarray(preds[i]), jnp.asarray(target[i]))
    return metric


def _merge_all(metrics):
    """Fold every rank's state into rank 0 — the host-sync algebra
    (``merge_states`` IS the documented checkpoint/sync merge rule)."""
    head, *rest = metrics
    for other in rest:
        if isinstance(head, MetricCollection):
            for k in head.keys():
                if other[k]._update_count:
                    head[k].merge_state(other[k])
        elif other._update_count:
            head.merge_state(other)
    return head


@pytest.mark.parametrize("w_save,w_load", [(4, 2), (2, 4), (4, 3), (1, 3)])
def test_stat_collection_elastic_equals_uninterrupted(tmp_path, w_save, w_load):
    split = 8
    for r in range(w_save):
        mc = _feed(_stat_collection(), range(r, split, w_save))
        save_checkpoint(mc, str(tmp_path), rank=r, world=w_save)
    resumed = []
    for r in range(w_load):
        mc = _stat_collection()
        load_checkpoint(mc, str(tmp_path), rank=r, world=w_load)
        _feed(mc, [i for i in range(split, N_BATCH) if i % w_load == r])
        resumed.append(mc)
    # every shard's update count lands on exactly one rank
    total_counts = sum(m["prec"]._update_count for m in resumed)
    assert total_counts == N_BATCH
    merged = _merge_all(resumed)
    uninterrupted = _feed(_stat_collection(), range(N_BATCH))
    for k, v in uninterrupted.compute().items():
        np.testing.assert_array_equal(np.asarray(merged.compute()[k]), np.asarray(v))


def test_rank_strided_assignment(tmp_path):
    """W=4 -> W'=3: rank 0 folds shards 0 and 3, ranks 1/2 get one each."""
    for r in range(4):
        m = Accuracy(num_classes=5)
        _feed(m, [r])  # one batch per saving rank
        save_checkpoint(m, str(tmp_path), rank=r, world=4)
    counts = []
    for r in range(3):
        m = load_checkpoint(Accuracy(num_classes=5), str(tmp_path), rank=r, world=3)
        counts.append(m._update_count)
    assert counts == [2, 1, 1]
    # rank 0's folded state == shard 0 merged with shard 3, leaf for leaf
    m0 = load_checkpoint(Accuracy(num_classes=5), str(tmp_path), rank=0, world=3)
    ref = _feed(Accuracy(num_classes=5), [0])
    ref.merge_state(_feed(Accuracy(num_classes=5), [3]))
    for k in ref._state:
        np.testing.assert_array_equal(np.asarray(m0._state[k]), np.asarray(ref._state[k]))


def test_scale_up_surplus_rank_restores_defaults(tmp_path):
    m = _feed(Accuracy(num_classes=5), range(2))
    save_checkpoint(m, str(tmp_path), rank=0, world=1)
    surplus = _feed(Accuracy(num_classes=5), range(3))  # stale pre-load state
    load_checkpoint(surplus, str(tmp_path), rank=2, world=3)
    assert surplus._update_count == 0
    assert int(np.asarray(surplus._state["correct"]).sum()) == 0
    assert not surplus._update_called


def test_catbuffer_curve_elastic_resume(tmp_path):
    split, w_save, w_load = 8, 4, 2

    def make():
        return AUROC().with_capacity(N_BATCH * 24)

    for r in range(w_save):
        m = _feed(make(), range(r, split, w_save), BPREDS, BTARGET)
        save_checkpoint(m, str(tmp_path), rank=r, world=w_save)
    resumed = []
    for r in range(w_load):
        m = load_checkpoint(make(), str(tmp_path), rank=r, world=w_load)
        _feed(m, [i for i in range(split, N_BATCH) if i % w_load == r], BPREDS, BTARGET)
        resumed.append(m)
    merged = _merge_all(resumed)
    # all rows present exactly once
    assert len(merged._state["preds"]) == N_BATCH * 24
    assert not bool(np.asarray(merged._state["preds"].overflowed))
    uninterrupted = _feed(make(), range(N_BATCH), BPREDS, BTARGET)
    np.testing.assert_array_equal(
        np.asarray(merged.compute()), np.asarray(uninterrupted.compute())
    )


def test_grouped_collection_elastic_resume_regroups(tmp_path):
    split, w_save, w_load = 6, 2, 3
    for r in range(w_save):
        mc = _feed(_stat_collection(), range(r, split, w_save))
        assert mc.compute_group_keys  # saved grouped
        save_checkpoint(mc, str(tmp_path), rank=r, world=w_save)
    resumed = []
    for r in range(w_load):
        mc = _stat_collection()
        load_checkpoint(mc, str(tmp_path), rank=r, world=w_load)
        _feed(mc, [i for i in range(split, N_BATCH) if i % w_load == r])
        # the loaded states are bit-equal across members, so the group
        # re-forms at the first post-resume dispatch
        assert mc.compute_group_keys == [["f1", "prec", "rec", "spec"]]
        resumed.append(mc)
    merged = _merge_all(resumed)
    uninterrupted = _feed(_stat_collection(), range(N_BATCH))
    for k, v in uninterrupted.compute().items():
        np.testing.assert_array_equal(np.asarray(merged.compute()[k]), np.asarray(v))


def test_elastic_resume_into_ungrouped_collection(tmp_path):
    """A grouped 2-rank snapshot resumes into compute_groups=False loaders."""
    split = 6
    for r in range(2):
        mc = _feed(_stat_collection(grouped=True), range(r, split, 2))
        save_checkpoint(mc, str(tmp_path), rank=r, world=2)
    mc = _stat_collection(grouped=False)
    load_checkpoint(mc, str(tmp_path), rank=0, world=1)  # folds both shards
    _feed(mc, range(split, N_BATCH))
    assert not mc.compute_group_keys
    uninterrupted = _feed(_stat_collection(), range(N_BATCH))
    for k, v in uninterrupted.compute().items():
        np.testing.assert_array_equal(np.asarray(mc.compute()[k]), np.asarray(v))


def test_non_mergeable_fold_refused_before_mutation(tmp_path):
    from metrics_tpu import Metric
    from metrics_tpu.utils.exceptions import CheckpointError

    class _Mean(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("avg", jnp.zeros(()), dist_reduce_fx="mean")

        def update(self, x):
            self.avg = jnp.asarray(x, jnp.float32).mean()

        def compute(self):
            return self.avg

    for r in range(2):
        m = _Mean()
        m.update(float(r + 1))
        save_checkpoint(m, str(tmp_path), rank=r, world=2)
    # same-world resume works (no fold needed)
    m_same = load_checkpoint(_Mean(), str(tmp_path), rank=1, world=2)
    np.testing.assert_allclose(float(m_same.compute()), 2.0)
    # scale-down needs a merge the "mean" reduction doesn't have: typed
    # refusal BEFORE any mutation
    target = _Mean()
    target.update(7.0)
    with pytest.raises(CheckpointError, match="no algebraic merge"):
        load_checkpoint(target, str(tmp_path), rank=0, world=1)
    np.testing.assert_allclose(float(np.asarray(target._state["avg"])), 7.0)


def test_fold_capacity_overflow_refused_before_mutation(tmp_path):
    from metrics_tpu.utils.exceptions import CheckpointError

    def make():
        return AUROC().with_capacity(32)  # one shard fits, two don't

    for r in range(2):
        m = make()
        m.update(jnp.asarray(BPREDS[r]), jnp.asarray(BTARGET[r]))  # 24 rows each
        save_checkpoint(m, str(tmp_path), rank=r, world=2)
    target = make()
    target.update(jnp.asarray(BPREDS[5]), jnp.asarray(BTARGET[5]))
    before = np.asarray(target._state["preds"].buffer)
    with pytest.raises(CheckpointError, match="with_capacity"):
        load_checkpoint(target, str(tmp_path), rank=0, world=1)
    np.testing.assert_array_equal(np.asarray(target._state["preds"].buffer), before)
    # each rank alone still fits — same-world resume unaffected
    load_checkpoint(make(), str(tmp_path), rank=0, world=2)


def test_same_world_resume_is_identity(tmp_path):
    for r in range(2):
        m = _feed(Accuracy(num_classes=5), range(r, 6, 2))
        save_checkpoint(m, str(tmp_path), rank=r, world=2)
    for r in range(2):
        m = load_checkpoint(Accuracy(num_classes=5), str(tmp_path), rank=r, world=2)
        ref = _feed(Accuracy(num_classes=5), range(r, 6, 2))
        for k in ref._state:
            np.testing.assert_array_equal(np.asarray(m._state[k]), np.asarray(ref._state[k]))
        assert m._update_count == ref._update_count
