"""Generic area under a curve (trapezoidal) — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/auc.py:20-133``.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array


def _auc_update(x: Array, y: Array) -> Tuple[Array, Array]:
    if x.ndim > 1:
        x = x.squeeze()
    if y.ndim > 1:
        y = y.squeeze()
    if x.ndim > 1 or y.ndim > 1:
        raise ValueError(
            f"Expected both `x` and `y` tensor to be 1d, but got tensors with dimension {x.ndim} and {y.ndim}"
        )
    if x.size != y.size:
        raise ValueError(
            f"Expected the same number of elements in `x` and `y` tensor but received {x.size} and {y.size}"
        )
    return x, y


def _auc_compute_without_check(x: Array, y: Array, direction: float) -> Array:
    return jnp.trapezoid(y, x) * direction


def _auc_compute(x: Array, y: Array, reorder: bool = False) -> Array:
    if reorder:
        order = jnp.argsort(x)
        x, y = x[order], y[order]
    dx = x[1:] - x[:-1]
    if (dx < 0).any():
        if (dx <= 0).all():
            direction = -1.0
        else:
            raise ValueError(
                "The `x` array is neither increasing or decreasing. Try setting the reorder argument to `True`."
            )
    else:
        direction = 1.0
    return _auc_compute_without_check(x, y, direction)


def auc(x: Array, y: Array, reorder: bool = False) -> Array:
    """Trapezoidal area under an arbitrary sampled ``(x, y)`` curve — the
    generic integrator behind AUROC, usable directly on any curve you
    produced yourself.

    Args:
        x: x-coordinates ``[N]``; must be monotonic unless ``reorder``.
        y: y-coordinates ``[N]``.
        reorder: sort the points by x first (ties keep input order).
            Leave False for curves that are already monotonic — sorting a
            non-injective curve (e.g. an ROC with repeated x) can change
            the area.

    Raises:
        ValueError: mismatched lengths, or non-monotonic x with
            ``reorder=False``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import auc
        >>> x = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> y = jnp.asarray([0.0, 1.0, 2.0, 2.0])
        >>> print(round(float(auc(x, y)), 4))
        4.0
    """
    x, y = _auc_update(x, y)
    return _auc_compute(x, y, reorder=reorder)
