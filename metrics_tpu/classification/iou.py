"""IoU (Jaccard index) module metric.

Behavioral analogue of the reference's ``torchmetrics/classification/iou.py``
(110 LoC): subclasses ConfusionMatrix.
"""
from typing import Any, Callable, Optional

from jax import Array

from metrics_tpu.classification.confusion_matrix import ConfusionMatrix
from metrics_tpu.functional.classification.iou import _iou_from_confmat


class IoU(ConfusionMatrix):
    r"""Intersection-over-union (Jaccard index)
    :math:`\frac{TP}{TP + FP + FN}` per class, read off an accumulated
    confusion matrix — diagonal over (row sum + column sum − diagonal).

    Inherits :class:`ConfusionMatrix`'s constant-memory ``[C, C]`` sum
    state and all its constructor arguments, adding:

    Args:
        ignore_index: class excluded from the final mean (its row/column
            still counts toward other classes' unions).
        absent_score: value a class contributes when it never occurs in
            either preds or target (0/0 union).
        reduction: ``"elementwise_mean"`` (default), ``"sum"``, or
            ``"none"`` for the per-class vector.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import IoU
        >>> preds = jnp.asarray([1, 0, 1, 1])
        >>> target = jnp.asarray([1, 0, 0, 1])
        >>> iou = IoU(num_classes=2)
        >>> print(round(float(iou(preds, target)), 4))
        0.5833
    """

    is_differentiable = False

    def __init__(
        self,
        num_classes: int,
        ignore_index: Optional[int] = None,
        absent_score: float = 0.0,
        threshold: float = 0.5,
        reduction: str = "elementwise_mean",
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            num_classes=num_classes,
            normalize=None,
            threshold=threshold,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.reduction = reduction
        self.ignore_index = ignore_index
        self.absent_score = absent_score

    def compute(self) -> Array:
        return _iou_from_confmat(
            self.confmat, self.num_classes, self.ignore_index, self.absent_score, self.reduction
        )
