"""RetrievalFallOut — analogue of reference
``torchmetrics/retrieval/retrieval_fallout.py`` (the empty-query policy is
keyed on queries with no NEGATIVE targets, inverted vs the other metrics)."""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.segment import GroupedByQuery, segment_sum
from metrics_tpu.retrieval.retrieval_metric import RetrievalMetric
from metrics_tpu.utils.checks import _check_retrieval_k


class RetrievalFallOut(RetrievalMetric):
    """Mean fall-out@k: non-relevant retrieved / all non-relevant.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalFallOut
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.7])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> fo2 = RetrievalFallOut(k=2)
        >>> print(round(float(fo2(preds, target, indexes=indexes)), 4))
        0.5
    """

    higher_is_better = False
    empty_on_negatives = True

    def __init__(
        self,
        empty_target_action: str = "pos",
        k: Optional[int] = None,
        num_queries: Optional[int] = None,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            empty_target_action=empty_target_action,
            num_queries=num_queries,
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        _check_retrieval_k(k)
        self.k = k

    def _segment_metric(self, g: GroupedByQuery) -> Array:
        nonrel = (g.target == 0).astype(jnp.float32)
        in_topk = nonrel if self.k is None else nonrel * (g.rank <= self.k)
        nneg = segment_sum(nonrel, g)
        return segment_sum(in_topk, g) / jnp.maximum(nneg, 1.0)
