"""Specificity full input-type × average × mdmc × ignore_index matrix.

Mirror of the reference's `tests/classification/test_specificity.py`: the
10-row input grid × average ∈ {micro, macro, none, weighted, samples} ×
ignore_index ∈ {None, 0}, with the sk reference built from sklearn's
``multilabel_confusion_matrix`` fp/tn counts pushed through the repo's own
``_reduce_stat_scores`` (the reference does the same with its reducer), plus
wrong-params / zero-division / no-support edge cases.
"""
from functools import partial
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import multilabel_confusion_matrix

from metrics_tpu import Specificity
from metrics_tpu.functional import specificity
from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores
from metrics_tpu.utils.checks import _input_format_classification
from tests.classification.inputs import (
    _input_binary,
    _input_binary_prob,
    _input_multiclass,
    _input_multiclass_prob as _input_mcls_prob,
    _input_multidim_multiclass as _input_mdmc,
    _input_multidim_multiclass_prob as _input_mdmc_prob,
    _input_multilabel as _input_mlb,
    _input_multilabel_prob as _input_mlb_prob,
)
from tests.helpers.testers import NUM_CLASSES, THRESHOLD, MetricTester


def _sk_stats_score(preds, target, reduce, num_classes, multiclass, ignore_index, top_k):
    """fp/tn via sklearn, following reference `test_specificity.py:42-81`."""
    preds, target, _ = _input_format_classification(
        preds, target, threshold=THRESHOLD, num_classes=num_classes, multiclass=multiclass, top_k=top_k
    )
    sk_preds, sk_target = np.asarray(preds), np.asarray(target)
    num_cols = sk_preds.shape[1]

    if reduce != "macro" and ignore_index is not None and num_cols > 1:
        sk_preds = np.delete(sk_preds, ignore_index, 1)
        sk_target = np.delete(sk_target, ignore_index, 1)

    if num_cols == 1 and reduce == "samples":
        sk_target = sk_target.T
        sk_preds = sk_preds.T

    sk_stats = multilabel_confusion_matrix(
        sk_target, sk_preds, samplewise=(reduce == "samples") and num_cols != 1
    )

    if num_cols == 1 and reduce != "samples":
        sk_stats = sk_stats[[1]].reshape(-1, 4)[:, [3, 1, 0, 2]]
    else:
        sk_stats = sk_stats.reshape(-1, 4)[:, [3, 1, 0, 2]]

    if reduce == "micro":
        sk_stats = sk_stats.sum(axis=0, keepdims=True)

    sk_stats = np.concatenate([sk_stats, sk_stats[:, [3]] + sk_stats[:, [0]]], 1)

    if reduce == "micro":
        sk_stats = sk_stats[0]

    if reduce == "macro" and ignore_index is not None and num_cols:
        sk_stats[ignore_index, :] = -1

    if reduce == "micro":
        _, fp, tn, _, _ = sk_stats
    else:
        fp, tn = sk_stats[:, 1], sk_stats[:, 2]
    return fp, tn


def _sk_spec(preds, target, reduce, num_classes, multiclass, ignore_index, top_k=None, mdmc_reduce=None, stats=None):
    """Reference `test_specificity.py:84-107`, with the repo reducer."""
    if stats:
        fp, tn = stats
    else:
        fp, tn = _sk_stats_score(preds, target, reduce, num_classes, multiclass, ignore_index, top_k)

    fp, tn = jnp.asarray(np.asarray(fp)), jnp.asarray(np.asarray(tn))
    spec = _reduce_stat_scores(
        numerator=tn,
        denominator=tn + fp,
        weights=None if reduce != "weighted" else tn + fp,
        average=reduce,
        mdmc_average=mdmc_reduce,
    )
    if reduce in [None, "none"] and ignore_index is not None:
        num_cols = np.asarray(
            _input_format_classification(
                preds, target, threshold=THRESHOLD, num_classes=num_classes, multiclass=multiclass, top_k=top_k
            )[0]
        ).shape[1]
        if num_cols > 1:
            spec = np.insert(np.asarray(spec), ignore_index, np.nan)
    return np.asarray(spec)


def _sk_spec_mdim_mcls(preds, target, reduce, mdmc_reduce, num_classes, multiclass, ignore_index, top_k=None):
    """Reference `test_specificity.py:110-128`."""
    preds, target, _ = _input_format_classification(
        preds, target, threshold=THRESHOLD, num_classes=num_classes, multiclass=multiclass, top_k=top_k
    )
    preds, target = np.asarray(preds), np.asarray(target)

    if mdmc_reduce == "global":
        preds = np.moveaxis(preds, 1, 2).reshape(-1, preds.shape[1])
        target = np.moveaxis(target, 1, 2).reshape(-1, target.shape[1])
        return _sk_spec(preds, target, reduce, num_classes, False, ignore_index, top_k, mdmc_reduce)

    fp, tn = [], []
    for i in range(preds.shape[0]):
        fp_i, tn_i = _sk_stats_score(preds[i].T, target[i].T, reduce, num_classes, False, ignore_index, top_k)
        fp.append(fp_i)
        tn.append(tn_i)
    return _sk_spec(
        preds[0], target[0], reduce, num_classes, multiclass, ignore_index, top_k, mdmc_reduce, (fp, tn)
    )


@pytest.mark.parametrize(
    "average, mdmc_average, num_classes, ignore_index, match_str",
    [
        ("wrong", None, None, None, "`average`"),
        ("micro", "wrong", None, None, "`mdmc"),
        ("macro", None, None, None, "number of classes"),
        ("macro", None, 1, 0, "ignore_index"),
    ],
)
def test_wrong_params(average, mdmc_average, num_classes, ignore_index, match_str):
    """Reference `test_specificity.py:131-159`."""
    with pytest.raises(ValueError, match=match_str):
        Specificity(average=average, mdmc_average=mdmc_average, num_classes=num_classes, ignore_index=ignore_index)
    with pytest.raises(ValueError, match=match_str):
        specificity(
            jnp.asarray(_input_binary.preds[0]),
            jnp.asarray(_input_binary.target[0]),
            average=average,
            mdmc_average=mdmc_average,
            num_classes=num_classes,
            ignore_index=ignore_index,
        )


def test_zero_division():
    """Reference `test_specificity.py:161-174`."""
    preds = jnp.asarray([1, 2, 1, 1])
    target = jnp.asarray([0, 0, 0, 0])
    cl_metric = Specificity(average="none", num_classes=3)
    cl_metric(preds, target)
    assert float(cl_metric.compute()[0]) == float(specificity(preds, target, average="none", num_classes=3)[0]) == 0


def test_no_support():
    """Reference `test_specificity.py:177-199`."""
    preds = jnp.asarray([1, 1, 0, 0])
    target = jnp.asarray([0, 0, 0, 0])
    cl_metric = Specificity(average="weighted", num_classes=2, ignore_index=1)
    cl_metric(preds, target)
    assert float(cl_metric.compute()) == float(
        specificity(preds, target, average="weighted", num_classes=2, ignore_index=1)
    ) == 0


@pytest.mark.parametrize("average", ["micro", "macro", None, "weighted", "samples"])
@pytest.mark.parametrize("ignore_index", [None, 0])
@pytest.mark.parametrize(
    "preds, target, num_classes, multiclass, mdmc_average, sk_wrapper",
    [
        (_input_binary_prob.preds, _input_binary_prob.target, 1, None, None, _sk_spec),
        (_input_binary.preds, _input_binary.target, 1, False, None, _sk_spec),
        (_input_mlb_prob.preds, _input_mlb_prob.target, NUM_CLASSES, None, None, _sk_spec),
        (_input_mlb.preds, _input_mlb.target, NUM_CLASSES, False, None, _sk_spec),
        (_input_mcls_prob.preds, _input_mcls_prob.target, NUM_CLASSES, None, None, _sk_spec),
        (_input_multiclass.preds, _input_multiclass.target, NUM_CLASSES, None, None, _sk_spec),
        (_input_mdmc.preds, _input_mdmc.target, NUM_CLASSES, None, "global", _sk_spec_mdim_mcls),
        (_input_mdmc_prob.preds, _input_mdmc_prob.target, NUM_CLASSES, None, "global", _sk_spec_mdim_mcls),
        (_input_mdmc.preds, _input_mdmc.target, NUM_CLASSES, None, "samplewise", _sk_spec_mdim_mcls),
        (_input_mdmc_prob.preds, _input_mdmc_prob.target, NUM_CLASSES, None, "samplewise", _sk_spec_mdim_mcls),
    ],
)
class TestSpecificityMatrix(MetricTester):
    @pytest.mark.parametrize("ddp", [False, True])
    def test_specificity_class(
        self,
        ddp: bool,
        preds: np.ndarray,
        target: np.ndarray,
        sk_wrapper: Callable,
        multiclass: Optional[bool],
        num_classes: Optional[int],
        average: str,
        mdmc_average: Optional[str],
        ignore_index: Optional[int],
    ):
        if num_classes == 1 and average == "samples":
            pytest.skip("'samples' average needs per-sample label sets; binary rows have none")
        # binary macro/weighted/none collapse to the single class's score, so
        # sklearn's 'binary' average IS the oracle (the wrapper maps it) —
        # r4: converted from reference-mirrored skips into live assertions
        if ignore_index is not None and num_classes == 1:
            pytest.skip("ignore_index is undefined for binary inputs (constructor raises)")
        if average == "weighted" and ignore_index is not None and mdmc_average is not None:
            pytest.skip("ignoring an entire sample under 'weighted' is a degenerate case")
        if mdmc_average == "samplewise":
            # the sk wrapper recomputes per-sample stats from ALL batches at
            # once; per-batch forward values cover only that batch
            check_batch = False
        else:
            check_batch = True

        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=Specificity,
            sk_metric=partial(
                sk_wrapper,
                reduce=average,
                num_classes=num_classes,
                multiclass=multiclass,
                ignore_index=ignore_index,
                mdmc_reduce=mdmc_average,
            ),
            metric_args={
                "num_classes": num_classes,
                "average": average,
                "threshold": THRESHOLD,
                "multiclass": multiclass,
                "ignore_index": ignore_index,
                "mdmc_average": mdmc_average,
            },
            check_batch=check_batch,
            check_jit=False,  # jit gates for every input type run in test_input_variants
        )

    def test_specificity_fn(
        self,
        preds: np.ndarray,
        target: np.ndarray,
        sk_wrapper: Callable,
        multiclass: Optional[bool],
        num_classes: Optional[int],
        average: str,
        mdmc_average: Optional[str],
        ignore_index: Optional[int],
    ):
        if num_classes == 1 and average == "samples":
            pytest.skip("'samples' average needs per-sample label sets; binary rows have none")
        # binary macro/weighted/none collapse to the single class's score, so
        # sklearn's 'binary' average IS the oracle (the wrapper maps it) —
        # r4: converted from reference-mirrored skips into live assertions
        if ignore_index is not None and num_classes == 1:
            pytest.skip("ignore_index is undefined for binary inputs (constructor raises)")

        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=specificity,
            sk_metric=partial(
                sk_wrapper,
                reduce=average,
                num_classes=num_classes,
                multiclass=multiclass,
                ignore_index=ignore_index,
                mdmc_reduce=mdmc_average,
            ),
            metric_args={
                "num_classes": num_classes,
                "average": average,
                "threshold": THRESHOLD,
                "multiclass": multiclass,
                "ignore_index": ignore_index,
                "mdmc_average": mdmc_average,
            },
        )
