"""Real multi-process DDP sync: 2 CPU processes over jax.distributed (gloo).

The analogue of the reference's persistent 2-process gloo pool
(``tests/helpers/testers.py:33-57``) — but as actual separate interpreters,
exercising `host_sync_state` / `gather_all_arrays` over a live process group:
even gathers, uneven-shape pad/trim gathers, Pearson's pairwise merge, and
the sync_context checkpoint pattern.
"""
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).with_name("ddp_worker.py")
REPO_ROOT = WORKER.parents[2]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_ddp_sync():
    port = _free_port()
    world = 2
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(rank), str(world), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=str(REPO_ROOT),
            env=env,
        )
        for rank in range(world)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("DDP workers timed out (collective hang?)")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank{rank} failed:\n{out}"
        assert f"rank{rank} OK" in out, f"rank{rank} missing OK:\n{out}"
