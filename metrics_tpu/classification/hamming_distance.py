"""HammingDistance module metric.

Behavioral analogue of the reference's
``torchmetrics/classification/hamming_distance.py`` (113 LoC).
"""
from typing import Any, Callable, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.core.metric import Metric
from metrics_tpu.functional.classification.hamming_distance import (
    _hamming_distance_compute,
    _hamming_distance_update,
)


class HammingDistance(Metric):
    r"""Hamming loss — the fraction of individual labels predicted wrong,
    scored independently per label. For multilabel input this is the
    natural "how many tags did I get wrong" rate (a sample with 9 of 10
    tags right contributes 0.1, where subset accuracy would score it 0).

    State is a correct/total counter pair ("sum" leaves; one ``psum``
    pair across the mesh).

    Args:
        threshold: binarization cut for probabilistic input.
        compute_on_step / dist_sync_on_step / process_group / dist_sync_fn:
            the standard runtime quartet (see :class:`~metrics_tpu.Metric`).

    Raises:
        ValueError: ``threshold`` outside ``(0, 1)``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import HammingDistance
        >>> preds = jnp.asarray([[0, 1], [1, 1]])
        >>> target = jnp.asarray([[0, 1], [0, 1]])
        >>> hamming = HammingDistance()
        >>> print(round(float(hamming(preds, target)), 4))
        0.25
    """

    is_differentiable = False

    def __init__(
        self,
        threshold: float = 0.5,
        compute_on_step: bool = True,
        dist_sync_on_step: bool = False,
        process_group: Optional[Any] = None,
        dist_sync_fn: Optional[Callable] = None,
    ) -> None:
        super().__init__(
            compute_on_step=compute_on_step,
            dist_sync_on_step=dist_sync_on_step,
            process_group=process_group,
            dist_sync_fn=dist_sync_fn,
        )
        self.add_state("correct", default=jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.zeros((), dtype=jnp.int32), dist_reduce_fx="sum")
        self.threshold = threshold

    def update_identity(self):
        """Compute-group key: HammingDistance's update is parameterized by
        ``threshold`` alone, so equal-threshold instances in a collection
        share one correct/total accumulation."""
        return ("hamming_distance", self.threshold)

    def update(self, preds: Array, target: Array) -> None:  # type: ignore[override]
        correct, total = _hamming_distance_update(preds, target, self.threshold)
        self.correct = self.correct + correct
        self.total = self.total + total

    def compute(self) -> Array:
        return _hamming_distance_compute(self.correct, self.total)
