#!/usr/bin/env python
"""Machine-checked suite-health gate (VERDICT r3 #5).

Runs a pytest command, then asserts the three health invariants the
reference's CI encodes in its pipeline config
(``/root/reference/azure-pipelines.yml:22-30`` 45-min envelope;
``.github/workflows/ci_test-full.yml`` matrix):

- zero failures/errors,
- wall time within the envelope,
- skip count within budget (skips are annotated, tests/README.md, but the
  budget stops the taxonomy from silently regrowing).

Usage::

    python scripts/suite_health.py --max-minutes 45 --max-skips 400 -- \
        python -m pytest tests/ -q -m "not slow and not nightly"

Exit code 0 only when every invariant holds; prints a one-line JSON verdict
either way (consumed by CI logs and by BENCH.md's suite-health row).
"""
import argparse
import json
import os
import re
import subprocess
import sys
import tempfile
import time
import xml.etree.ElementTree as ET


def _counts_from_junitxml(path: str):
    """Machine-readable counts (ADVICE r4: regex over a bounded output tail
    could undercount when a long warnings footer truncates the summary)."""
    root = ET.parse(path).getroot()
    suites = [root] if root.tag == "testsuite" else list(root)
    c = {"passed": 0, "failed": 0, "errors": 0, "skipped": 0}
    for s in suites:
        tests = int(s.get("tests", 0))
        failures = int(s.get("failures", 0))
        errors = int(s.get("errors", 0))
        skipped = int(s.get("skipped", 0))
        c["failed"] += failures
        c["errors"] += errors
        c["skipped"] += skipped
        c["passed"] += max(tests - failures - errors - skipped, 0)
    return c


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-minutes", type=float, required=True)
    ap.add_argument("--max-skips", type=int, required=True)
    ap.add_argument("cmd", nargs=argparse.REMAINDER, help="-- then the pytest command")
    args = ap.parse_args()
    cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
    if not cmd:
        print("no command given", file=sys.stderr)
        return 2

    # counts come from pytest's junitxml (exact), not from scraping stdout
    xml_path = None
    if any("pytest" in part for part in cmd) and not any("--junitxml" in part for part in cmd):
        fd, xml_path = tempfile.mkstemp(suffix=".xml", prefix="suite_health_")
        os.close(fd)
        cmd = cmd + [f"--junitxml={xml_path}"]

    t0 = time.monotonic()
    proc = subprocess.run(cmd, capture_output=True, text=True)
    minutes = (time.monotonic() - t0) / 60.0
    tail = (proc.stdout + proc.stderr)[-4000:]
    sys.stdout.write(tail)

    counts = None
    if xml_path:
        try:
            counts = _counts_from_junitxml(xml_path)
        except Exception as e:  # noqa: BLE001 — fall back to the tail scrape
            print(f"junitxml parse failed ({e}); falling back to tail scrape", file=sys.stderr)
        finally:
            try:
                os.unlink(xml_path)
            except OSError:
                pass
    if counts is None:
        counts = {k: 0 for k in ("passed", "failed", "errors", "skipped")}
        # pytest summary line: "4180 passed, 398 skipped, 3 warnings in 2400.00s"
        for num, word in re.findall(r"(\d+) (passed|failed|error[s]?|skipped)", tail):
            counts["errors" if word.startswith("error") else word] += int(num)

    ok = (
        proc.returncode == 0
        and counts["failed"] == 0
        and counts["errors"] == 0
        and counts["passed"] > 0
        and counts["skipped"] <= args.max_skips
        and minutes <= args.max_minutes
    )
    print(json.dumps({
        "suite_health": "ok" if ok else "FAILED",
        "passed": counts["passed"],
        "failed": counts["failed"] + counts["errors"],
        "skipped": counts["skipped"],
        "skip_budget": args.max_skips,
        "minutes": round(minutes, 1),
        "envelope_minutes": args.max_minutes,
        "pytest_rc": proc.returncode,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
