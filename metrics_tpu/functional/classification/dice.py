"""Dice score — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/dice.py:23-112``, vectorized over
classes (the reference loops per class with data-dependent branches; here the
per-class counts come from one one-hot pass and the no-foreground / nan cases
are masked — one fused XLA kernel, jit-safe).
"""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.parallel.sync import reduce
from metrics_tpu.utils.data import to_categorical


def dice_score(
    preds: Array,
    target: Array,
    bg: bool = False,
    nan_score: float = 0.0,
    no_fg_score: float = 0.0,
    reduction: str = "elementwise_mean",
) -> Array:
    """Dice = 2·TP / (2·TP + FP + FN) per class, reduced over classes.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import dice_score
        >>> pred = jnp.asarray([[0.85, 0.05, 0.05, 0.05], [0.05, 0.85, 0.05, 0.05]])
        >>> target = jnp.asarray([0, 1])
        >>> print(round(float(dice_score(pred, target)), 4))
        0.3333
    """
    num_classes = preds.shape[1]
    start = 0 if bg else 1

    if preds.ndim == target.ndim + 1:
        preds = to_categorical(preds, argmax_dim=1)

    classes = jnp.arange(num_classes)
    pred_is = preds.ravel()[None, :] == classes[:, None]   # [C, N]
    targ_is = target.ravel()[None, :] == classes[:, None]
    tp = jnp.sum(pred_is & targ_is, axis=1).astype(jnp.float32)
    fp = jnp.sum(pred_is & ~targ_is, axis=1).astype(jnp.float32)
    fn = jnp.sum(~pred_is & targ_is, axis=1).astype(jnp.float32)
    support = jnp.sum(targ_is, axis=1)

    denom = 2 * tp + fp + fn
    score = jnp.where(denom == 0, nan_score, 2 * tp / jnp.where(denom == 0, 1.0, denom))
    score = jnp.where(support == 0, no_fg_score, score)
    return reduce(score[start:], reduction=reduction)
