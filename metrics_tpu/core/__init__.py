from metrics_tpu.core.cat_buffer import CatBuffer
from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import CompositionalMetric, Metric
