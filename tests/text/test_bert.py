"""BERTScore tests: mechanism correctness with deterministic models (the
reference compares against the `bert_score` package with a pretrained BERT —
unavailable offline, so these tests pin the algorithm itself)."""
from typing import Dict, List, Union

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from metrics_tpu import BERTScore
from metrics_tpu.functional import bert_score

_PREDS = ["hello there", "general kenobi"]
_REFS = ["hello there", "master kenobi"]


_GRID_PREDS = _PREDS + ["the quick brown fox", "jumps over the lazy dog"]
_GRID_REFS = _REFS + ["a quick brown fox", "leaps over a sleepy dog"]
_GRID_BASELINE_CACHE: Dict[tuple, dict] = {}


def _grid_baseline(idf, all_layers):
    """batch_size=64 reference score, computed once per (idf, all_layers)."""
    key = (idf, all_layers)
    if key not in _GRID_BASELINE_CACHE:
        _GRID_BASELINE_CACHE[key] = bert_score(
            predictions=_GRID_PREDS, references=_GRID_REFS, max_length=16,
            idf=idf, all_layers=all_layers, batch_size=64,
        )
    return _GRID_BASELINE_CACHE[key]


@pytest.mark.parametrize("idf", [False, True])
@pytest.mark.parametrize("all_layers", [False, True])
@pytest.mark.parametrize("batch_size", [1, 2, 4])
def test_module_functional_grid(idf, all_layers, batch_size):
    """Reference `test_bertscore.py` grid (fn vs class × idf × all_layers ×
    batch_size): module streaming equals the one-shot functional, and the
    score is invariant to the embedding batch size."""
    import jax

    preds, refs = _GRID_PREDS, _GRID_REFS
    # different batch sizes are different XLA programs: pin matmul precision
    # so the cross-batch-size comparison is exact on TPU (bf16 default) too
    with jax.default_matmul_precision("float32"):
        fn = bert_score(
            predictions=preds, references=refs, max_length=16,
            idf=idf, all_layers=all_layers, batch_size=batch_size,
        )
        baseline = _grid_baseline(idf, all_layers)
        for key in ("precision", "recall", "f1"):
            np.testing.assert_allclose(
                np.asarray(fn[key]), np.asarray(baseline[key]), atol=1e-5, rtol=1e-5,
                err_msg=f"{key} not batch-size invariant",
            )

        m = BERTScore(max_length=16, idf=idf, all_layers=all_layers, batch_size=batch_size)
        m.update(preds[:2], refs[:2])
        m.update(preds[2:], refs[2:])
        streamed = m.compute()
        for key in ("precision", "recall", "f1"):
            np.testing.assert_allclose(
                np.asarray(streamed[key]), np.asarray(fn[key]), atol=1e-6,
                err_msg=f"{key} module != functional",
            )


def test_identical_sentences_score_one():
    out = bert_score(predictions=_PREDS, references=_PREDS, max_length=16)
    np.testing.assert_allclose(out["precision"], 1.0, atol=1e-3)
    np.testing.assert_allclose(out["recall"], 1.0, atol=1e-3)
    np.testing.assert_allclose(out["f1"], 1.0, atol=1e-3)


def test_precision_recall_symmetry():
    a = bert_score(predictions=_PREDS, references=_REFS, max_length=16)
    b = bert_score(predictions=_REFS, references=_PREDS, max_length=16)
    np.testing.assert_allclose(a["precision"], b["recall"], atol=1e-6)
    np.testing.assert_allclose(a["recall"], b["precision"], atol=1e-6)


def test_module_matches_functional_and_streams():
    m = BERTScore(max_length=16)
    m.update(_PREDS[:1], _REFS[:1])
    m.update(_PREDS[1:], _REFS[1:])
    streamed = m.compute()
    batched = bert_score(predictions=_PREDS, references=_REFS, max_length=16)
    np.testing.assert_allclose(streamed["f1"], batched["f1"], atol=1e-6)


def test_module_merge_states():
    """Cat-state merge across simulated ranks == all-data evaluation (the
    DDP-sync fix over reference text/bert.py:170-171)."""
    m1, m2 = BERTScore(max_length=16), BERTScore(max_length=16)
    m1.update(_PREDS[:1], _REFS[:1])
    m2.update(_PREDS[1:], _REFS[1:])
    merged = m1.merge_states(m1._state, m2._state)
    out = m1.pure_compute(merged)
    batched = bert_score(predictions=_PREDS, references=_REFS, max_length=16)
    np.testing.assert_allclose(out["f1"], batched["f1"], atol=1e-6)


def test_idf_changes_scores():
    # "the" appears in every reference (idf 0) while content words are rare,
    # so idf weighting must shift the weighted average
    preds = ["the the the cat", "the dog in the park"]
    refs = ["the cat sat on the mat", "the dog runs in the park"]
    plain = bert_score(predictions=preds, references=refs, max_length=16)
    weighted = bert_score(predictions=preds, references=refs, max_length=16, idf=True)
    assert not np.allclose(plain["f1"], weighted["f1"], atol=1e-6)


def test_all_layers_returns_per_layer_scores():
    out = bert_score(predictions=_PREDS, references=_REFS, max_length=16, all_layers=True)
    # default in-framework config has 4 layers + embeddings = 5 representations
    assert np.asarray(out["f1"]).shape == (5, 2)


def test_rescale_with_baseline_array():
    out = bert_score(predictions=_PREDS, references=_REFS, max_length=16)
    baseline = jnp.full((5, 3), 0.5)
    rescaled = bert_score(
        predictions=_PREDS,
        references=_REFS,
        max_length=16,
        rescale_with_baseline=True,
        baseline=baseline,
    )
    np.testing.assert_allclose(
        rescaled["f1"], (np.asarray(out["f1"]) - 0.5) / 0.5, atol=1e-5
    )


def test_rescale_with_baseline_csv_path(tmp_path):
    """End-to-end `baseline_path` workflow: the bundled example csv (and a
    tsv copy) drive `_read_baseline_csv` + `_rescale_with_baseline`
    (VERDICT r2: the csv path was dead code in the suite)."""
    from metrics_tpu.functional.text.bert import _read_baseline_csv, bundled_baseline_path

    csv_path = bundled_baseline_path()
    baseline = np.asarray(_read_baseline_csv(csv_path))
    assert baseline.shape == (5, 3)  # embeddings + 4 layers, [P, R, F]

    plain = bert_score(predictions=_PREDS, references=_REFS, max_length=16)
    rescaled = bert_score(
        predictions=_PREDS,
        references=_REFS,
        max_length=16,
        rescale_with_baseline=True,
        baseline_path=csv_path,
    )
    # default single-layer score rescales with the LAST row (num_layers=-1)
    scale = baseline[-1]
    for i, key in enumerate(("precision", "recall", "f1")):
        np.testing.assert_allclose(
            np.asarray(rescaled[key]),
            (np.asarray(plain[key]) - scale[i]) / (1 - scale[i]),
            atol=1e-5,
        )

    # tsv flavor goes through the tab-delimited branch
    tsv = tmp_path / "baseline.tsv"
    with open(csv_path) as f:
        tsv.write_text(f.read().replace(",", "\t"))
    rescaled_tsv = bert_score(
        predictions=_PREDS,
        references=_REFS,
        max_length=16,
        rescale_with_baseline=True,
        baseline_path=str(tsv),
    )
    np.testing.assert_allclose(
        np.asarray(rescaled_tsv["f1"]), np.asarray(rescaled["f1"]), atol=1e-6
    )


def test_rescale_with_baseline_csv_all_layers():
    """all_layers rescaling consumes every baseline row."""
    from metrics_tpu.functional.text.bert import _read_baseline_csv, bundled_baseline_path

    baseline = np.asarray(_read_baseline_csv(bundled_baseline_path()))
    plain = bert_score(predictions=_PREDS, references=_REFS, max_length=16, all_layers=True)
    rescaled = bert_score(
        predictions=_PREDS,
        references=_REFS,
        max_length=16,
        all_layers=True,
        rescale_with_baseline=True,
        baseline_path=bundled_baseline_path(),
    )
    expected = (np.asarray(plain["f1"]) - baseline[:, 2:3]) / (1 - baseline[:, 2:3])
    np.testing.assert_allclose(np.asarray(rescaled["f1"]), expected, atol=1e-5)


def test_empty_inputs():
    out = bert_score(predictions=[], references=[])
    assert out == {"precision": [0.0], "recall": [0.0], "f1": [0.0]}


def test_length_mismatch():
    with pytest.raises(ValueError, match="must be the same"):
        bert_score(predictions=["a"], references=["a", "b"])


def test_return_hash():
    out = bert_score(predictions=_PREDS, references=_REFS, max_length=16, return_hash=True)
    assert out["hash"] == "None_LNone_no-idf"


# ---------------------------------------------------------------------------
# own-model path (port of the reference acceptance example
# tm_examples/bert_score-own_model.py)
# ---------------------------------------------------------------------------

_MODEL_DIM = 4
_MAX_LEN = 6


class UserTokenizer:
    """Embedding-valued tokenizer: 'input_ids' are word vectors."""

    CLS, SEP, PAD = "<cls>", "<sep>", "<pad>"

    def __init__(self) -> None:
        self.word2vec = {
            "hello": 0.5 * np.ones((1, _MODEL_DIM), dtype=np.float32),
            "world": -0.5 * np.ones((1, _MODEL_DIM), dtype=np.float32),
            self.CLS: np.zeros((1, _MODEL_DIM), dtype=np.float32),
            self.SEP: np.zeros((1, _MODEL_DIM), dtype=np.float32),
            self.PAD: np.zeros((1, _MODEL_DIM), dtype=np.float32),
        }

    def __call__(self, sentences: Union[str, List[str]], max_len: int = _MAX_LEN) -> Dict[str, np.ndarray]:
        if isinstance(sentences, str):
            sentences = [sentences]
        sentences = [" ".join([self.CLS, s, self.SEP]) for s in sentences]
        tokenized = [
            s.lower().split()[:max_len] + [self.PAD] * (max_len - len(s.lower().split()))
            for s in sentences
        ]
        ids = np.stack([np.concatenate([self.word2vec[w] for w in s]) for s in tokenized])
        mask = np.stack([[1 if w != self.PAD else 0 for w in s] for s in tokenized]).astype(np.int32)
        return {"input_ids": ids, "attention_mask": mask}


def _user_model(input_ids: np.ndarray) -> np.ndarray:
    """Deterministic 'encoder': L2-normalize word vectors + positional tilt."""
    x = jnp.asarray(input_ids)
    pos = jnp.linspace(0.0, 0.1, x.shape[1])[None, :, None]
    return x + pos


def _user_forward_fn(model, batch):
    return model(batch["input_ids"])


_OWN_PREDS = ["hello", "hello world", "world world world"]
_OWN_REFS = ["hello", "hello hello", "hello world hello"]


def test_own_model_functional():
    out = bert_score(
        predictions=_OWN_PREDS,
        references=_OWN_REFS,
        model=_user_model,
        user_tokenizer=UserTokenizer(),
        user_forward_fn=_user_forward_fn,
        max_length=_MAX_LEN,
    )
    assert len(out["f1"]) == 3
    # first pair identical -> perfect score
    assert out["f1"][0] == pytest.approx(1.0, abs=1e-3)
    assert all(np.isfinite(out["f1"]))


def test_own_model_module():
    metric = BERTScore(
        model=_user_model,
        user_tokenizer=UserTokenizer(),
        user_forward_fn=_user_forward_fn,
        max_length=_MAX_LEN,
    )
    metric.update(_OWN_PREDS, _OWN_REFS)
    out = metric.compute()
    batched = bert_score(
        predictions=_OWN_PREDS,
        references=_OWN_REFS,
        model=_user_model,
        user_tokenizer=UserTokenizer(),
        user_forward_fn=_user_forward_fn,
        max_length=_MAX_LEN,
    )
    np.testing.assert_allclose(out["f1"], batched["f1"], atol=1e-6)


def test_single_sentence_returns_list():
    out = bert_score(predictions=["hello there"], references=["hello there"], max_length=16)
    assert isinstance(out["f1"], list) and len(out["f1"]) == 1
    assert out["f1"][0] == pytest.approx(1.0, abs=1e-3)


def test_simple_tokenizer_stable_across_instances():
    from metrics_tpu.functional.text.bert import SimpleTokenizer

    a = SimpleTokenizer(max_length=8)(["hello world"])
    b = SimpleTokenizer(max_length=8)(["hello world"])
    np.testing.assert_array_equal(a["input_ids"], b["input_ids"])
