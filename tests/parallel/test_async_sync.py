"""Async overlapped sync equivalence + protocol suite (ISSUE 7 tentpole).

The contract under test: a non-blocking, double-buffered sync round
(``parallel/async_sync.py``, ``sync(blocking=False)``,
``sync_mode="overlap"``) resolves **bit-identically** to a blocking sync of
the same update stream — reduce states, CatBuffers and grouped collections
included — while the collectives ride a background lane; staleness is
reported per :attr:`staleness_policy`, never silently mixed; launch/resolve
epochs are negotiated symmetrically through the health word (protocol v3);
``unsync()`` mid-flight cancels by draining on every rank; and checkpoints
refuse an in-flight round. Real two-rank payloads run through
:class:`LockstepWorld` with one background executor lane per rank.
"""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.parallel.async_sync as async_mod
import metrics_tpu.parallel.sync as sync_mod
from metrics_tpu.core.collections import MetricCollection
from metrics_tpu.core.metric import Metric
from metrics_tpu import Precision, Recall
from metrics_tpu.parallel.bucketing import clear_sync_plan_cache, sync_plan_cache_info
from metrics_tpu.parallel.health import reset_channel_health
from metrics_tpu.utils.exceptions import (
    MetricsTPUUserError,
    StaleSyncError,
    StateDivergenceError,
)
from tests.helpers.fake_world import LockstepWorld

WORLD = 2


@pytest.fixture(autouse=True)
def _fresh_channel_and_plans():
    clear_sync_plan_cache()
    reset_channel_health()
    with async_mod._PENDING_LOCK:
        async_mod._PENDING.clear()
    yield
    clear_sync_plan_cache()
    reset_channel_health()
    with async_mod._PENDING_LOCK:
        async_mod._PENDING.clear()


@pytest.fixture
def lockstep(monkeypatch):
    """Two real ranks on threads, rendezvous collectives, and one
    background async-sync lane per rank (the production per-process
    executor, simulated per fake rank)."""
    world = LockstepWorld(WORLD)
    monkeypatch.setattr(jax, "process_count", lambda: world.world)
    monkeypatch.setattr(sync_mod, "_raw_process_allgather", world.allgather)
    monkeypatch.setattr(async_mod, "_get_executor", world.executor_for_current_rank)
    monkeypatch.setattr(async_mod, "_current_domain", world.rank_domain)
    yield world
    world.shutdown_executors()


class _Sum(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", jnp.zeros(()), dist_reduce_fx="sum")
        self.add_state("count", jnp.zeros((), jnp.int32), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)
        self.count = self.count + jnp.asarray(jnp.size(x), jnp.int32)

    def compute(self):
        return self.total / self.count


class _Cat(Metric):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("rows", [], dist_reduce_fx="cat")
        self.add_state("seen", jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, x):
        self.rows.append(x)
        self.seen = self.seen + 1.0

    def compute(self):
        return jnp.concatenate([r[None] if r.ndim == 0 else r for r in self.rows])


def _state_bytes(m):
    out = []
    for name in sorted(m._defaults):
        v = m._state[name]
        if isinstance(v, list):
            out.append(tuple(np.asarray(x).tobytes() for x in v))
        elif hasattr(v, "values") and hasattr(v, "capacity"):  # CatBuffer
            out.append(np.asarray(v.values()).tobytes())
        else:
            out.append(np.asarray(v).tobytes())
    return tuple(out)


# ---------------------------------------------------------------------------
# bit-identity: overlapped resolve ≡ blocking sync, same update stream
# ---------------------------------------------------------------------------


def test_overlap_resolves_bit_identical_reduce(lockstep):
    def body(rank):
        feed = jnp.asarray([1.0 + rank, 2.0 * (rank + 1)])
        over, block = _Sum(sync_timeout=0), _Sum(sync_timeout=0)
        over.update(feed)
        block.update(feed)
        block.sync()
        over.sync(blocking=False)  # launch; returns un-synced
        assert not over._is_synced and over.__dict__["_inflight"] is not None
        over.sync()  # resolve the in-flight round
        assert over._is_synced
        bits = (_state_bytes(over), _state_bytes(block))
        over.unsync()
        block.unsync()
        stats = over.sync_stats()
        assert stats["launched"] == 1 and stats["resolved"] == 1
        assert stats["stale_resolves"] == 0
        return bits, _state_bytes(over), _state_bytes(block)

    results = lockstep.run(body)
    for (synced_o, synced_b), local_o, local_b in results:
        assert synced_o == synced_b  # bit-identical synced view
        assert local_o == local_b  # bit-identical restored locals


def test_overlap_resolves_bit_identical_catbuffer(lockstep):
    def body(rank):
        over = _Cat(sync_timeout=0).with_capacity(16)
        block = _Cat(sync_timeout=0).with_capacity(16)
        for i in range(2 + rank):  # uneven rows per rank
            row = jnp.asarray([float(rank), float(i), 1.0])
            over.update(row)
            block.update(row)
        block.sync()
        over.sync(blocking=False)
        over.sync()
        bits = (_state_bytes(over), _state_bytes(block))
        over.unsync()
        block.unsync()
        return bits, _state_bytes(over), _state_bytes(block)

    for (synced_o, synced_b), local_o, local_b in lockstep.run(body):
        assert synced_o == synced_b
        assert local_o == local_b


def test_overlap_grouped_collection_bit_identical(lockstep):
    preds = [jnp.asarray(np.random.RandomState(3 + r).rand(24, 5).astype(np.float32)) for r in range(WORLD)]
    target = [jnp.asarray(np.random.RandomState(7 + r).randint(0, 5, (24,))) for r in range(WORLD)]

    def make():
        mc = MetricCollection(
            {
                "prec": Precision(num_classes=5, average="macro"),
                "rec": Recall(num_classes=5, average="macro"),
            }
        )
        for m in mc.values():
            m.sync_timeout = 0
        return mc

    def body(rank):
        over, block = make(), make()
        over.update(preds[rank], target[rank])
        block.update(preds[rank], target[rank])
        assert over.compute_group_keys  # the pair actually grouped
        block.sync()
        over.sync(blocking=False)
        assert over.__dict__["_inflight_round"] is not None
        over.sync()  # resolve: all members applied all-or-nothing
        bits = tuple(_state_bytes(m) for m in over.values())
        bbits = tuple(_state_bytes(m) for m in block.values())
        vals = {k: np.asarray(v) for k, v in over.compute().items()}
        bvals = {k: np.asarray(v) for k, v in block.compute().items()}
        over.unsync()
        block.unsync()
        stats = over.sync_stats()
        assert stats["collection"]["launched"] == 1
        assert stats["collection"]["resolved"] == 1
        return bits, bbits, vals, bvals

    for bits, bbits, vals, bvals in lockstep.run(body):
        assert bits == bbits
        for k in vals:
            assert (vals[k] == bvals[k]).all()


def test_collection_overlap_uses_one_fused_round(lockstep):
    def body(rank):
        mc = MetricCollection({"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)})
        mc.update(jnp.asarray([1.0 + rank]))
        before = lockstep.calls
        mc.sync(blocking=False)
        mc.sync()
        mc.unsync()
        return lockstep.calls - before

    rounds = lockstep.run(body)
    # ONE header + one reduce bucket (f32) + one (i32) for the whole
    # two-member collection — same collective budget as the blocking fused
    # path, just off the critical path (`calls` counts rendezvous rounds,
    # shared by both ranks)
    assert rounds[0] <= 3


# ---------------------------------------------------------------------------
# staleness policies
# ---------------------------------------------------------------------------


def _stale_setup(rank, policy, **kwargs):
    m = _Sum(sync_timeout=0, staleness_policy=policy, **kwargs)
    m.update(jnp.asarray([1.0 + rank]))  # snapshot accumulation: 1+rank
    m.sync(blocking=False)
    m.update(jnp.asarray([10.0]))  # post-snapshot delta on every rank
    return m


def test_staleness_snapshot_serves_consistent_cut(lockstep):
    def body(rank):
        m = _stale_setup(rank, "snapshot")
        m.sync()
        synced = float(np.asarray(m.total))
        m.unsync()
        local = float(np.asarray(m.total))
        assert m.sync_stats()["stale_resolves"] == 1
        return synced, local

    for rank, (synced, local) in enumerate(lockstep.run(body)):
        assert synced == pytest.approx(3.0)  # (1+0) + (1+1): the snapshot cut
        assert local == pytest.approx(1.0 + rank + 10.0)  # full accumulation


def test_staleness_merge_folds_local_delta(lockstep):
    def body(rank):
        m = _stale_setup(rank, "merge")
        m.sync()
        synced = float(np.asarray(m.total))
        m.unsync()
        return synced, float(np.asarray(m.total))

    for rank, (synced, local) in enumerate(lockstep.run(body)):
        assert synced == pytest.approx(3.0 + 10.0)  # world cut + THIS rank's delta
        assert local == pytest.approx(11.0 + rank)


def test_staleness_fresh_raises_typed_and_degrades(lockstep):
    def body(rank):
        m = _stale_setup(rank, "fresh")
        with pytest.raises(StaleSyncError):
            m.sync()
        # the full accumulation was restored before the raise
        assert float(np.asarray(m.total)) == pytest.approx(11.0 + rank)
        # degradation path: local fallback keeps the accumulation (the
        # LOCAL-ONLY warning itself is asserted in the single-threaded
        # fault-injection suite — warning capture is not thread-safe here)
        m2 = _stale_setup(rank, "fresh", sync_on_error="local")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            m2.sync()
        assert not m2._is_synced and m2._sync_degraded
        assert m2.sync_stats()["degraded"] == 1
        assert float(np.asarray(m2.total)) == pytest.approx(11.0 + rank)
        m2.unsync()  # tolerated no-op after degradation
        return True

    assert all(lockstep.run(body))


# ---------------------------------------------------------------------------
# epoch negotiation + cancel + pipeline
# ---------------------------------------------------------------------------


def test_epoch_skew_raises_symmetrically(lockstep):
    """Rank 0 resolving overlapped round 1 while rank 1 contributes a
    blocking sync (epoch 0) is the background/foreground mispairing the
    health word's sync_epoch column (protocol v3) must catch on BOTH
    ranks."""

    def body(rank):
        m = _Sum(sync_timeout=0)
        m.update(jnp.asarray([1.0 + rank]))
        if rank == 0:
            m.sync(blocking=False)
            with pytest.raises(StateDivergenceError, match="sync-round skew"):
                m.sync()
            return "resolved-skew"
        with pytest.raises(StateDivergenceError, match="sync-round skew"):
            m.sync()
        return "blocking-skew"

    assert lockstep.run(body) == ["resolved-skew", "blocking-skew"]


def test_unsync_mid_flight_cancels_by_draining(lockstep):
    def body(rank):
        m = _Sum(sync_timeout=0)
        m.update(jnp.asarray([2.0 + rank]))
        m.sync(blocking=False)
        m.update(jnp.asarray([5.0]))  # delta while the round flies
        m.unsync()  # cancel: drain + fold back, never future.cancel()
        assert m.__dict__.get("_inflight") is None
        stats = m.sync_stats()
        assert stats["cancelled"] == 1 and stats["resolved"] == 0
        local = float(np.asarray(m.total))
        # a later blocking sync still works (the channel stayed healthy)
        m.sync()
        world_total = float(np.asarray(m.total))
        m.unsync()
        return local, world_total

    for rank, (local, world_total) in enumerate(lockstep.run(body)):
        assert local == pytest.approx(7.0 + rank)
        assert world_total == pytest.approx(7.0 + 8.0)


def test_overlap_pipeline_compute_every_n(lockstep):
    """sync_mode="overlap": compute() serves the previous interval's world
    value (first call: local) while the next round rides behind the step —
    the compute()-every-N-costs-~0 contract."""
    K = 3

    def body(rank):
        m = _Sum(sync_timeout=0, sync_mode="overlap")
        values = []
        for _interval in range(3):
            for _ in range(K):
                m.update(jnp.asarray([float(rank + 1)]))
            values.append(float(np.asarray(m.compute())))
            m._computed = None  # next interval recomputes
        stats = m.sync_stats()
        m.unsync()  # drain the tail round symmetrically
        return values, stats

    results = lockstep.run(body)
    for rank, (values, stats) in enumerate(results):
        # interval 1: no resolved round yet — local-only serve (mean = rank+1)
        assert values[0] == pytest.approx(rank + 1.0)
        # intervals 2..: the PREVIOUS interval's world snapshot (both ranks'
        # accumulations at that cut), identical on both ranks
        assert values[1] == pytest.approx(1.5)
        assert values[2] == pytest.approx(1.5)
        assert stats["launched"] == 3
        assert stats["resolved"] == 2
        assert stats["served_local"] == 1


def test_collection_overlap_pipeline(lockstep):
    K = 2

    def body(rank):
        mc = MetricCollection(
            {"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)},
            sync_mode="overlap",
        )
        values = []
        for _interval in range(3):
            for _ in range(K):
                mc.update(jnp.asarray([float(rank + 1)]))
            vals = mc.compute()
            values.append({k: float(np.asarray(v)) for k, v in vals.items()})
            for m in mc.values():
                m._computed = None
        assert all(not m._is_synced for m in mc.values())  # restored each time
        mc.unsync()  # drain the tail round
        stats = mc.sync_stats()["collection"]
        assert stats["launched"] == 3 and stats["resolved"] == 2
        assert stats["served_local"] == 1
        return values

    for rank, values in enumerate(lockstep.run(body)):
        assert values[0]["a"] == pytest.approx(rank + 1.0)  # local serve
        assert values[1]["a"] == pytest.approx(3.0 / 2.0)  # previous world cut
        assert values[2]["a"] == pytest.approx(3.0 / 2.0)


def test_member_read_resolves_collection_round(lockstep):
    def body(rank):
        mc = MetricCollection({"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)})
        mc.update(jnp.asarray([1.0 + rank]))
        mc.sync(blocking=False)
        # a single member's compute() resolves the WHOLE collection round
        val = float(np.asarray(mc["a"].compute()))
        assert mc.__dict__["_inflight_round"] is None
        assert mc["b"]._is_synced  # sibling left synced (all-or-nothing)
        mc.unsync()
        assert not mc["b"]._is_synced
        return val

    for val in lockstep.run(body):
        assert val == pytest.approx((1.0 + 2.0) / 2.0)


def test_member_reset_cancels_collection_round(lockstep):
    """reset() on one member while a COLLECTION round is in flight must
    cancel the round (symmetric drain + fold-back) — otherwise the resolve
    would resurrect the pre-reset accumulation."""

    def body(rank):
        mc = MetricCollection({"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)})
        mc.update(jnp.asarray([1.0 + rank]))
        mc.sync(blocking=False)
        mc["a"].reset()
        assert mc.__dict__["_inflight_round"] is None
        assert mc.sync_stats()["collection"]["cancelled"] == 1
        # "a" is reset, "b" kept its folded-back accumulation
        a_local = float(np.asarray(mc["a"].total))
        b_local = float(np.asarray(mc["b"].total))
        return a_local, b_local

    for rank, (a_local, b_local) in enumerate(lockstep.run(body)):
        assert a_local == 0.0
        assert b_local == pytest.approx(1.0 + rank)


def test_collection_serve_local_caches_are_delta_buffers(lockstep):
    """The pipeline's first interval serves the snapshot view, but every
    member's unsync cache — group peers included — must hold the fresh
    DELTA buffers: unsync restores the delta side of the double buffer,
    never the snapshot (which the in-flight round owns)."""
    preds = [jnp.asarray(np.random.RandomState(13 + r).rand(16, 5).astype(np.float32)) for r in range(WORLD)]
    target = [jnp.asarray(np.random.RandomState(17 + r).randint(0, 5, (16,))) for r in range(WORLD)]

    def body(rank):
        mc = MetricCollection(
            {
                "prec": Precision(num_classes=5, average="macro"),
                "rec": Recall(num_classes=5, average="macro"),
            },
            sync_mode="overlap",
        )
        for m in mc.values():
            m.sync_timeout = 0
        mc.update(preds[rank], target[rank])
        assert mc.compute_group_keys
        mc.sync()  # auto overlap: launch + serve local
        for m in mc.values():
            defaults = m._default_state()
            for name in defaults:
                assert (
                    np.asarray(m._cache[name]).tobytes()
                    == np.asarray(defaults[name]).tobytes()
                ), name
        mc.unsync()  # members back on their (empty) delta buffers
        for m in mc.values():
            defaults = m._default_state()
            for name in defaults:
                assert (
                    np.asarray(m._state[name]).tobytes()
                    == np.asarray(defaults[name]).tobytes()
                ), name
        mc.unsync()  # cancel the pending round: fold the accumulation back
        total = float(sum(np.asarray(m._state["tp"]).sum() for m in mc.values()))
        return total

    totals = lockstep.run(body)
    assert all(t > 0 for t in totals)  # accumulation survived the cancel


def test_collection_deepcopy_and_pickle_drain_inflight_round(lockstep):
    import copy
    import pickle

    def body(rank):
        mc = MetricCollection({"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)})
        mc.update(jnp.asarray([1.0 + rank]))
        mc.sync(blocking=False)
        clone = copy.deepcopy(mc)  # drains symmetrically, no thread-lock crash
        assert mc.__dict__["_inflight_round"] is None
        mc.update(jnp.asarray([1.0]))
        mc.sync(blocking=False)
        blob = pickle.dumps(mc)  # same guard on the pickle path
        restored = pickle.loads(blob)
        return (
            float(np.asarray(clone["a"].total)),
            float(np.asarray(restored["a"].total)),
        )

    for rank, (cloned, restored) in enumerate(lockstep.run(body)):
        assert cloned == pytest.approx(1.0 + rank)
        assert restored == pytest.approx(2.0 + rank)


def test_member_clone_under_collection_round_keeps_accumulation(lockstep):
    """Cloning (or pickling) a single MEMBER while a COLLECTION round owns
    its accumulation must drain the round first — the copy would otherwise
    silently capture only the post-snapshot delta."""
    import copy

    def body(rank):
        mc = MetricCollection({"a": _Sum(sync_timeout=0), "b": _Sum(sync_timeout=0)})
        mc.update(jnp.asarray([5.0 + rank]))
        mc.sync(blocking=False)
        mc.update(jnp.asarray([7.0]))  # delta while the round flies
        clone = copy.deepcopy(mc["a"])
        assert mc.__dict__["_inflight_round"] is None  # round drained
        return float(np.asarray(clone.total)), float(np.asarray(mc["a"].total))

    for rank, (cloned, live) in enumerate(lockstep.run(body)):
        assert cloned == pytest.approx(12.0 + rank)  # snapshot ⊕ delta, not delta
        assert live == pytest.approx(12.0 + rank)


def test_plan_cache_reused_across_rounds(lockstep):
    def body(rank):
        m = _Sum(sync_timeout=0)
        for i in range(3):
            m.update(jnp.asarray([1.0 + rank + i]))
            m.sync(blocking=False)
            m.sync()
            m.unsync()
        return True

    assert all(lockstep.run(body))
    info = sync_plan_cache_info()
    # one plan built, every later overlapped round hits it (both ranks +
    # background lanes share the lock-protected cache)
    assert info["misses"] == 1
    assert info["hits"] >= 4


# ---------------------------------------------------------------------------
# interactions: checkpoint refusal, compiled updates, update-while-in-flight
# ---------------------------------------------------------------------------


def test_checkpoint_refuses_in_flight_round(lockstep, tmp_path):
    from metrics_tpu.core.checkpoint import save_checkpoint

    def body(rank):
        m = _Sum(sync_timeout=0)
        m.update(jnp.asarray([1.0 + rank]))
        m.sync(blocking=False)
        with pytest.raises(MetricsTPUUserError, match="in flight"):
            save_checkpoint(m, str(tmp_path / f"ck{rank}"), rank=rank, world=WORLD)
        m.unsync()  # cancel; now the snapshot is legal again
        path = save_checkpoint(m, str(tmp_path / f"ck{rank}"), rank=rank, world=WORLD)
        return bool(path)

    assert all(lockstep.run(body))


def test_compiled_updates_ride_the_overlap_window(lockstep):
    """The donation discipline: launch clears `_donation_ready`, so compiled
    (donating) updates during the window can never invalidate the snapshot
    the background gather is reading — values stay bit-identical."""

    def body(rank):
        over = _Sum(sync_timeout=0, compiled_update=True)
        block = _Sum(sync_timeout=0, compiled_update=True)
        for i in range(3):  # compiled from step 1 (knob skips warm-up)
            x = jnp.asarray([1.0 + rank + i])
            over.update(x)
            block.update(x)
        over.sync(blocking=False)
        for m, i in ((over, 3), (block, 3)):  # compiled delta updates mid-flight
            m.update(jnp.asarray([2.0 * rank + i]))
        block.sync()
        over.staleness_policy = "merge"  # fold the delta: same data as block
        over.sync()
        bits = (_state_bytes(over), _state_bytes(block))
        over.unsync()
        block.unsync()
        assert over.compile_stats()["dispatches"] > 0  # the path actually engaged
        return bits, _state_bytes(over), _state_bytes(block)

    for (synced_o, synced_b), local_o, local_b in lockstep.run(body):
        assert local_o == local_b


def test_state_dict_resolves_in_flight_round(lockstep):
    def body(rank):
        m = _Sum(sync_timeout=0)
        m.persistent(True)
        m.update(jnp.asarray([1.0 + rank]))
        m.sync(blocking=False)
        snap = m.state_dict()  # resolves: the snapshot is the SYNCED view
        assert m._is_synced
        m.unsync()
        return float(np.asarray(snap["total"])), float(np.asarray(m.total))

    for rank, (synced_total, local_total) in enumerate(lockstep.run(body)):
        assert synced_total == pytest.approx(3.0)
        assert local_total == pytest.approx(1.0 + rank)


def test_reset_drains_in_flight_round(lockstep):
    def body(rank):
        m = _Sum(sync_timeout=0)
        m.update(jnp.asarray([1.0 + rank]))
        m.sync(blocking=False)
        m.reset()
        assert m.__dict__.get("_inflight") is None
        assert m.sync_stats()["cancelled"] == 1
        assert float(np.asarray(m.total)) == 0.0
        return True

    assert all(lockstep.run(body))


def test_overlap_refused_for_non_mergeable_state(lockstep):
    class _NoMerge(Metric):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("v", jnp.zeros(()), dist_reduce_fx="mean")

        def update(self, x):
            self.v = (self.v + jnp.mean(x)) / 2.0

        def compute(self):
            return self.v

    def body(rank):
        m = _NoMerge(sync_timeout=0)
        m.update(jnp.asarray([1.0]))
        with pytest.raises(MetricsTPUUserError, match="merge"):
            m.sync(blocking=False)
        return True

    assert all(lockstep.run(body))
