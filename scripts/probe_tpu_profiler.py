"""One-shot probe: what device-time evidence can this TPU window give us?

Run via subprocess with a timeout (tunnel may hang). Prints JSON lines:
- device kind + platform
- whether jax.profiler.trace writes an xplane file and its size
- whether Compiled.cost_analysis() returns flops on this backend
"""
import glob
import json
import os
import sys
import tempfile


def main() -> None:
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    print(json.dumps({"platform": d.platform, "device_kind": d.device_kind,
                      "jax_version": jax.__version__}))

    @jax.jit
    def f(x):
        return (x @ x).sum()

    x = jnp.ones((512, 512), jnp.float32)
    lowered = jax.jit(lambda x: x @ x).lower(x)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        print(json.dumps({"cost_analysis_flops": ca.get("flops"),
                          "cost_analysis_keys": sorted(ca)[:20]}))
    except Exception as e:
        print(json.dumps({"cost_analysis_error": f"{type(e).__name__}: {e}"[:300]}))

    float(f(x))  # warm
    td = tempfile.mkdtemp(prefix="jaxprof_")
    try:
        with jax.profiler.trace(td):
            for _ in range(3):
                float(f(x))
        files = sorted(glob.glob(os.path.join(td, "**", "*"), recursive=True))
        listing = [(os.path.relpath(p, td), os.path.getsize(p))
                   for p in files if os.path.isfile(p)]
        print(json.dumps({"trace_files": listing}))
    except Exception as e:
        print(json.dumps({"trace_error": f"{type(e).__name__}: {e}"[:300]}))


if __name__ == "__main__":
    main()
