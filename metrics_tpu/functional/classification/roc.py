"""ROC curve — functional layer.

Behavioral analogue of the reference's
``torchmetrics/functional/classification/roc.py:24-273``.
"""
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_clf_curve,
    _precision_recall_curve_update,
)

_roc_update = _precision_recall_curve_update


def _roc_compute_single_class(
    preds: Array,
    target: Array,
    pos_label: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[Array, Array, Array]:
    fps, tps, thresholds = _binary_clf_curve(preds, target, sample_weights, pos_label)
    # prepend a point so the curve starts at (0, 0)
    tps = jnp.concatenate([jnp.zeros(1, dtype=tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, dtype=fps.dtype), fps])
    thresholds = jnp.concatenate([thresholds[0][None] + 1, thresholds])

    if fps[-1] <= 0:
        raise ValueError("No negative samples in targets, false positive value should be meaningless")
    fpr = fps / fps[-1]
    if tps[-1] <= 0:
        raise ValueError("No positive samples in targets, true positive value should be meaningless")
    tpr = tps / tps[-1]
    return fpr, tpr, thresholds


def _roc_compute_multi_class(
    preds: Array,
    target: Array,
    num_classes: int,
    sample_weights: Optional[Sequence] = None,
) -> Tuple[List[Array], List[Array], List[Array]]:
    fpr, tpr, thresholds = [], [], []
    for cls in range(num_classes):
        if preds.shape == target.shape:
            target_cls, pos_label = target[:, cls], 1
        else:
            target_cls, pos_label = target, cls
        res = roc(preds[:, cls], target_cls, num_classes=1, pos_label=pos_label, sample_weights=sample_weights)
        fpr.append(res[0])
        tpr.append(res[1])
        thresholds.append(res[2])
    return fpr, tpr, thresholds


def _roc_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    if num_classes == 1 and preds.ndim == 1:
        if pos_label is None:
            pos_label = 1
        return _roc_compute_single_class(preds, target, pos_label, sample_weights)
    return _roc_compute_multi_class(preds, target, num_classes, sample_weights)


def roc(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    sample_weights: Optional[Sequence] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Receiver-operating-characteristic curve in one call (the stateless
    twin of :class:`~metrics_tpu.ROC`).

    Sorts the scores once, cumulative-sums hits/misses over the sorted
    order (`_binary_clf_curve`) and prepends the conventional origin
    point — O(N log N), no python loop, jittable for binary input.

    Args:
        preds: binary scores ``[N]``, or per-class scores ``[N, C]``.
        target: labels ``[N]`` (binary/multiclass) or ``[N, C]``
            (multilabel).
        num_classes: class count for multiclass scores; inferred from the
            trailing dimension when possible.
        pos_label: label counted as positive for binary input.
        sample_weights: optional per-sample weights folded into the
            true/false-positive counts.

    Returns:
        ``(fpr, tpr, thresholds)`` arrays for binary input; for
        multiclass/multilabel, three lists with one array per class.
        ``thresholds[0]`` is one above the best score (the "predict
        nothing" end of the curve).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import roc
        >>> pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 1])
        >>> fpr, tpr, thresholds = roc(pred, target, pos_label=1)
        >>> print(fpr)
        [0. 0. 0. 0. 1.]
        >>> print(tpr)
        [0.         0.33333334 0.6666667  1.         1.        ]
    """
    preds, target, num_classes, pos_label = _roc_update(preds, target, num_classes, pos_label)
    return _roc_compute(preds, target, num_classes, pos_label, sample_weights)
