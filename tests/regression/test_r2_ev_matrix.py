"""R2Score / ExplainedVariance single/multi-target × multioutput matrices.

Mirror of the reference's `tests/regression/test_r2.py` (adjusted ∈ {0,5,10}
× multioutput × targets × ddp × per-step sync) and
`test_explained_variance.py` (multioutput × targets × ddp × per-step sync),
both against sklearn.
"""
from collections import namedtuple
from functools import partial

import numpy as np
import pytest
from sklearn.metrics import explained_variance_score as sk_ev
from sklearn.metrics import r2_score as sk_r2score

from metrics_tpu import ExplainedVariance, R2Score
from metrics_tpu.functional import explained_variance, r2_score
from tests.helpers.testers import BATCH_SIZE, NUM_BATCHES, MetricTester

NUM_TARGETS = 5
rng = np.random.RandomState(42)

Input = namedtuple("Input", ["preds", "target"])

_single = Input(
    preds=rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
    target=rng.rand(NUM_BATCHES, BATCH_SIZE).astype(np.float32),
)
_multi = Input(
    preds=rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_TARGETS).astype(np.float32),
    target=rng.rand(NUM_BATCHES, BATCH_SIZE, NUM_TARGETS).astype(np.float32),
)


def _sk_r2(preds, target, adjusted, multioutput, num_outputs):
    p = preds.reshape(-1, num_outputs) if num_outputs > 1 else preds.reshape(-1)
    t = target.reshape(-1, num_outputs) if num_outputs > 1 else target.reshape(-1)
    score = sk_r2score(t, p, multioutput=multioutput)
    if adjusted != 0:
        n = p.shape[0]
        score = 1 - (1 - score) * (n - 1) / (n - adjusted - 1)
    return score


def _sk_explained_variance(preds, target, multioutput, num_outputs):
    p = preds.reshape(-1, num_outputs) if num_outputs > 1 else preds.reshape(-1)
    t = target.reshape(-1, num_outputs) if num_outputs > 1 else target.reshape(-1)
    return sk_ev(t, p, multioutput=multioutput)


@pytest.mark.parametrize("adjusted", [0, 5, 10])
@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
@pytest.mark.parametrize(
    "preds, target, num_outputs",
    [
        (_single.preds, _single.target, 1),
        (_multi.preds, _multi.target, NUM_TARGETS),
    ],
    ids=["single_target", "multi_target"],
)
class TestR2Matrix(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_r2_class(self, adjusted, multioutput, preds, target, num_outputs, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=R2Score,
            sk_metric=partial(_sk_r2, adjusted=adjusted, multioutput=multioutput, num_outputs=num_outputs),
            dist_sync_on_step=dist_sync_on_step,
            metric_args=dict(adjusted=adjusted, multioutput=multioutput, num_outputs=num_outputs),
        )

    def test_r2_fn(self, adjusted, multioutput, preds, target, num_outputs):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=r2_score,
            sk_metric=partial(_sk_r2, adjusted=adjusted, multioutput=multioutput, num_outputs=num_outputs),
            metric_args=dict(adjusted=adjusted, multioutput=multioutput),
        )


def test_r2_wrong_params():
    """Reference `test_r2.py:110-132`: negative adjusted / bad multioutput."""
    with pytest.raises(ValueError):
        R2Score(adjusted=-1)
    with pytest.raises(ValueError):
        r2_score(np.asarray([1.0, 2.0]), np.asarray([1.0, 2.0]), multioutput="bogus")


@pytest.mark.parametrize("multioutput", ["raw_values", "uniform_average", "variance_weighted"])
@pytest.mark.parametrize(
    "preds, target, num_outputs",
    [
        (_single.preds, _single.target, 1),
        (_multi.preds, _multi.target, NUM_TARGETS),
    ],
    ids=["single_target", "multi_target"],
)
class TestExplainedVarianceMatrix(MetricTester):
    atol = 1e-5

    @pytest.mark.parametrize("ddp", [True, False])
    @pytest.mark.parametrize("dist_sync_on_step", [True, False])
    def test_ev_class(self, multioutput, preds, target, num_outputs, ddp, dist_sync_on_step):
        self.run_class_metric_test(
            ddp=ddp,
            preds=preds,
            target=target,
            metric_class=ExplainedVariance,
            sk_metric=partial(_sk_explained_variance, multioutput=multioutput, num_outputs=num_outputs),
            dist_sync_on_step=dist_sync_on_step,
            metric_args=dict(multioutput=multioutput),
        )

    def test_ev_fn(self, multioutput, preds, target, num_outputs):
        self.run_functional_metric_test(
            preds,
            target,
            metric_functional=explained_variance,
            sk_metric=partial(_sk_explained_variance, multioutput=multioutput, num_outputs=num_outputs),
            metric_args=dict(multioutput=multioutput),
        )


def test_ev_wrong_multioutput():
    with pytest.raises(ValueError):
        explained_variance(np.asarray([1.0, 2.0]), np.asarray([1.0, 2.0]), multioutput="bogus")
