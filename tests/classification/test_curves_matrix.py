"""ROC / PrecisionRecallCurve class-path matrices over every prob fixture.

Complement to `test_curves.py` (single-batch functional parity): here the
CLASS metrics accumulate all NUM_BATCHES batches (cat-list states), optionally
across two simulated ranks merged with `merge_state`, and the resulting
curves are compared point-for-point with sklearn on the concatenated data —
mirror of the reference's `test_roc.py` / `test_precision_recall_curve.py`
grids (binary / multiclass / mdmc / multilabel / mlmd).
"""
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import precision_recall_curve as sk_precision_recall_curve
from sklearn.metrics import roc_curve as sk_roc_curve

from metrics_tpu import ROC, PrecisionRecallCurve
from tests.classification.inputs import (
    _input_binary_prob,
    _input_multiclass_prob as _input_mcls_prob,
    _input_multidim_multiclass_prob as _input_mdmc_prob,
    _input_multilabel_multidim_prob as _input_mlmd_prob,
    _input_multilabel_prob as _input_mlb_prob,
)
from tests.helpers.testers import NUM_CLASSES, accumulate_and_merge

# (fixture, num_classes, flavor); flavor decides how sklearn per-class truth
# is built from the concatenated raw data
_GRID = [
    (_input_binary_prob, 1, "binary"),
    (_input_mcls_prob, NUM_CLASSES, "multiclass"),
    (_input_mdmc_prob, NUM_CLASSES, "mdmc"),
    (_input_mlb_prob, NUM_CLASSES, "multilabel"),
    (_input_mlmd_prob, NUM_CLASSES, "mlmd"),
]
_IDS = [g[2] for g in _GRID]


def _flatten(inputs, flavor, num_classes):
    """Concatenate all batches and collapse to (scores[N, C] or [N], labels)."""
    preds = np.concatenate(list(inputs.preds), axis=0)
    target = np.concatenate(list(inputs.target), axis=0)
    if flavor == "binary":
        return preds.reshape(-1), target.reshape(-1)
    if flavor == "multiclass":
        return preds.reshape(-1, num_classes), target.reshape(-1)
    if flavor == "mdmc":
        return np.moveaxis(preds, 1, -1).reshape(-1, num_classes), target.reshape(-1)
    if flavor == "multilabel":
        return preds.reshape(-1, num_classes), target.reshape(-1, num_classes)
    if flavor == "mlmd":
        return (
            np.moveaxis(preds, 1, -1).reshape(-1, num_classes),
            np.moveaxis(target, 1, -1).reshape(-1, num_classes),
        )
    raise ValueError(flavor)


def _class_truth(scores, labels, flavor, c):
    if flavor in ("multilabel", "mlmd"):
        return labels[:, c], scores[:, c]
    return (labels == c).astype(int), scores[:, c]


def _accumulate(metric_cls, inputs, num_classes, world):
    kwargs = {} if num_classes == 1 else {"num_classes": num_classes}
    return accumulate_and_merge(lambda: metric_cls(**kwargs), inputs.preds, inputs.target, world)


@pytest.mark.parametrize("inputs, num_classes, flavor", _GRID, ids=_IDS)
@pytest.mark.parametrize("world", [1, 2], ids=["single", "ddp_merge"])
def test_roc_class_matrix(inputs, num_classes, flavor, world):
    fpr, tpr, _ = _accumulate(ROC, inputs, num_classes, world)
    scores, labels = _flatten(inputs, flavor, num_classes)
    if flavor == "binary":
        sk_fpr, sk_tpr, _ = sk_roc_curve(labels, scores, drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr), sk_fpr, atol=1e-6)
        np.testing.assert_allclose(np.asarray(tpr), sk_tpr, atol=1e-6)
        return
    for c in range(num_classes):
        t, s = _class_truth(scores, labels, flavor, c)
        sk_fpr, sk_tpr, _ = sk_roc_curve(t, s, drop_intermediate=False)
        np.testing.assert_allclose(np.asarray(fpr[c]), sk_fpr, atol=1e-6, err_msg=f"class {c} fpr")
        np.testing.assert_allclose(np.asarray(tpr[c]), sk_tpr, atol=1e-6, err_msg=f"class {c} tpr")


@pytest.mark.parametrize("inputs, num_classes, flavor", _GRID, ids=_IDS)
@pytest.mark.parametrize("world", [1, 2], ids=["single", "ddp_merge"])
def test_prc_class_matrix(inputs, num_classes, flavor, world):
    precision, recall, _ = _accumulate(PrecisionRecallCurve, inputs, num_classes, world)
    scores, labels = _flatten(inputs, flavor, num_classes)

    def check(ours_p, ours_r, t, s, msg):
        sk_p, sk_r, _ = sk_precision_recall_curve(t, s)
        # the reference truncates the full-recall plateau to its last point;
        # sklearn keeps the plateau, so our curve equals sklearn's tail
        off = len(sk_p) - len(np.asarray(ours_p))
        assert off >= 0, f"{msg}: curve longer than sklearn's ({len(np.asarray(ours_p))} vs {len(sk_p)})"
        np.testing.assert_allclose(np.asarray(ours_p), sk_p[off:], atol=1e-6, err_msg=msg)
        np.testing.assert_allclose(np.asarray(ours_r), sk_r[off:], atol=1e-6, err_msg=msg)

    if flavor == "binary":
        check(precision, recall, labels, scores, "binary")
        return
    for c in range(num_classes):
        t, s = _class_truth(scores, labels, flavor, c)
        check(precision[c], recall[c], t, s, f"class {c}")
