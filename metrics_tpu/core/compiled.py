"""Compiled eager hot path — auto-JIT scaffolding for ``update``/``forward``.

The torchmetrics-style eager surface pays one host→device dispatch per jnp
op inside ``update()`` (1.5–6.8 ms/step for a 4-metric stat-score collection
on CPU — bench config 9) while the same math fused into one XLA program runs
in tens of microseconds (bench config 1). This module holds the machinery
that closes that gap without changing the eager semantics: the stateful
``Metric.update()``/``forward()`` route through a cached
``jax.jit(pure_update)`` program with the state buffers donated, so a hot
loop costs ONE XLA dispatch per step and zero per-step allocation churn —
the same move data-parallel training systems make when they compile the
weight-update step into the main program (arXiv:2004.13336) instead of
running it op-by-op from the host.

Pieces (wired into ``core/metric.py`` / ``core/collections.py``):

- Knobs: ``METRICS_TPU_COMPILED_UPDATE=0`` disables the path process-wide
  (the escape hatch; ``Metric.compiled_update = False`` is the per-metric
  equivalent, ``True`` forces immediate compilation).
  ``METRICS_TPU_COMPILED_WARMUP`` (default 16) sets how many eager steps an
  instance observes before it invests in a trace — unit-test-sized
  workloads never pay compile time, hot loops amortize it within a few
  hundredths of their step count.
- :func:`split_call` partitions an eager call's ``(args, kwargs)`` into
  dynamic array leaves (traced; jax retraces per shape/dtype signature) and
  a hashable static skeleton — python scalars and flags are closed over
  exactly as the eager call saw them, so ``update(x, True)``-style
  signatures keep their python-branch semantics.
- :class:`CompiledDispatcher` — per-instance program cache, trace/dispatch
  counters (the ``compile_stats()`` observability surface), permanent
  per-instance fallback bookkeeping with a one-time diagnostic, and the
  recompile-storm warn counter: ragged epoch tails recompile once per new
  shape and then hit the cache, but unbounded shape churn warns instead of
  silently degrading into a compile loop.
- :func:`probe_traceable` — the first-trace eligibility probe: a compile-free
  ``jax.eval_shape`` dry run that catches data-dependent python control flow
  (``ConcretizationTypeError`` and friends) and undeclared instance-attribute
  side effects *before* any state buffer is donated, restoring whatever the
  probe touched. Families with declared side-effect latches
  (``Metric._group_shared_attrs`` — Accuracy's input-mode latch, the curve
  family's inferred ``num_classes``) are routed to eager statically, without
  a probe.

The correctness contract is **compiled ≡ eager, leaf for leaf** — update
counts, ``check_finite`` poison flags, CatBuffer appends and overflow
latches, dtype persistence and compute-group dispatch all behave
bit-identically (``tests/bases/test_compiled_update.py``).
"""
import itertools
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from metrics_tpu.observability import diagnostics, journal

#: Env escape hatch: set to 0/false/off to disable compiled eager dispatch
#: process-wide (every update/forward then runs the per-op eager path).
COMPILED_UPDATE_ENV = "METRICS_TPU_COMPILED_UPDATE"

#: Eager steps an instance observes before its first trace (default 16).
#: ``Metric.compiled_update = True`` skips the warm-up entirely.
COMPILED_WARMUP_ENV = "METRICS_TPU_COMPILED_WARMUP"

#: Retrace count at which the shape-churn diagnostic fires (default 8).
TRACE_WARN_ENV = "METRICS_TPU_COMPILED_TRACE_WARN"


def dispatch_program(disp: "CompiledDispatcher", kind: str, prog: Callable, states, dynamic):
    """Guarded donating execution, shared by every compiled dispatch site.

    Returns ``(handled, out)``. A failing execution falls back to eager —
    permanently for this ``kind`` — *provided* the donated input buffers
    survived; buffers consumed mid-failure are unrecoverable, so that case
    re-raises instead of silently corrupting state. Donation itself is
    best-effort per backend (CPU has no buffer aliasing and may warn once
    that the donated buffers went unused — python's default once-per-location
    warning dedup keeps that to a single line, and the fallback is an
    ordinary copy, exactly what the eager path pays; the global warning
    filters are deliberately left untouched).
    """
    # journal gate read once: when the recorder is off the step path pays
    # this one attribute read — no clock calls, no allocation
    active = journal.ACTIVE
    if active:
        t0 = time.monotonic()
        traces0 = disp.traces
    try:
        out = prog(states, dynamic)
    except Exception as err:  # noqa: BLE001 - recover to eager when state survived
        if any(
            getattr(leaf, "is_deleted", bool)()
            for leaf in jax.tree_util.tree_leaves(states)
        ):
            raise  # donation consumed the buffers mid-failure: unrecoverable
        disp.mark_fallback(
            kind, f"compiled dispatch failed ({type(err).__name__}: {str(err)[:160]})"
        )
        return False, None
    disp.note_dispatch()
    if active:
        now = time.monotonic()
        if disp.traces > traces0:
            journal.record(
                "compiled.trace", label=disp.label, step=disp.steps_seen,
                op=kind, traces=disp.traces,
            )
        journal.record(
            "compiled.dispatch", label=disp.label, step=disp.steps_seen,
            op=kind, dur_s=now - t0,
        )
    return True, out


def compiled_update_enabled() -> bool:
    """Default policy: on, unless the env knob opts the process out."""
    return os.environ.get(COMPILED_UPDATE_ENV, "1").strip().lower() not in (
        "0", "false", "off", "no",
    )


def compiled_warmup() -> int:
    try:
        return int(os.environ.get(COMPILED_WARMUP_ENV, "16"))
    except ValueError:
        return 16


def trace_warn_threshold() -> int:
    try:
        return int(os.environ.get(TRACE_WARN_ENV, "8"))
    except ValueError:
        return 8


def trace_storm_threshold() -> int:
    """Retrace count at which an instance gives up on compiling entirely
    (4x the warn threshold): sustained churn — every step a new shape, or a
    python scalar argument that changes per batch — means each dispatch pays
    a probe + compile instead of a cache hit, which is strictly worse than
    eager, and the per-key program cache would otherwise grow without bound."""
    return 4 * trace_warn_threshold()


def compile_stats_view(stats: Dict[str, Any]) -> Dict[str, Any]:
    """The public ``compile_stats()`` shape, derived from the registry's
    ``compile`` domain (``observability/registry.py``): raw counters pass
    through, ``cache_hits`` is computed, an empty fallback map reads as
    ``None`` (API compatibility with the historical dict bookkeeping)."""
    fallback = stats.get("fallback")
    return {
        "traces": stats.get("traces", 0),
        "dispatches": stats.get("dispatches", 0),
        "cache_hits": max(stats.get("dispatches", 0) - stats.get("traces", 0), 0),
        "steps_seen": stats.get("steps_seen", 0),
        "fallback": dict(fallback) if fallback else None,
    }


class _Dynamic:
    """Positional placeholder for a traced leaf inside the static skeleton."""

    _instance: Optional["_Dynamic"] = None

    def __new__(cls) -> "_Dynamic":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return "<dynamic>"


DYNAMIC = _Dynamic()


def split_call(args: Tuple, kwargs: Dict[str, Any]):
    """Partition an eager call into traced leaves and a static skeleton.

    Returns ``(treedef, dyn_ix, statics, dynamic)``: ``dynamic`` is the list
    of array-typed leaves (anything with ``dtype``+``shape`` — jnp/np arrays
    and numpy scalars) in flattening order, ``statics`` the full leaf list
    with those positions replaced by the :data:`DYNAMIC` sentinel, and
    ``dyn_ix`` their indices. ``(treedef, dyn_ix, statics)`` is the hashable
    program-cache key component; python scalars/flags stay static so the
    compiled call sees exactly the values the eager call saw (a new static
    value is a new program, same as a new shape). Raises ``TypeError`` when
    a non-array leaf is unhashable — the caller falls back to eager.
    """
    leaves, treedef = jax.tree_util.tree_flatten((args, dict(kwargs)))
    dyn_ix: List[int] = []
    dynamic: List[Any] = []
    statics: List[Any] = []
    for i, leaf in enumerate(leaves):
        if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
            dyn_ix.append(i)
            dynamic.append(leaf)
            statics.append(DYNAMIC)
        else:
            hash(leaf)  # TypeError -> caller falls back to eager
            statics.append(leaf)
    return treedef, tuple(dyn_ix), tuple(statics), dynamic


def rebuild_call(treedef, dyn_ix: Tuple[int, ...], statics: Tuple, dynamic: Sequence):
    """Inverse of :func:`split_call` inside the traced program."""
    leaves = list(statics)
    for pos, i in enumerate(dyn_ix):
        leaves[i] = dynamic[pos]
    return jax.tree_util.tree_unflatten(treedef, leaves)


#: Bookkeeping attributes the runtime itself mutates around a trace — never
#: evidence of an update side effect.
_PROBE_EXEMPT = frozenset(
    {
        "_state",
        "_defaults",
        "_computed",
        "_update_called",
        "_forward_cache",
        "_update_count",
        "_pure_mode",
        "_donation_ready",
        "_compiled",
        "_plan_binding",
        "_cache",
        "_update_kwarg_names",
        "_ckpt_suppress",
        "_to_sync",
    }
)

_MISSING = object()


def _snapshot_attr(v: Any) -> Any:
    """Snapshot one instance attribute for side-effect detection: mutable
    containers are shallow-copied (so in-place ``append``/``add``/``[k]=``
    mutations are detectable), everything else is held by reference and
    compared by identity."""
    if isinstance(v, list):
        return list(v)
    if isinstance(v, set):
        return set(v)
    if isinstance(v, dict):
        return dict(v)
    return v


def _attr_changed(now: Any, snap: Any) -> bool:
    """Did an attribute change vs its probe snapshot? Containers compare by
    length/keys plus element *identity* (never ``==`` — elements may be
    arrays with elementwise equality); everything else by identity. One
    container level deep, matching the nested-metric scan."""
    if isinstance(snap, list):
        return not (
            isinstance(now, list) and len(now) == len(snap)
            and all(a is b for a, b in zip(now, snap))
        )
    if isinstance(snap, set):
        # set elements are hashable by construction, so == is safe here
        return not (isinstance(now, set) and now == snap)
    if isinstance(snap, dict):
        return not (
            isinstance(now, dict) and set(now) == set(snap)
            and all(now[k] is snap[k] for k in snap)
        )
    return now is not snap


def probe_traceable(fn: Callable, state: Any, dynamic: Sequence, owners: Sequence) -> Optional[str]:
    """First-trace eligibility probe: abstract-evaluate ``fn(state, dynamic)``.

    ``jax.eval_shape`` runs the full trace without compiling, so data-
    dependent python control flow (``ConcretizationTypeError`` and friends)
    and genuine update bugs surface here at near-zero cost. Afterwards every
    ``owner``'s instance ``__dict__`` is compared against a pre-probe
    snapshot — by identity for plain attributes, by shallow contents for
    mutable containers (an in-place ``self.seen.append(...)`` is as much of
    a latch as ``self.mode = ...``): any such side effect is work the
    compiled replay would skip, so it disqualifies the owner. Returns
    ``None`` when the trace is clean, else a human-readable fallback reason;
    anything the probe mutated is restored either way, so the subsequent
    eager run re-derives its own latches.
    """
    snaps = [
        {k: _snapshot_attr(v) for k, v in m.__dict__.items() if k not in _PROBE_EXEMPT}
        for m in owners
    ]

    def _restore() -> None:
        for m, snap in zip(owners, snaps):
            for k in list(m.__dict__):
                if k not in _PROBE_EXEMPT and k not in snap:
                    object.__delattr__(m, k)
            for k, v in snap.items():
                if _attr_changed(m.__dict__.get(k, _MISSING), v):
                    object.__setattr__(m, k, v)

    try:
        jax.eval_shape(fn, state, list(dynamic))
    except Exception as err:  # noqa: BLE001 - any trace failure routes to eager
        _restore()
        return f"update is not traceable ({type(err).__name__}: {str(err)[:160]})"
    changed: List[str] = []
    for m, snap in zip(owners, snaps):
        for k in set(m.__dict__) | set(snap):
            if k in _PROBE_EXEMPT:
                continue
            if _attr_changed(m.__dict__.get(k, _MISSING), snap.get(k, _MISSING)):
                changed.append(f"{type(m).__name__}.{k}")
    if changed:
        _restore()
        return (
            "update mutates instance attribute(s) "
            + ", ".join(sorted(changed))
            + " — a side-effect latch the compiled replay would skip"
        )
    return None


def consult_static(pairs) -> Tuple[str, Optional[str]]:
    """metricslint pre-classification for an eligibility probe: aggregate
    ``(metric, kinds)`` pairs into ``("clean"|"dirty"|"unknown", detail)``.

    ``clean`` means every instance's class was statically verified (writes
    only declared states, no host-sync antipatterns, fully resolved scan) —
    the ``jax.eval_shape`` probe is redundant and may be skipped; a residual
    trace failure still recovers to eager via :func:`dispatch_program`
    (trace errors precede any buffer consumption). ``dirty`` means the
    static report *refuted* eligibility — ``detail`` names the offending
    attribute and source line, the definition-time diagnostic that replaces
    the generic probe message. ``unknown`` (unresolvable source, dynamic
    writes, ``METRICS_TPU_ANALYSIS_PRECLASSIFY=0``) keeps the runtime probe
    as the last word, exactly the pre-classification-free behavior.
    """
    try:
        from metrics_tpu.analysis.runtime import static_probe_verdict_many
    except Exception:  # pragma: no cover - analysis package always ships
        return "unknown", None
    return static_probe_verdict_many(pairs)


_compile_cache_checked = False


def _ensure_persistent_compile_cache() -> None:
    """Honor ``METRICS_TPU_COMPILE_CACHE`` for compiled eager programs too.

    The entry points that opt into jax's persistent on-disk compile cache
    (``__graft_entry__``, ``bench.py``) call ``compile_cache.enable_from_env``
    themselves; a user hot loop that triggers auto-JIT through the eager API
    deserves the same treatment without code changes. No-op when the env
    knob is unset.
    """
    global _compile_cache_checked
    if _compile_cache_checked:
        return
    _compile_cache_checked = True
    from metrics_tpu.utils.compile_cache import enable_from_env

    enable_from_env()


class CompiledDispatcher:
    """Per-instance compiled-dispatch state: program cache + observability.

    One dispatcher hangs off each :class:`~metrics_tpu.Metric` (and each
    ``MetricCollection``) that ever considers the compiled path. It owns

    - the jitted-program cache, keyed by ``(kind, call skeleton)`` — jax's
      own jit cache handles per-shape retracing *within* each key. The
      storage lives in the owner's :class:`~metrics_tpu.core.plan.
      PlanBinding` (``Metric._compiled_dispatcher`` passes it), so the
      dispatcher is a *view* into the unified execution plan rather than an
      independent schema-keyed cache — the whole-step fused programs
      (``plan.compiled_step``) share the same namespace under disjoint
      keys;
    - the counters ``traces`` / ``dispatches`` / ``steps_seen`` surfaced by
      ``compile_stats()`` (``cache_hits = dispatches - traces``);
    - the permanent per-kind ``fallback`` map with its one-time diagnostic
      (probe/dispatch-discovered fallbacks warn once per instance; the
      statically-declared ones — side-effect families, growing list states —
      stay silent by design, they are documented behavior);
    - the recompile-storm warn counter (``METRICS_TPU_COMPILED_TRACE_WARN``).

    Programs close over their owner, so copies never share: ``__deepcopy__``
    and pickling hand the clone a fresh, empty dispatcher.
    """

    __slots__ = (
        "label",
        "uid",
        "_stats",
        "_binding",
        "_churn_warned",
    )

    #: monotonically-increasing dispatcher ids: the warn_once dedupe keys
    #: must survive this dispatcher's garbage collection (an ``id(self)``
    #: key can be REUSED by a later allocation, silently eating a brand-new
    #: instance's first warning)
    _uid_counter = itertools.count()

    def __init__(
        self,
        label: str,
        stats: Optional[Dict[str, Any]] = None,
        binding: Optional[Any] = None,
    ) -> None:
        self.label = label
        self.uid = next(CompiledDispatcher._uid_counter)
        self._churn_warned = False
        # counter storage: the owner's telemetry-registry "compile" domain
        # when bound (Metric._compiled_dispatcher passes it), else a private
        # dict of the same shape — compile_stats() is a VIEW over this dict
        # either way (one storage, no hand-maintained copies)
        self._stats = stats if stats is not None else {}
        self._stats.setdefault("traces", 0)
        self._stats.setdefault("dispatches", 0)
        self._stats.setdefault("steps_seen", 0)
        if not isinstance(self._stats.get("fallback"), dict):
            self._stats["fallback"] = {}
        # program/probe storage: the owner's PlanBinding when bound, else a
        # private binding of the same shape — either way the dispatcher is a
        # view, never an independent cache
        if binding is None:
            from metrics_tpu.core.plan import PlanBinding

            binding = PlanBinding(label)
        self._binding = binding

    @property
    def _programs(self) -> Dict[Any, Any]:
        return self._binding.programs

    @property
    def _probed(self) -> set:
        return self._binding.probed

    # counter shims: every counting site reads/writes the registry dict
    @property
    def traces(self) -> int:
        return self._stats["traces"]

    @traces.setter
    def traces(self, v: int) -> None:
        self._stats["traces"] = v

    @property
    def dispatches(self) -> int:
        return self._stats["dispatches"]

    @dispatches.setter
    def dispatches(self, v: int) -> None:
        self._stats["dispatches"] = v

    @property
    def steps_seen(self) -> int:
        return self._stats["steps_seen"]

    @steps_seen.setter
    def steps_seen(self, v: int) -> None:
        self._stats["steps_seen"] = v

    @property
    def fallback(self) -> Dict[str, str]:
        return self._stats["fallback"]

    def stats(self) -> Dict[str, Any]:
        return compile_stats_view(self._stats)

    def mark_fallback(self, kind: str, reason: str, warn: bool = True) -> None:
        """Permanently route ``kind`` dispatches to eager for this instance."""
        if kind in self.fallback:
            return
        self.fallback[kind] = reason
        if journal.ACTIVE:
            journal.record(
                "compiled.fallback", label=self.label, step=self.steps_seen,
                op=kind, reason=reason,
            )
        if warn:
            diagnostics.warn_once(
                ("compiled-fallback", self.uid),
                f"{self.label}: compiled eager {kind} disabled for this instance — "
                f"{reason}. The per-op eager path (bit-identical, slower) is used "
                f"instead; escape hatches: {COMPILED_UPDATE_ENV}=0 process-wide or "
                "`metric.compiled_update = False`.",
                UserWarning,
            )

    def probed(self, key: Any) -> bool:
        return key in self._probed

    def mark_probed(self, key: Any) -> None:
        self._probed.add(key)

    def program(self, key: Any, build: Callable[[], Callable]) -> Callable:
        """The jitted program for ``key`` (built and cached on first use)."""
        prog = self._programs.get(key)
        if prog is None:
            _ensure_persistent_compile_cache()
            raw = build()

            def counted(state, dyn, _raw=raw):
                # runs once per trace: the trace counter is how shape churn
                # becomes visible (compile_stats / the storm warning below)
                self.traces += 1
                return _raw(state, dyn)

            prog = jax.jit(counted, donate_argnums=(0,))
            self._programs[key] = prog
        return prog

    def note_dispatch(self) -> None:
        self.dispatches += 1
        # the per-instance bool keeps the warn_once lock + dedupe-set probe
        # off the hot step path once the threshold has been crossed (this
        # method runs on EVERY compiled dispatch)
        if not self._churn_warned and self.traces >= trace_warn_threshold():
            self._churn_warned = True
            diagnostics.warn_once(
                ("compiled-trace-churn", self.uid),
                f"{self.label}: the compiled eager path retraced {self.traces} times — "
                "churn in the call signature (ragged last batches, a state whose shape "
                "grows every step, or a python-scalar argument whose value changes per "
                "batch). Each new signature compiles once and then hits the cache, so a "
                "few ragged epoch tails are cheap after the first epoch; unbounded "
                "variety is not. Pad batches to a fixed size (or a small set of bucket "
                "sizes) and pass per-batch scalars as jnp arrays, or set "
                "`compiled_update=False` on this metric. At "
                f"{trace_storm_threshold()} traces this instance falls back to eager "
                "permanently.",
                UserWarning,
            )

    def storming(self, kind: str) -> bool:
        """True once retraces crossed the storm threshold: marks ``kind``
        permanently eager (each further compile would cost more than the
        dispatch it saves, and the program cache must stop growing)."""
        if self.traces < trace_storm_threshold():
            return False
        self.mark_fallback(
            kind,
            f"recompile storm: {self.traces} traces — the call signature (shapes or "
            "static python-scalar values) changes too often for a cached program to "
            "pay off",
        )
        return True

    # copies/pickles must never share programs: every cached program closes
    # over the ORIGINAL owner instance, and its statistics describe it alone
    def __deepcopy__(self, memo: dict) -> "CompiledDispatcher":
        return CompiledDispatcher(self.label)

    def __reduce__(self):
        return (CompiledDispatcher, (self.label,))
