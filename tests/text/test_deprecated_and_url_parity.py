"""Signature-parity additions vs the reference (AST sweep findings).

The reference v0.6 ships three constructor params the repo lacked:
ROUGEScore(newline_sep, decimal_places) and WER(concatenate_texts) —
deprecated warn-only kwargs (`/root/reference/torchmetrics/text/rouge.py:84-102`,
`text/wer.py:74-87`) — and BERTScore(baseline_url), a real feature
(`text/bert.py:142`, `functional/text/bert.py:396-425`). The url path is
exercised offline through ``file://`` URLs (urllib handles them natively).
"""
import numpy as np
import pytest

from metrics_tpu import WER, BERTScore, ROUGEScore
from metrics_tpu.functional.text.bert import (
    _read_baseline_csv,
    _read_baseline_url,
    bundled_baseline_path,
)


@pytest.mark.parametrize("kwargs", [{"newline_sep": True}, {"decimal_places": True}])
def test_rouge_deprecated_kwargs_warn(kwargs):
    key = next(iter(kwargs))
    with pytest.warns(UserWarning, match=f"`{key}` is deprecated in v0.6"):
        ROUGEScore(**kwargs)


def test_rouge_deprecated_kwargs_silent_when_unset():
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ROUGEScore()


def test_wer_concatenate_texts_warns_and_is_inert():
    with pytest.warns(DeprecationWarning, match="`concatenate_texts` has been deprecated in v0.6"):
        m = WER(concatenate_texts=True)
    m.update(["hello world"], ["hello world"])
    assert float(m.compute()) == 0.0


def test_read_baseline_url_file_scheme(tmp_path):
    """file:// URLs drive the same reader as HTTP — csv and tsv variants."""
    src = bundled_baseline_path()
    want = np.asarray(_read_baseline_csv(src))

    got_csv = np.asarray(_read_baseline_url(f"file://{src}"))
    np.testing.assert_array_equal(got_csv, want)

    tsv = tmp_path / "baseline.tsv"
    tsv.write_text(open(src).read().replace(",", "\t"))
    got_tsv = np.asarray(_read_baseline_url(f"file://{tsv}"))
    np.testing.assert_array_equal(got_tsv, want)


def test_bertscore_baseline_url_end_to_end():
    """BERTScore(baseline_url=file://...) rescales identically to the same
    baseline passed via baseline_path."""
    preds = ["the cat sat on the mat"] * 2
    refs = ["a cat sat on a mat"] * 2
    src = bundled_baseline_path()

    by_url = BERTScore(max_length=32, rescale_with_baseline=True, baseline_url=f"file://{src}")
    by_url.update(preds, refs)
    out_url = by_url.compute()

    by_path = BERTScore(max_length=32, rescale_with_baseline=True, baseline_path=src)
    by_path.update(preds, refs)
    out_path = by_path.compute()

    for key in ("precision", "recall", "f1"):
        np.testing.assert_allclose(np.asarray(out_url[key]), np.asarray(out_path[key]), atol=1e-7)


def test_bertscore_bad_url_degrades_with_warning():
    m = BERTScore(max_length=32, rescale_with_baseline=True,
                  baseline_url="file:///nonexistent/baseline.tsv")
    m.update(["hi there"], ["hi there"])
    with pytest.warns(UserWarning, match="Baseline"):
        out = m.compute()
    assert np.isfinite(np.asarray(out["f1"])).all()