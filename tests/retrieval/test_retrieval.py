"""Retrieval metrics: vectorized segment compute vs per-query numpy references
(sklearn average_precision / ndcg + hand-rolled), mirroring the reference's
`tests/retrieval/` strategy."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.metrics import average_precision_score as sk_ap, ndcg_score as sk_ndcg

from metrics_tpu import (
    RetrievalFallOut,
    RetrievalMAP,
    RetrievalMRR,
    RetrievalNormalizedDCG,
    RetrievalPrecision,
    RetrievalRecall,
)
from metrics_tpu.functional import (
    retrieval_average_precision,
    retrieval_fall_out,
    retrieval_normalized_dcg,
    retrieval_precision,
    retrieval_recall,
    retrieval_reciprocal_rank,
)
from tests.helpers import seed_all

seed_all(42)

N_QUERIES = 20
ROWS = 400


def _make_inputs(binary_target=True, guarantee_pos=False):
    rng = np.random.RandomState(7)
    indexes = rng.randint(0, N_QUERIES, ROWS)
    preds = rng.rand(ROWS).astype(np.float32)
    if binary_target:
        target = rng.randint(0, 2, ROWS)
    else:
        target = rng.randint(0, 5, ROWS)
    if guarantee_pos:
        for q in range(N_QUERIES):
            rows = np.nonzero(indexes == q)[0]
            if len(rows) and target[rows].sum() == 0:
                target[rows[0]] = 1
    return indexes, preds, target


def _per_query_mean(indexes, preds, target, fn, empty="neg", empty_on_neg=False):
    scores = []
    for q in np.unique(indexes):
        rows = indexes == q
        t, p = target[rows], preds[rows]
        empty_cond = (1 - (t > 0)).sum() == 0 if empty_on_neg else (t > 0).sum() == 0
        if empty_cond:
            if empty == "neg":
                scores.append(0.0)
            elif empty == "pos":
                scores.append(1.0)
            elif empty == "skip":
                continue
        else:
            scores.append(fn(p, t))
    return np.mean(scores) if scores else 0.0


def _np_ap(p, t):
    order = np.argsort(-p)
    t = t[order] > 0
    cum = np.cumsum(t)
    pos = np.arange(1, len(t) + 1)
    return (cum[t] / pos[t]).mean()


def _np_rr(p, t):
    order = np.argsort(-p)
    t = t[order] > 0
    return 1.0 / (np.argmax(t) + 1)


def _np_prec(p, t, k):
    kk = len(p) if k is None else k
    order = np.argsort(-p)
    return (t[order] > 0)[:kk].sum() / kk


def _np_rec(p, t, k):
    kk = len(p) if k is None else k
    order = np.argsort(-p)
    return (t[order] > 0)[:kk].sum() / (t > 0).sum()


def _np_fallout(p, t, k):
    kk = len(p) if k is None else k
    order = np.argsort(-p)
    neg = (t[order] == 0)[:kk].sum()
    return neg / (t == 0).sum()


def _np_ndcg(p, t, k):
    kk = len(p) if k is None else k
    order = np.argsort(-p)
    st = t[order][:kk]
    it = np.sort(t)[::-1][:kk]
    dcg = (st / np.log2(np.arange(len(st)) + 2)).sum()
    idcg = (it / np.log2(np.arange(len(it)) + 2)).sum()
    return 0.0 if idcg == 0 else dcg / idcg


@pytest.mark.parametrize("empty_action", ["neg", "pos", "skip"])
def test_retrieval_map(empty_action):
    indexes, preds, target = _make_inputs()
    m = RetrievalMAP(empty_target_action=empty_action)
    # feed in two batches
    m.update(jnp.asarray(preds[:200]), jnp.asarray(target[:200]), jnp.asarray(indexes[:200]))
    m.update(jnp.asarray(preds[200:]), jnp.asarray(target[200:]), jnp.asarray(indexes[200:]))
    expected = _per_query_mean(indexes, preds, target, _np_ap, empty=empty_action)
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


def test_retrieval_mrr():
    indexes, preds, target = _make_inputs()
    m = RetrievalMRR()
    m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    expected = _per_query_mean(indexes, preds, target, _np_rr)
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize("k", [None, 1, 3, 10])
def test_retrieval_precision_recall(k):
    indexes, preds, target = _make_inputs()
    mp = RetrievalPrecision(k=k)
    mr = RetrievalRecall(k=k)
    mp.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    mr.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    exp_p = _per_query_mean(indexes, preds, target, lambda p, t: _np_prec(p, t, k))
    exp_r = _per_query_mean(indexes, preds, target, lambda p, t: _np_rec(p, t, k))
    np.testing.assert_allclose(np.asarray(mp.compute()), exp_p, atol=1e-5)
    np.testing.assert_allclose(np.asarray(mr.compute()), exp_r, atol=1e-5)


@pytest.mark.parametrize("k", [None, 3])
def test_retrieval_fallout(k):
    indexes, preds, target = _make_inputs()
    m = RetrievalFallOut(k=k)
    m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    expected = _per_query_mean(
        indexes, preds, target, lambda p, t: _np_fallout(p, t, k), empty="pos", empty_on_neg=True
    )
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


@pytest.mark.parametrize("k", [None, 5])
def test_retrieval_ndcg(k):
    indexes, preds, target = _make_inputs(binary_target=False)
    m = RetrievalNormalizedDCG(k=k)
    m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    expected = _per_query_mean(indexes, preds, target, lambda p, t: _np_ndcg(p, t, k))
    np.testing.assert_allclose(np.asarray(m.compute()), expected, atol=1e-5)


def test_retrieval_empty_error():
    indexes = np.asarray([0, 0, 1, 1])
    preds = np.asarray([0.1, 0.2, 0.3, 0.4], dtype=np.float32)
    target = np.asarray([1, 0, 0, 0])  # query 1 has no positive
    m = RetrievalMAP(empty_target_action="error")
    m.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    with pytest.raises(ValueError, match="no positive target"):
        m.compute()


def test_functional_single_query_parity_vs_sklearn():
    rng = np.random.RandomState(3)
    p = rng.rand(50).astype(np.float32)
    t = rng.randint(0, 2, 50)
    np.testing.assert_allclose(
        np.asarray(retrieval_average_precision(jnp.asarray(p), jnp.asarray(t))), sk_ap(t, p), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(retrieval_normalized_dcg(jnp.asarray(p), jnp.asarray(t))),
        sk_ndcg(t[None], p[None]),
        atol=1e-5,
    )
    # doctest values from the reference
    np.testing.assert_allclose(
        np.asarray(retrieval_reciprocal_rank(jnp.asarray([0.2, 0.3, 0.5]), jnp.asarray([False, True, False]))),
        0.5,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(retrieval_precision(jnp.asarray([0.2, 0.3, 0.5]), jnp.asarray([True, False, True]), k=2)),
        0.5,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(retrieval_recall(jnp.asarray([0.2, 0.3, 0.5]), jnp.asarray([True, False, True]), k=2)),
        0.5,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(retrieval_fall_out(jnp.asarray([0.2, 0.3, 0.5]), jnp.asarray([True, False, True]), k=2)),
        1.0,
        atol=1e-6,
    )


def test_retrieval_invalid_inputs():
    m = RetrievalMAP()
    with pytest.raises(ValueError, match="same shape"):
        m.update(jnp.asarray([0.1, 0.2]), jnp.asarray([1]), jnp.asarray([0, 0]))
    with pytest.raises(ValueError, match="long integers"):
        m.update(jnp.asarray([0.1]), jnp.asarray([1]), jnp.asarray([0.5]))
    with pytest.raises(ValueError, match="binary"):
        m.update(jnp.asarray([0.1]), jnp.asarray([3]), jnp.asarray([0]))
    with pytest.raises(ValueError, match="wrong value"):
        RetrievalMAP(empty_target_action="bogus")


def test_retrieval_merge_across_instances():
    indexes, preds, target = _make_inputs()
    full = RetrievalMAP()
    full.update(jnp.asarray(preds), jnp.asarray(target), jnp.asarray(indexes))
    a, b = RetrievalMAP(), RetrievalMAP()
    a.update(jnp.asarray(preds[:150]), jnp.asarray(target[:150]), jnp.asarray(indexes[:150]))
    b.update(jnp.asarray(preds[150:]), jnp.asarray(target[150:]), jnp.asarray(indexes[150:]))
    a.merge_state(b)
    np.testing.assert_allclose(np.asarray(a.compute()), np.asarray(full.compute()), atol=1e-6)


class TestStaticNumQueries:
    """`num_queries` static upper bound: compute becomes one jittable XLA
    program; padding group ids are masked out of every policy's mean."""

    def _data(self, rng, n=512, queries=37):
        idx = jnp.asarray(rng.randint(0, queries, (n,)))
        preds = jnp.asarray(rng.rand(n).astype(np.float32))
        target = jnp.asarray(rng.randint(0, 2, (n,)))
        return idx, preds, target

    @pytest.mark.parametrize("action", ["neg", "pos", "skip"])
    def test_matches_eager_data_derived_count(self, action):
        rng = np.random.RandomState(7)
        idx, preds, target = self._data(rng)
        for cls in (RetrievalMAP, RetrievalMRR, RetrievalNormalizedDCG):
            eager = cls(empty_target_action=action)
            eager.update(preds, target, indexes=idx)
            exp = float(eager.compute())

            static = cls(empty_target_action=action, num_queries=64)  # > 37: padding
            state = static.pure_update(static.init_state(), preds, target, indexes=idx)
            got = jax.jit(static.pure_compute)(state)
            np.testing.assert_allclose(float(got), exp, atol=1e-6)

    def test_jit_compiles_once_and_caches(self):
        rng = np.random.RandomState(8)
        m = RetrievalMAP(num_queries=64)
        compute = jax.jit(m.pure_compute)
        vals = []
        for _ in range(2):
            idx, preds, target = self._data(rng)
            state = m.pure_update(m.init_state(), preds, target, indexes=idx)
            vals.append(float(compute(state)))
        assert compute._cache_size() == 1  # same shapes -> one trace
        assert vals[0] != vals[1]  # but genuinely different data

    def test_error_action_rejected(self):
        with pytest.raises(ValueError, match="num_queries"):
            RetrievalMAP(empty_target_action="error", num_queries=8)




def test_num_queries_bounds_distinct_ids_not_magnitude():
    """Non-contiguous / hash-like query ids are fine: the static bound
    constrains the number of DISTINCT ids (dense gids), and a genuinely
    too-small bound raises eagerly at compute instead of silently dropping."""
    m = RetrievalMAP(num_queries=2)
    m.update(
        jnp.asarray([0.9, 0.1, 0.8, 0.2]),
        jnp.asarray([1, 0, 0, 1]),
        indexes=jnp.asarray([1000, 1000, 5001, 5001]),
    )
    eager = RetrievalMAP()
    eager.update(
        jnp.asarray([0.9, 0.1, 0.8, 0.2]),
        jnp.asarray([1, 0, 0, 1]),
        indexes=jnp.asarray([1000, 1000, 5001, 5001]),
    )
    np.testing.assert_allclose(float(m.compute()), float(eager.compute()), atol=1e-6)

    too_small = RetrievalMAP(num_queries=2)
    too_small.update(
        jnp.asarray([0.9, 0.1, 0.8]), jnp.asarray([1, 0, 1]), indexes=jnp.asarray([0, 1, 2])
    )
    with pytest.raises(ValueError, match="DISTINCT"):
        too_small.compute()
